"""Continuous batching: slot-based LLM decode serving.

The static-batch :class:`~.serving.ModelReplica` decodes one request (or
one fixed batch) at a time; modern LLM serving interleaves many requests
in ONE resident decode batch so the weight stream (the decode
bottleneck) is amortized over every live request and a new request never
waits for the whole batch to finish.  The reference has nothing in this
space (its LLM element shells out to Ollama per request,
examples/llm/elements_llm.py:191-220).

TPU-native design — static shapes throughout:

* The server owns ``slots`` decode lanes and a KV cache of shape
  ``(slots, max_seq, …)``.  A request is ONE slot for its lifetime.
* Admission: prompts are right-padded to a power-of-2 bucket, and each
  admission wave prefills per-bucket groups in power-of-2 sub-batches
  (causal attention keeps every row's numerics exact regardless of pad
  garbage or batch-mates; pow2 everywhere keeps the compile-shape
  count bounded), landing each sub-batch's KV rows in its slots with
  one jitted batched scatter (cache donated → in-place).
  The slot is seeded with the LAST prompt token at position
  ``prompt_len - 1``: its KV rewrite is idempotent, and the first chunk
  step then emits the first generated token — no separate
  "logits-after-prefill" path exists to disagree with.
* Decode: :func:`~..models.llama.decode_chunk_ragged` scans
  ``chunk_steps`` greedy steps for ALL slots in one compiled program —
  every slot at its own position (``positions`` vector), finished /
  empty slots masked by ``active``.  Admission happens between chunks.
* Completion: a slot retires when it hits its token budget or emits
  ``eos_id``; the freed slot admits a queued request at the next chunk
  boundary.

Greedy decode through this path EXACTLY matches per-request
``generate_tokens`` output regardless of admission order (tested), so
batching is a pure throughput optimization, never a quality trade.

Layered on the same slot machinery (each independently tested, all
composable — see docs/SERVING.md):

* **lookahead** — multi-step scheduling: chunks chained device-side,
  one host sync per run;
* **chunk_prefill_tokens** — chunked-prefill admission: long prompts
  prefill between decode runs instead of stalling them;
* **adapters=** — multi-adapter LoRA serving (SLoRA-style stacked
  factors, PEFT hot-deploy over the wire, id 0 = base);
* **draft_config_name=** — per-slot SPECULATIVE decoding: one ragged
  verify pass per round; greedy exact, sampled slots via the
  device-side MRS kernel (distribution-preserving);
* token streaming (``stream: 1``), ``(infer_cancel id)``, and
  TTFT/total latency on every response.

:class:`ContinuousReplica` speaks the same ``(infer …)`` wire protocol
as :class:`~.serving.ModelReplica` (discovery, router and failover
compose unchanged; :class:`~.client.InferClient` packages the client
side); a delayed self-post pump (the reference's own retry idiom,
main/actor.py:229-253) runs chunks while slots are live —
deterministic under the VirtualClock test engine, where flatout
handlers only run inside the blocking loop.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..obs import compiles, flight, profiler, steplog, trace
from ..obs.metrics import CounterDict, Histogram, REGISTRY
from ..runtime import faults
from ..runtime.actor import Actor
from ..utils.sexpr import generate, parse

__all__ = ["ContinuousBatchingServer", "ContinuousReplica",
           "DecodeRequest"]

#: Distinct ``instance=`` metric label per server in this process.
_SERVER_INSTANCE_IDS = itertools.count()


@dataclasses.dataclass
class DecodeRequest:
    request_id: str
    prompt: "np.ndarray"           # (prompt_len,) int32
    max_new_tokens: int
    response_topic: Optional[str] = None
    #: 0 = greedy (exact, default); > 0 samples with optional nucleus.
    temperature: float = 0.0
    top_p: float = 1.0
    #: Deliver ``(infer_partial …)`` token increments as decode chunks
    #: complete (the final ``(infer_response …)`` still carries the
    #: full sequence).  The reference's LLM element blocks on the whole
    #: completion (examples/llm/elements_llm.py:185); streaming falls
    #: out of continuous batching for free.
    stream: bool = False
    #: Named LoRA adapter this request runs under (None = base model).
    #: Requests with different adapters share ONE decode batch — the
    #: base weight stream is paid once for all of them (SLoRA-style;
    #: server must be constructed with ``adapters=``).
    adapter: Optional[str] = None
    #: Named grammar from the server's ``automata`` registry: output
    #: is masked to the automaton's allowed sets and deterministic
    #: segments commit as jump-forward speculation windows.  None =
    #: unconstrained (the automaton applies to GENERATED tokens only,
    #: never the prompt).
    automaton: Optional[str] = None
    #: Absolute host-monotonic deadline (``deadline_ms`` on the wire
    #: travels as a RELATIVE budget — clocks never cross processes).
    #: Expired requests are rejected at admission and evicted from
    #: their slot with ``error="deadline_exceeded"``.
    deadline_ts: Optional[float] = None
    # Filled by the server:
    tokens: Optional[List[int]] = None
    error: Optional[str] = None
    #: Back-off hint attached to an ``error="overloaded"`` shed.
    retry_after_ms: Optional[int] = None
    #: Latency telemetry (monotonic seconds, host-observed): TTFT is
    #: measured at the host sync that DELIVERS the first token — the
    #: number a client actually experiences under lookahead/chunked
    #: admission, not the device-internal emission time.
    submitted_ts: Optional[float] = None
    first_token_ts: Optional[float] = None
    finished_ts: Optional[float] = None
    #: Slot activation (admission wave that reserved the slot) — with
    #: the stamps above this decomposes the request's life into the
    #: phases the obs layer histograms: queue-wait (submit→activate),
    #: prefill (activate→first token), decode (first→finish).
    activated_ts: Optional[float] = None
    #: Milliseconds spent restoring this request's prefix KV from a
    #: remote replica (0 when no kv_source hint / local hit).
    kv_restore_ms: float = 0.0
    #: Propagated trace context (``trace_id/span_id`` wire form) — the
    #: replica synthesizes phase spans under it at response time.
    trace_ctx: Optional[str] = None
    #: Encoded spans fetched alongside a remote KV restore (the
    #: source's ``kv_export`` span) — merged into the response tree.
    remote_spans: Optional[str] = None
    #: Per-spec-round accepted-proposal counts for THIS request (one
    #: entry per verify pass that advanced it; empty without a draft).
    #: Loadgen histograms these — the per-request acceptance shape,
    #: not just the fleet-mean rate.
    spec_accepted_rounds: Optional[List[int]] = None


def _bucket(n: int, minimum: int = 16) -> int:
    size = minimum
    while size < n:
        size *= 2
    return size


class ContinuousBatchingServer:
    """Slot-based continuous batching around a Llama-family model."""

    def __init__(self, config_name: str = "tiny", slots: int = 4,
                 max_seq: Optional[int] = None, chunk_steps: int = 8,
                 quantize: bool = False, eos_id: Optional[int] = None,
                 seed: int = 0, quantize_kv: bool = False, mesh=None,
                 lookahead: int = 1, adapters: Optional[Dict] = None,
                 lora_config=None, chunk_prefill_tokens: int = 0,
                 draft_config_name: Optional[str] = None,
                 draft_params=None, spec_k: int = 4,
                 draft_quantize: bool = False,
                 draft_mode: str = "auto", spec_ladder=None,
                 spec_adaptive: bool = False, automata=None,
                 params=None,
                 max_queue: Optional[int] = None,
                 watchdog_s: float = 0.0, replica_mesh=None,
                 compilation_cache_dir: Optional[str] = None,
                 compact_upload: bool = True,
                 ring_max: Optional[int] = None):
        import jax
        import jax.numpy as jnp
        from ..models import llama

        self._jax = jax
        self._jnp = jnp
        self._llama = llama
        # Persistent compilation cache (PR 14): opt-in per replica,
        # wired BEFORE any jit below so the very first prefill/serve
        # compiles land in (or load from) the cache — a warm restart
        # then skips recompilation entirely (SERVING.md warm-restart;
        # loadgen.run_compile_cache_ab gates cold vs warm).
        self.compilation_cache_dir = compilation_cache_dir
        if compilation_cache_dir:
            compiles.enable_persistent_cache(compilation_cache_dir)
        self.config = llama.CONFIGS[config_name]
        if params is not None:
            # Caller-built weights (trained, imported, or
            # random_quantized_params) — an 8B-class server on a
            # 16 GB chip cannot afford the bf16 init below just to
            # requantize it.  ``quantize=`` then only DECLARES the
            # tree's layout (for the TP spec choice); no
            # re-quantization happens.
            self.params = params
        else:
            self.params = llama.init_params(self.config,
                                            jax.random.PRNGKey(seed))
            if quantize:
                self.params = llama.quantize_params(self.params)
        if mesh is not None:
            # Multi-chip serving: megatron-TP-shard the (possibly
            # quantized) params over the mesh's "tp" axis; the decode
            # state (cache/positions/tokens) stays replicated and XLA
            # inserts the activation collectives.  This is the
            # composition a TP serving deployment runs.
            from jax.sharding import NamedSharding
            specs = (llama.quantized_param_specs(self.config)
                     if quantize else llama.param_specs(self.config))
            self.params = jax.tree.map(
                lambda leaf, spec: jax.device_put(
                    leaf, NamedSharding(mesh, spec)),
                self.params, specs)
        # Tensor-parallel replica: ONE replica owns ONE mesh.  Weights
        # shard on their output-feature axis, the paged KV pool shards
        # on the kv-head dimension, and the per-slot decode state stays
        # replicated — the host admission/commit protocol is untouched.
        # Collectives are all-gathers (pure data movement), so greedy
        # decode is BITWISE equal to the single-chip server (tested).
        self.replica_mesh = replica_mesh
        self._mesh = None
        self.tp_degree = 1
        self.sp_degree = 1
        self.ep_degree = 1
        self.mesh_shape = ""
        if replica_mesh is not None:
            if mesh is not None:
                raise ValueError(
                    "mesh= (GSPMD megatron sharding) and replica_mesh= "
                    "(shard_map TP engine) are distinct parallel "
                    "paths; pass one")
            replica_mesh.validate(self.config)
            from ..models import llama_tp
            self._llama_tp = llama_tp
            self._mesh = replica_mesh.build()
            self.tp_degree = int(replica_mesh.tp)
            self.sp_degree = int(replica_mesh.sp)
            self.ep_degree = int(replica_mesh.ep)
            self.mesh_shape = f"{replica_mesh.axis}={self.tp_degree}"
            second = replica_mesh.second_axis
            if second is not None:
                n2 = self.sp_degree if replica_mesh.sp > 1 \
                    else self.ep_degree
                self.mesh_shape += f",{second}={n2}"
            self.params = llama_tp.shard_params(
                self.params, self._mesh, replica_mesh.axis,
                ep_axis=(replica_mesh.ep_axis
                         if replica_mesh.ep > 1 else None),
                overlap=replica_mesh.overlap)
        self.slots = slots
        # Row max_seq-1 is the inactive-slot scratch row (see
        # decode_chunk_ragged); a live request may use at most
        # max_seq-2 positions.
        self.max_seq = max_seq or self.config.max_seq_len
        self.chunk_steps = chunk_steps
        # Multi-step scheduling: dispatch up to ``lookahead`` chunks
        # back-to-back with the device-returned tokens/positions chained
        # chunk-to-chunk, then sync to host ONCE for the whole run.
        # Bookkeeping (EOS, budgets, admission) lags by the run length,
        # but the device never idles waiting on a host round trip —
        # over the relay (~100 ms/dispatch) that round trip, not
        # compute, dominates the serving sections.  1 = sync every
        # chunk (the exact original behavior).  GREEDY outputs are
        # identical for every value (slot isolation is exact, tested);
        # SAMPLED outputs are identical while the chunk-vs-admission
        # timeline is unchanged (tested) but may legitimately differ
        # when a mid-run EOS shifts a queued request's admission chunk
        # — the request then draws different RNG chunk keys.
        self.lookahead = max(1, int(lookahead))
        # Chunked-prefill admission: prompts longer than this prefill
        # ``chunk_prefill_tokens`` tokens per step, INTERLEAVED with
        # the running slots' decode chunks — a long prompt no longer
        # stalls every live request for its whole prefill (the
        # decode-latency/SLO half of vLLM-style chunked prefill).
        # 0 = off (whole-bucket admission).  Power of two so chunk
        # programs share one shape per bucket size — plus at most one
        # tail-chunk shape when ``max_seq`` clamps a bucket to a
        # non-multiple of the chunk width.
        self.chunk_prefill_tokens = int(chunk_prefill_tokens)
        if self.chunk_prefill_tokens:
            if self.chunk_prefill_tokens < 16 or \
                    self.chunk_prefill_tokens & \
                    (self.chunk_prefill_tokens - 1):
                raise ValueError(
                    "chunk_prefill_tokens must be a power of two >= "
                    f"16, got {self.chunk_prefill_tokens}")
        #: slot -> in-progress chunked admission state.
        self._prefilling: Dict[int, Dict] = {}
        # Per-slot SPECULATIVE decoding: a small draft model proposes
        # spec_k tokens for every live slot in one ragged chunk; ONE
        # target verify pass (llama.verify_chunk_ragged) scores all
        # proposals, and each slot commits its own accepted prefix
        # plus the target's correction/bonus token — greedy outputs
        # stay EXACTLY equal to the plain server (tested).  The draft
        # keeps its own (slots, max_seq) contiguous cache, prefilled
        # at admission alongside the target's.
        self._draft = None
        if draft_config_name is not None:
            # Speculation now composes with chunked-prefill admission
            # (the draft's prompt KV lands whole at _finish_prefill —
            # the draft is small, so one un-chunked prefill does not
            # reintroduce the stall chunking removes) and with
            # replica_mesh TP (draft replicated on the mesh, below).
            # Still-unsupported combos stay LOUD errors:
            if mesh is not None:
                raise ValueError(
                    "speculative decoding does not compose with mesh= "
                    "(GSPMD megatron sharding): draft placement is "
                    "only defined for replica_mesh= (shard_map TP, "
                    "draft replicated) — or pass no mesh")
            # NOTE the verify-window width guard (k+1 vs the prompt
            # bucket floor) moved to validate_ladder below: the paged
            # layout may RAISE the floor to block_size, so the check
            # must run after _init_layout — and it now names the
            # whole LADDER, the thing actually bounding compiled
            # shapes under adaptive k.
            draft_config = llama.CONFIGS[draft_config_name]
            if draft_config.vocab_size != self.config.vocab_size:
                raise ValueError("draft and target must share a "
                                 "vocabulary")
            if draft_params is None:
                draft_params = llama.init_params(
                    draft_config, jax.random.PRNGKey(seed + 1))
            if draft_quantize:
                draft_params = llama.quantize_params(draft_params)
            self._draft = dict(
                config=draft_config, params=draft_params,
                k=int(spec_k),
                cache=llama.init_cache(draft_config, slots,
                                       self.max_seq))
            if self._mesh is not None:
                # TP replica: the draft model rides the SAME mesh,
                # fully replicated (params + its contiguous cache).
                # Draft dispatches then run the ordinary jitted
                # programs on every device with no collectives — each
                # chip computes the identical proposal stream, so TP
                # spec greedy output is bitwise the single-chip
                # server's (invariants 9 + 11).
                self._draft["params"] = self._llama_tp.replicate(
                    self._draft["params"], self._mesh)
                self._draft["cache"] = self._llama_tp.replicate(
                    self._draft["cache"], self._mesh)
        self.eos_id = eos_id
        self.quantize_kv = quantize_kv
        self._bucket_minimum = 16
        #: Speculation policy (set after _init_layout — the ladder
        #: validates against the FINAL bucket floor).  None = plain
        #: decode; _draft above is only the model-mode proposer.
        self._spec = None
        self._automata = None
        self._autostates = None
        self._init_layout()
        self._init_spec(draft_mode, spec_k, spec_ladder, spec_adaptive,
                        automata)
        # Decode-attention dispatch tag ("kernel" = Pallas paged
        # decode kernel, "reference" = jnp oracle) + the block
        # geometry of the attention view — decided once at init, so
        # bench regressions are attributable to the path taken.
        from ..ops.paged_attention import decode_attention_path
        from ..ops.paged_prefill import prefill_attention_path
        self.decode_attention_path = decode_attention_path()
        self.prefill_attention_path = prefill_attention_path()
        self._attn_block_size, self._attn_total_blocks = \
            self._attention_blocks()
        # Bookkeeping state lives HOST-side (numpy): admissions and
        # retirements mutate it for free, and it rides into the chunk
        # dispatch as three tiny h2d transfers.  The device-returned
        # copies are never fetched — the host mirror advances by the
        # same deterministic rule the compiled chunk applies
        # (positions += steps for chunk-active slots, next seed token
        # = last emitted).  Before this, every admission cost ~4
        # separate device scatters; over the relay those round-trips
        # dominated the serving sections.
        # Multi-adapter LoRA serving (SLoRA-style): stack the named
        # adapters once (index 0 = all-zero identity = base model);
        # each slot carries the index of ITS adapter and prefill +
        # decode gather per-row factors — mixed-adapter batches pay
        # the base weight stream once.
        self._adapter_index: Dict[str, int] = {}
        self._lora_shared = None
        self._lora_config = lora_config
        self._free_adapter_ids: List[int] = []
        if adapters:
            from ..models import lora as lora_mod
            if lora_config is None:
                raise ValueError("adapters= requires lora_config=")
            names = list(adapters)
            self._adapter_index = {name: i + 1
                                   for i, name in enumerate(names)}
            self._lora_shared = self._place_lora(
                lora_mod.stack_adapters(
                    self.config, lora_config,
                    [adapters[name] for name in names]))
        self._adapter_ids = np.zeros((slots,), np.int32)
        # Multi-tenant load provenance: warm = restacked from paged
        # storage, cold = factors shipped in from outside.
        self.adapter_warm_loads = 0
        self.adapter_cold_loads = 0
        self.positions = np.zeros((slots,), np.int32)
        self.active = np.zeros((slots,), bool)
        self.tokens = np.zeros((slots, 1), np.int32)
        self._temperatures = np.zeros(slots, np.float32)
        self._top_ps = np.ones(slots, np.float32)
        self._rng = jax.random.PRNGKey(seed)
        self._any_sampled = False
        self._requests: List[Optional[DecodeRequest]] = [None] * slots
        self._emitted = np.zeros(slots, np.int64)  # tokens emitted so far
        self._queue: List[DecodeRequest] = []
        self.completed: List[DecodeRequest] = []
        # ---- device-resident serving state + async dispatch ring ---- #
        # The decode state (token tail, positions, active, remaining
        # budget, sampling controls, adapter ids — plus block tables in
        # the paged layout) lives in ``self._state``, a chain of small
        # immutable device dicts: each dispatched chunk consumes the
        # head and returns the next.  The host keeps numpy mirrors for
        # bookkeeping, but they ride to the device ONLY through
        # ``_sync_dirty`` — a single masked merge covering the slots an
        # admission/retirement actually touched — so the steady-state
        # decode loop performs ZERO host→device uploads.
        self._remaining = np.zeros(slots, np.int32)
        self._state = self._init_device_state()
        if self._mesh is not None:
            # Slot state (and the paged layout's block tables) must be
            # REPLICATED jax.Arrays on the replica mesh so shard_map's
            # P() in_specs see one consistent copy per shard.
            self._state = self._llama_tp.replicate(self._state,
                                                   self._mesh)
        # In-flight ring: results of dispatched-but-unconsumed chunks.
        # Depth max(2, lookahead) double-buffers at minimum: step t+1
        # launches while step t's tiny (tokens, counts, active) result
        # is still in flight, and np.asarray happens only at consume.
        # The depth is ADAPTIVE between ``ring_min`` and ``ring_max``:
        # ``_ring_policy`` widens while the device is starved (ring
        # syncs return instantly AND the ring keeps running dry
        # between host passes) and shrinks back while the device is
        # saturated (syncs dwarf dispatch cost) — extra depth then
        # only delays retire/admit decisions by more chunks.
        from collections import deque
        self._ring = deque()
        self.ring_min = max(2, self.lookahead)
        self.ring_max = (int(ring_max) if ring_max is not None
                         else max(4, 2 * self.ring_min))
        if self.ring_max < self.ring_min:
            raise ValueError(
                f"ring_max {self.ring_max} below the double-buffer "
                f"floor max(2, lookahead) = {self.ring_min}")
        self._ring_depth = self.ring_min
        self._ema_wait_ms: Optional[float] = None
        self._ema_dispatch_ms: Optional[float] = None
        self._starved_streak = 0
        #: the next dispatch follows an admission wave whose last
        #: prefill may still be in flight (steplog classification only)
        self._post_admission = False
        #: compact dirty-row uploads (default): ``_sync_dirty``
        #: gathers ONLY the dirty mirror rows into a pow2-bucketed
        #: packet and row-scatters it into the resident state.  False
        #: = the legacy full-mirror masked merge — kept as the parity
        #: reference the compact path is tested bitwise against.
        self.compact_upload = bool(compact_upload)
        #: per-slot admission generation: an in-flight entry only
        #: applies to a slot whose serial still matches the entry's
        #: snapshot, so a retire-then-readmit can never credit a stale
        #: chunk's tokens to the new occupant.
        self._slot_serial = np.zeros(slots, np.int64)
        #: decode steps dispatched but not yet consumed, per slot —
        #: dispatch sizing subtracts this so a slot is never scheduled
        #: past its budget while results are in flight.
        self._inflight_sched = np.zeros(slots, np.int64)
        #: slots whose host mirror changed since the last dispatch.
        self._dirty = np.zeros(slots, bool)
        #: slots with a live sampling-param edit pending (uploads ONLY
        #: the sampling leaves — the slot may have chunks in flight).
        self._dirty_sampling = np.zeros(slots, bool)
        # Registry-mirrored engine counters: the dict API is unchanged
        # (tests and stats() read it directly) while every write also
        # lands in the process metrics registry under
        # ``aiko_server_<key>{instance=…}`` for the (metrics …) dump.
        # Process-monotonic instance id: ``id(self)`` hashes collide
        # when the allocator reuses a freed server's address, silently
        # MERGING two servers' registry series (histogram counts
        # accumulate across unrelated servers).
        self._instance_id = next(_SERVER_INSTANCE_IDS)
        self._metrics_labels = {"instance": f"srv{self._instance_id}"}
        self.counters: Dict = CounterDict(dict(
            dispatches=0, decode_steps=0, tokens_committed=0,
            host_syncs=0, sync_wait_ms=0.0, sync_elements=0,
            state_uploads=0, dirty_rows_uploaded=0, max_in_flight=0,
            ring_starved_steps=0, admission_deferred=0,
            decode_blocks_read=0, prefill_tokens=0,
            sp_prefill_dispatches=0,
            deadline_exceeded=0, shed=0, watchdog_trips=0),
            prefix="server", labels=self._metrics_labels)
        # Per-phase latency histograms — FIXED log-spaced buckets, so
        # the router/loadgen can merge them across replicas exactly
        # (they ride EC shares as ``hist.<phase>`` encoded strings).
        # Registry-created, so the (metrics …) scrape renders them as
        # proper ``_bucket``/``_sum``/``_count`` series too.
        self.latency_hists: Dict[str, Histogram] = {
            phase: REGISTRY.histogram(
                f"aiko_latency_{phase}_ms",
                help=f"Per-request {phase} latency (ms).",
                labels=self._metrics_labels)
            for phase in ("ttft", "total", "queue", "prefill",
                          "decode", "kv_restore")}
        self._serve_started: Optional[float] = None
        # ---- robustness: backpressure + device watchdog -------------- #
        #: bounded queue: submits past this depth shed with
        #: ``error="overloaded"`` + a retry-after hint (None = unbounded,
        #: the pre-robustness behavior).
        self.max_queue = max_queue
        #: host-side stall threshold (seconds) around the in-flight
        #: ring sync; 0 disables.  A sync past the threshold trips the
        #: watchdog: in-flight work fails with the RETRIABLE
        #: ``error="watchdog_stalled"`` and the replica goes (and
        #: stays) unhealthy until an operator restarts it.
        self.watchdog_s = float(watchdog_s)
        self.healthy = True
        self._watchdog_tripped = False
        # ---- on-demand device profiling (PR 14) ---------------------- #
        #: measured per-step device ms from the last (profile) bracket
        #: (None until one ran; replaces attrib's probe estimate).
        self._device_step_ms: Optional[float] = None
        self._profiles = 0
        self._profile_idle = 0

        @jax.jit
        def merge_state(state, host_state, mask):
            def merge(dev, host):
                m = mask.reshape((-1,) + (1,) * (dev.ndim - 1))
                return jnp.where(m, host.astype(dev.dtype), dev)
            return jax.tree.map(merge, state, host_state)

        self._merge_state = merge_state

    def _init_device_state(self) -> Dict:
        """Device-resident per-slot serving state (layout hook: the
        paged server adds its block tables)."""
        jnp = self._jnp
        slots = self.slots
        return {
            "token": jnp.zeros((slots, 1), jnp.int32),
            "positions": jnp.zeros((slots,), jnp.int32),
            "active": jnp.zeros((slots,), bool),
            "remaining": jnp.zeros((slots,), jnp.int32),
            "temps": jnp.zeros((slots,), jnp.float32),
            "tops": jnp.ones((slots,), jnp.float32),
            "adapter_ids": jnp.zeros((slots,), jnp.int32),
        }

    def _host_state(self) -> Dict:
        """Host mirror of :meth:`_init_device_state` (same keys; numpy
        views, uploaded only for dirty slots by ``_sync_dirty``)."""
        return {
            "token": self.tokens,
            "positions": self.positions,
            "active": self.active,
            "remaining": self._remaining,
            "temps": self._temperatures,
            "tops": self._top_ps,
            "adapter_ids": self._adapter_ids,
        }

    def _sync_dirty(self) -> None:
        """Merge dirty host-mirror rows into the resident device state
        — the ONLY host→device path for decode state.  No admissions or
        retirements since the last dispatch ⇒ no upload at all.

        Compact path (default): gather ONLY the dirty rows into a
        small ``(n_dirty, …)`` packet, pad to a pow2 bucket (repeating
        the last row — idempotent under the duplicate scatter), and
        row-scatter it into the resident state via
        :func:`~..models.llama.scatter_state_rows` (its
        :mod:`~..models.llama_tp` twin under a replica mesh).  Upload
        cost is O(dirty), not O(slots), and compile shapes stay
        log-bounded in the fleet size.

        The mirrors are SNAPSHOTTED (copied) here: the CPU backend may
        alias a numpy argument zero-copy into the async computation,
        and the host keeps mutating the mirrors (consume, retire)
        before the merge actually reads them — without the copy the
        merge races its own inputs.  The compact packet is race-safe
        by construction (fancy indexing always copies); the legacy
        masked-merge fallback keeps the full-shape operand its mask
        needs but copies live data for the DIRTY rows only.

        Two dirty classes.  STRUCTURAL rows (``_dirty``: admission,
        retirement, budget rebase) upload every leaf — valid only
        because such a slot has no live in-flight entries (the serial
        bump / ring drain guarantees it), so the mirrors equal the
        resident truth.  SAMPLING rows (``_dirty_sampling``: live
        ``update_sampling`` edits) may have chunks in flight whose
        progress leaves (``token``/``positions``/``remaining``) the
        host cannot know yet — those rows scatter ONLY the sampling
        leaves, never the progress leaves."""
        structural = self._dirty
        sampling = self._dirty_sampling & ~structural
        if not (structural.any() or sampling.any()):
            return
        rows = np.nonzero(structural)[0].astype(np.int32)
        sampling_rows = np.nonzero(sampling)[0].astype(np.int32)
        n_dirty = len(rows) + len(sampling_rows)
        if steplog.RECORDER is not None:
            steplog.RECORDER.record("state_upload", rows=n_dirty)
        if not self.compact_upload:
            # Legacy merge has no per-leaf mask; update_sampling
            # settles the ring before marking on this path, so every
            # dirty row is safe to merge wholesale.
            mask = structural | sampling
            merge_rows = np.nonzero(mask)[0]
            if compiles.LEDGER is not None:
                compiles.set_label("merge_state")
            snapshot = {}
            for key, value in self._host_state().items():
                buffer = np.zeros_like(value)
                buffer[merge_rows] = value[merge_rows]
                snapshot[key] = buffer
            self._state = self._merge_state(self._state, snapshot,
                                            mask.copy())
        else:
            if len(rows):
                padded = self._pow2_rows(rows)
                packet = {key: value[padded]
                          for key, value in self._host_state().items()}
                if compiles.LEDGER is not None:
                    compiles.set_label("scatter_rows",
                                       f"r{len(padded)}")
                self._state = self._scatter_rows(self._state, padded,
                                                 packet)
            if len(sampling_rows):
                padded = self._pow2_rows(sampling_rows)
                packet = {"temps": self._temperatures[padded],
                          "tops": self._top_ps[padded]}
                if compiles.LEDGER is not None:
                    compiles.set_label("scatter_sampling",
                                       f"r{len(padded)}")
                sub = {key: self._state[key] for key in packet}
                merged = self._scatter_rows(sub, padded, packet)
                self._state = {**self._state, **merged}
        self._dirty[:] = False
        self._dirty_sampling[:] = False
        self.counters["state_uploads"] += 1
        self.counters["dirty_rows_uploaded"] += n_dirty

    def _pow2_rows(self, rows: np.ndarray) -> np.ndarray:
        """Pad a dirty-row index vector to its pow2 bucket (clamped to
        the fleet size) by repeating the LAST row — duplicate indices
        scatter identical payloads, so the merge stays exact while the
        compile-shape count stays log-bounded."""
        bucket = 1
        while bucket < len(rows):
            bucket *= 2
        bucket = min(bucket, self.slots)
        padded = np.empty(bucket, np.int32)
        padded[:len(rows)] = rows
        padded[len(rows):] = rows[-1]
        return padded

    def _scatter_rows(self, state, padded, packet):
        """Route the row scatter to the single-chip kernel or its TP
        twin (which re-replicates the packet onto the replica mesh)."""
        if self._mesh is not None:
            return self._llama_tp.scatter_state_rows(
                state, padded, packet, self._mesh)
        return self._llama.scatter_state_rows(state, padded, packet)

    def _attention_blocks(self):
        """``(block_size, total_blocks_per_row)`` of the decode-
        attention view: the contiguous cache is the kernel's degenerate
        block pool (the paged server overrides with its real pool
        geometry)."""
        from ..ops.paged_attention import contiguous_block_size
        block_size = contiguous_block_size(self.max_seq) or self.max_seq
        return block_size, -(-self.max_seq // block_size)

    def _note_decode_blocks(self, live, sched) -> None:
        """Estimate the KV blocks each dispatched decode step reads,
        from the host position mirrors (positions as of dispatch;
        intra-chunk advance is ignored — at most ``steps/block_size``
        blocks/row of undercount).  Kernel path: only the row's live
        blocks, window-clamped; reference path: the whole cache/table
        every step — the counter makes the O(max_seq) → O(len) traffic
        difference a tracked number."""
        sched_live = sched[live]
        if self.decode_attention_path == "kernel":
            block_size = self._attn_block_size
            blocks = (self.positions[live]
                      + block_size) // block_size   # ceil((pos+1)/bs)
            window = self.config.sliding_window
            if window:
                blocks = np.minimum(blocks, window // block_size + 1)
        else:
            blocks = np.full(sched_live.shape, self._attn_total_blocks,
                             np.int64)
        self.counters["decode_blocks_read"] += int(
            (blocks * sched_live).sum())

    def _init_layout(self):
        """Cache-layout hook (overridden by the paged server): the
        contiguous layout reserves ``slots x max_seq`` rows."""
        jax = self._jax

        self.cache = self._llama.init_cache(
            self.config, self.slots, self.max_seq,
            quantize_kv=self.quantize_kv)
        if self._mesh is not None:
            # Contiguous layout under a replica mesh: weights are
            # sharded (output axis), cache/state replicated, and the
            # existing jitted programs run under GSPMD — XLA inserts
            # the activation all-gathers.  The paged layout instead
            # uses the explicit shard_map TPEngine (pool sharding).
            self.cache = self._llama_tp.replicate(self.cache,
                                                  self._mesh)

        @functools.partial(jax.jit, donate_argnames=("cache",),
                           static_argnames=("padded",))
        def insert_slots(cache, bucket_cache, slot_rows, padded):
            """Land a (k, padded, …) prefilled bucket batch in the k
            rows named by ``slot_rows`` (rows past each prompt hold
            pad garbage; each is rewritten by the decode step that
            first makes it attendable) — ONE dispatch per admission
            sub-batch instead of one per admission."""
            new_cache = []
            for cache_layer, filled in zip(cache, bucket_cache):
                layer = {}
                for key in cache_layer:
                    dst = cache_layer[key]
                    layer[key] = dst.at[slot_rows, :padded].set(
                        filled[key].astype(dst.dtype))
                new_cache.append(layer)
            return new_cache

        self._insert_slots = insert_slots

    def _init_spec(self, draft_mode: str, spec_k: int, spec_ladder,
                   spec_adaptive: bool, automata) -> None:
        """Speculation v2 policy wiring (after ``_init_layout`` — the
        ladder validates against the FINAL prompt-bucket floor, which
        the paged layout raises to ``block_size``).  Three proposers
        share one verify/accept/commit path:

        * ``model`` — the PR-10 paired draft (``draft_config_name``);
        * ``ngram`` — model-free self-drafting: suffix-match proposals
          from each slot's own committed history, assembled host-side;
        * grammar jump-forward — ``automata`` registers named
          :class:`~..models.constrained.TokenAutomaton` grammars;
          requests naming one get masked free tokens and deterministic
          segments committed as speculation windows.

        ``draft_mode="auto"`` resolves to ``model`` when a draft is
        configured, else ``ngram``; speculation is OFF only when no
        draft, no explicit ngram, and no automata are given."""
        spec_on = (self._draft is not None
                   or draft_mode in ("ngram", "model")
                   or bool(automata))
        if not spec_on:
            if draft_mode not in ("auto", "model", "ngram"):
                raise ValueError(
                    f"draft_mode must be 'model', 'ngram' or 'auto', "
                    f"got {draft_mode!r}")
            return
        mode = draft_mode
        if mode == "auto":
            mode = "model" if self._draft is not None else "ngram"
        if mode not in ("model", "ngram"):
            raise ValueError(
                f"draft_mode must be 'model', 'ngram' or 'auto', got "
                f"{draft_mode!r}")
        if mode == "model" and self._draft is None:
            raise ValueError(
                "draft_mode='model' requires draft_config_name=")
        if mode == "ngram" and self._draft is not None:
            raise ValueError(
                "draft_mode='ngram' does not take draft_config_name= "
                "(the slot's own committed history is the draft)")
        from .spec_control import (SpecController, default_ladder,
                                   validate_ladder)
        ladder = (tuple(int(k) for k in spec_ladder)
                  if spec_ladder is not None
                  else default_ladder(int(spec_k)))
        ladder = validate_ladder(ladder, self._bucket_minimum)
        if ladder[-1] < 1:
            raise ValueError(
                f"spec ladder {ladder} has no usable rung: the top "
                "rung must be >= 1 (k=0 alone is just plain decode)")
        controller = (SpecController(self.slots, ladder)
                      if spec_adaptive else None)
        self._spec = dict(mode=mode, k=int(ladder[-1]), ladder=ladder,
                          controller=controller,
                          adaptive=bool(spec_adaptive))
        from ..models.speculative import SpecStats
        self.spec_stats = SpecStats()
        if automata:
            from ..models.constrained import stack_automata
            table = stack_automata(dict(automata))
            if table.vocab != self.config.vocab_size:
                raise ValueError(
                    f"automata vocab {table.vocab} != model vocab "
                    f"{self.config.vocab_size}")
            allowed = self._jnp.asarray(table.allowed)
            if self._mesh is not None:
                allowed = self._llama_tp.replicate(allowed, self._mesh)
            self._automata = dict(table=table, allowed=allowed)
            #: per-slot GLOBAL automaton state; -1 = unconstrained.
            self._autostates = np.full(self.slots, -1, np.int64)

    # ------------------------------------------------------------- #

    def submit(self, request: DecodeRequest) -> None:
        request.tokens = []
        request.submitted_ts = time.monotonic()
        if request.deadline_ts is not None \
                and request.submitted_ts >= request.deadline_ts:
            # Expired on arrival (queueing upstream, transit): never
            # admit work whose answer nobody is waiting for.
            self._finish_rejected(request, "deadline_exceeded")
            return
        if not self.healthy:
            # Tripped watchdog: the router re-dispatches on this error.
            self._finish_rejected(request, "watchdog_stalled")
            return
        if self.max_queue is not None \
                and len(self._queue) >= self.max_queue:
            request.retry_after_ms = self._retry_after_ms()
            self._finish_rejected(request, "overloaded")
            return
        prompt_len = int(np.asarray(request.prompt).shape[0])
        reason = self._admission_reject(prompt_len, request)
        if reason:
            request.error = reason
            self.completed.append(request)
            return
        self._queue.append(request)

    def _finish_rejected(self, request: DecodeRequest,
                         reason: str) -> None:
        """Terminal admission rejection on the robustness paths —
        counted, stamped, and flowed out through the normal completion
        list (the replica publishes it like any other response)."""
        request.error = reason
        request.finished_ts = time.monotonic()
        if reason == "deadline_exceeded":
            self.counters["deadline_exceeded"] += 1
        elif reason == "overloaded":
            self.counters["shed"] += 1
        self.completed.append(request)

    def _retry_after_ms(self) -> int:
        """Shed hint: scale with how far over capacity we are — a
        saturated queue at 2× capacity hints twice the wait of one at
        1×.  Coarse by design; clients jitter their own retries."""
        depth = len(self._queue)
        per_request_ms = 50
        return int(min(5_000, per_request_ms * max(1, depth)))

    def _admission_reject(self, prompt_len: int,
                          request: DecodeRequest) -> Optional[str]:
        """Reject hook: a non-None reason fails the request at submit
        time (never queue what can never run — a deferred-forever head
        request would starve the whole FIFO)."""
        if prompt_len == 0:
            # There is no last prompt token to seed the slot with; an
            # empty prompt would decode an all-pad bucket into
            # plausible-looking garbage.
            return "empty_prompt"
        if prompt_len + request.max_new_tokens > self.max_seq - 1:
            return "prompt_too_long"
        if request.adapter is not None \
                and request.adapter not in self._adapter_index:
            return "unknown_adapter"
        if request.automaton is not None \
                and (self._automata is None
                     or request.automaton
                     not in self._automata["table"].offsets):
            return "unknown_automaton"
        if self._spec is not None:
            if prompt_len + request.max_new_tokens \
                    + self._spec["k"] + 1 > self.max_seq:
                # Speculation writes k rows past the live position;
                # without this headroom the verify slab's clamped
                # write would corrupt committed rows.  Bounded by the
                # ladder TOP — adaptivity can only narrow.
                return "prompt_too_long"
        return None

    def live_requests(self) -> List[DecodeRequest]:
        """Requests currently holding a decode slot (streaming
        delivery and operator introspection)."""
        return [r for r in self._requests if r is not None]

    @property
    def slots_active(self) -> int:
        """Live decode lanes (operator telemetry)."""
        return len(self.live_requests())

    @property
    def queue_depth(self) -> int:
        """Requests awaiting a slot (operator telemetry)."""
        return len(self._queue)

    @property
    def busy(self) -> bool:
        # Prefilling slots hold their request in _requests, so
        # slots_active covers chunked admissions too; in-flight ring
        # entries carry undelivered tokens even after every slot's
        # final chunk has been dispatched.
        return bool(self._queue) or self.slots_active > 0 \
            or bool(self._ring)

    def _admit(self) -> None:
        admissions = []
        for slot in range(self.slots):
            if self._requests[slot] is not None or not self._queue:
                continue
            request = self._queue[0]
            prompt = np.asarray(request.prompt, np.int32)[None, :]
            prompt_len = prompt.shape[1]
            # Clamp the bucket to the cache: a prompt near max_seq must
            # not prefill a bucket larger than the slot rows.
            padded = min(_bucket(prompt_len, self._bucket_minimum),
                         self.max_seq)
            if not self._reserve_slot(slot, padded, request):
                self.counters["admission_deferred"] += 1
                break      # capacity (paged pool) exhausted; next chunk
            self._queue.pop(0)
            request.activated_ts = time.monotonic()
            prompt_padded = np.zeros((1, padded), np.int32)
            prompt_padded[:, :prompt_len] = prompt
            if self.chunk_prefill_tokens \
                    and prompt_len > self.chunk_prefill_tokens:
                # Chunked admission: the slot is OCCUPIED (queued
                # requests cannot take it) but not yet active —
                # chunks are fed one per step between the running
                # slots' decode runs (standalone _advance_prefills
                # here; folded into the mixed decode dispatch on the
                # paged backend).
                self._requests[slot] = request
                self._begin_chunked_prefill(slot, request,
                                            prompt_padded, prompt_len)
                continue
            admissions.append((slot, request, prompt_padded, prompt_len))
        if steplog.RECORDER is not None:
            if admissions or self._prefilling:
                steplog.RECORDER.record("admission",
                                        slots=len(admissions),
                                        chunked=len(self._prefilling))
        if not admissions:
            return
        self._prefill_and_insert(admissions)
        for slot, request, prompt_padded, prompt_len in admissions:
            self._activate_slot(slot, request, prompt_padded,
                                prompt_len)
        # The wave's LAST prefill is still in flight here (nothing
        # blocks on it); on a one-in-flight backend the next decode
        # dispatch absorbs its compute.  Flag it so attribution can
        # file that gap under admission, not the decode loop.
        self._post_admission = True

    def _activate_slot(self, slot: int, request, prompt_padded,
                       prompt_len: int) -> None:
        """Seed a prefilled slot for decode — with the LAST prompt
        token at its own position: the next chunk's first step
        re-writes that KV row with identical values and emits the
        first generated token.  The ONE activation path for both
        whole-bucket and chunked admission."""
        self.tokens[slot, 0] = prompt_padded[0, prompt_len - 1]
        self.positions[slot] = prompt_len - 1
        self.active[slot] = True
        self._adapter_ids[slot] = self._adapter_id(request)
        self._temperatures[slot] = max(0.0, float(request.temperature))
        self._top_ps[slot] = float(request.top_p)
        self._requests[slot] = request
        self._emitted[slot] = 0
        self._remaining[slot] = request.max_new_tokens
        self._inflight_sched[slot] = 0
        self._slot_serial[slot] += 1
        self._dirty[slot] = True
        self._any_sampled = bool((self._temperatures > 0).any())
        if self._spec is not None \
                and self._spec["controller"] is not None:
            # New occupant: forget the previous request's acceptance
            # history (optimistic start at the ladder top).
            self._spec["controller"].reset(slot)
        if self._automata is not None:
            name = request.automaton
            self._autostates[slot] = (
                self._automata["table"].start(name)
                if name is not None else -1)
        if steplog.RECORDER is not None:
            steplog.RECORDER.record(
                "sampling_edit", slot=slot,
                temperature=float(request.temperature),
                top_p=float(request.top_p))

    def _begin_chunked_prefill(self, slot: int, request, prompt_padded,
                               prompt_len: int) -> None:
        """Layout hook: open a chunked admission for ``slot``.  The
        contiguous layout prefills into a private batch-1 bucket that
        :func:`_finish_prefill` seals into the slot cache; the paged
        server overrides this to append straight into the slot's
        block chain (no bucket ever exists)."""
        self._prefilling[slot] = dict(
            request=request, prompt_padded=prompt_padded,
            prompt_len=prompt_len, start=0,
            lora=self._request_lora(request),
            bucket=self._llama.init_cache(
                self.config, 1, prompt_padded.shape[1],
                quantize_kv=self.quantize_kv))

    def _advance_prefills(self) -> None:
        """Run ONE prefill chunk for every in-progress chunked
        admission; a slot whose chunks now cover its whole prompt is
        sealed into the main cache and becomes decode-active."""
        jnp = self._jnp
        for slot in list(self._prefilling):
            state = self._prefilling[slot]
            start = state["start"]
            size = min(self.chunk_prefill_tokens,
                       state["prompt_padded"].shape[1] - start)
            chunk = state["prompt_padded"][:, start:start + size]
            _, state["bucket"] = self._llama.prefill_chunk(
                self.params, jnp.asarray(chunk), state["bucket"],
                jnp.int32(start), self.config, lora=state["lora"])
            state["start"] = start + size
            self._note_prefill(size)
            if state["start"] >= state["prompt_len"]:
                # Rows past prompt_len stay zero-initialized — exactly
                # as unattendable as the whole-prefill path's
                # pad-garbage rows (absolute-position masking).
                self._finish_prefill(slot, state)

    def _finish_prefill(self, slot: int, state: Dict) -> None:
        jnp = self._jnp
        self.cache = self._insert_slots(
            self.cache, state["bucket"],
            jnp.asarray(np.asarray([slot], np.int32)),
            state["prompt_padded"].shape[1])
        del self._prefilling[slot]
        if self._draft is not None:
            # The draft needs the SAME committed history before the
            # slot's first spec round.  Whole-prompt in one dispatch:
            # the draft is small by construction, so this does not
            # reintroduce the batch stall chunked admission removes.
            self._prefill_draft_rows([slot], state["prompt_padded"])
        self._activate_slot(slot, state["request"],
                            state["prompt_padded"],
                            state["prompt_len"])

    def _prefill_and_insert(self, admissions) -> None:
        """Admission-group hook.  Contiguous layout: group admissions
        by bucket size, prefill each group batched (causal attention
        keeps every row's numerics independent of its batch-mates),
        and land each batch with ONE batched scatter — dispatch count
        per admission wave drops from 2 × admissions to ~2 × distinct
        bucket sizes.  Groups split into power-of-2 sub-batches so the
        compile-shape count stays bounded at log2(slots) × n_buckets
        (every compile is a relay risk; same pow2 discipline as the
        prompt buckets themselves).  (The paged server overrides this
        with its per-slot prefix-cache walk.)"""
        jnp = self._jnp
        groups: Dict[int, List] = {}
        for slot, request, prompt_padded, prompt_len in admissions:
            adapter_id = self._adapter_id(request)
            groups.setdefault(prompt_padded.shape[1], []).append(
                (slot, prompt_padded, adapter_id))
        for padded, group in groups.items():
            start = 0
            while start < len(group):
                # Largest power of two <= the remaining group.
                size = 1 << ((len(group) - start).bit_length() - 1)
                sub = group[start:start + size]
                start += size
                slots = [slot for slot, _, _ in sub]
                prompts = np.concatenate([p for _, p, _ in sub],
                                         axis=0)
                if compiles.LEDGER is not None:
                    # Shape-bucket signature: any compile with a
                    # signature OUTSIDE the pow2 grid is a bucket-
                    # discipline breach (the ledger's log-bound test).
                    compiles.set_label("prefill",
                                       f"b{padded}x{len(sub)}")
                # The prompt KV must be built under the SAME adapter
                # the decode chunks will run (None for all-base).
                lora = self._make_lora([aid for _, _, aid in sub])
                bucket_cache = self._llama.init_cache(
                    self.config, len(sub), padded,
                    quantize_kv=self.quantize_kv)
                _, bucket_cache = self._llama.prefill(
                    self.params, jnp.asarray(prompts), bucket_cache,
                    self.config, lora=lora)
                slot_rows = jnp.asarray(np.asarray(slots, np.int32))
                self.cache = self._insert_slots(
                    self.cache, bucket_cache, slot_rows, padded)
                self._note_prefill(len(sub) * padded)
                if self._draft is not None:
                    # The draft needs the SAME committed history: its
                    # prompt KV lands in its own slot cache alongside.
                    self._prefill_draft_rows(slots, prompts)

    def _prefill_draft_rows(self, slots_list, prompts) -> None:
        """Land the draft model's prompt KV for ``slots_list`` (its
        contiguous per-slot cache rows), batched.  The ONE draft
        admission path shared by every layout and admission mode:
        whole-bucket waves, chunked-admission finishes, and the paged
        server's per-request appends all funnel here — the draft has
        no prefix cache and no pool, so it always prefills the whole
        padded prompt regardless of what the target reused."""
        draft, jax, jnp = self._draft, self._jax, self._jnp
        if "insert" not in draft:
            # Same insert-batch closure as the contiguous target
            # layout, built lazily because the paged server's
            # _init_layout never creates one.
            @functools.partial(jax.jit, donate_argnames=("cache",),
                               static_argnames=("padded",))
            def draft_insert(cache, bucket_cache, slot_rows, padded):
                new_cache = []
                for cache_layer, filled in zip(cache, bucket_cache):
                    layer = {}
                    for key in cache_layer:
                        dst = cache_layer[key]
                        layer[key] = dst.at[slot_rows, :padded].set(
                            filled[key].astype(dst.dtype))
                    new_cache.append(layer)
                return new_cache

            draft["insert"] = draft_insert
        padded = prompts.shape[1]
        if compiles.LEDGER is not None:
            compiles.set_label("draft_prefill",
                               f"b{padded}x{len(slots_list)}")
        bucket = self._llama.init_cache(draft["config"],
                                        len(slots_list), padded)
        _, bucket = self._llama.prefill(
            draft["params"], jnp.asarray(prompts), bucket,
            draft["config"])
        slot_rows = jnp.asarray(np.asarray(slots_list, np.int32))
        draft["cache"] = draft["insert"](draft["cache"], bucket,
                                         slot_rows, padded)

    def _reserve_slot(self, slot: int, padded: int, request) -> bool:
        """Capacity hook: claim layout resources for an admission.
        Contiguous layout always has room (the slot IS the room)."""
        return True

    def _adapter_id(self, request) -> int:
        """Stacked-factor index for a request (0 = base identity;
        unknown names are rejected at submit)."""
        return self._adapter_index.get(request.adapter, 0)

    @property
    def adapters_loaded(self) -> List[str]:
        """Names currently servable (operator telemetry)."""
        return sorted(self._adapter_index)

    def adapter_slot_counts(self) -> Dict[str, int]:
        """name -> decode slots currently pinned to that adapter
        (dashboard pane + pool census; host-side reads only)."""
        if not self._adapter_index:
            return {}
        slot_ids = np.asarray(self._adapter_ids).reshape(-1)
        return {name: int(np.sum(slot_ids == index))
                for name, index in sorted(self._adapter_index.items())}

    def _adapter_users(self, name: str) -> int:
        """Requests pinning adapter ``name`` — by NAME, not stacked
        index: a chunk-prefilling slot holds its request before
        ``_activate_slot`` assigns the id, and queued requests have no
        slot at all, yet both will decode under the name."""
        live = sum(1 for r in self._requests
                   if r is not None and r.adapter == name)
        return live + sum(1 for r in self._queue if r.adapter == name)

    def _adapter_load_counter(self, kind: str):
        """Lazily-created ``aiko_adapter_loads_total{kind=}`` mirror
        of the warm/cold load attributes (lazy so base-model servers
        never emit the series)."""
        counters = getattr(self, "_adapter_load_counters", None)
        if counters is None:
            counters = self._adapter_load_counters = {}
        counter = counters.get(kind)
        if counter is None:
            counter = REGISTRY.counter(
                "aiko_adapter_loads_total",
                "adapter hot-deploys by provenance (warm = restacked "
                "from a paged pool copy, cold = client-uploaded "
                "factor bytes)",
                labels=dict(self._metrics_labels, kind=kind))
            counters[kind] = counter
        return counter

    def load_adapter(self, name: str, lora_params=None,
                     lora_config=None) -> None:
        """Register (or replace) a LoRA adapter at RUNTIME — deploy a
        new fine-tune without restarting the replica.  The first load
        on an adapter-less server defines the shared LoRAConfig; later
        loads must match it (one stacked shape per server).  Replacing
        a name requires no live request on it (``adapter_busy``).

        ``lora_params=None`` is a WARM load: the factors restack from
        the replica's paged adapter storage (any tier — the shared
        pool keeps unloaded adapters warm) with no client re-upload;
        ``KeyError`` when no paged copy survives (``adapter_cold``)."""
        from ..models import lora as lora_mod
        jnp = self._jnp

        if lora_params is None:
            fetched = self._fetch_adapter_pages(name)
            if fetched is None:
                raise KeyError(f"adapter_cold: no paged copy of "
                               f"{name!r} to warm-load")
            lora_params, paged_config = fetched
            if lora_config is None:
                lora_config = paged_config
            self.adapter_warm_loads += 1
            self._adapter_load_counter("warm").inc()
        else:
            self.adapter_cold_loads += 1
            self._adapter_load_counter("cold").inc()

        if self._lora_config is None:
            if lora_config is None:
                raise ValueError("first load_adapter needs lora_config")
            # Committed only after stack_adapters validates it below —
            # a failed first load must not wedge the server with a
            # config that never actually loaded.
        elif lora_config is not None and (
                lora_config.rank != self._lora_config.rank
                or set(lora_config.targets)
                != set(self._lora_config.targets)
                or lora_config.alpha != self._lora_config.alpha):
            # Targets compare as SETS: PEFT serializes target_modules
            # from a set, so order varies while the stacked layout
            # (keyed by target name) is unaffected.
            # The stacked scale (= alpha/rank) is shared server-wide;
            # a mismatched adapter would serve at the wrong scale.
            raise ValueError(
                f"adapter {name!r} config (rank {lora_config.rank}, "
                f"alpha {lora_config.alpha}, targets "
                f"{lora_config.targets}) does not match the server's "
                f"(rank {self._lora_config.rank}, alpha "
                f"{self._lora_config.alpha}, targets "
                f"{self._lora_config.targets})")
        # Direct-API callers may omit the config (the wire path always
        # supplies one); stack_adapters below shape-verifies every
        # factor against the server's config — but alpha is NOT
        # recoverable from the weights, so an adapter trained at a
        # different alpha with matching shapes MUST pass its config to
        # be rejected; omitting it asserts the server's scale.
        candidate_config = self._lora_config or lora_config
        stacked_one = lora_mod.stack_adapters(
            self.config, candidate_config, [lora_params])
        self._lora_config = candidate_config
        if self._lora_shared is None:
            self._lora_shared = self._place_lora(stacked_one)
            self._adapter_index[name] = 1
            self._register_adapter_pages(name, lora_params)
            return
        existing = self._adapter_index.get(name)
        if existing is not None:
            if self._adapter_users(name):
                raise ValueError(f"adapter_busy: {name!r} has live "
                                 "requests")
            index = existing
            # New weights under an old id: cached prompt KV built with
            # the previous weights must not be served (paged prefix
            # cache keys carry the numeric id).
            self._invalidate_adapter_cache(index)
        elif self._free_adapter_ids:
            index = self._free_adapter_ids.pop()
        else:
            index = None           # append (stack widens; recompile)
        new_layers = []
        for layer, one in zip(self._lora_shared["layers"],
                              stacked_one["layers"]):
            merged = {}
            for target, factors in layer.items():
                fresh = one[target]
                if index is None:
                    merged[target] = {
                        "a": jnp.concatenate(
                            [factors["a"], fresh["a"][1:]]),
                        "b": jnp.concatenate(
                            [factors["b"], fresh["b"][1:]]),
                    }
                else:
                    merged[target] = {
                        "a": factors["a"].at[index].set(fresh["a"][1]),
                        "b": factors["b"].at[index].set(fresh["b"][1]),
                    }
            new_layers.append(merged)
        self._lora_shared = self._place_lora(
            {"scale": self._lora_shared["scale"],
             "layers": new_layers})
        if index is None:
            index = self._lora_shared["layers"][0][
                next(iter(new_layers[0]))]["a"].shape[0] - 1
        self._adapter_index[name] = index
        self._register_adapter_pages(name, lora_params)

    def unload_adapter(self, name: str) -> None:
        """Remove a served adapter; its stacked index is zeroed and
        recycled (no recompile).  Requires no live request on it.
        Paged adapter storage is deliberately NOT dropped: the pages
        stay resident under the shared eviction clock, so a future
        ``load_adapter(name)`` warm-loads with no re-upload."""
        jnp = self._jnp
        index = self._adapter_index.get(name)
        if index is None:
            raise KeyError(name)
        if self._adapter_users(name):
            raise ValueError(f"adapter_busy: {name!r} has live "
                             "requests")
        new_layers = []
        for layer in self._lora_shared["layers"]:
            merged = {}
            for target, factors in layer.items():
                merged[target] = {
                    "a": factors["a"].at[index].set(
                        jnp.zeros_like(factors["a"][index])),
                    "b": factors["b"].at[index].set(
                        jnp.zeros_like(factors["b"][index])),
                }
            new_layers.append(merged)
        self._lora_shared = self._place_lora(
            {"scale": self._lora_shared["scale"],
             "layers": new_layers})
        del self._adapter_index[name]
        # The id will be recycled: stale cached KV under it must go
        # before a future adapter can collide with its chain keys.
        self._invalidate_adapter_cache(index)
        self._free_adapter_ids.append(index)

    def _invalidate_adapter_cache(self, index: int) -> None:
        """Layout hook: drop any cached state keyed by this stacked
        adapter id (the paged prefix cache overrides this; the
        contiguous layout caches nothing across requests)."""

    def _register_adapter_pages(self, name: str, adapter) -> int:
        """Layout hook: mirror a loaded adapter's factors into paged
        storage so it stays warm across unloads (the paged layout
        overrides this; the contiguous layout has no pool)."""
        return 0

    def _fetch_adapter_pages(self, name: str):
        """Layout hook: recover ``(lora_params, LoRAConfig)`` for a
        previously paged adapter, or None when cold (the paged layout
        overrides this; the contiguous layout never pages)."""
        return None

    def _place_lora(self, lora_shared):
        """Layout hook: place the stacked adapter tree for the serving
        programs.  Single chip: host tree as-is.  Contiguous layout
        under a replica mesh: REPLICATE the factors — the GSPMD
        programs then compute every rank-r delta identically on each
        device (exact; the factors are tiny).  The paged layout
        overrides with the TPEngine's explicit column sharding
        (:func:`~..models.llama_tp.shard_lora`)."""
        if lora_shared is not None and self._mesh is not None:
            return self._llama_tp.replicate(lora_shared, self._mesh)
        return lora_shared

    def _make_lora(self, ids):
        """Assemble the batched lora argument for per-row adapter
        ``ids`` — or None when no row actually runs an adapter, so
        all-base traffic keeps the adapter-free compiled program (no
        gather/einsum work; the same discipline ``_any_sampled``
        applies to sampling math)."""
        ids = np.asarray(ids, np.int32)
        if self._lora_shared is None or not ids.any():
            return None
        return dict(ids=self._jnp.asarray(ids), **self._lora_shared)

    def _request_lora(self, request):
        """Batch-1 lora argument for a single request's prefill (the
        paged per-slot admission path)."""
        return self._make_lora([self._adapter_id(request)])

    def _prefill_bucket(self, slot: int, prompt_padded,
                        prompt_len: int, lora=None):
        """Prefill hook: run the padded prompt into a fresh batch-1
        bucket cache.  Used by the PAGED server's cache-miss path (its
        prefix-cache walk is per-slot); the contiguous layout itself
        admits through the batched ``_prefill_and_insert``."""
        llama, jnp = self._llama, self._jnp
        bucket_cache = llama.init_cache(
            self.config, 1, prompt_padded.shape[1],
            quantize_kv=self.quantize_kv)
        _, bucket_cache = llama.prefill(
            self.params, jnp.asarray(prompt_padded), bucket_cache,
            self.config, lora=lora)
        return bucket_cache

    def _release_slot(self, slot: int) -> None:
        """Layout hook: return a retiring slot's resources."""

    def _retire(self, slot: int) -> None:
        request = self._requests[slot]
        if request is not None:
            request.finished_ts = time.monotonic()
            self.completed.append(request)
        self._release_slot(slot)
        self._requests[slot] = None
        self.active[slot] = False
        self._adapter_ids[slot] = 0
        self._remaining[slot] = 0
        self._inflight_sched[slot] = 0
        # Bump the admission generation: any still-in-flight entry's
        # data for this slot is now stale and will be skipped.
        self._slot_serial[slot] += 1
        self._dirty[slot] = True
        if self._autostates is not None:
            self._autostates[slot] = -1
        # Reset sampling state so an all-greedy batch returns to the
        # pure-greedy compiled program (no sort/softmax per step).
        self._temperatures[slot] = 0.0
        self._top_ps[slot] = 1.0
        self._any_sampled = bool((self._temperatures > 0).any())

    def update_sampling(self, request_id: str,
                        temperature: Optional[float] = None,
                        top_p: Optional[float] = None,
                        max_new_tokens: Optional[int] = None) -> bool:
        """Edit a live (or still-queued) request's sampling params /
        decode budget in place — DEVICE-RESIDENT: for a live slot the
        edit updates the host mirrors, marks the slot sampling-dirty,
        and rides the next dispatch's compact packet — uploading ONLY
        the sampling leaves, because the slot may have chunks in
        flight whose progress leaves the host cannot mirror yet.  No
        full-mirror upload, no dedicated round trip.  Edits take
        effect from the next dispatched chunk (chunks already in
        flight keep the params they were dispatched with).

        Budget edits additionally drain the in-flight ring first: the
        device's resident ``remaining`` counter must be rebased
        against a settled ``emitted`` count, and an in-flight chunk
        retiring the lane under the OLD budget while the packet
        revives it would strand the slot.  A new budget at or below
        the tokens already emitted retires the request immediately
        (finished, no error).  Returns False for an unknown id."""
        for request in self._queue:
            if request.request_id == request_id:
                if temperature is not None:
                    request.temperature = float(temperature)
                if top_p is not None:
                    request.top_p = float(top_p)
                if max_new_tokens is not None:
                    request.max_new_tokens = int(max_new_tokens)
                return True
        for slot in range(self.slots):
            request = self._requests[slot]
            if request is None or request.request_id != request_id:
                continue
            if max_new_tokens is not None:
                self._drain_ring()
                if self._requests[slot] is not request:
                    return True    # finished naturally while draining
                request.max_new_tokens = int(max_new_tokens)
                if request.max_new_tokens <= self._emitted[slot]:
                    self._prefilling.pop(slot, None)
                    self._retire(slot)
                    return True
                self._remaining[slot] = (request.max_new_tokens
                                         - self._emitted[slot])
            if temperature is not None:
                request.temperature = float(temperature)
                self._temperatures[slot] = max(
                    0.0, float(temperature))
            if top_p is not None:
                request.top_p = float(top_p)
                self._top_ps[slot] = float(top_p)
            if max_new_tokens is not None:
                # The ring is drained (above): the mirrors are exact,
                # so the full-row structural upload is safe — and the
                # rebased ``remaining`` must reach the device.
                self._dirty[slot] = True
            elif self.compact_upload:
                # Sampling-only edit on a slot that may have chunks in
                # flight: a full-row upload would stomp the device's
                # progress leaves (token/positions/remaining) with
                # stale mirrors — ride the sampling-leaf scatter.
                self._dirty_sampling[slot] = True
            else:
                # Legacy full-mirror merge has no per-leaf mask:
                # settle the ring so the mirrors are exact first.
                self._drain_ring()
                if self._requests[slot] is not request:
                    return True     # finished while settling
                self._dirty[slot] = True
            self._any_sampled = bool((self._temperatures > 0).any())
            if steplog.RECORDER is not None:
                steplog.RECORDER.record(
                    "sampling_edit", slot=slot,
                    temperature=float(self._temperatures[slot]),
                    top_p=float(self._top_ps[slot]))
            return True
        return False

    def cancel(self, request_id: str) -> bool:
        """Cancel by id, wherever the request currently lives: queued
        (dropped), chunk-prefilling (admission aborted, slot freed), or
        decoding (retired early, partial tokens kept).  The request
        completes with ``error="cancelled"`` and flows out through the
        normal completion path.  Returns False for an unknown id."""
        for i, request in enumerate(self._queue):
            if request.request_id == request_id:
                self._queue.pop(i)
                request.error = "cancelled"
                request.finished_ts = time.monotonic()
                self.completed.append(request)
                return True
        for slot in range(self.slots):
            request = self._requests[slot]
            if request is None or request.request_id != request_id:
                continue
            if slot not in self._prefilling:
                # Decoding: drain the in-flight ring FIRST so chunks
                # already dispatched deliver their partial tokens and
                # the device provably stops touching this lane before
                # its resources (paged blocks) are freed for reuse.
                self._drain_ring()
                if self._requests[slot] is not request:
                    return True      # finished naturally while draining
            request.error = "cancelled"
            self._prefilling.pop(slot, None)
            self._retire(slot)
            return True
        return False

    def step(self) -> List[DecodeRequest]:
        """Admit pending requests, keep the in-flight ring full, apply
        one (or, at the drain tail, every) completed chunk's results,
        retire finished slots.  Returns (and clears) the completed
        list.

        Async double-buffering: dispatch fills the ring to the
        adaptive depth (``ring_min = max(2, lookahead)`` floor, widened
        toward ``ring_max`` by ``_ring_policy`` while the device runs
        dry), then consume drains it to depth-1 in ONE batched pass —
        so in steady state every ``step()`` launches the next chunk
        BEFORE blocking on the previous one's (tiny) result, and the
        device never idles on host bookkeeping.  When nothing can be
        dispatched (all budgets scheduled, or no live slot) the ring is
        drained completely so results are never stranded."""
        self._evict_expired()
        self._admit()
        self._advance_prefills()
        if profiler.PROFILER is not None \
                and profiler.PROFILER.wants(id(self)):
            # On-demand device profiling: the FIRST engine whose step
            # loop sees a pending session claims it (jax.profiler is
            # process-global) and runs its next N steps synchronously
            # inside the trace bracket — the one step mode where we
            # deliberately give up double-buffering, because the
            # timed dispatch→sync window is the real device ms the
            # attribution table wants.
            self._profiled_step()
            if self._watchdog_tripped:
                self._fail_all("watchdog_stalled")
            done, self.completed = self.completed, []
            return done
        if self.slots_active and not self._ring:
            # The device drained everything we ever handed it before
            # this host pass came back — a starvation marker the ring
            # controller turns into extra depth.
            self.counters["ring_starved_steps"] += 1
            self._starved_streak += 1
        else:
            self._starved_streak = 0
        depth = self._ring_depth
        dispatched = False
        while len(self._ring) < depth and self._dispatch_round():
            dispatched = True
        target = depth - 1 if dispatched else 0
        if len(self._ring) > target:
            self._consume_ready(len(self._ring) - target)
        self._ring_depth = self._ring_policy(
            depth, self.ring_min, self.ring_max, self._ema_wait_ms,
            self._ema_dispatch_ms, self._starved_streak)
        if self._watchdog_tripped:
            # A stalled device step already failed this batch's
            # guarantees — fail everything live/queued with the
            # retriable error so routers move the work, rather than
            # letting clients discover the wedge by timeout.
            self._fail_all("watchdog_stalled")
        done, self.completed = self.completed, []
        return done

    def _evict_expired(self) -> None:
        """Deadline enforcement between chunks: drop expired queued
        requests, and evict live slots past deadline (draining the
        in-flight ring first, same discipline as :meth:`cancel`, so
        the device provably stops touching the lane before its
        resources are reused)."""
        now = time.monotonic()
        for index in reversed(range(len(self._queue))):
            request = self._queue[index]
            if request.deadline_ts is not None \
                    and now >= request.deadline_ts:
                self._queue.pop(index)
                request.error = "deadline_exceeded"
                request.finished_ts = now
                self.counters["deadline_exceeded"] += 1
                self.completed.append(request)
        expired = [slot for slot in range(self.slots)
                   if self._requests[slot] is not None
                   and self._requests[slot].deadline_ts is not None
                   and now >= self._requests[slot].deadline_ts]
        if not expired:
            return
        self._drain_ring()
        for slot in expired:
            request = self._requests[slot]
            if request is None or request.deadline_ts is None \
                    or time.monotonic() < request.deadline_ts:
                continue       # finished naturally while draining
            request.error = "deadline_exceeded"
            self.counters["deadline_exceeded"] += 1
            self._prefilling.pop(slot, None)
            self._retire(slot)

    def _fail_all(self, reason: str) -> None:
        """Fail every queued and live request with ``reason`` (the
        watchdog path — in-flight ring results are consumed first so
        partial tokens are preserved on the responses)."""
        self._drain_ring()
        now = time.monotonic()
        for request in self._queue:
            request.error = reason
            request.finished_ts = now
            self.completed.append(request)
        self._queue.clear()
        for slot in range(self.slots):
            if self._requests[slot] is not None:
                self._requests[slot].error = reason
                self._prefilling.pop(slot, None)
                self._retire(slot)

    def _plan_remaining(self) -> "np.ndarray":
        """Per-slot decode budget still UNSCHEDULED: max_new − emitted
        − in-flight.  A slot at zero needs no further dispatch — the
        chunks already in flight are guaranteed to finish it (the in-jit
        budget cap retires the lane the moment ``remaining`` hits 0)."""
        plan = np.zeros(self.slots, np.int64)
        for slot in range(self.slots):
            request = self._requests[slot]
            if request is None or not self.active[slot]:
                continue
            plan[slot] = (request.max_new_tokens - self._emitted[slot]
                          - self._inflight_sched[slot])
        return plan

    @staticmethod
    def _ring_policy(depth: int, ring_min: int, ring_max: int,
                     wait_ema, dispatch_ema, starved_streak: int) -> int:
        """Adaptive ring-depth decision (pure, unit-tested): widen
        while the DEVICE is starved, shrink under HOST backlog, clamp
        to ``[ring_min, ring_max]``.

        Signals: ``wait_ema``/``dispatch_ema`` are EMAs of the ms the
        host blocked in a ring sync vs the ms a dispatch call took;
        ``starved_streak`` counts consecutive host passes that found
        the ring already empty with live slots.  Syncs returning
        near-instantly WHILE the ring keeps running dry means the
        device finished everything between host passes — queue more
        chunks ahead.  Syncs dwarfing dispatch cost means the device
        is saturated — extra depth buys nothing and delays every
        retire/admit decision by more in-flight chunks, so decay back
        toward the double-buffer floor."""
        if wait_ema is not None and dispatch_ema is not None \
                and dispatch_ema > 0.0:
            if starved_streak >= 2 and wait_ema < 0.25 * dispatch_ema:
                depth += 1
            elif wait_ema > 2.0 * dispatch_ema:
                depth -= 1
        return max(ring_min, min(ring_max, depth))

    def _dispatch_round(self) -> bool:
        """Launch one decode chunk (or speculative round) against the
        resident device state WITHOUT waiting for its result.  Returns
        False when no slot needs scheduling.  The call's duration
        feeds the dispatch-tax EMA the ring controller weighs sync
        waits against."""
        began = time.monotonic()
        if self._spec is not None:
            dispatched = self._dispatch_spec_round()
        else:
            dispatched = self._dispatch_chunk()
        if dispatched:
            elapsed_ms = (time.monotonic() - began) * 1e3
            self._ema_dispatch_ms = (
                elapsed_ms if self._ema_dispatch_ms is None
                else 0.25 * elapsed_ms + 0.75 * self._ema_dispatch_ms)
        return dispatched

    def _dispatch_chunk(self) -> bool:
        plan = self._plan_remaining()
        live = plan > 0
        if not live.any():
            return False
        steps = int(min(self.chunk_steps, int(plan[live].max())))
        self._sync_dirty()
        rng_key = None
        if self._any_sampled:
            # One split per dispatched chunk — the RNG schedule the
            # sampled-determinism tests pin down.
            self._rng, rng_key = self._jax.random.split(self._rng)
        # Snapshot slot occupancy BEFORE the dispatch: a mixed step
        # whose slice finishes the prompt calls _finish_prefill →
        # _activate_slot inside _serve_chunk, bumping the slot serial.
        # The entry must carry the serials of the occupancy the
        # program actually READ — copying after the bump would judge
        # the freshly activated request by an active_after flag
        # computed while its lane was still a scratch row, silently
        # retiring it with zero tokens.
        serial = self._slot_serial.copy()
        if compiles.LEDGER is not None:
            compiles.set_label("serve_chunk", f"s{steps}")
        tokens_d, counts_d, self._state = self._serve_chunk(
            self._state, steps,
            -1 if self.eos_id is None else int(self.eos_id),
            self._any_sampled, rng_key, self._serve_lora())
        sched = np.where(live, np.minimum(steps, plan), 0)
        self._inflight_sched += sched
        self._note_decode_blocks(live, sched)
        self._ring.append(dict(
            kind="chunk", tokens=tokens_d, counts=counts_d,
            active_after=self._state["active"], steps=steps,
            sched=sched, serial=serial))
        self._note_dispatch()
        return True

    def _serve_lora(self):
        """Stacked adapter factors for a serve dispatch — WITHOUT ids:
        per-row routing comes from the resident ``adapter_ids`` state.
        None while no live slot runs an adapter, so all-base traffic
        keeps the adapter-free compiled program."""
        if self._lora_shared is None or not self._adapter_ids.any():
            return None
        return self._lora_shared

    def _serve_chunk(self, state, steps: int, eos_id: int,
                     sampled: bool, rng_key, lora_shared):
        """Cache-layout strategy hook: dispatch ``steps`` device-
        resident decode steps.  The paged server overrides this with
        :func:`~..models.llama.serve_chunk_paged`; ALL bookkeeping —
        admission order, budgets, EOS, retirement — stays in this
        class (and most of THAT now runs in-jit)."""
        tokens_d, counts_d, new_state, self.cache = \
            self._llama.serve_chunk_ragged(
                self.params, state, self.cache, steps, self.config,
                eos_id=eos_id, sampled=sampled, rng_key=rng_key,
                lora_shared=lora_shared)
        return tokens_d, counts_d, new_state

    def _dispatch_spec_round(self) -> bool:
        """ONE per-slot speculative round, dispatched entirely on
        device: a proposer fills each live slot's ``k``-token window
        (paired draft model, or host-assembled n-gram/prompt-lookup
        continuations, or grammar jump-forward segments), ONE target
        verify pass scores it, the acceptance kernel (greedy
        argmax-prefix or MRS — per-slot ``caps`` from the adaptive
        controller narrow individual rows) picks each slot's committed
        window, and :func:`~..models.speculative.spec_commit` applies
        EOS/budget caps and advances the resident state in-jit.
        Results flow through the same in-flight ring as plain chunks.
        Greedy outputs are exactly the plain server's under EVERY
        proposer/cap combination (invariants 11 + 18); sampled slots
        commit tokens distributed exactly as target-only sampling (MRS
        for model drafts, its delta-draft degenerate form for ngram).

        Adaptive rounds run at ``round_k`` = the max controller rung
        over live slots — always a ladder member, so the compiled
        shape set stays bounded (warm_spec_ladder pre-compiles it).
        ``round_k == 0`` (every live slot degraded) delegates to the
        plain chunk program."""
        plan = self._plan_remaining()
        live = plan > 0
        if not live.any():
            return False
        jnp, spec = self._jnp, self._spec
        mode = spec["mode"]
        controller = spec["controller"]
        cons_live = None
        if self._autostates is not None:
            cons_live = live & (self._autostates >= 0)
            if not cons_live.any():
                cons_live = None
        if (mode == "ngram" or cons_live is not None) and self._ring:
            # Host-fed proposers need SETTLED host mirrors: with
            # entries in flight, ngram would propose from stale
            # history (quality loss only) and — worse — grammar
            # jump-forward would walk forced segments from a stale
            # automaton state (committed unconditionally: a
            # correctness bug).  Serialize: consume first, dispatch
            # on the next pass.
            return False
        k = spec["k"]
        caps_host = None
        if controller is not None:
            k = controller.round_k(live)
            if cons_live is not None:
                # Grammar rows always get the full window: forced
                # jump-forward segments want width, and the masked
                # free token is cap-independent.
                k = spec["k"]
            caps_host = controller.caps(live)
            controller.note_dispatch(live)
            if k == 0:
                # Every live slot parked at k=0: run the ordinary
                # multi-step chunk program — the ladder's "plain
                # decode" rung — and tick the re-probe counters.
                controller.tick_cold_round(live)
                return self._dispatch_chunk()
        self._sync_dirty()
        if compiles.LEDGER is not None:
            compiles.set_label("spec_round", f"k{k}")
        st = self._state
        lora_shared = self._serve_lora()
        lora = (dict(lora_shared, ids=st["adapter_ids"])
                if lora_shared is not None else None)
        from ..models.speculative import (delta_draft_logits,
                                          greedy_accept_batch,
                                          merge_forced,
                                          mrs_accept_batch,
                                          ngram_propose, spec_commit)
        draft_key = accept_key = cons_key = None
        if self._any_sampled:
            self._rng, draft_key, accept_key, cons_key = \
                self._jax.random.split(self._rng, 4)
        draft_logits = None
        if mode == "model":
            proposals, draft_logits = self._draft_propose(st, k,
                                                          draft_key)
        else:
            # Self-draft: suffix-match each live slot's own committed
            # history (prompt + delivered tokens — settled, see the
            # serialization gate above).  Host numpy only; proposals
            # ride the dispatch as one tiny (slots, k) upload.
            props = np.zeros((self.slots, k), np.int32)
            hits = 0
            for slot in np.nonzero(live)[0]:
                request = self._requests[int(slot)]
                history = list(request.prompt) + list(request.tokens)
                row, hit = ngram_propose(history, k)
                props[slot] = row
                hits += int(hit)
            self.spec_stats.ngram_hits += hits
            proposals = jnp.asarray(props)
            if self._any_sampled:
                # Delta-draft MRS: q = point mass at the proposal, so
                # accept w.p. min(1, p(prop)) and the residual is the
                # target's own distribution with the proposal's mass
                # removed — the textbook rejection decomposition of p.
                # Committed tokens stay EXACTLY target-distributed
                # with no draft model in sight.
                draft_logits = delta_draft_logits(
                    proposals, self.config.vocab_size)
        forced_counts = None
        cons_states = None
        if cons_live is not None:
            # Grammar jump-forward: while a slot's automaton state
            # admits exactly one token, that token is the ONLY output
            # a masked decode could produce — emit the whole forced
            # chain as its proposal window (committed via the same
            # verify pass, which writes its KV rows).
            table = self._automata["table"]
            forced_host = np.zeros((self.slots, k), np.int32)
            forced_counts = np.zeros(self.slots, np.int32)
            cons_states = np.zeros(self.slots, np.int32)
            for slot in np.nonzero(cons_live)[0]:
                slot = int(slot)
                segment, end_state = table.deterministic_segment(
                    int(self._autostates[slot]), k)
                forced_host[slot, :len(segment)] = segment
                forced_counts[slot] = len(segment)
                cons_states[slot] = end_state
            proposals = merge_forced(proposals,
                                     jnp.asarray(forced_host),
                                     jnp.asarray(cons_live))
        chunk = jnp.concatenate([st["token"], proposals], axis=1)
        logits = self._spec_verify(st, chunk, lora)
        caps_dev = (jnp.asarray(caps_host)
                    if caps_host is not None else None)
        if self._any_sampled:
            window, counts_raw = mrs_accept_batch(
                logits, draft_logits, proposals, st["temps"],
                st["tops"], accept_key, caps=caps_dev)
        else:
            window, counts_raw = greedy_accept_batch(
                logits, proposals, caps=caps_dev)
        if cons_live is not None:
            from ..models.constrained import constrained_accept_batch
            if cons_key is None:
                cons_key = self._jax.random.PRNGKey(0)
            window, counts_raw = constrained_accept_batch(
                logits, window, counts_raw,
                jnp.asarray(forced_host), jnp.asarray(forced_counts),
                jnp.asarray(cons_states), jnp.asarray(cons_live),
                self._automata["allowed"], st["temps"], st["tops"],
                cons_key)
        prev_positions, prev_active = st["positions"], st["active"]
        (emit_tokens, emit_counts, drafted, accepted, resync,
         self._state) = spec_commit(
            st, window, counts_raw,
            eos_id=-1 if self.eos_id is None else int(self.eos_id))
        if mode == "model":
            self._draft_resync(st, resync, prev_positions, prev_active)
        # A round commits AT LEAST one token per live lane, so 1 is
        # the safe in-flight schedule increment (over-dispatch is
        # harmless: exhausted lanes go inactive in-jit and emit 0).
        # (A terminal-state grammar lane can commit 0 — the consume
        # pass retires it immediately, settling the over-count.)
        sched = np.where(live, 1, 0)
        self._inflight_sched += sched
        self._ring.append(dict(
            kind="spec", tokens=emit_tokens, counts=emit_counts,
            counts_full=jnp.where(prev_active, counts_raw, 0),
            drafted=drafted, accepted=accepted,
            active_after=self._state["active"], steps=1, sched=sched,
            serial=self._slot_serial.copy(), width=k + 1,
            caps=caps_host,
            drafted_host=(int(caps_host[live].sum())
                          if caps_host is not None else None),
            cons=(cons_live.copy() if cons_live is not None else None),
            forced=(forced_counts.copy()
                    if forced_counts is not None else None)))
        self._note_dispatch()
        return True

    def _draft_propose(self, st, k: int, draft_key):
        """Model-mode proposer hook (cache-layout strategy): run the
        paired draft ``k`` ragged decode steps from the resident
        state.  Contiguous layout decodes against the draft's own
        (slots, max_seq) cache; the paged server overrides this with
        the pool-resident draft (``decode_chunk_paged`` over the
        target's block tables).  Returns ``(proposals (slots, k),
        draft_logits | None)``."""
        draft, llama = self._draft, self._llama
        if draft_key is not None:
            proposals, draft_logits, _, _, draft["cache"] = \
                llama.decode_chunk_ragged(
                    draft["params"], st["token"], draft["cache"],
                    st["positions"], st["active"], k, draft["config"],
                    temperatures=st["temps"], top_ps=st["tops"],
                    rng_key=draft_key, return_logits=True)
            return proposals, draft_logits
        proposals, _, _, draft["cache"] = llama.decode_chunk_ragged(
            draft["params"], st["token"], draft["cache"],
            st["positions"], st["active"], k, draft["config"])
        return proposals, None

    def _draft_resync(self, st, resync, prev_positions,
                      prev_active) -> None:
        """Draft-cache resync hook: replay committed[:-1] so the
        draft's KV matches the target's committed history before the
        next round (spans positions+1 onward, zero-padded; idempotent
        rewrites — stale pad rows are rewritten before they become
        attendable, the same policy as
        models.speculative._resync_draft)."""
        draft = self._draft
        _, draft["cache"] = self._llama.verify_chunk_ragged(
            draft["params"], resync, draft["cache"],
            prev_positions + 1, prev_active, draft["config"])

    def _spec_verify(self, st, chunk, lora):
        """Target-verify dispatch hook (cache-layout strategy): score
        the (slots, k+1) window against the resident cache, every row
        at its own absolute position.  Contiguous layout appends into
        the slot rows via :func:`~..models.llama.verify_chunk_ragged`;
        the paged server overrides this with the pool-direct
        :func:`~..models.llama.verify_chunk_paged` (and its TPEngine
        twin under a replica mesh)."""
        logits, self.cache = self._llama.verify_chunk_ragged(
            self.params, chunk, self.cache, st["positions"],
            st["active"], self.config, lora=lora)
        return logits

    def _note_spec_rollback(self, slot: int, advance: int,
                            width: int) -> None:
        """Layout hook: account KV rows a spec round wrote past the
        committed frontier (``advance`` of ``width`` window rows
        kept).  The contiguous layout has nothing to account — slot
        rows are reserved wholesale; the paged server counts the
        rolled-back BLOCKS (``spec_rollback_blocks``)."""

    def _note_dispatch(self) -> None:
        if self._serve_started is None:
            self._serve_started = time.monotonic()
        self.counters["dispatches"] += 1
        self.counters["max_in_flight"] = max(
            self.counters["max_in_flight"], len(self._ring))
        if steplog.RECORDER is not None:
            if self._post_admission:
                steplog.RECORDER.record("dispatch", ring=len(self._ring),
                                        after_admission=1)
            else:
                steplog.RECORDER.record("dispatch", ring=len(self._ring))
        self._post_admission = False

    def _note_prefill(self, tokens: int) -> None:
        """Count prompt tokens dispatched to prefill (any path:
        whole-bucket, standalone chunk, mixed step).  Prefix-cache
        hits never reach a prefill dispatch, so this measures work
        actually done — the gap to raw admitted prompt length IS the
        cache's savings."""
        if self._serve_started is None:
            self._serve_started = time.monotonic()
        self.counters["prefill_tokens"] += int(tokens)

    def _consume_one(self) -> None:
        """Apply the OLDEST in-flight entry's results (see
        :meth:`_consume_ready` — the batched form this delegates
        to)."""
        self._consume_ready(1)

    def _consume_ready(self, max_entries: int) -> None:
        """Apply the oldest ``max_entries`` in-flight entries' results
        to host bookkeeping in ONE pass: deliver tokens, advance
        mirrors, retire lanes the device deactivated.  This is the
        only device→host transfer on the serving path — per entry,
        (slots × steps) token ids plus two slots-sized vectors, never
        logits.

        Batching is the drain-tail optimisation: one watchdog window,
        one sync-wait measurement, one vectorized live-mask sweep and
        ONE steplog sync/token-dispatch/commit record cover the whole
        batch, instead of paying the fixed host cost per entry.
        Per-slot delivery still walks entries oldest-first, so
        streaming order — and the router's token-offset dedup
        contract — is exactly the sequential path's."""
        count = min(int(max_entries), len(self._ring))
        if count <= 0:
            return
        entries = [self._ring.popleft() for _ in range(count)]
        wait_start = time.monotonic()
        if faults.PLAN is not None:
            stall = faults.PLAN.check("stall_step")
            if stall is not None:
                # Simulated device wedge: the sync below "takes" this
                # long — exactly what the watchdog exists to catch.
                time.sleep(float(stall.get("ms", 50.0)) / 1e3)
        alarm = None
        if self.watchdog_s > 0:
            # The alarm thread flips ``healthy`` even while this thread
            # is still blocked inside np.asarray (a truly wedged jit
            # never returns) — telemetry readers on other threads see
            # the trip; the post-sync check below handles the
            # recoverable-stall case deterministically.
            alarm = threading.Timer(self.watchdog_s,
                                    self._trip_watchdog)
            alarm.daemon = True
            alarm.start()
        # Entries were dispatched in program order on one device
        # stream, so materializing them oldest-first never waits on
        # work younger than the entry being read.
        elements = 0
        for entry in entries:
            entry["tokens"] = np.asarray(entry["tokens"])
            entry["counts"] = np.asarray(entry["counts"])
            entry["active_after"] = np.asarray(entry["active_after"])
            elements += (entry["tokens"].size + entry["counts"].size
                         + entry["active_after"].size)
            if entry["kind"] == "spec":
                entry["counts_full"] = np.asarray(entry["counts_full"])
        if alarm is not None:
            alarm.cancel()
            if time.monotonic() - wait_start > self.watchdog_s:
                self._trip_watchdog()
        now = time.monotonic()
        wait_ms = (now - wait_start) * 1e3
        self._ema_wait_ms = (wait_ms if self._ema_wait_ms is None
                             else 0.25 * wait_ms
                             + 0.75 * self._ema_wait_ms)
        batch_steps = sum(int(entry["steps"]) for entry in entries)
        self.counters["host_syncs"] += 1
        self.counters["sync_wait_ms"] += wait_ms
        self.counters["sync_elements"] += elements
        self.counters["decode_steps"] += batch_steps
        if steplog.RECORDER is not None:
            steplog.RECORDER.record(
                "sync", wait_ms=round(wait_ms, 3), steps=batch_steps,
                entries=count)
        # ONE vectorized live-mask sweep across the whole batch: an
        # entry's lane is live iff its dispatch-time serial still
        # matches, the slot is active and occupied.  Serials only
        # change mid-batch via _retire below (admission never runs
        # inside consume), so rows retired while walking entry i are
        # explicitly cleared from the younger entries' masks — the
        # exact effect the per-entry serial recheck had.
        dispatch_start = time.monotonic()
        serials = np.stack([np.asarray(entry["serial"])
                            for entry in entries])
        batch_live = ((serials == self._slot_serial) & self.active
                      & np.fromiter((request is not None
                                     for request in self._requests),
                                    bool, self.slots))
        delivered = 0
        committed_upper = 0
        touched_slots = set()
        for index, entry in enumerate(entries):
            spec = entry["kind"] == "spec"
            if spec:
                self.spec_stats.target_passes += 1
                # Adaptive rounds proposed each slot only its CAP, not
                # the window width the device program sees — the host
                # snapshot is the truthful "drafted" count.
                if entry.get("drafted_host") is not None:
                    self.spec_stats.drafted += entry["drafted_host"]
                else:
                    self.spec_stats.drafted += int(
                        np.asarray(entry["drafted"]))
                self.spec_stats.accepted += int(
                    np.asarray(entry["accepted"]))
            live = batch_live[index]
            sched = np.asarray(entry["sched"])
            self._inflight_sched[live] -= sched[live]
            # Batched token dispatch: one tolist() per result field
            # turns the entry's whole token matrix into Python ints up
            # front and the walk touches only live lanes — no
            # per-token numpy scalar boxing, no per-slot ndarray
            # indexing (the host-path tax the step log attributed to
            # token delivery).
            token_rows = entry["tokens"].tolist()
            count_list = entry["counts"].tolist()
            full_list = (entry["counts_full"].tolist() if spec
                         else count_list)
            active_list = entry["active_after"].tolist()
            committed_upper += int(entry["counts"].sum())
            cons_mask = entry.get("cons") if spec else None
            forced_ct = entry.get("forced") if spec else None
            caps_snap = entry.get("caps") if spec else None
            for slot in np.nonzero(live)[0]:
                slot = int(slot)
                touched_slots.add(slot)
                request = self._requests[slot]
                count = count_list[slot]
                constrained = (cons_mask is not None
                               and bool(cons_mask[slot]))
                must_retire = not active_list[slot]
                if count:
                    if request.first_token_ts is None:
                        request.first_token_ts = now
                    request.tokens.extend(token_rows[slot][:count])
                    self._emitted[slot] += count
                    self._remaining[slot] = (request.max_new_tokens
                                             - self._emitted[slot])
                    # Mirrors advance by what the device WROTE: the
                    # full committed window for spec rounds (cache
                    # rows exist past the emit caps), the emitted
                    # prefix for chunks.
                    advance = full_list[slot]
                    if spec:
                        # Pre-advance mirror position = the window's
                        # first written row; the layout hook turns the
                        # rejected tail into its block-rollback
                        # accounting.
                        self._note_spec_rollback(slot, advance,
                                                 entry["width"])
                        if request.spec_accepted_rounds is None:
                            request.spec_accepted_rounds = []
                        request.spec_accepted_rounds.append(advance - 1)
                        if constrained:
                            self.spec_stats.jump_forward_tokens += min(
                                int(forced_ct[slot]), count)
                    self.positions[slot] += advance
                    self.tokens[slot, 0] = token_rows[slot][advance - 1] \
                        if spec else token_rows[slot][count - 1]
                    delivered += count
                if spec and caps_snap is not None and not constrained \
                        and self._spec is not None \
                        and self._spec["controller"] is not None:
                    # Acceptance feedback at the cap the round ran
                    # under for THIS slot (k=0 ticks the re-probe
                    # counter instead).  Grammar rows are excluded:
                    # their acceptance is the grammar's, not the
                    # request's predictability.
                    self._spec["controller"].observe(
                        slot, int(caps_snap[slot]),
                        (full_list[slot] - 1) if count else 0)
                if constrained and self._autostates[slot] >= 0:
                    # Advance the host automaton over the DELIVERED
                    # tokens; a terminal state (no legal continuation)
                    # ends the request — grammar rounds serialize, so
                    # nothing else is in flight for this lane.
                    table = self._automata["table"]
                    state = int(self._autostates[slot])
                    for tok in token_rows[slot][:count]:
                        state = table.advance(state, int(tok))
                        if state < 0:
                            break
                    self._autostates[slot] = state
                    if state < 0 or table.is_terminal(state):
                        must_retire = True
                if must_retire:
                    self._retire(slot)
                    batch_live[index + 1:, slot] = False
        self.counters["tokens_committed"] += delivered
        if steplog.RECORDER is not None:
            steplog.RECORDER.record(
                "token_dispatch", slots=len(touched_slots),
                tokens=delivered,
                ms=round((time.monotonic() - dispatch_start) * 1e3, 3))
            # Device-reported emit counts: stale-serial lanes may be
            # excluded above, so this is an upper bound on committed.
            steplog.RECORDER.record("commit", tokens=committed_upper)

    def _trip_watchdog(self) -> None:
        """Mark the replica wedged (idempotent; callable from the
        alarm thread).  ``step()`` fails outstanding work on its next
        pass; recovery is an operator restart, never self-clearing —
        a device that stalled once is not trustworthy."""
        if self._watchdog_tripped:
            return
        self._watchdog_tripped = True
        self.healthy = False
        self.counters["watchdog_trips"] += 1
        if flight.FLIGHT is not None:
            # Forensics around the stall: correlate the bundle with
            # whichever request's trace context is in flight (if any),
            # so the fleet-wide dump joins on one trace id.
            carrier = next((r.trace_ctx for r in self._requests
                            if r is not None and r.trace_ctx), "")
            context = trace.extract(carrier)
            flight.FLIGHT.capture(
                "watchdog",
                trace_id=context.trace_id if context else None,
                reason=f"ring sync stalled past {self.watchdog_s:g}s")

    def _drain_ring(self) -> None:
        while self._ring:
            self._consume_ready(len(self._ring))

    # ---- on-demand device profiling (PR 14) -------------------------- #

    def request_profile(self, steps: int = 4, reason: str = "",
                        trace_id: str = "", out_dir=None) -> bool:
        """Ask for a ``(profile)`` bracket around this process's next
        ``steps`` engine steps.  Returns False when a session is
        already pending (one bracket at a time per process —
        ``jax.profiler`` is process-global)."""
        session = profiler.request(
            out_dir=out_dir, steps=steps, reason=reason,
            trace_id=trace_id,
            service=f"srv{self._instance_id}")
        return session is not None

    def _profiled_step(self) -> None:
        """One SYNCHRONOUS timed chunk inside the profiler bracket:
        drain the ring, start the trace (first pass), dispatch one
        round and sync it, and book the dispatch→sync wall ms as that
        chunk's device time — on a saturated device the host does
        nothing else in that window, which is exactly the number the
        attribution table wants in place of the probe estimate.  An
        idle engine (nothing live to dispatch) finishes the session
        after a bounded number of empty passes rather than holding the
        process-global profiler hostage."""
        session = None
        if profiler.PROFILER is not None:
            session = profiler.PROFILER
        if session is None:
            return
        self._drain_ring()
        if not session.ensure_started():
            return                       # start failed; session closed
        steps_before = self.counters["decode_steps"]
        began = time.monotonic()
        dispatched = self._dispatch_round()
        self._drain_ring()
        if dispatched:
            self._profile_idle = 0
            session.chunk_done(
                (time.monotonic() - began) * 1e3,
                int(self.counters["decode_steps"] - steps_before))
        else:
            self._profile_idle += 1
        if session.remaining == 0 or self._profile_idle >= 50:
            self._finish_profile(session)

    def _finish_profile(self, session) -> None:
        self._profile_idle = 0
        live_ids = []
        for request in self._requests:
            if request is not None and request.trace_ctx:
                context = trace.extract(request.trace_ctx)
                if context:
                    live_ids.append(context.trace_id)
        manifest = session.finish(live_trace_ids=live_ids)
        if manifest.get("steps"):
            self._device_step_ms = manifest["device_step_ms"]
        self._profiles += 1
        if flight.FLIGHT is not None:
            # Park the manifest in a bundle immediately: the artifact
            # dir is outside the bundle ring, but the manifest (and
            # the ledger section) ride the ring like any capture.
            flight.FLIGHT.capture(
                "profile",
                trace_id=session.trace_id or None,
                reason=manifest.get("reason", "")
                or f"profile bracket: {manifest.get('steps', 0)} steps")

    def stats(self) -> Dict:
        """Serving perf counters + derived rates (dashboard payloads,
        bench sections, smoke assertions)."""
        steps = self.counters["decode_steps"]
        elapsed = (time.monotonic() - self._serve_started
                   if self._serve_started is not None else 0.0)
        out = dict(
            self.counters,
            in_flight=len(self._ring),
            ring_depth=self._ring_depth,
            queue_depth=self.queue_depth,
            slots_active=self.slots_active,
            free_slots=self.slots - self.slots_active,
            healthy=int(self.healthy),
            tp_degree=self.tp_degree,
            sp_degree=self.sp_degree,
            ep_degree=self.ep_degree,
            mesh_shape=self.mesh_shape,
            decode_attention_path=self.decode_attention_path,
            prefill_attention_path=self.prefill_attention_path,
            blocks_read_per_step=(
                round(self.counters["decode_blocks_read"] / steps, 2)
                if steps else 0.0),
            decode_steps_per_sec=(
                round(steps / elapsed, 1) if elapsed > 0 else 0.0),
            prefill_tokens_per_sec=(
                round(self.counters["prefill_tokens"] / elapsed, 1)
                if elapsed > 0 else 0.0),
            prefill_queue_depth=len(self._prefilling),
            sync_stalls_per_100_steps=(
                round(100.0 * self.counters["host_syncs"] / steps, 2)
                if steps else 0.0))
        if self._spec is not None:
            # Speculation counters (host-side SpecStats increments in
            # _consume_one — never traced, invariant 7).
            controller = self._spec["controller"]
            out.update(
                spec_k=self._spec["k"],
                spec_rounds=self.spec_stats.target_passes,
                spec_proposed=self.spec_stats.drafted,
                spec_accepted=self.spec_stats.accepted,
                spec_acceptance_rate=round(
                    self.spec_stats.acceptance_rate, 4),
                spec_tokens_per_target_pass=round(
                    self.spec_stats.tokens_per_target_pass, 4),
                spec_rollback_blocks=self.spec_stats.rollback_blocks,
                spec_draft_mode=self._spec["mode"],
                spec_k_effective=(controller.hist_string()
                                  if controller is not None else "-"),
                spec_jump_forward_tokens=(
                    self.spec_stats.jump_forward_tokens),
                spec_ngram_hits=self.spec_stats.ngram_hits)
        if compiles.LEDGER is not None:
            # Compile-ledger view (PR 14): rides EC shares via
            # TELEMETRY_KEYS so the router's steady-compile watch and
            # the dashboard pane see it without extra plumbing.  The
            # ledger is process-wide; a multi-engine process reports
            # the same numbers from each engine (documented).
            out.update(
                compiles=compiles.LEDGER.compiles,
                compiles_steady_state=compiles.LEDGER.steady_compiles,
                compile_cache_hits=compiles.LEDGER.cache_hits,
                compile_cache_misses=compiles.LEDGER.cache_misses,
                compile_wall_ms=round(compiles.LEDGER.total_ms, 1))
        if self._device_step_ms is not None:
            out.update(device_step_ms=round(self._device_step_ms, 3),
                       profiles=self._profiles)
        return out

    def warm_spec_ladder(self, sampled: bool = False) -> None:
        """Pre-compile every spec-round program shape the ladder can
        reach — call while the engine is IDLE (no live slots, empty
        ring): each rung's proposer/verify/accept/commit programs run
        once against the real all-inactive resident state (inactive
        rows write the scratch row/block and the commit is a masked
        no-op, so state content is unchanged).  After this, adaptive k
        can wander the whole ladder without a single steady-state
        compile — the PR-14 ledger gate
        (``aiko_compiles_steady_state_total == 0``) survives
        adaptivity by construction.  ``sampled=True`` additionally
        warms the MRS/sampled-draft variants."""
        if self._spec is None:
            return
        if self.slots_active or self._ring:
            raise RuntimeError(
                "warm_spec_ladder must run on an idle engine")
        jnp, jax = self._jnp, self._jax
        from ..models.speculative import (delta_draft_logits,
                                          greedy_accept_batch,
                                          mrs_accept_batch,
                                          spec_commit)
        adaptive = self._spec["controller"] is not None
        for k in self._spec["ladder"]:
            if k == 0:
                continue       # the plain chunk program; warmed by
                               # ordinary traffic/warmup
            if compiles.LEDGER is not None:
                compiles.set_label("spec_round", f"k{k}")
            st = self._state
            draft_key = (jax.random.PRNGKey(0) if sampled else None)
            if self._spec["mode"] == "model":
                proposals, draft_logits = self._draft_propose(
                    st, k, draft_key)
            else:
                proposals = jnp.zeros((self.slots, k), jnp.int32)
                draft_logits = (delta_draft_logits(
                    proposals, self.config.vocab_size)
                    if sampled else None)
            chunk = jnp.concatenate([st["token"], proposals], axis=1)
            logits = self._spec_verify(st, chunk, None)
            caps = (jnp.zeros((self.slots,), jnp.int32)
                    if adaptive else None)
            if sampled:
                window, counts_raw = mrs_accept_batch(
                    logits, draft_logits, proposals, st["temps"],
                    st["tops"], jax.random.PRNGKey(1), caps=caps)
            else:
                window, counts_raw = greedy_accept_batch(
                    logits, proposals, caps=caps)
            prev_positions = st["positions"]
            prev_active = st["active"]
            _, _, _, _, resync, self._state = spec_commit(
                st, window, counts_raw,
                eos_id=-1 if self.eos_id is None else int(self.eos_id))
            if self._spec["mode"] == "model":
                self._draft_resync(st, resync, prev_positions,
                                   prev_active)

    def run_until_drained(self, max_chunks: int = 10_000):
        """Synchronous helper (tests / batch jobs): pump until every
        queued request completes."""
        finished, self.completed = self.completed, []
        chunks = 0
        while self.busy:
            finished.extend(self.step())
            chunks += 1
            if chunks > max_chunks:
                raise RuntimeError("continuous batching did not drain")
        return finished


class ContinuousReplica(Actor):
    """Actor wrapper: same ``(infer …)`` protocol as
    :class:`~.serving.ModelReplica`, but requests join the continuous
    batch instead of running serially.  A delayed self-post pump runs
    decode chunks between message deliveries while any slot is live.

    Paged servers with the prefix cache enabled additionally join the
    distributed KV cache (:mod:`~..kvstore`): the replica advertises
    its cached prefix digest on its EC-share state topic (every pump,
    plus a slow re-advertise timer so idle replicas keep their
    directory lease alive), answers ``(kv_export …)`` block-transfer
    RPCs from peers, and — when a routed request carries a
    ``kv_source`` hint — pulls the prefix from the named owner before
    admission, falling back to plain local prefill if the owner does
    not answer within ``kv_fetch_timeout_s`` (a dead owner costs
    latency, never correctness).

    ``prefill_only=True`` makes this a dedicated PREFILL replica for
    the opt-in disaggregated mode: generation budgets clamp to one
    token (the admission seed), the cache retains the prompt's
    blocks, and the digest advertises role ``prefill`` so routers
    never send it decode traffic."""

    #: Re-advertise the prefix digest this often even when idle —
    #: must stay well under the router directory's ``lease_s`` or an
    #: idle replica's cached prefixes drop out of routing.
    KV_ADVERTISE_S = 5.0

    def __init__(self, context, process=None, server=None,
                 prefill_only: bool = False,
                 kv_fetch_timeout_s: float = 2.0):
        from .serving import REPLICA_PROTOCOL
        context.protocol = context.protocol or REPLICA_PROTOCOL
        super().__init__(context, process)
        self.server = server or ContinuousBatchingServer()
        self.prefill_only = prefill_only
        self.kv_fetch_timeout_s = kv_fetch_timeout_s
        self._command_handlers["infer"] = self._wire_infer
        self._command_handlers["pump"] = self._pump
        self._command_handlers["adapter_load"] = self._wire_adapter_load
        self._command_handlers["adapter_unload"] = \
            self._wire_adapter_unload
        self._command_handlers["infer_cancel"] = self._wire_cancel
        self._command_handlers["kv_export"] = self._wire_kv_export
        self._command_handlers["retire"] = self._wire_retire
        self._command_handlers["migrate_prepare"] = \
            self._wire_migrate_prepare
        self.share["slots"] = self.server.slots
        self.share["tp_degree"] = getattr(self.server, "tp_degree", 1)
        self.share["mesh_shape"] = getattr(self.server, "mesh_shape",
                                           "")
        self.share["requests_served"] = 0
        self._pumping = False
        #: Graceful drain in progress (``(retire)`` received): routers
        #: stop sending new work; queued/active requests finish here.
        self._retiring = False
        #: id(request) -> tokens already delivered via infer_partial.
        #: Keyed by object identity, not request_id: the client owns
        #: that string and may reuse it across concurrent requests.
        self._stream_sent: Dict[int, int] = {}
        #: request ids a router is live-migrating AWAY from this
        #: replica: while non-empty the prefix digest carries the
        #: ``/migrating`` flag (routers stop scoring this replica for
        #: NEW prefix placement) and the shared lifecycle reads
        #: ``migrating``.  Ids clear when their request terminates
        #: here (usually via the post-cutover cancel).
        self._migrating_ids: set = set()
        #: slowest completed requests — ``(total_ms, request_id,
        #: {phase: ms})`` kept sorted descending; surfaces in the EC
        #: share as ``slow_requests`` for the dashboard pane.
        self._slow: List = []
        # Warm-start fetches in flight: token -> parked DecodeRequest.
        self._kv_pending: Dict[str, DecodeRequest] = {}
        self._kv_started: Dict[str, float] = {}
        self._kv_counter = 0
        self._kv_topic = f"{self.topic_path}/kv"
        if self._kv_capable():
            self.process.add_message_handler(self._on_kv_message,
                                             self._kv_topic)
            self.process.event.add_timer_handler(
                self._kv_advertise, self.KV_ADVERTISE_S)

    def _kv_capable(self) -> bool:
        return getattr(self.server, "enable_prefix_cache", False) \
            and hasattr(self.server, "kv_export_payload")

    @property
    def kv_role(self) -> str:
        return "prefill" if self.prefill_only else "decode"

    def _wire_infer(self, request_id, response_topic, payload=None):
        from ..pipeline.codec import decode_swag
        request = DecodeRequest(request_id=str(request_id), prompt=None,
                                max_new_tokens=0, tokens=[],
                                response_topic=str(response_topic))
        try:
            inputs = decode_swag(payload or {})
            request.prompt = np.asarray(inputs["tokens"],
                                        np.int32).reshape(-1)
            request.max_new_tokens = int(
                np.asarray(inputs.get("max_new_tokens", 16)))
            request.temperature = float(
                np.asarray(inputs.get("temperature", 0.0)))
            request.top_p = float(np.asarray(inputs.get("top_p", 1.0)))
            request.stream = bool(
                int(np.asarray(inputs.get("stream", 0))))
            adapter = inputs.get("adapter")
            request.adapter = str(adapter) if adapter else None
            automaton = inputs.get("automaton")
            request.automaton = str(automaton) if automaton else None
            deadline_ms = inputs.get("deadline_ms")
            if deadline_ms is not None:
                # Relative budget → local monotonic deadline (wall
                # clocks never cross processes; transit time before
                # arrival is not charged).
                request.deadline_ts = time.monotonic() + \
                    float(np.asarray(deadline_ms)) / 1e3
            carrier = inputs.get("trace")
            if carrier:
                request.trace_ctx = str(carrier)
            kv_source = inputs.get("kv_source")
            kv_tier_hint = inputs.get("kv_tier_hint")
            kv_migrate = bool(
                int(np.asarray(inputs.get("kv_migrate", 0))))
            if self.prefill_only or inputs.get("prefill_only"):
                # Dedicated prefill: the admission seed IS the one
                # generated token; the prompt's blocks stay cached
                # for the decode replica to pull.
                request.max_new_tokens = 1
                request.stream = False
        except Exception:  # noqa: BLE001 - bad request must still respond
            self.logger.exception("%s: malformed infer request %s",
                                  self.name, request_id)
            request.error = "infer_failed"
            self._respond(request)
            return
        if kv_source and self._kv_capable() \
                and request.adapter is None:
            if self._begin_kv_fetch(request, str(kv_source),
                                    migrate=kv_migrate):
                return        # parked until import or timeout
        if kv_tier_hint and request.adapter is None \
                and hasattr(self.server, "prefetch_promote"):
            # Router hinted this prompt at a demoted/spilled chain:
            # start the async promotion NOW so the restore overlaps
            # the request's queue wait instead of beginning at its
            # admission deferral (tier-aware prefetch).
            self.server.prefetch_promote(request.prompt)
        self.server.submit(request)
        self._ensure_pumping()

    def _wire_retire(self, *_args):
        """``(retire)`` — graceful drain (autoscaler scale-in): flip
        the shared ``lifecycle`` to ``retiring`` so routers stop
        sending NEW work, keep serving whatever is queued or active,
        and advertise ``drained 1`` once idle so the supervisor knows
        the process is safe to stop.  Requests that raced the flip in
        transit are still served — zero-lost outranks a prompt exit."""
        if self._retiring:
            return
        self._retiring = True
        self.logger.info("%s: retiring — draining %d queued / %d active",
                         self.name, self.server.queue_depth,
                         self.server.slots_active)
        updates = {"lifecycle": "retiring"}
        if not self.server.busy and not self._kv_pending:
            updates["drained"] = 1
        self.share.update(updates)
        if self.ec_producer is not None:
            for key, value in updates.items():
                self.ec_producer.update(key, value)
        if self.server.busy:
            self._ensure_pumping()

    def _wire_migrate_prepare(self, request_id, response_topic,
                              payload=None):
        """``(migrate_prepare mid reply swag{request_id})`` — a router
        is live-migrating one of our requests away.  Register the
        request's LIVE chain (prompt + committed tokens) in the prefix
        index so ``kv_export`` can serve it, mark the request
        migrating (digest flag + ``migrating`` lifecycle), and answer
        ``(migrate_ready mid swag{request_id, blocks, tokens})`` — or
        an error swag the router degrades on (cold resume, or abort
        when the request is simply gone).  We KEEP serving the
        request: the double-delivery window is the whole point."""
        from ..pipeline.codec import decode_swag, encode_swag
        mid = str(request_id)
        try:
            target_id = str(decode_swag(payload or {})["request_id"])
        except Exception:  # noqa: BLE001 - malformed → router aborts
            target_id = ""
        request = next(
            (r for r in self.server.live_requests()
             if r.request_id == target_id), None)
        if request is None:
            outputs: Dict = {"request_id": target_id,
                             "error": "migrate_unknown_request"}
        elif not self._kv_capable() \
                or not hasattr(self.server, "publish_live_chain"):
            outputs = {"request_id": target_id,
                       "error": "migrate_unsupported"}
        else:
            try:
                blocks = int(self.server.publish_live_chain(request))
            except Exception:  # noqa: BLE001 - degrade to cold resume
                self.logger.exception(
                    "%s: publish_live_chain failed for %s",
                    self.name, target_id)
                blocks = -1
            if blocks < 0:
                outputs = {"request_id": target_id,
                           "error": "migrate_export_failed"}
            else:
                outputs = {"request_id": target_id, "blocks": blocks,
                           "tokens": len(request.tokens or [])}
                self._migrating_ids.add(target_id)
                updates = {}
                if self.share.get("lifecycle") == "ready":
                    updates["lifecycle"] = "migrating"
                # Push the flagged digest NOW — routers must stop
                # scoring us for new prefix placement before the
                # transfer traffic starts, not at the next pump.
                updates["kv_prefixes"] = self.server.prefix_digest(
                    role=self.kv_role, migrating=True)
                self.share.update(updates)
                if self.ec_producer is not None:
                    for key, value in updates.items():
                        self.ec_producer.update(key, value)
        self.process.message.publish(
            str(response_topic),
            generate("migrate_ready", [mid, encode_swag(outputs)]))

    def _ensure_pumping(self):
        if not self._pumping:
            self._pumping = True
            self._schedule_pump()

    def _schedule_pump(self):
        from ..runtime.actor import ActorMessage, Mailbox
        self._post_message(Mailbox.IN, ActorMessage("pump", []),
                           delay=0.001)

    def _pump(self):
        if faults.PLAN is not None:
            hit = faults.PLAN.check("kill_replica", key=self.name)
            if hit is not None:
                # Die mid-decode with requests in flight — the LWT
                # (absent) fires, the Registrar evicts this process's
                # services, and routers re-dispatch.  ``hard=1``
                # additionally kills the OS process (cross-process
                # chaos; the exit code marks an injected death).
                self.logger.warning("%s: fault kill_replica firing",
                                    self.name)
                self._pumping = False
                self.process.kill()
                if hit.get("hard"):
                    import os
                    os._exit(13)
                return
            if self._migrating_ids:
                hit = faults.PLAN.check("kill_source_mid_migration",
                                        key=self.name)
                if hit is not None:
                    # Die as the SOURCE of an in-flight migration —
                    # the router must promote the destination when
                    # the resume was dispatched, else fall back to
                    # the plain re-dispatch replay.  Same LWT path
                    # as kill_replica.
                    self.logger.warning(
                        "%s: fault kill_source_mid_migration firing",
                        self.name)
                    self._pumping = False
                    self.process.kill()
                    if hit.get("hard"):
                        import os
                        os._exit(13)
                    return
        finished = self.server.step()
        self._stream_partials()
        for request in finished:
            self._respond(request)
        self._share_telemetry()
        if self.server.busy or self.server.completed:
            self._schedule_pump()
        else:
            self._pumping = False

    def _share_telemetry(self):
        """Operator view (dashboard / any ECConsumer): live slot
        occupancy, queue depth, async-loop perf counters, latency
        quantiles and encoded histograms, refreshed every pump.

        Quantiles come from the server's fixed-bucket histograms
        (obs.metrics) rather than a rolling raw-sample window: the
        SAME bucket bounds everywhere mean a router can merge the
        ``hist.<phase>`` encodings it watches across replicas and
        quote exact fleet-level p50/p95/p99 — nearest-rank lists
        cannot merge without shipping every sample."""
        from .serving import serving_telemetry
        updates = serving_telemetry(self.server.stats())
        if self._kv_capable():
            updates["kv_prefixes"] = self.server.prefix_digest(
                role=self.kv_role,
                migrating=bool(self._migrating_ids))
        hists = self.server.latency_hists
        if hists["ttft"].count:
            updates["ttft_p50_ms"] = round(hists["ttft"].quantile(0.5), 1)
            # p95 is the admission-stall number SLOs watch (p50 hides
            # a prefill convoy behind the median).
            updates["ttft_p95_ms"] = round(
                hists["ttft"].quantile(0.95), 1)
        if hists["total"].count:
            updates["total_p50_ms"] = round(
                hists["total"].quantile(0.5), 1)
        for phase, hist in hists.items():
            if hist.count:
                updates[f"hist.{phase}"] = hist.encode()
        slot_counts = self.server.adapter_slot_counts() \
            if hasattr(self.server, "adapter_slot_counts") else {}
        if slot_counts:
            # Per-adapter slot occupancy for the dashboard's adapter
            # pane — ``name=count`` pairs, space-joined like
            # ``slow_requests``.
            updates["adapter_slots"] = " ".join(
                f"{name}={count}"
                for name, count in slot_counts.items())
        if self._slow:
            updates["slow_requests"] = " ".join(
                f"{request_id}:{total_ms}:" + ",".join(
                    f"{phase}={value}" for phase, value
                    in sorted(breakdown.items()))
                for total_ms, request_id, breakdown in self._slow)
        if flight.FLIGHT is not None and flight.FLIGHT.captures:
            # Recent flight-recorder triggers, newest last — the
            # dashboard's recent-triggers pane reads this.
            updates["flight_captures"] = flight.FLIGHT.captures
            recent = flight.FLIGHT.recent()
            if recent:
                updates["last_capture"] = " ".join(
                    f"{entry['trigger']}@{entry['ts']:.0f}"
                    for entry in recent[-3:])
        if self._retiring and not self.server.busy \
                and not self._kv_pending:
            # Drain complete: every queued/active request reached a
            # terminal state.  The supervisor watches this key before
            # stopping the process.
            updates["drained"] = 1
        if not self.server.healthy \
                and self.share.get("lifecycle") != "unhealthy":
            # The router watches lifecycle on the replica's state
            # topic: flipping it drains this replica (in-flight work
            # re-dispatched, no new routes) without waiting for the
            # process to die.
            updates["lifecycle"] = "unhealthy"
        changed = {key: value for key, value in updates.items()
                   if self.share.get(key) != value}
        if not changed:
            return
        self.share.update(changed)
        if self.ec_producer is not None:
            for key, value in changed.items():
                self.ec_producer.update(key, value)

    # -- distributed KV cache (kvstore subsystem) ------------------- #

    def _kv_advertise(self, *_args):
        """Slow periodic re-advertise: refreshes the router
        directory's lease on this replica's prefixes while idle (no
        pump runs, so :meth:`_share_telemetry`'s diff never fires),
        and catches routers that subscribed after the last change."""
        if not self._kv_capable():
            return
        digest = self.server.prefix_digest(
            role=self.kv_role, migrating=bool(self._migrating_ids))
        self.share["kv_prefixes"] = digest
        if self.ec_producer is not None:
            self.ec_producer.update("kv_prefixes", digest)

    def _wire_kv_export(self, request_id, response_topic,
                        payload=None):
        """``(kv_export id reply swag)`` — peer block-transfer RPC:
        resolve the requested chain segment and answer with the pool
        rows, or an error the importer treats as a recompute
        fallback."""
        from ..obs import trace
        from ..pipeline.codec import decode_swag, encode_swag
        started = trace.now()
        carrier = None
        outputs = {"error": "kv_unsupported"}
        if self._kv_capable():
            try:
                inputs = decode_swag(payload or {})
                carrier = inputs.get("trace")
                keys = [str(k) for k in inputs["kv_keys"]]
                exported = self.server.kv_export_payload(
                    keys,
                    int(np.asarray(inputs.get("kv_start_depth", 0))))
                if faults.PLAN is not None:
                    if exported is not None \
                            and inputs.get("kv_migrate") \
                            and faults.PLAN.check(
                                "drop_migration_block",
                                key=str(request_id)) is not None:
                        # Ship the migration chain one block short:
                        # the destination's import comes up short and
                        # its admission walk recomputes the tail —
                        # colder, never wrong.
                        from ..kvstore.transfer import drop_one_block
                        self.logger.warning(
                            "%s: fault drop_migration_block firing",
                            self.name)
                        exported = drop_one_block(exported)
                outputs = exported if exported is not None \
                    else {"error": "kv_prefix_gone"}
            except Exception:  # noqa: BLE001 - RPC must answer
                self.logger.exception("%s: kv_export failed",
                                      self.name)
                outputs = {"error": "kv_export_failed"}
        if carrier and "error" not in outputs:
            # Transfer-source span: the exporter's share of a traced
            # request's warm start, riding back with the blocks.
            span = trace.synth_span(
                "kv_export", str(carrier), self.name, started,
                trace.now(), attrs={"keys": len(keys)})
            outputs["trace_spans"] = trace.encode_spans([span])
        self.process.message.publish(
            str(response_topic),
            generate("kv_export_response",
                     [str(request_id), encode_swag(outputs)]))

    def _begin_kv_fetch(self, request: DecodeRequest,
                        kv_source: str,
                        migrate: bool = False) -> bool:
        """Warm start: request the prompt's missing prefix blocks
        from the owner the router named.  Returns False when there is
        nothing worth fetching (prompt too short, already cached
        locally, or the owner is this replica) — the caller submits
        normally.  Otherwise the request PARKS until the import lands
        or the fallback timer fires; either way it is submitted
        exactly once."""
        from ..pipeline.codec import encode_swag
        if kv_source == self.topic_path:
            return False
        keys = self.server.prefix_keys_hex(request.prompt)
        local = self.server.prefix_local_depth(request.prompt)
        if not keys or local >= len(keys):
            return False
        self._kv_counter += 1
        token = f"kvf{self._kv_counter}"
        self._kv_pending[token] = request
        self._kv_started[token] = time.monotonic()
        swag = {"kv_keys": keys[local:], "kv_start_depth": local}
        if migrate:
            # Marks the export as a live-migration transfer: the
            # source tags its accountant flows and the
            # ``drop_migration_block`` fault point keys off it.
            swag["kv_migrate"] = 1
        if request.trace_ctx:
            # The owner answers with its "kv_export" span under the
            # SAME trace — the transfer source joins the request tree.
            swag["trace"] = request.trace_ctx
        self.process.message.publish(
            f"{kv_source}/in",
            generate("kv_export",
                     [token, self._kv_topic, encode_swag(swag)]))
        self.process.event.add_timer_handler(
            lambda: self._kv_fetch_timeout(token),
            self.kv_fetch_timeout_s, once=True)
        return True

    def _kv_fetch_timeout(self, token: str):
        """Owner never answered (dead, partitioned, or slow): fall
        back to plain local prefill — correctness never depended on
        the transfer."""
        request = self._kv_pending.pop(token, None)
        started = self._kv_started.pop(token, None)
        if request is None:
            return                    # import landed first
        if started is not None:
            # The wait WAS spent — latency the kv_restore phase owns
            # even though no blocks arrived.
            request.kv_restore_ms = round(
                (time.monotonic() - started) * 1e3, 3)
        self.server.kv_transfer_failures += 1
        self.logger.warning("%s: kv fetch %s timed out — local "
                            "prefill fallback", self.name, token)
        self.server.submit(request)
        self._ensure_pumping()

    def _on_kv_message(self, _topic: str, payload: str):
        """``(kv_export_response token swag)`` from the owner:
        import, then submit the parked request (the admission hit
        walk adopts the imported blocks)."""
        from ..pipeline.codec import decode_swag
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command != "kv_export_response" or len(params) < 2:
            return
        request = self._kv_pending.pop(str(params[0]), None)
        started = self._kv_started.pop(str(params[0]), None)
        if request is None:
            return                    # timed out already; late reply
        try:
            outputs = decode_swag(params[1])
            if "error" in outputs:
                self.server.kv_transfer_failures += 1
            else:
                # Async landing: the keys register behind the
                # RESTORING sentinel now, the rows land a few blocks
                # per step — the submit below parks on the hit walk's
                # restore_wait defer until the chain is whole, and
                # decode keeps producing meanwhile.
                self.server.kv_import_payload(
                    outputs, engine=self.process.event,
                    async_import=True)
                remote = outputs.get("trace_spans")
                if remote:
                    request.remote_spans = str(remote)
        except Exception:  # noqa: BLE001 - fall back to local prefill
            self.logger.exception("%s: kv import failed", self.name)
            self.server.kv_transfer_failures += 1
        if started is not None:
            request.kv_restore_ms = round(
                (time.monotonic() - started) * 1e3, 3)
        self.server.submit(request)
        self._ensure_pumping()

    def _wire_cancel(self, request_id, response_topic=None):
        """``(infer_cancel request_id [response_topic])``: the
        cancelled request's normal ``infer_response`` (error
        ``cancelled``, any partial tokens) is the acknowledgement.  An
        unknown id — already responded, or aged out — resolves the
        caller's future with ``error="cancel_unrouted"`` when a reply
        topic rides along (the true response may still arrive first;
        the client's terminal-state race rules apply)."""
        if self.server.cancel(str(request_id)):
            self._ensure_pumping()
            return
        self.logger.info("%s: infer_cancel for unknown id %s",
                         self.name, request_id)
        if response_topic:
            from ..pipeline.codec import encode_swag
            self.process.message.publish(
                str(response_topic),
                generate("infer_response",
                         [request_id,
                          encode_swag({"error": "cancel_unrouted"})]))

    def _wire_adapter_load(self, request_id, response_topic,
                           payload=None):
        """``(adapter_load id resp (name: n) (path: dir))``: import a
        PEFT-layout adapter directory and make it servable — deploy a
        fine-tune to a RUNNING replica.  Responds
        ``(adapter_response id ok|error …)``."""
        def action(inputs):
            from ..tools.import_weights import import_lora
            name = str(inputs["name"])
            lora_params, lora_config = import_lora(
                str(inputs["path"]), self.server.config)
            self.server.load_adapter(name, lora_params, lora_config)
            return name

        self._adapter_action("adapter_load", action, request_id,
                             response_topic, payload)

    def _wire_adapter_unload(self, request_id, response_topic,
                             payload=None):
        def action(inputs):
            name = str(inputs["name"])
            self.server.unload_adapter(name)
            return name

        self._adapter_action("adapter_unload", action, request_id,
                             response_topic, payload)

    def _adapter_action(self, what, action, request_id, response_topic,
                        payload):
        from ..pipeline.codec import decode_swag, encode_swag
        try:
            name = action(decode_swag(payload or {}))
            outputs = {"ok": name,
                       "adapters": " ".join(
                           self.server.adapters_loaded)}
        except Exception as error:  # noqa: BLE001 - must respond
            self.logger.warning("%s: %s failed: %s", self.name, what,
                                error)
            outputs = {"error": str(error)}
        self._share_adapters()
        if response_topic:
            self.process.message.publish(
                str(response_topic),
                generate("adapter_response",
                         [request_id, encode_swag(outputs)]))

    def _share_adapters(self):
        loaded = " ".join(self.server.adapters_loaded)
        if self.share.get("adapters") == loaded:
            return
        self.share["adapters"] = loaded
        if self.ec_producer is not None:
            self.ec_producer.update("adapters", loaded)

    def _stream_partials(self):
        """Deliver newly decoded tokens for every live streaming
        request — one ``(infer_partial request_id swag)`` per pump
        with the increment since the last delivery."""
        for request in self.server.live_requests():
            self._emit_partial(request)

    def _emit_partial(self, request: DecodeRequest):
        if not (request.stream and request.response_topic
                and request.tokens):
            return
        sent = self._stream_sent.get(id(request), 0)
        if len(request.tokens) <= sent:
            return
        from ..pipeline.codec import encode_swag
        increment = np.asarray(request.tokens[sent:], np.int32)
        self._stream_sent[id(request)] = len(request.tokens)
        self.process.message.publish(
            request.response_topic,
            generate("infer_partial",
                     [request.request_id,
                      encode_swag({"tokens_out": increment})]))

    def _respond(self, request: DecodeRequest):
        from ..pipeline.codec import encode_swag
        # Flush the final streaming increment first: concatenated
        # partials always equal the final sequence.
        self._emit_partial(request)
        self._stream_sent.pop(id(request), None)
        if request.request_id in self._migrating_ids:
            # The migrated-away request reached a terminal state here
            # (usually the post-cutover cancel): this replica is no
            # longer anyone's migration source.
            self._migrating_ids.discard(request.request_id)
            if not self._migrating_ids \
                    and self.share.get("lifecycle") == "migrating":
                self.share["lifecycle"] = "ready"
                if self.ec_producer is not None:
                    self.ec_producer.update("lifecycle", "ready")
        self.share["requests_served"] += 1
        if self.ec_producer is not None:
            self.ec_producer.update("requests_served",
                                    self.share["requests_served"])
        if request.error is not None:
            outputs: Dict = {"error": request.error}
            if request.error == "cancelled" and request.tokens:
                # Partial tokens are real work the client may keep.
                outputs["tokens_out"] = np.asarray(request.tokens,
                                                   np.int32)
            if request.retry_after_ms is not None:
                outputs["retry_after_ms"] = int(request.retry_after_ms)
        else:
            outputs = {"tokens_out": np.asarray(request.tokens,
                                                np.int32)}
        if request.spec_accepted_rounds is not None:
            # Per-round accepted-token counts (draft replicas only):
            # the client-side acceptance histogram loadgen A/B runs
            # aggregate without touching server internals.
            outputs["spec_accepted_rounds"] = np.asarray(
                request.spec_accepted_rounds, np.int32)
        served = request.error is None
        phases = self._phase_latencies(request)
        for phase, seconds in phases.items():
            outputs[f"{phase}_ms"] = round(seconds * 1e3, 2)
        if served:
            # Aggregates track SERVED requests only: a burst of
            # queued-then-cancelled requests must not drag the
            # dashboard's p50 toward zero.
            for phase, seconds in phases.items():
                self.server.latency_hists[phase].observe(seconds * 1e3)
            self._note_slow(request, phases)
        if request.trace_ctx:
            outputs["trace_spans"] = self._request_spans(request)
        if request.response_topic:
            encoded = encode_swag(outputs)
            if faults.PLAN is not None:
                if faults.PLAN.check("corrupt_response",
                                     key=request.request_id) is not None:
                    # Undecodable swag on the wire: the client resolves
                    # the future with error="corrupt_response".
                    encoded = "!corrupt!"
            self.process.message.publish(
                request.response_topic,
                generate("infer_response",
                         [request.request_id, encoded]))

    def _phase_latencies(self, request: DecodeRequest) -> Dict[str, float]:
        """Seconds per phase from the request's lifecycle stamps:
        ``queue`` (submit→slot), ``prefill`` (slot→first token),
        ``decode`` (first→finish), the classic end-to-end ``ttft`` /
        ``total``, and any ``kv_restore`` time (the warm-start fetch
        runs BEFORE submission, so it is invisible to — not double-
        counted by — the queue phase).  Keys match the server's
        ``latency_hists`` phases and respond as ``<phase>_ms``."""
        out: Dict[str, float] = {}
        if request.submitted_ts is None:
            return out
        if request.first_token_ts is not None:
            out["ttft"] = request.first_token_ts - request.submitted_ts
        if request.finished_ts is not None:
            out["total"] = request.finished_ts - request.submitted_ts
        if request.activated_ts is not None:
            out["queue"] = request.activated_ts - request.submitted_ts
            if request.first_token_ts is not None:
                out["prefill"] = (request.first_token_ts
                                  - request.activated_ts)
                if request.finished_ts is not None:
                    out["decode"] = (request.finished_ts
                                     - request.first_token_ts)
        if request.kv_restore_ms:
            out["kv_restore"] = request.kv_restore_ms / 1e3
        return out

    _SLOW_K = 5

    def _note_slow(self, request: DecodeRequest,
                   phases: Dict[str, float]) -> None:
        """Track the top-k slowest served requests with their phase
        breakdown — the dashboard's \"slowest requests\" pane."""
        total = phases.get("total")
        if total is None:
            return
        self._slow.append((round(total * 1e3, 1), request.request_id,
                           {phase: round(seconds * 1e3, 1)
                            for phase, seconds in phases.items()}))
        self._slow.sort(key=lambda entry: -entry[0])
        del self._slow[self._SLOW_K:]

    def _request_spans(self, request: DecodeRequest) -> str:
        """Synthesize this replica's phase spans for a TRACED request
        (``trace_ctx`` arrived on the wire) from its lifecycle stamps
        — no tracer calls anywhere near the engine hot path, and an
        untraced request pays exactly one ``is None`` test.

        The monotonic stamps convert to the epoch-aligned span clock
        through one wall-clock anchor taken here; sub-ms skew at
        worst, far below the cross-process clock sync the tree
        already tolerates."""
        from ..obs import trace
        offset = time.time() - time.monotonic()
        spans = []
        if request.submitted_ts is not None:
            submitted = offset + request.submitted_ts
            finished = offset + (request.finished_ts
                                 or request.submitted_ts)
            restore_s = request.kv_restore_ms / 1e3
            replica_span = trace.synth_span(
                "replica", request.trace_ctx, self.name,
                submitted - restore_s, finished,
                attrs={"request_id": request.request_id,
                       "tokens_out": len(request.tokens or [])})
            if request.error is not None:
                replica_span.set_attr("error", request.error)
            spans.append(replica_span)
            parent = trace.inject(replica_span)
            if restore_s:
                spans.append(trace.synth_span(
                    "kv_restore", parent, self.name,
                    submitted - restore_s, submitted))
            if request.activated_ts is not None:
                activated = offset + request.activated_ts
                spans.append(trace.synth_span(
                    "queue", parent, self.name, submitted, activated))
                if request.first_token_ts is not None:
                    first = offset + request.first_token_ts
                    spans.append(trace.synth_span(
                        "prefill", parent, self.name, activated,
                        first))
                    decode_span = trace.synth_span(
                        "decode", parent, self.name, first, finished)
                    decode_span.mark("first_token", first)
                    decode_span.mark("last_token", finished)
                    spans.append(decode_span)
        encoded = [span.to_dict() for span in spans]
        if request.remote_spans:
            encoded.extend(span.to_dict() for span in
                           trace.decode_spans(request.remote_spans))
        return trace.encode_spans(encoded)

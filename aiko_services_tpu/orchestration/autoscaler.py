"""SLO-driven fleet autoscaler: self-healing, scale-out, graceful drain.

Closes the loop that every prior serving PR left open: the router
publishes ``shed``/``redispatches``/``fleet_ttft_p95_ms``, replicas
publish ``queue_depth``/``healthy``/``lifecycle`` — and a human picks
the replica count.  :class:`FleetAutoscaler` is the supervisor actor
that converts that EC-share telemetry into spawn/drain decisions
against an SLO target (DistServe's *goodput* framing: requests served
WITHIN the TTFT SLO per replica, not raw throughput):

* **Self-healing** — a dead or permanently-unhealthy replica (Registrar
  LWT eviction, watchdog ``healthy=false``) is respawned into the same
  logical *slot*, with per-slot exponential backoff; a slot that dies
  ``crash_loop_threshold`` times inside ``crash_loop_window_s`` is
  **quarantined** instead of hot-looped (effective capacity drops — a
  crash-looper replaced by a fresh crash-looper is the loop, not a
  fix; ``(clear_quarantine slot)`` is the operator override).
* **Scale out** — TTFT p95 over the SLO or a non-zero shed rate for
  ``breach_windows`` consecutive ticks raises the target (hysteresis),
  never more than once per ``cooldown_s`` (burst damping).
* **Scale in** — after ``clear_windows`` healthy ticks with an idle
  queue, the idlest replica gets ``(retire)``: the router stops
  routing to it immediately (ARCHITECTURE invariant 8), its in-flight
  work finishes in place (or re-dispatches if it dies mid-drain), it
  advertises ``drained 1``, and only then is the process stopped
  through the escalating kill ladder.  Zero lost requests, chaos-gated
  (``tools/loadgen.run_elastic_chaos``).

In the disaggregated prefill/decode mode the controller holds separate
targets per role and rebalances the ratio: TTFT breaches grow the
``prefill`` pool (admission latency lives there), shed breaches grow
``decode``.

The decision core is :func:`decide` — a PURE function of a
:class:`FleetSnapshot` + :class:`AutoscalerPolicy` + controller state,
no clock, no RNG, no I/O — so scaling behavior is unit-testable and a
production incident replays from logged snapshots.  The actor is a
thin shell: build snapshot → ``decide`` → execute actions.

Fault points ``fail_spawn`` and ``slow_start`` (``runtime/faults.py``)
are wired into the spawn path behind the standard zero-cost
``PLAN is not None`` guard, so chaos schedules can fail or delay
replacements while a drain is in flight.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import compiles, flight
from ..obs.metrics import CounterDict
from ..registry.services_cache import services_cache_create_singleton
from ..runtime import faults
from ..runtime.actor import Actor
from ..runtime.service import ServiceFilter
from ..utils.sexpr import generate, parse

__all__ = [
    "AUTOSCALER_PROTOCOL", "AutoscalerPolicy", "ReplicaView",
    "PendingView", "DeathEvent", "FleetSnapshot", "Action",
    "ControllerState", "decide", "FleetAutoscaler",
    "manager_spawner", "manager_terminator",
]

AUTOSCALER_PROTOCOL = "autoscaler:0"

#: Role names the controller balances independently in disaggregated
#: mode.  ``decode`` is the default role for every adopted replica.
ROLES = ("decode", "prefill")


# ------------------------------------------------------------------ #
# Telemetry snapshot (decide()'s entire world)
# ------------------------------------------------------------------ #

@dataclasses.dataclass(frozen=True)
class ReplicaView:
    """One announced replica as the controller sees it this tick."""
    slot: str
    role: str = "decode"
    healthy: bool = True
    retiring: bool = False
    drained: bool = False
    queue_depth: int = 0
    slots_active: int = 0
    deadline_exceeded: int = 0
    #: chips this replica occupies (TP=k replica = k chips in the
    #: capacity ledger; 1 = the single-chip replica).
    tp_degree: int = 1


@dataclasses.dataclass(frozen=True)
class PendingView:
    """A spawn in flight: initiated, not yet announced.  ``due`` is
    the announce deadline; past it the actor reports a spawn
    failure."""
    slot: str
    role: str = "decode"
    due: float = 0.0


@dataclasses.dataclass(frozen=True)
class DeathEvent:
    """A replica (or spawn attempt) that went away since the previous
    tick.  ``expected=True`` marks a drain-completion termination the
    controller itself ordered — bookkeeping, not a crash."""
    slot: str
    ts: float
    exit_code: Optional[int] = None
    spawn_failure: bool = False
    expected: bool = False


@dataclasses.dataclass(frozen=True)
class FleetSnapshot:
    """Everything :func:`decide` may look at.  ``now`` is the only
    clock; deltas are since the previous decide call."""
    now: float
    replicas: Tuple[ReplicaView, ...] = ()
    pending: Tuple[PendingView, ...] = ()
    deaths: Tuple[DeathEvent, ...] = ()
    ttft_p95_ms: Optional[float] = None
    shed_delta: int = 0
    redispatch_delta: int = 0


@dataclasses.dataclass
class AutoscalerPolicy:
    """SLO target + scaling discipline.  Windows are DECIDE TICKS
    (the actor calls decide once per ``tick_s``)."""
    ttft_slo_ms: float = 500.0
    #: sheds per tick tolerated before the tick counts as a breach.
    shed_tolerance: int = 0
    min_replicas: int = 1
    max_replicas: int = 8
    #: initial decode target (adopted replicas can exceed it).
    target: int = 1
    #: dedicated prefill replicas (0 = aggregated mode).
    prefill_target: int = 0
    prefill_min: int = 0
    prefill_max: int = 4
    #: consecutive breach ticks before scaling out (hysteresis).
    breach_windows: int = 3
    #: consecutive clear ticks before scaling in.
    clear_windows: int = 6
    #: total queued requests at or under this allow scale-in.
    scale_in_max_queue: int = 0
    #: minimum seconds between scale-target changes.
    cooldown_s: float = 10.0
    #: a spawn that has not announced by then counts as failed.
    spawn_timeout_s: float = 30.0
    #: a drain that has not reported ``drained`` by then is stopped
    #: anyway (the kill ladder + router re-dispatch cover stragglers).
    drain_timeout_s: float = 30.0
    #: per-slot respawn backoff: ``base * 2^(deaths-1)`` capped.
    backoff_base_s: float = 0.5
    backoff_cap_s: float = 30.0
    crash_loop_threshold: int = 3
    crash_loop_window_s: float = 60.0
    quarantine_s: float = 300.0

    #: Per-role TP degree for spawned replicas (the DistServe
    #: argument: prefill is compute-bound and wants wide TP, decode is
    #: memory-bandwidth-bound and wants narrow TP × more replicas).
    #: The spawner builds ``ReplicaMesh(tp=role_tp(role))``; cross-
    #: degree KV transfer between the roles is exact (the pool's host
    #: view is degree-agnostic, tested in test_kvstore).
    decode_tp: int = 1
    #: 0 = same as ``decode_tp`` (homogeneous fleet, the default).
    prefill_tp: int = 0

    #: Drain-free scale-in: emit ``migrate`` instead of ``drain`` for
    #: surplus capacity.  The executor live-migrates the victim's
    #: in-flight population to the rest of the fleet before retiring
    #: it, so scale-in (and resharding, below) opens no goodput hole
    #: waiting for long-tail requests to finish on a retiring replica.
    migrate_drains: bool = False
    #: In-place TP resharding: when a live replica's chip weight no
    #: longer matches ``role_tp(role)`` (the operator changed
    #: ``decode_tp``/``prefill_tp`` under a running fleet), spawn a
    #: replacement at the new degree and migrate the old-degree
    #: replica out — one replacement in flight at a time per role.
    #: Requires ``migrate_drains`` to be drain-free end to end.
    reshard_tp: bool = False

    def role_tp(self, role: str) -> int:
        if role == "prefill" and self.prefill_tp:
            return int(self.prefill_tp)
        return max(1, int(self.decode_tp))

    def role_bounds(self, role: str) -> Tuple[int, int]:
        if role == "prefill":
            return self.prefill_min, self.prefill_max
        return self.min_replicas, self.max_replicas

    def initial_targets(self) -> Dict[str, int]:
        targets = {"decode": int(self.target)}
        if self.prefill_target > 0:
            targets["prefill"] = int(self.prefill_target)
        return targets


# ------------------------------------------------------------------ #
# Controller state & actions
# ------------------------------------------------------------------ #

@dataclasses.dataclass
class ControllerState:
    """Persistent memory between decide calls.  decide() never mutates
    its input — it returns a fresh copy — so a snapshot sequence
    replays identically (the purity the unit tests pin)."""
    targets: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: slot -> role, every slot the controller owns (live, pending,
    #: backing off or draining — NOT quarantined-forgotten).
    slots: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: slot -> recent unexpected-death timestamps (pruned to window).
    deaths: Dict[str, List[float]] = dataclasses.field(
        default_factory=dict)
    #: slot -> do-not-respawn-before timestamp.
    backoff_until: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    #: slot -> quarantine release timestamp.
    quarantined: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    #: slot -> chip weight (TP degree) last seen in telemetry — kept
    #: here so a draining or dead TP=4 replica still counts as 4
    #: chips in the ledger after its telemetry stops.
    chips: Dict[str, int] = dataclasses.field(default_factory=dict)
    breach_streak: int = 0
    clear_streak: int = 0
    last_scale_ts: Optional[float] = None
    spawn_seq: int = 0

    def copy(self) -> "ControllerState":
        return ControllerState(
            targets=dict(self.targets),
            slots=dict(self.slots),
            deaths={slot: list(ts) for slot, ts in self.deaths.items()},
            backoff_until=dict(self.backoff_until),
            quarantined=dict(self.quarantined),
            chips=dict(self.chips),
            breach_streak=self.breach_streak,
            clear_streak=self.clear_streak,
            last_scale_ts=self.last_scale_ts,
            spawn_seq=self.spawn_seq)


@dataclasses.dataclass(frozen=True)
class Action:
    """One controller decision.  ``spawn`` (new slot or respawn into
    an existing one), ``drain`` (begin graceful retire), ``migrate``
    (drain-free retire: live-migrate the in-flight population to the
    rest of the fleet — or to ``dest`` — THEN retire), ``quarantine``
    (stop respawning a crash-looper)."""
    kind: str
    slot: str
    role: str = "decode"
    reason: str = ""
    #: chips the spawned replica should occupy (``spawn`` only):
    #: the policy's per-role TP degree, for the spawner to build the
    #: matching ReplicaMesh.
    tp_degree: int = 1
    #: migration destination SLOT (``migrate`` only; empty = let the
    #: router pick a destination per request).
    dest: str = ""

    def describe(self) -> str:
        return f"{self.kind}:{self.slot}" + \
            (f"->{self.dest}" if self.dest else "") + \
            (f" ({self.reason})" if self.reason else "")


# ------------------------------------------------------------------ #
# The pure decision function
# ------------------------------------------------------------------ #

def _scale_out_role(policy: AutoscalerPolicy, ttft_breach: bool) -> str:
    """Breach attribution in disaggregated mode: admission latency
    (TTFT) lives in the prefill pool, saturation sheds in decode."""
    if policy.prefill_target > 0 and ttft_breach:
        return "prefill"
    return "decode"


def decide(snapshot: FleetSnapshot, policy: AutoscalerPolicy,
           state: Optional[ControllerState] = None
           ) -> Tuple[List[Action], ControllerState]:
    """Pure scaling decision: ``(actions, next_state)`` from a
    telemetry snapshot.  No clock (``snapshot.now`` is the time), no
    RNG, no I/O — identical snapshot sequences yield identical action
    sequences, which is what makes fleet behavior testable and a
    production trace replayable."""
    state = state.copy() if state is not None else ControllerState()
    if not state.targets:
        state.targets = policy.initial_targets()
    now = snapshot.now
    actions: List[Action] = []

    # -- adopt replicas spawned outside this controller ------------- #
    for view in snapshot.replicas:
        state.slots.setdefault(view.slot, view.role)
        state.chips[view.slot] = max(1, int(view.tp_degree))

    # -- ingest deaths ---------------------------------------------- #
    for death in snapshot.deaths:
        if death.expected:
            # Drain completion: the slot's story ends cleanly.
            state.slots.pop(death.slot, None)
            state.deaths.pop(death.slot, None)
            state.backoff_until.pop(death.slot, None)
            state.chips.pop(death.slot, None)
            continue
        history = state.deaths.setdefault(death.slot, [])
        history.append(death.ts)
        history[:] = [ts for ts in history
                      if ts > death.ts - policy.crash_loop_window_s]
        if len(history) >= policy.crash_loop_threshold:
            if death.slot not in state.quarantined:
                state.quarantined[death.slot] = \
                    death.ts + policy.quarantine_s
                actions.append(Action(
                    "quarantine", death.slot,
                    role=state.slots.get(death.slot, "decode"),
                    reason=f"{len(history)} deaths in "
                           f"{policy.crash_loop_window_s:g}s"
                           + (f", exit={death.exit_code}"
                              if death.exit_code is not None else "")))
        else:
            delay = min(policy.backoff_cap_s,
                        policy.backoff_base_s
                        * (2 ** (len(history) - 1)))
            state.backoff_until[death.slot] = death.ts + delay

    # -- release expired quarantines -------------------------------- #
    for slot, release in list(state.quarantined.items()):
        if now >= release:
            state.quarantined.pop(slot)
            state.deaths.pop(slot, None)

    # -- SLO window accounting --------------------------------------- #
    ttft_breach = (snapshot.ttft_p95_ms is not None
                   and snapshot.ttft_p95_ms > policy.ttft_slo_ms)
    shed_breach = snapshot.shed_delta > policy.shed_tolerance
    if ttft_breach or shed_breach:
        state.breach_streak += 1
        state.clear_streak = 0
    else:
        state.clear_streak += 1
        state.breach_streak = 0

    cooled = (state.last_scale_ts is None
              or now - state.last_scale_ts >= policy.cooldown_s)
    total_queue = sum(v.queue_depth for v in snapshot.replicas)

    # -- scale out ---------------------------------------------------- #
    if state.breach_streak >= policy.breach_windows and cooled:
        role = _scale_out_role(policy, ttft_breach)
        _, cap = policy.role_bounds(role)
        if state.targets.get(role, 0) < cap:
            state.targets[role] = state.targets.get(role, 0) + 1
            state.last_scale_ts = now
            state.breach_streak = 0
            cooled = False

    # -- scale in ----------------------------------------------------- #
    elif (state.clear_streak >= policy.clear_windows and cooled
          and not snapshot.pending
          and total_queue <= policy.scale_in_max_queue):
        # Shrink the role with the most headroom above its floor
        # (deterministic tie-break by role name).
        candidates = [(state.targets[role] - policy.role_bounds(role)[0],
                       role) for role in sorted(state.targets)
                      if state.targets[role]
                      > policy.role_bounds(role)[0]]
        if candidates:
            _, role = max(candidates)
            state.targets[role] -= 1
            state.last_scale_ts = now
            state.clear_streak = 0

    # -- reconcile slots against targets ------------------------------ #
    # Capacity ledger per role: ``owned`` is every slot the controller
    # answers for — live, pending, draining, down-awaiting-respawn,
    # even quarantined.  Quarantined slots COUNT as capacity on
    # purpose: backfilling a crash-looper with a fresh slot that will
    # crash-loop in turn is the hot loop with extra steps, so a
    # quarantine deliberately shrinks the effective fleet until the
    # operator intervenes (or the quarantine expires).  Draining slots
    # are capacity on the way OUT, so the eventual fleet size is
    # ``owned − draining`` — that is what reconciles to the target.
    alive = {v.slot: v for v in snapshot.replicas}
    pending = {p.slot for p in snapshot.pending}
    for role in sorted(state.targets):
        target = state.targets[role]
        owned = [slot for slot, slot_role in sorted(state.slots.items())
                 if slot_role == role]
        live = [slot for slot in owned if slot in alive
                and not alive[slot].retiring]
        draining = [slot for slot in owned if slot in alive
                    and alive[slot].retiring]
        down = [slot for slot in owned
                if slot not in alive and slot not in pending
                and slot not in state.quarantined]
        quarantined = [slot for slot in owned
                       if slot in state.quarantined]
        # The ledger counts CHIPS, not replicas: a TP=k replica is k
        # chips of capacity, so targets reconcile in chip units.  With
        # every weight 1 (the TP=1 fleet) this is exactly the old
        # replica count.
        weight = lambda slot: state.chips.get(slot, 1)
        eventual = sum(weight(slot) for slot in owned) \
            - sum(weight(slot) for slot in draining)

        # Shrinking with dead surplus: forget down slots outright —
        # respawning capacity the target no longer wants just to
        # drain it again is churn.
        while down and eventual > target:
            slot = down.pop()
            state.slots.pop(slot, None)
            state.backoff_until.pop(slot, None)
            state.deaths.pop(slot, None)
            eventual -= weight(slot)
            state.chips.pop(slot, None)

        # Self-healing: respawn dead owned slots once backoff expires.
        for slot in down:
            if now >= state.backoff_until.get(slot, 0.0):
                actions.append(Action("spawn", slot, role=role,
                                      reason="replace",
                                      tp_degree=policy.role_tp(role)))
                state.chips[slot] = policy.role_tp(role)

        # New capacity up to the target, counted in CHIPS: a policy
        # with role_tp(role) = k closes a k-chip gap with ONE spawn
        # (with every degree 1 this is exactly the old replica loop).
        # The sequence number skips names already owned — adopted
        # replicas may squat on them.
        while eventual < target:
            state.spawn_seq += 1
            slot = f"{role}{state.spawn_seq}"
            while slot in state.slots or slot in state.quarantined:
                state.spawn_seq += 1
                slot = f"{role}{state.spawn_seq}"
            state.slots[slot] = role
            actions.append(Action("spawn", slot, role=role,
                                  reason="scale_out",
                                  tp_degree=policy.role_tp(role)))
            state.chips[slot] = policy.role_tp(role)
            eventual += policy.role_tp(role)

        # Surplus: drain the idlest live replica.  One per tick per
        # role — drains are deliberate, not avalanches.  A
        # quarantined slot pads the ledger against backfill but is NOT
        # serving capacity: it must never get a healthy replica
        # drained on its behalf.  Surplus is measured in chips; prefer
        # a replica that FITS the surplus (draining a TP=4 replica to
        # shed one chip of excess overshoots by three), falling back
        # to any live replica when none fits.
        surplus = eventual - sum(weight(s) for s in quarantined) \
            - target
        if surplus > 0 and live:
            fitting = [slot for slot in live
                       if weight(slot) <= surplus] or live
            # Under resharding, surplus exists BECAUSE a new-degree
            # replacement came up: evict mismatched-degree replicas
            # first so the fleet converges on the policy degree.
            idlest = min(fitting, key=lambda slot: (
                (weight(slot) == policy.role_tp(role))
                if policy.reshard_tp else False,
                alive[slot].queue_depth, alive[slot].slots_active,
                slot))
            kind = "migrate" if policy.migrate_drains else "drain"
            actions.append(Action(kind, idlest, role=role,
                                  reason="scale_in"))

        # In-place TP resharding: with the fleet stable at target and
        # nothing pending, replace ONE mismatched-degree live replica
        # per tick by spawning its new-degree successor.  The spawn
        # overshoots the chip target; next tick's surplus branch
        # (migrate, per the mismatched-first victim preference above)
        # evicts old-degree capacity until the ledger re-balances —
        # repeat until every replica matches ``role_tp(role)``.
        elif (policy.reshard_tp and surplus == 0 and live
              and not pending):
            mismatched = [slot for slot in live
                          if weight(slot) != policy.role_tp(role)]
            if mismatched:
                state.spawn_seq += 1
                slot = f"{role}{state.spawn_seq}"
                while slot in state.slots or slot in state.quarantined:
                    state.spawn_seq += 1
                    slot = f"{role}{state.spawn_seq}"
                state.slots[slot] = role
                actions.append(Action(
                    "spawn", slot, role=role,
                    reason=f"reshard:{sorted(mismatched)[0]}",
                    tp_degree=policy.role_tp(role)))
                state.chips[slot] = policy.role_tp(role)

    return actions, state


# ------------------------------------------------------------------ #
# ProcessManager adapters
# ------------------------------------------------------------------ #

def manager_spawner(manager, command: str,
                    argv_fn: Optional[Callable] = None,
                    env_fn: Optional[Callable] = None) -> Callable:
    """Spawner backed by :class:`~.process_manager.ProcessManager`:
    ``spawn(slot, role)`` launches ``command`` with
    ``argv_fn(slot, role)`` arguments and ``env_fn(slot, role)`` env.
    Wire ``manager.exit_handler`` to
    :meth:`FleetAutoscaler.note_exit` so exit codes reach the
    crash-loop detector."""
    def spawn(slot: str, role: str) -> None:
        arguments = list(argv_fn(slot, role)) if argv_fn else []
        env = env_fn(slot, role) if env_fn else None
        manager.create(slot, command, arguments, env=env)
    return spawn


def manager_terminator(manager, grace: float = 5.0,
                       wait: float = 5.0) -> Callable:
    """Terminator riding the escalating kill ladder
    (terminate → grace → kill)."""
    def terminate(slot: str, mode: str = "drain_complete") -> None:
        manager.delete(slot, grace=grace, wait=wait)
    return terminate


# ------------------------------------------------------------------ #
# The supervisor actor
# ------------------------------------------------------------------ #

class FleetAutoscaler(Actor):
    """Supervisor actor around :func:`decide`.

    ``spawner(slot, role)`` must (eventually) produce a replica actor
    whose NAME is ``slot`` — that name is how announcements map back
    to logical slots; ``terminator(slot, mode)`` must stop it
    (``mode`` is ``drain_complete``, ``drain_timeout`` or
    ``replace``).  Both default to no-ops so a telemetry-only
    autoscaler can run in observe mode.

    Operator commands: ``(scale_target N)`` / ``(scale_target role N)``
    pins a role's target; ``(clear_quarantine slot)`` lifts a
    quarantine and resets the slot's death history;
    ``(rolling_upgrade)`` / ``(rolling_upgrade role)`` replaces every
    live replica one at a time with the in-flight population
    live-migrated across (zero-downtime weight/version upgrade)."""

    def __init__(self, context, process=None,
                 spawner: Optional[Callable] = None,
                 terminator: Optional[Callable] = None,
                 policy: Optional[AutoscalerPolicy] = None,
                 replica_protocol: Optional[str] = None,
                 router_protocol: Optional[str] = None,
                 tick_s: float = 0.5):
        from .serving import REPLICA_PROTOCOL, ROUTER_PROTOCOL
        context.protocol = context.protocol or AUTOSCALER_PROTOCOL
        super().__init__(context, process)
        self.policy = policy or AutoscalerPolicy()
        self.tick_s = float(tick_s)
        self._spawner = spawner or (lambda slot, role: None)
        self._terminator = terminator or (lambda slot, mode: None)
        self.state = ControllerState(
            targets=self.policy.initial_targets())
        self._command_handlers["scale_target"] = self._wire_scale_target
        self._command_handlers["clear_quarantine"] = \
            self._wire_clear_quarantine
        self._command_handlers["rolling_upgrade"] = \
            self._wire_rolling_upgrade

        #: rolling upgrade: sources awaiting replacement, FIFO.
        self._upgrade_queue: List[str] = []
        #: replacement slot -> source slot it supersedes.
        self._upgrade_pairs: Dict[str, str] = {}
        self._upgrade_seq = 0

        #: slot -> latest telemetry parsed off the replica state topic.
        self._telemetry: Dict[str, Dict] = {}
        #: slot -> topic path (announced replicas).
        self._topics: Dict[str, str] = {}
        #: slot -> PendingView (spawn initiated, not announced).
        self._pending: Dict[str, PendingView] = {}
        #: slot -> drain deadline (retire sent, terminator not yet).
        self._draining: Dict[str, float] = {}
        #: slots we terminated on purpose (their removal is expected).
        self._expected_down: set = set()
        #: slots whose exit already reached note_exit (skip the
        #: duplicate death the services-cache removal would add).
        self._exit_noted: set = set()
        #: slot -> last exit code from the process supervisor.
        self._exit_codes: Dict[str, Optional[int]] = {}
        self._deaths: List[DeathEvent] = []
        self._router_topic: Optional[str] = None
        self._router_stats: Dict[str, float] = {}
        self._last_shed = 0.0
        self._last_redispatch = 0.0
        self._last_tick: Optional[float] = None

        self.counters: Dict[str, int] = CounterDict(dict(
            spawns=0, respawns=0, spawn_failures=0, slow_starts=0,
            drains=0, drain_completed=0, drain_timeouts=0,
            migrates=0, upgrades_started=0, upgrades_completed=0,
            scale_out=0, scale_in=0, quarantines=0,
            deaths_observed=0),
            prefix="autoscaler", labels={"actor": self.name})
        self.share.update(self.counters)
        self.share["replicas_live"] = 0
        self.share["replicas_pending"] = 0
        self.share["replicas_draining"] = 0
        self.share["quarantine"] = ""
        self.share["last_action"] = ""
        self.share["slo_headroom_ms"] = ""
        #: ∫ live-replica count dt — the denominator of
        #: goodput-per-replica (loadgen reads this).
        self.share["replica_seconds"] = 0.0
        for role, target in self.state.targets.items():
            self.share[f"target_{role}"] = target

        self._cache = services_cache_create_singleton(self.process)
        self._cache.add_handler(
            ServiceFilter(protocol=replica_protocol or REPLICA_PROTOCOL),
            self._replica_added, self._replica_removed)
        self._cache.add_handler(
            ServiceFilter(protocol=router_protocol or ROUTER_PROTOCOL),
            self._router_added, self._router_removed)
        self.process.event.add_timer_handler(self._tick, self.tick_s)

    # -- membership --------------------------------------------------- #

    def _replica_added(self, fields):
        slot = fields.name
        self._topics[slot] = fields.topic_path
        self._pending.pop(slot, None)
        self._exit_noted.discard(slot)
        self._telemetry.setdefault(slot, {})
        self.process.add_message_handler(
            self._replica_state, f"{fields.topic_path}/state")
        self.logger.info("%s: replica %s announced (%s)", self.name,
                         slot, fields.topic_path)
        source = self._upgrade_pairs.pop(slot, None)
        if source is not None:
            self._complete_upgrade(source, slot)

    def _replica_removed(self, fields):
        slot = fields.name
        if self._topics.pop(slot, None) is None:
            return
        self.process.remove_message_handler(
            self._replica_state, f"{fields.topic_path}/state")
        self._telemetry.pop(slot, None)
        # A replica killed while it was DRAINING is an expected death:
        # the controller already decided it goes away, the router
        # re-dispatches whatever was in flight — do not respawn it.
        expected = (slot in self._expected_down
                    or self._draining.pop(slot, None) is not None)
        self._expected_down.discard(slot)
        if slot in self._exit_noted:
            # note_exit already queued this death with its exit code.
            self._exit_noted.discard(slot)
            return
        self._note_death(slot, expected=expected,
                         exit_code=self._exit_codes.pop(slot, None))

    def _router_added(self, fields):
        if self._router_topic is not None:
            return
        self._router_topic = fields.topic_path
        self.process.add_message_handler(
            self._router_state, f"{fields.topic_path}/state")

    def _router_removed(self, fields):
        if self._router_topic != fields.topic_path:
            return
        self.process.remove_message_handler(
            self._router_state, f"{fields.topic_path}/state")
        self._router_topic = None

    # -- telemetry ----------------------------------------------------- #

    def _replica_state(self, topic: str, payload: str):
        try:
            command, params = parse(payload)
        except Exception:  # noqa: BLE001 - junk broadcast, skip
            return
        if command not in ("update", "add") or len(params) < 2:
            return
        replica_topic = topic[:-len("/state")]
        slot = next((s for s, t in self._topics.items()
                     if t == replica_topic), None)
        if slot is None:
            return
        key, value = str(params[0]), params[1]
        telemetry = self._telemetry.setdefault(slot, {})
        if key in ("queue_depth", "slots_active", "deadline_exceeded",
                   "drained", "tp_degree"):
            try:
                telemetry[key] = int(value)
            except (TypeError, ValueError):
                pass
        elif key == "healthy":
            telemetry["healthy"] = str(value) not in ("0", "False")
        elif key == "lifecycle":
            telemetry["lifecycle"] = str(value)
        elif key == "ttft_p95_ms":
            try:
                telemetry["ttft_p95_ms"] = float(value)
            except (TypeError, ValueError):
                pass

    def _router_state(self, _topic: str, payload: str):
        try:
            command, params = parse(payload)
        except Exception:  # noqa: BLE001 - junk broadcast, skip
            return
        if command not in ("update", "add") or len(params) < 2:
            return
        key, value = str(params[0]), params[1]
        if key in ("shed", "redispatches", "fleet_ttft_p95_ms"):
            try:
                self._router_stats[key] = float(value)
            except (TypeError, ValueError):
                pass

    # -- death funnel -------------------------------------------------- #

    def note_exit(self, slot, _command=None,
                  exit_code: Optional[int] = None) -> None:
        """Process-supervisor exit funnel — wire as
        ``ProcessManager(exit_handler=autoscaler.note_exit)``.
        ``exit_code is None`` means the spawn itself failed.  Exit
        codes feed the crash-loop detector; a child that dies before
        it ever announces (instant crash) is caught HERE, not by the
        spawn timeout."""
        slot = str(slot)
        self._exit_codes[slot] = exit_code
        if slot in self._pending:
            self._pending.pop(slot, None)
            self._note_death(slot, exit_code=exit_code,
                             spawn_failure=exit_code is None)
            return
        if slot in self._topics:
            # Announced and died: the cache removal is coming — note
            # the code now, skip the duplicate event later.  Dying
            # mid-drain counts as expected (drain completed abruptly).
            expected = (slot in self._expected_down
                        or self._draining.pop(slot, None) is not None)
            self._expected_down.discard(slot)
            self._exit_noted.add(slot)
            self._note_death(slot, expected=expected,
                             exit_code=exit_code)

    def _note_death(self, slot: str, expected: bool = False,
                    exit_code: Optional[int] = None,
                    spawn_failure: bool = False) -> None:
        self._deaths.append(DeathEvent(
            slot=slot, ts=self.process.event.now(),
            exit_code=exit_code, spawn_failure=spawn_failure,
            expected=expected))
        if not expected:
            self._bump("deaths_observed")
            self.logger.warning(
                "%s: replica %s died (exit=%s%s)", self.name, slot,
                exit_code, ", spawn failure" if spawn_failure else "")

    # -- operator commands --------------------------------------------- #

    def _wire_scale_target(self, *params):
        """``(scale_target N)`` or ``(scale_target role N)``."""
        try:
            if len(params) >= 2:
                role, value = str(params[0]), int(str(params[1]))
            else:
                role, value = "decode", int(str(params[0]))
        except (IndexError, ValueError):
            self.logger.warning("%s: bad scale_target %r", self.name,
                                params)
            return
        if role not in ROLES:
            self.logger.warning("%s: unknown role %r", self.name, role)
            return
        floor, cap = self.policy.role_bounds(role)
        self.state.targets[role] = max(floor, min(cap, value))
        self._set_share(f"target_{role}", self.state.targets[role])
        self._set_share("last_action",
                        f"scale_target:{role}={self.state.targets[role]}")

    def _wire_clear_quarantine(self, *params):
        slot = str(params[0]) if params else ""
        if self.state.quarantined.pop(slot, None) is not None:
            self.state.deaths.pop(slot, None)
            self.state.backoff_until.pop(slot, None)
            self._set_share("quarantine", " ".join(
                sorted(self.state.quarantined)))
            self.logger.info("%s: quarantine cleared for %s",
                             self.name, slot)

    # -- the control loop ---------------------------------------------- #

    def snapshot(self) -> FleetSnapshot:
        """Assemble the pure decision input from watched telemetry."""
        now = self.process.event.now()
        replicas = []
        for slot in sorted(self._topics):
            telemetry = self._telemetry.get(slot, {})
            lifecycle = telemetry.get("lifecycle", "")
            replicas.append(ReplicaView(
                slot=slot,
                role=self.state.slots.get(
                    slot, "prefill" if "prefill" in slot else "decode"),
                healthy=bool(telemetry.get("healthy", True))
                and lifecycle != "unhealthy",
                retiring=lifecycle == "retiring"
                or slot in self._draining,
                drained=bool(telemetry.get("drained", 0)),
                queue_depth=int(telemetry.get("queue_depth", 0)),
                slots_active=int(telemetry.get("slots_active", 0)),
                deadline_exceeded=int(
                    telemetry.get("deadline_exceeded", 0)),
                tp_degree=int(telemetry.get("tp_degree", 1) or 1)))
        shed = self._router_stats.get("shed", 0.0)
        redispatch = self._router_stats.get("redispatches", 0.0)
        shed_delta = max(0, int(shed - self._last_shed))
        redispatch_delta = max(0, int(redispatch
                                      - self._last_redispatch))
        self._last_shed, self._last_redispatch = shed, redispatch
        ttft = self._router_stats.get("fleet_ttft_p95_ms")
        if ttft is None:
            # No router quantile yet: the worst replica-reported p95
            # stands in (same histograms, unmerged).
            values = [t["ttft_p95_ms"] for t in self._telemetry.values()
                      if "ttft_p95_ms" in t]
            ttft = max(values) if values else None
        deaths, self._deaths = tuple(self._deaths), []
        return FleetSnapshot(
            now=now, replicas=tuple(replicas),
            pending=tuple(self._pending.values()), deaths=deaths,
            ttft_p95_ms=ttft, shed_delta=shed_delta,
            redispatch_delta=redispatch_delta)

    def _tick(self):
        now = self.process.event.now()
        self._check_pending(now)
        self._check_draining(now)
        self._check_upgrades(now)
        snapshot = self.snapshot()
        before = dict(self.state.targets)
        streak_before = self.state.breach_streak
        actions, self.state = decide(snapshot, self.policy, self.state)
        self._maybe_flight_capture(snapshot, streak_before)
        for role, target in self.state.targets.items():
            if before.get(role) != target:
                self._bump("scale_out" if target > before.get(role, 0)
                           else "scale_in")
                self._set_share(f"target_{role}", target)
                self._set_share(
                    "last_action",
                    f"{'scale_out' if target > before.get(role, 0) else 'scale_in'}"
                    f":{role}={target}")
        for action in actions:
            self._execute(action, now)
        self._publish_fleet_state(snapshot, now)
        self._last_tick = now

    def _maybe_flight_capture(self, snapshot: FleetSnapshot,
                              streak_before: int) -> None:
        """SLO-breach flight trigger: fires at the tick the breach
        streak CROSSES ``policy.breach_windows`` — the same streak
        ``decide()`` scales out on (which resets it to 0 when it
        does) — capturing local forensics and asking the router to
        fan one fleet-wide capture out around a shared trace id.
        The scale-out fixes the symptom; the bundle records why."""
        breach = ((snapshot.ttft_p95_ms is not None
                   and snapshot.ttft_p95_ms > self.policy.ttft_slo_ms)
                  or snapshot.shed_delta > self.policy.shed_tolerance)
        streak = self.state.breach_streak
        crossed = breach and (
            streak == self.policy.breach_windows
            or (streak == 0
                and streak_before == self.policy.breach_windows - 1))
        if not crossed:
            return
        reason = (f"slo breach streak={streak_before + 1} "
                  f"ttft_p95={snapshot.ttft_p95_ms} "
                  f"shed_delta={snapshot.shed_delta}")
        if compiles.LEDGER is not None \
                and compiles.LEDGER.steady_compiles:
            # A steady-state compile storm stalls steps fleet-wide —
            # name the prime TTFT-breach suspect in the bundle reason.
            reason += (" steady_compiles="
                       f"{compiles.LEDGER.steady_compiles}")
        if flight.FLIGHT is not None:
            flight.FLIGHT.capture("slo_breach", reason=reason)
        if self._router_topic is not None:
            self.process.message.publish(
                f"{self._router_topic}/in",
                generate("capture", ["", "", "slo_breach", reason]))

    def _execute(self, action: Action, now: float) -> None:
        if action.kind == "spawn":
            self._begin_spawn(action, now)
        elif action.kind == "drain":
            self._begin_drain(action, now)
        elif action.kind == "migrate":
            self._begin_migrate(action, now)
        elif action.kind == "quarantine":
            self._bump("quarantines")
            self._set_share("quarantine", " ".join(
                sorted(self.state.quarantined)))
            self._set_share("last_action", action.describe())
            self.logger.warning("%s: QUARANTINED %s (%s)", self.name,
                                action.slot, action.reason)

    def _begin_spawn(self, action: Action, now: float) -> None:
        slot, role = action.slot, action.role
        delay_s = 0.0
        if faults.PLAN is not None:
            hit = faults.PLAN.check("fail_spawn", key=slot)
            if hit is not None:
                # The launch fails outright: report through the same
                # funnel as a real spawn failure and let backoff /
                # quarantine decide what happens next.
                self._bump("spawn_failures")
                self._set_share("last_action", f"fail_spawn:{slot}")
                self.logger.warning("%s: fault fail_spawn firing for %s",
                                    self.name, slot)
                self._note_death(slot, exit_code=None,
                                 spawn_failure=True)
                return
            hit = faults.PLAN.check("slow_start", key=slot)
            if hit is not None:
                delay_s = float(hit.get("ms", 1000.0)) / 1e3
                self._bump("slow_starts")
                self.logger.warning(
                    "%s: fault slow_start delaying %s by %.2fs",
                    self.name, slot, delay_s)
        self._bump("respawns" if action.reason == "replace"
                   else "spawns")
        self._pending[slot] = PendingView(
            slot=slot, role=role,
            due=now + delay_s + self.policy.spawn_timeout_s)
        self._set_share("last_action", action.describe())
        if delay_s > 0:
            self.process.event.add_timer_handler(
                lambda: self._do_spawn(slot, role), delay_s, once=True)
        else:
            self._do_spawn(slot, role)

    def _do_spawn(self, slot: str, role: str) -> None:
        if slot not in self._pending:
            return    # spawn was cancelled/superseded during the delay
        try:
            self._spawner(slot, role)
        except Exception:  # noqa: BLE001 - spawn failure, not our death
            self.logger.exception("%s: spawner failed for %s",
                                  self.name, slot)
            self._pending.pop(slot, None)
            self._bump("spawn_failures")
            self._note_death(slot, exit_code=None, spawn_failure=True)

    def _begin_drain(self, action: Action, now: float) -> None:
        slot = action.slot
        topic = self._topics.get(slot)
        if topic is None or slot in self._draining:
            return
        self._draining[slot] = now + self.policy.drain_timeout_s
        self._bump("drains")
        self._set_share("last_action", action.describe())
        self.logger.info("%s: draining %s (%s)", self.name, slot,
                         action.reason)
        self.process.message.publish(f"{topic}/in", "(retire)")

    def _begin_migrate(self, action: Action, now: float) -> None:
        """Drain-free retire: ask the router to live-migrate the
        victim's in-flight population away (to ``action.dest`` when
        set, else router's choice per request), then retire it.  The
        retire lands with the population already moving, so the slot
        reports ``drained`` as soon as the cutovers finish instead of
        after its longest request does."""
        slot = action.slot
        topic = self._topics.get(slot)
        if topic is None or slot in self._draining:
            return
        if self._router_topic is not None:
            params = [topic]
            dest_topic = self._topics.get(action.dest)
            if dest_topic:
                params.append(dest_topic)
            self.process.message.publish(
                f"{self._router_topic}/in",
                generate("migrate", params))
        self._draining[slot] = now + self.policy.drain_timeout_s
        self._bump("migrates")
        self._set_share("last_action", action.describe())
        self.logger.info("%s: migrating %s away (%s)", self.name,
                         slot, action.reason)
        self.process.message.publish(f"{topic}/in", "(retire)")

    # -- rolling upgrades ---------------------------------------------- #

    def _wire_rolling_upgrade(self, *params):
        """``(rolling_upgrade)`` / ``(rolling_upgrade role)``: replace
        every live replica (of one role, or all) one at a time —
        spawn a successor, live-migrate the in-flight population onto
        it at announce, retire the predecessor — so a weight/version
        upgrade rolls through the fleet with zero downtime and the
        population carried across."""
        role_filter = str(params[0]) if params else ""
        added = 0
        for slot in sorted(self._topics):
            if role_filter and \
                    self.state.slots.get(slot, "decode") != role_filter:
                continue
            if slot in self._draining or slot in self._upgrade_queue \
                    or slot in self._upgrade_pairs.values():
                continue
            self._upgrade_queue.append(slot)
            added += 1
        self._set_share("last_action",
                        f"rolling_upgrade:{added} queued")
        self.logger.info("%s: rolling upgrade queued for %d replicas",
                         self.name, added)

    def _check_upgrades(self, now: float) -> None:
        # A replacement that died before announcing (spawn failure,
        # instant crash): abort that leg and requeue the source so a
        # later attempt still replaces it.
        for new_slot, source in list(self._upgrade_pairs.items()):
            if new_slot in self._pending or new_slot in self._topics:
                continue
            self._upgrade_pairs.pop(new_slot, None)
            self._draining.pop(source, None)
            self.logger.warning(
                "%s: upgrade replacement %s for %s died before "
                "announcing — requeueing the source", self.name,
                new_slot, source)
            if source in self._topics:
                self._upgrade_queue.insert(0, source)
        # One replacement in flight at a time: the fleet never dips
        # below (or spikes above) target by more than one replica.
        if self._upgrade_pairs or self._pending \
                or not self._upgrade_queue:
            return
        while self._upgrade_queue:
            source = self._upgrade_queue.pop(0)
            if source in self._topics \
                    and source not in self._draining:
                break
        else:
            return
        role = self.state.slots.get(source, "decode")
        self._upgrade_seq += 1
        new_slot = f"{role}u{self._upgrade_seq}"
        while new_slot in self.state.slots \
                or new_slot in self.state.quarantined:
            self._upgrade_seq += 1
            new_slot = f"{role}u{self._upgrade_seq}"
        tp = int(self.state.chips.get(source, 0)) \
            or self.policy.role_tp(role)
        # Register the successor in the ledger AND mark the source
        # draining now: the chip total stays at target through the
        # handoff, so decide() never drains a healthy bystander to
        # shed the temporary overlap.  The generous deadline covers
        # the spawn; it tightens once the retire actually goes out.
        self.state.slots[new_slot] = role
        self.state.chips[new_slot] = tp
        self._upgrade_pairs[new_slot] = source
        self._draining[source] = now + self.policy.spawn_timeout_s \
            + self.policy.drain_timeout_s
        self._bump("upgrades_started")
        self._begin_spawn(Action(
            "spawn", new_slot, role=role,
            reason=f"upgrade:{source}", tp_degree=tp), now)

    def _complete_upgrade(self, source: str, dest: str) -> None:
        """The upgrade successor announced: hand the source's live
        population to it and retire the source.  With
        ``policy.migrate_drains`` off this degrades to the drain-based
        replacement (retire and wait out the in-flight tail) — the
        A/B control the bench compares against."""
        topic = self._topics.get(source)
        if topic is None:
            self._bump("upgrades_completed")
            return
        if self._router_topic is not None and \
                self.policy.migrate_drains:
            self.process.message.publish(
                f"{self._router_topic}/in",
                generate("migrate", [topic, self._topics[dest]]))
            self._bump("migrates")
        self._draining[source] = self.process.event.now() \
            + self.policy.drain_timeout_s
        self._bump("upgrades_completed")
        self._set_share("last_action", f"upgrade:{source}->{dest}")
        self.logger.info("%s: upgrade handoff %s -> %s", self.name,
                         source, dest)
        self.process.message.publish(f"{topic}/in", "(retire)")

    def _check_pending(self, now: float) -> None:
        for slot, pending in list(self._pending.items()):
            if now >= pending.due:
                self._pending.pop(slot, None)
                self._bump("spawn_failures")
                self.logger.warning(
                    "%s: spawn of %s timed out (never announced)",
                    self.name, slot)
                self._note_death(slot, exit_code=None,
                                 spawn_failure=True)

    def _check_draining(self, now: float) -> None:
        for slot, deadline in list(self._draining.items()):
            telemetry = self._telemetry.get(slot, {})
            drained = bool(telemetry.get("drained", 0))
            if not drained and now < deadline:
                continue
            self._draining.pop(slot, None)
            self._expected_down.add(slot)
            mode = "drain_complete" if drained else "drain_timeout"
            if not drained:
                self._bump("drain_timeouts")
                self.logger.warning(
                    "%s: drain of %s timed out — stopping anyway "
                    "(router re-dispatch covers stragglers)",
                    self.name, slot)
            else:
                self._bump("drain_completed")
            self._set_share("last_action", f"{mode}:{slot}")
            try:
                self._terminator(slot, mode)
            except Exception:  # noqa: BLE001 - supervisor must survive
                self.logger.exception("%s: terminator failed for %s",
                                      self.name, slot)

    # -- shares -------------------------------------------------------- #

    def _bump(self, counter: str, by: int = 1):
        self.counters[counter] += by
        self._set_share(counter, self.counters[counter])

    def _set_share(self, key: str, value):
        self.share[key] = value
        if self.ec_producer is not None:
            self.ec_producer.update_if_changed(key, value)

    def _publish_fleet_state(self, snapshot: FleetSnapshot,
                             now: float) -> None:
        live = [v for v in snapshot.replicas if not v.retiring]
        self._set_share("replicas_live", len(live))
        self._set_share("replicas_pending", len(self._pending))
        self._set_share("replicas_draining", len(self._draining))
        if snapshot.ttft_p95_ms is not None:
            self._set_share(
                "slo_headroom_ms",
                round(self.policy.ttft_slo_ms - snapshot.ttft_p95_ms,
                      1))
        if self._last_tick is not None:
            dt = max(0.0, now - self._last_tick)
            self.share["replica_seconds"] = round(
                float(self.share["replica_seconds"])
                + len(snapshot.replicas) * dt, 3)

    @property
    def quarantined_slots(self) -> List[str]:
        return sorted(self.state.quarantined)

    def stats(self) -> Dict:
        """Counters + fleet state for bench/loadgen reporting."""
        return dict(self.counters,
                    replicas_live=self.share["replicas_live"],
                    replicas_draining=self.share["replicas_draining"],
                    replica_seconds=self.share["replica_seconds"],
                    quarantine=self.share["quarantine"],
                    targets=dict(self.state.targets))

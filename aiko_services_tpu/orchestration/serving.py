"""Data-parallel model replica serving.

The reference's LifeCycleManager runs fleets of identical clients
(SURVEY.md §2.6 maps that to data-parallel replica serving); this module
gives that shape a concrete model-serving form, matching the
BASELINE.md "multi-replica serving actors, DP over chips" workload:

- :class:`ModelReplica` — an Actor hosting one model instance (one chip
  / one mesh slice).  Wire protocol:
  ``(infer request_id response_topic (payload…))`` → runs the model,
  publishes ``(infer_response request_id (outputs…))`` to
  ``response_topic`` — the reference's response-topic idiom
  (main/storage.py:87-103).
- :class:`ReplicaRouter` — an Actor that discovers replicas through the
  ServicesCache (by protocol), load-balances requests (power-of-two-
  choices over replica-published queue depth, round-robin while load is
  unknown), and prunes replicas the moment the Registrar evicts them
  (LWT death or lease expiry).  Requests OUTLIVE replicas: the router
  proxies responses through its own reply topic, tracks every in-flight
  request, and on replica death or health-state change re-dispatches
  the stranded work to a survivor with bounded exponential backoff +
  jitter.  Greedy requests replay idempotently from the prompt (the
  paged prefix cache makes the retry cheap); streaming clients get
  token-offset dedup, so no token is ever delivered twice.  When every
  candidate replica is saturated the router sheds explicitly
  (``error="overloaded"`` + ``retry_after_ms``) instead of queueing
  silently.  See docs/SERVING.md "Failure model & fault injection".

Payloads are swag-codec dicts (numpy arrays travel as typed tags), so
token tensors cross process boundaries losslessly.
"""

from __future__ import annotations

import random
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

import numpy as np

from ..obs import flight, trace
from ..obs.metrics import CounterDict, Histogram
from ..pipeline.codec import decode_swag, decode_value, encode_swag
from ..registry.services_cache import services_cache_create_singleton
from ..runtime.actor import Actor
from ..runtime.service import ServiceFilter
from ..utils.sexpr import generate, parse

__all__ = ["ModelReplica", "ReplicaRouter", "REPLICA_PROTOCOL",
           "ROUTER_PROTOCOL", "make_llama_infer",
           "make_speculative_infer", "make_constrained_infer",
           "serving_telemetry"]

REPLICA_PROTOCOL = "model_replica:0"
ROUTER_PROTOCOL = "replica_router:0"

#: Replica-reported errors the router retries on a different replica
#: instead of forwarding to the client (the failure is the REPLICA's,
#: not the request's).
RETRIABLE_ERRORS = ("watchdog_stalled",)

#: Server-stats keys worth broadcasting to operators.  Shared by
#: ContinuousReplica EC shares, dashboard rendering, and bench
#: reporting so all three show the SAME derived counters.
TELEMETRY_KEYS = (
    "slots_active", "queue_depth", "in_flight",
    "decode_steps_per_sec", "sync_stalls_per_100_steps",
    "admission_deferred", "state_uploads", "tokens_committed",
    # Host-tax levers (PR 16): the adaptive dispatch ring and the
    # compact dirty-row upload path
    "ring_depth", "ring_starved_steps", "dirty_rows_uploaded",
    "prefix_hits", "prefix_misses", "prefix_evictions",
    "prefix_remote_hits", "kv_transfer_bytes", "kv_transfer_ms",
    "kv_transfer_failures", "kv_demotions", "kv_restores",
    "kv_host_blocks", "kv_host_bytes", "restore_queue_depth",
    "prefix_hits_host", "kv_export_sync_count",
    "kv_transfer_host_ms", "kv_imports_async",
    "kv_spills", "kv_disk_blocks", "kv_disk_bytes",
    "kv_disk_restores", "kv_checksum_failures", "kv_adopted_chains",
    "kv_prefetch_promotions",
    "decode_attention_path", "blocks_read_per_step",
    "prefill_tokens_per_sec", "prefill_queue_depth",
    "prefill_attention_path",
    "deadline_exceeded", "shed", "watchdog_trips", "free_slots",
    "healthy", "tp_degree", "mesh_shape",
    # 2-D replica meshes (PR 18): second-axis degrees and the count of
    # admission dispatches that went through the sp-sharded window path
    "sp_degree", "ep_degree", "sp_prefill_dispatches",
    # Speculative decoding (present only when a draft is configured)
    "spec_k", "spec_rounds", "spec_proposed", "spec_accepted",
    "spec_acceptance_rate", "spec_tokens_per_target_pass",
    "spec_rollback_blocks",
    # Speculation v2 (PR 17): draft mode, per-slot effective-k
    # histogram (adaptive controller), grammar jump-forward and
    # n-gram self-draft counters
    "spec_draft_mode", "spec_k_effective",
    "spec_jump_forward_tokens", "spec_ngram_hits",
    # Compile ledger + device profiling (PR 14; present only when a
    # CompileLedger is installed / a profile bracket ran)
    "compiles", "compiles_steady_state", "compile_cache_hits",
    "compile_cache_misses", "compile_wall_ms",
    "device_step_ms", "profiles",
    # Memory accountant + pool auditor (PR 15; kv_hbm_* always on a
    # paged server, audit counters only when an AUDITOR is installed)
    "kv_hbm_blocks", "kv_hbm_bytes",
    "kv_audit_sweeps", "kv_audit_violations",
    # Multi-tenant adapters (PR 20): paged adapter-weight residency per
    # tier plus warm-vs-cold load provenance, so the dashboard's
    # adapter pane and the loadgen A/B read the same counters.
    "adapter_pages_hbm", "adapter_pages_host", "adapter_pages_disk",
    "adapter_warm_loads", "adapter_cold_loads",
    "adapters_loaded_count",
)


def serving_telemetry(stats: Dict) -> Dict:
    """Project a server's :meth:`stats` dict onto the operator
    telemetry keys (ints stay ints, rates stay floats, tags stay
    strings; absent keys — e.g. prefix counters on a non-paged server
    — are omitted)."""
    out = {}
    for key in TELEMETRY_KEYS:
        if key in stats:
            value = stats[key]
            if isinstance(value, str):
                out[key] = value
            elif isinstance(value, float):
                out[key] = round(float(value), 2)
            else:
                out[key] = int(value)
    return out


def _register_unsupported_adapter_commands(actor) -> None:
    """Adapter hot-deploy is a ContinuousReplica capability; other
    protocol speakers ACK with an error instead of silently dropping
    the command (a client future must always resolve)."""
    def unsupported(request_id, response_topic, payload=None):
        actor.process.message.publish(
            str(response_topic),
            generate("adapter_response",
                     [str(request_id),
                      encode_swag({"error": "unsupported_command"})]))

    actor._command_handlers["adapter_load"] = unsupported
    actor._command_handlers["adapter_unload"] = unsupported


class ModelReplica(Actor):
    """Hosts one model instance and serves ``infer`` requests."""

    def __init__(self, context, process=None,
                 infer: Optional[Callable[[Dict], Dict]] = None):
        context.protocol = context.protocol or REPLICA_PROTOCOL
        super().__init__(context, process)
        self._infer = infer or (lambda payload: payload)
        self._command_handlers["infer"] = self._wire_infer
        _register_unsupported_adapter_commands(self)
        self.share["requests_served"] = 0

    def _wire_infer(self, request_id, response_topic, payload=None):
        inputs = decode_swag(payload or {})
        try:
            outputs = self._infer(inputs)
        except Exception:  # noqa: BLE001 - a bad request must not kill us
            self.logger.exception("%s: infer failed for %s", self.name,
                                  request_id)
            outputs = {"error": "infer_failed"}
        self.share["requests_served"] += 1
        if self.ec_producer is not None:
            self.ec_producer.update("requests_served",
                                    self.share["requests_served"])
        self.process.message.publish(
            response_topic,
            generate("infer_response",
                     [str(request_id), encode_swag(outputs)]))


class ReplicaRouter(Actor):
    """Discovers :class:`ModelReplica` services, load-balances
    ``infer`` requests across the live set, and guarantees that a
    request outlives the replica serving it.

    Survivability machinery (each piece off the hot path until a
    failure actually happens):

    * Responses are PROXIED: replicas answer on the router's reply
      topic, the router forwards to the client — this is what lets it
      observe completion (in-flight tracking), dedup re-played
      streaming tokens by offset, and intercept retriable errors.
    * Registrar eviction (LWT death) or a replica flipping its shared
      ``lifecycle`` to ``unhealthy`` re-dispatches that replica's
      in-flight requests to survivors with bounded exponential
      backoff + seeded jitter (``backoff_base_s``·2^attempt, capped;
      ``max_redispatch`` attempts, then ``error="redispatch_failed"``).
    * Routing is power-of-two-choices over replica-published
      ``queue_depth`` (watched passively off each replica's EC-share
      state topic — no lease held); while no load is known it is exact
      round-robin.  When every candidate sits at ``shed_queue_depth``
      or beyond, the request sheds immediately with
      ``error="overloaded"`` and a ``retry_after_ms`` hint.
    """

    def __init__(self, context, process=None,
                 replica_protocol: str = REPLICA_PROTOCOL,
                 shed_queue_depth: int = 32,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0,
                 max_redispatch: int = 4, seed: int = 0,
                 prefix_alpha: float = 1.0,
                 host_prefix_weight: float = 0.5,
                 disk_prefix_weight: float = 0.25,
                 adapter_affinity: float = 1.0,
                 kv_transfer: bool = False,
                 disaggregate: bool = False,
                 directory_lease_s: float = 30.0,
                 anomaly_interval_s: float = 2.0):
        context.protocol = context.protocol or ROUTER_PROTOCOL
        super().__init__(context, process)
        self._replicas: List[str] = []   # replica topic paths, stable order
        self._next = 0
        self._command_handlers["infer"] = self.route
        self._command_handlers["infer_cancel"] = self._route_cancel
        _register_unsupported_adapter_commands(self)
        self.shed_queue_depth = shed_queue_depth
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.max_redispatch = max_redispatch
        #: Prefix-aware scoring weight: a candidate's score is
        #: ``queue_depth − prefix_alpha · matched_prefix_blocks``
        #: (lower wins).  0 disables prefix routing entirely (exact
        #: PR-4 behavior); with no directory match the route falls
        #: back to exact P2C regardless.
        self.prefix_alpha = prefix_alpha
        #: Value of a HOST-tier matched block relative to an HBM one
        #: (tiered KV cache): an advertised block that needs a restore
        #: upload before decode can read it scores ``host_prefix_weight
        #: · prefix_alpha`` instead of ``prefix_alpha``.  The default
        #: 0.5 prices a restore below an HBM hit but well above a
        #: recompute (weight 0); 1.0 ignores tier entirely.
        self.host_prefix_weight = host_prefix_weight
        #: Value of a DISK-tier (spilled) matched block: the restore
        #: pays an SSD read on top of the upload, so the default 0.25
        #: prices it below a host hit and still above a recompute —
        #: the tower's full ordering HBM > host > disk > nothing.
        self.disk_prefix_weight = disk_prefix_weight
        #: Adapter-locality weight (multi-tenant LoRA serving): a
        #: candidate whose digest advertises the request's adapter
        #: scores an extra ``adapter_affinity`` when the factors sit
        #: in HBM, discounted by ``host_prefix_weight`` /
        #: ``disk_prefix_weight`` for demoted/spilled copies — the
        #: same tier pricing as prefix blocks, because restoring a
        #: paged adapter rides the same promotion machinery.  A warm
        #: adapter ANYWHERE beats a cold one: when no prefix matches
        #: at all, the route still prefers a warm-adapter replica over
        #: plain P2C.  0 disables adapter-aware routing (adapter-blind
        #: baseline for the loadgen A/B).
        self.adapter_affinity = adapter_affinity
        #: Attach ``kv_source`` warm-start hints when the prefix
        #: owner is not the chosen target (opt-in: transfers cost
        #: wire bytes; prefix AFFINITY alone is free).
        self.kv_transfer = kv_transfer
        #: Opt-in disaggregated serving: requests prefill on a
        #: ``prefill``-role replica first, then decode on a decode
        #: replica that pulls the prefix.  Requires ``kv_transfer``
        #: semantics regardless of the flag.
        self.disaggregate = disaggregate
        from ..kvstore import PrefixDirectory
        self.directory = PrefixDirectory(lease_s=directory_lease_s)
        self._rng = random.Random(seed)
        #: request_id -> replica topic path, so infer_cancel follows
        #: its request to the SAME replica.  Bounded ring evicting the
        #: OLDEST ROUTED id: a cancel for an aged-out id resolves with
        #: ``error="cancel_unrouted"``, so size the ring well above the
        #: maximum in-flight window (entries are two short strings
        #: each).  Entries persist after completion: a cancel lost in
        #: transit can be retried.
        self._routed: "OrderedDict[str, str]" = OrderedDict()
        self._routed_limit = 65536
        #: request_id -> live routing record (replica, client topic,
        #: original payload, delivery offsets, attempts).  Unlike
        #: ``_routed`` this IS completion-aware — entries leave when
        #: the terminal response forwards.  Bounded as a safety net
        #: against clients that never complete.
        self._inflight: "OrderedDict[str, Dict]" = OrderedDict()
        self._inflight_limit = 4096
        #: replica topic path -> latest load numbers parsed off its
        #: EC-share state topic (passive watch; no lease).
        self._loads: Dict[str, Dict] = {}
        self._unhealthy: set = set()
        #: replica topic paths mid graceful drain (``lifecycle`` flip
        #: to ``retiring``, usually by the autoscaler): excluded from
        #: NEW routing immediately, but — unlike ``_unhealthy`` — their
        #: in-flight requests are left to finish in place.  Death while
        #: retiring falls through to the normal re-dispatch path, so
        #: drain + kill still loses nothing.
        self._retiring: set = set()
        #: replica topic path -> {phase: encoded histogram string}
        #: parsed off EC-share ``hist.*`` broadcasts — the mergeable
        #: replacements for sampling one replica's nearest-rank p95.
        self._replica_hists: Dict[str, Dict[str, str]] = {}
        self.counters: Dict[str, int] = CounterDict(dict(
            redispatches=0, replica_deaths_observed=0, shed=0,
            deadline_exceeded=0, cancel_unrouted=0,
            prefix_routed=0, prefix_routed_host=0,
            prefix_routed_disk=0, kv_tier_hints=0, kv_remote_hints=0,
            adapter_warm_routes=0, adapter_cold_routes=0,
            anomaly_flags=0, fleet_captures=0, fleet_profiles=0,
            fleet_steady_compiles=0, fleet_censuses=0,
            fleet_audit_violations=0,
            migrations_started=0, migrations_completed=0,
            migrations_aborted=0, migration_blocks_streamed=0),
            prefix="router", labels={"actor": self.name})
        #: replica topic path -> last compiles_steady_state broadcast;
        #: a DELTA is a bucket-discipline breach somewhere in the
        #: fleet — flagged as an anomaly + fleet capture (PR 14).
        self._steady_compiles: Dict[str, int] = {}
        #: replica topic path -> {kv_hbm_bytes, kv_host_bytes,
        #: kv_disk_bytes} parsed off EC broadcasts; folded into
        #: ``fleet_kv_<tier>_bytes`` share keys for the dashboard's
        #: fleet memory pane (PR 15).
        self._replica_memory: Dict[str, Dict[str, int]] = {}
        #: replica topic path -> last kv_audit_violations broadcast;
        #: a DELTA means a replica's pool auditor caught the
        #: accountant disagreeing with ground truth — anomaly + fleet
        #: capture, exactly like a steady-state compile.
        self._audit_violations: Dict[str, int] = {}
        self.share["replicas"] = 0
        self.share["replicas_retiring"] = 0
        self.share["requests_routed"] = 0
        self.share["kv_directory_size"] = 0
        self.share.update(self.counters)
        #: replicas answer here; _on_reply forwards to the client.
        self.topic_reply = f"{self.topic_path}/reply"
        self.process.add_message_handler(self._on_reply,
                                         self.topic_reply)
        #: Live-migration machinery (drain-free replica replacement):
        #: destination replies arrive on a DISTINCT topic keyed by
        #: migration id, which is what attributes them during the
        #: double-delivery window.
        from .migration import MigrationController
        self.migration = MigrationController(self)
        self.topic_migrate = f"{self.topic_path}/migrate"
        self.process.add_message_handler(self._on_migrate_reply,
                                         self.topic_migrate)
        self._command_handlers["migrate"] = self._wire_migrate
        self.share["migration_cutover_ms"] = 0.0
        self._cache = services_cache_create_singleton(self.process)
        self._cache.add_handler(
            ServiceFilter(protocol=replica_protocol),
            self._replica_added, self._replica_removed)
        #: Per-window p95 drift over the EXACT fleet merges — delta
        #: histograms (element-wise count subtraction) flag drift
        #: BEFORE the autoscaler's SLO hard-trip.  0 disables the
        #: timer entirely.
        self.anomaly_interval_s = float(anomaly_interval_s)
        self._drift = flight.P95DriftDetector()
        self._anomaly_phases = ("ttft", "total")
        self.share["last_anomaly"] = ""
        if self.anomaly_interval_s > 0:
            self.process.event.add_timer_handler(
                self._anomaly_tick, self.anomaly_interval_s)

    # -- membership & health ---------------------------------------- #

    def _replica_added(self, fields):
        if fields.topic_path not in self._replicas:
            self._replicas.append(fields.topic_path)
            self._replicas.sort()
            # Passive load watch: the replica's ECProducer broadcasts
            # every share mutation on its state topic; queue depth and
            # lifecycle arrive without holding a lease.
            self.process.add_message_handler(
                self._replica_state, f"{fields.topic_path}/state")
            self._update_share()
            self.logger.info("%s: replica up %s (%d live)", self.name,
                             fields.topic_path, len(self._replicas))

    def _replica_removed(self, fields):
        if fields.topic_path in self._replicas:
            self._replicas.remove(fields.topic_path)
            self.process.remove_message_handler(
                self._replica_state, f"{fields.topic_path}/state")
            self._loads.pop(fields.topic_path, None)
            self._replica_hists.pop(fields.topic_path, None)
            self._steady_compiles.pop(fields.topic_path, None)
            self._audit_violations.pop(fields.topic_path, None)
            if self._replica_memory.pop(fields.topic_path, None):
                self._publish_fleet_memory()
            self._unhealthy.discard(fields.topic_path)
            self._set_retiring(fields.topic_path, False)
            # A dead owner's advertised prefixes must stop attracting
            # routes IMMEDIATELY — survivors recompute (in-flight
            # fetches against it time out into local prefill).
            self.directory.evict_replica(fields.topic_path)
            self._update_directory_share()
            self._bump("replica_deaths_observed")
            self._update_share()
            self.logger.info("%s: replica down %s (%d live)", self.name,
                             fields.topic_path, len(self._replicas))
            self._drain_replica(fields.topic_path)

    def _replica_state(self, topic: str, payload: str):
        """EC-share broadcast off a replica's state topic:
        ``(update|add key value)``.  Load keys feed P2C routing;
        a ``lifecycle`` flip to ``unhealthy`` drains the replica."""
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command not in ("update", "add") or len(params) < 2:
            return
        replica = topic[:-len("/state")]
        key, value = str(params[0]), params[1]
        if key in ("queue_depth", "slots_active", "free_slots",
                   "free_blocks", "slots"):
            try:
                self._loads.setdefault(replica, {})[key] = int(value)
            except (TypeError, ValueError):
                pass
        elif key == "kv_prefixes":
            now = self.process.event.now()
            if self.directory.update(replica, str(value), now):
                self.directory.purge_expired(now)
                self._update_directory_share()
        elif key.startswith("hist."):
            self._replica_hists.setdefault(
                replica, {})[key[len("hist."):]] = str(value)
            self._publish_fleet_latency(key[len("hist."):])
        elif key == "compiles_steady_state":
            self._watch_steady_compiles(replica, value)
        elif key in ("kv_hbm_bytes", "kv_host_bytes", "kv_disk_bytes"):
            try:
                self._replica_memory.setdefault(
                    replica, {})[key] = int(value)
            except (TypeError, ValueError):
                return
            self._publish_fleet_memory()
        elif key == "kv_audit_violations":
            self._watch_audit_violations(replica, value)
        elif key == "healthy":
            self._set_health(replica, str(value) not in ("0", "False"))
        elif key == "lifecycle":
            self._set_retiring(replica, str(value) == "retiring")
            self._set_health(replica, str(value) != "unhealthy")

    def _update_directory_share(self):
        size = self.directory.size
        if self.ec_producer is not None:
            self.ec_producer.update_if_changed("kv_directory_size", size)
        self.share["kv_directory_size"] = size

    def _set_retiring(self, replica: str, retiring: bool):
        """Graceful-drain membership: a retiring replica NEVER receives
        a new route (ARCHITECTURE invariant 8) but keeps its in-flight
        work — the drain's whole point is letting that work finish
        instead of re-dispatch-replaying it."""
        if retiring == (replica in self._retiring):
            return
        if retiring:
            self._retiring.add(replica)
            # Its cached prefixes must stop attracting routes too.
            self.directory.evict_replica(replica)
            self._update_directory_share()
            self.logger.info("%s: replica %s retiring — no new routes",
                             self.name, replica)
        else:
            self._retiring.discard(replica)
        self.share["replicas_retiring"] = len(self._retiring)
        if self.ec_producer is not None:
            self.ec_producer.update_if_changed(
                "replicas_retiring", len(self._retiring))

    def _set_health(self, replica: str, healthy: bool):
        if healthy:
            self._unhealthy.discard(replica)
            return
        if replica in self._unhealthy:
            return
        self._unhealthy.add(replica)
        self.directory.evict_replica(replica)
        self._update_directory_share()
        self.logger.warning("%s: replica %s unhealthy — draining",
                            self.name, replica)
        self._drain_replica(replica)

    def _candidates(self) -> List[str]:
        live = [r for r in self._replicas if r not in self._unhealthy
                and r not in self._retiring]
        if live:
            return live
        # A fleet that is ALL retiring still serves: the drain is an
        # operator intent, not a failure — better to extend one
        # replica's drain than to shed everything.
        live = [r for r in self._replicas if r not in self._unhealthy]
        # A fleet that is ALL unhealthy beats routing nowhere: the
        # watchdogged replica still answers (with a retriable error)
        # faster than a black hole.
        return live or list(self._replicas)

    def _update_share(self):
        self.share["replicas"] = len(self._replicas)
        if self.ec_producer is not None:
            self.ec_producer.update("replicas", len(self._replicas))

    def _bump(self, counter: str, by: int = 1):
        self.counters[counter] += by
        self.share[counter] = self.counters[counter]
        if self.ec_producer is not None:
            self.ec_producer.update(counter, self.counters[counter])

    # -- fleet latency (merged replica histograms) -------------------- #

    def fleet_histogram(self, phase: str) -> Histogram:
        """Merge every replica's ``hist.<phase>`` EC broadcast into one
        histogram — EXACT because the buckets are fixed process-wide,
        unlike sampling one replica's window."""
        merged = Histogram(name=f"fleet_{phase}")
        for hists in self._replica_hists.values():
            encoded = hists.get(phase)
            if not encoded:
                continue
            try:
                merged.merge(Histogram.decode(encoded))
            except (ValueError, IndexError):
                continue
        return merged

    def _publish_fleet_latency(self, phase: str):
        """Fleet p50/p95/p99 for the phase that just updated, into the
        router's own share (dashboard + loadgen read these)."""
        merged = self.fleet_histogram(phase)
        if not merged.count:
            return
        for label, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            key = f"fleet_{phase}_{label}_ms"
            value = round(merged.quantile(q), 2)
            self.share[key] = value
            if self.ec_producer is not None:
                self.ec_producer.update_if_changed(key, value)

    # -- fleet memory (merged replica census digests) ----------------- #

    def _publish_fleet_memory(self):
        """Fold every replica's broadcast per-tier KV byte counters
        into ``fleet_kv_<tier>_bytes`` share keys — the live fleet
        memory pane.  Sums are exact because each replica's numbers
        come from its memory accountant (PR 15), not a sample."""
        totals = {"kv_hbm_bytes": 0, "kv_host_bytes": 0,
                  "kv_disk_bytes": 0}
        for digest in self._replica_memory.values():
            for key in totals:
                totals[key] += int(digest.get(key, 0))
        for key, value in totals.items():
            share_key = f"fleet_{key}"
            self.share[share_key] = value
            if self.ec_producer is not None:
                self.ec_producer.update_if_changed(share_key, value)

    # -- anomaly detection & fleet capture ---------------------------- #

    def _anomaly_tick(self):
        """Per-window p95 drift check over the fleet merges.  A flag
        bumps ``anomaly_flags``, lands in the share for the dashboard,
        and fans a flight capture out fleet-wide — the early-warning
        record EXISTS by the time the SLO hard-trips."""
        for phase in self._anomaly_phases:
            merged = self.fleet_histogram(phase)
            if not merged.count:
                continue
            drift = self._drift.observe(phase, merged)
            if drift is None:
                continue
            self._bump("anomaly_flags")
            note = (f"{phase}: p95 {drift['p95_ms']:g}ms vs baseline "
                    f"{drift['baseline_ms']:g}ms "
                    f"({drift['ratio']:g}x, n={drift['window_count']})")
            self.share["last_anomaly"] = note
            if self.ec_producer is not None:
                self.ec_producer.update_if_changed("last_anomaly", note)
            self.logger.warning("%s: p95 drift — %s", self.name, note)
            self.capture(trigger="anomaly", reason=note)

    def _watch_steady_compiles(self, replica: str, value):
        """Steady-state compile watch (PR 14): a replica's broadcast
        ``compiles_steady_state`` counter MOVING means XLA compiled
        something after that replica's warmup fence — a pow2
        bucket-discipline regression in production.  Treated exactly
        like p95 drift: anomaly flag, share note, fleet capture (the
        breaching replica's bundle carries its compile ledger)."""
        try:
            count = int(value)
        except (TypeError, ValueError):
            return
        previous = self._steady_compiles.get(replica, 0)
        self._steady_compiles[replica] = count
        if count <= previous:
            return
        self._bump("anomaly_flags")
        self._bump("fleet_steady_compiles", by=count - previous)
        note = (f"steady-state compile on {replica.rsplit('/', 1)[-1]}: "
                f"+{count - previous} (total {count})")
        self.share["last_anomaly"] = note
        if self.ec_producer is not None:
            self.ec_producer.update_if_changed("last_anomaly", note)
        self.logger.warning("%s: %s", self.name, note)
        self.capture(trigger="compile", reason=note)

    def _watch_audit_violations(self, replica: str, value):
        """Pool-audit watch (PR 15): a replica's broadcast
        ``kv_audit_violations`` counter MOVING means its online
        auditor caught the memory accountant disagreeing with pool
        ground truth — invariant 16 held (serving is unaffected) but
        the books are wrong somewhere.  Treated exactly like p95
        drift: anomaly flag, share note, fleet capture (the breaching
        replica's bundle carries its full census)."""
        try:
            count = int(value)
        except (TypeError, ValueError):
            return
        previous = self._audit_violations.get(replica, 0)
        self._audit_violations[replica] = count
        if count <= previous:
            return
        self._bump("anomaly_flags")
        self._bump("fleet_audit_violations", by=count - previous)
        note = (f"pool audit violation on {replica.rsplit('/', 1)[-1]}: "
                f"+{count - previous} (total {count})")
        self.share["last_anomaly"] = note
        if self.ec_producer is not None:
            self.ec_producer.update_if_changed("last_anomaly", note)
        self.logger.warning("%s: %s", self.name, note)
        self.capture(trigger="pool_audit", reason=note)

    def capture(self, trace_id: str = "", response_topic: str = "",
                trigger: str = "operator", reason: str = ""):
        """Router override of the actor built-in: capture locally AND
        fan the command out to every live replica with ONE shared
        trace id, so one anomaly (or one operator ``(capture)``)
        yields one fleet-wide bundle set that ``tools/doctor.py``
        groups back together."""
        trace_id = str(trace_id) or flight.new_trace_id()
        super().capture(trace_id=trace_id,
                        response_topic=response_topic,
                        trigger=trigger, reason=reason)
        for replica in list(self._replicas):
            self.process.message.publish(
                f"{replica}/in",
                generate("capture", [trace_id, str(response_topic),
                                     str(trigger),
                                     str(reason)
                                     or f"fleet capture via {self.name}"]))
        self._bump("fleet_captures")

    def profile(self, steps: int = 4, trace_id: str = "",
                response_topic: str = "", reason: str = ""):
        """Router override of the ``(profile …)`` built-in: fan the
        bracket request out to every live replica with ONE shared
        trace id (the router itself carries no engine, so the local
        built-in answers ``unsupported`` — the fan-out is the point).
        Each replica's bracket finishes into its own flight bundle;
        ``doctor`` groups the set by the shared trace id."""
        trace_id = str(trace_id) or flight.new_trace_id()
        super().profile(steps=steps, trace_id=trace_id,
                        response_topic=response_topic, reason=reason)
        for replica in list(self._replicas):
            self.process.message.publish(
                f"{replica}/in",
                generate("profile", [str(steps), trace_id,
                                     str(response_topic),
                                     str(reason)
                                     or f"fleet profile via {self.name}"]))
        self._bump("fleet_profiles")

    def census(self, trace_id: str = "", response_topic: str = "",
               reason: str = ""):
        """Router override of the ``(census …)`` built-in: snapshot
        locally (the router carries no pool, so its bundle documents
        the fleet counters) AND fan the command out to every live
        replica with ONE shared trace id — each replica dumps its
        pool census into its own bundle, and ``tools/doctor.py``
        groups the set back into one fleet memory report."""
        trace_id = str(trace_id) or flight.new_trace_id()
        super().census(trace_id=trace_id,
                       response_topic=response_topic, reason=reason)
        for replica in list(self._replicas):
            self.process.message.publish(
                f"{replica}/in",
                generate("census", [trace_id, str(response_topic),
                                    str(reason)
                                    or f"fleet census via {self.name}"]))
        self._bump("fleet_censuses")

    # -- tracing ------------------------------------------------------ #

    @staticmethod
    def _trace_ctx(payload) -> Optional[str]:
        """Propagated trace context out of an ENCODED swag (the route
        hot path never decodes the full payload)."""
        carrier = (payload or {}).get("trace")
        if not carrier:
            return None
        try:
            return str(decode_value(str(carrier)))
        except Exception:  # noqa: BLE001 - junk context → no parent
            return None

    def _finish_trace(self, request_id: str, entry: Dict, swag):
        """Terminal response passing through the proxy: close the
        route span, merge the replica's ride-back spans with the
        router's own, and return a REBUILT response payload carrying
        the combined ``trace_spans``.  Only called when this request
        actually has router spans — untraced requests forward the
        replica's payload byte-identical."""
        spans = entry.get("spans") or []
        route_span = entry.get("route_span")
        if route_span is not None and trace.TRACER is not None:
            trace.TRACER.finish(route_span)
        try:
            outputs = decode_swag(swag)
        except Exception:  # noqa: BLE001 - corrupt stays corrupt
            return None
        remote = outputs.get("trace_spans")
        combined = (trace.decode_spans(remote) if remote else [])
        combined += [span for span in spans if span.end is not None]
        outputs["trace_spans"] = trace.encode_spans(combined)
        return generate("infer_response",
                        [request_id, encode_swag(outputs)])

    # -- routing ----------------------------------------------------- #

    def _pick(self, candidates: List[str]) -> str:
        """Power-of-two-choices by reported queue depth; exact
        round-robin while load is unknown (cold start, static
        ModelReplica fleets that publish no queue_depth)."""
        known = [r for r in candidates if "queue_depth"
                 in self._loads.get(r, ())]
        if len(known) < 2 or len(known) < len(candidates):
            target = candidates[self._next % len(candidates)]
            self._next += 1
            return target
        first, second = self._rng.sample(known, 2)
        return first if (self._loads[first]["queue_depth"]
                         <= self._loads[second]["queue_depth"]) else second

    # -- prefix-aware routing (kvstore directory) -------------------- #

    def _decode_candidates(self, candidates: List[str]) -> List[str]:
        """Exclude dedicated PREFILL replicas from decode routing —
        they clamp generation to one token.  A fleet that is ALL
        prefill still serves (degraded) rather than black-holing."""
        decode = [r for r in candidates
                  if self.directory.role(r) != "prefill"]
        return decode or candidates

    def _prefill_candidates(self) -> List[str]:
        return [r for r in self._candidates()
                if self.directory.role(r) == "prefill"]

    def _prompt_keys(self, payload) -> Dict[int, List[str]]:
        """Directory-width chain keys of the request's prompt, one
        list per block size advertised in the fleet (usually one).
        Decodes only the ``tokens`` entry of the swag — and only when
        a directory exists to match against."""
        from ..kvstore import chain_keys_hex
        from ..pipeline.codec import decode_value
        try:
            tokens = np.asarray(
                decode_value(payload["tokens"])).reshape(-1)
        except Exception:  # noqa: BLE001 - malformed → no prefix info
            return {}
        sizes = {self.directory.block_size(r)
                 for r in self.directory.replicas()}
        return {bs: chain_keys_hex(tokens, bs)
                for bs in sizes if bs}

    def _request_adapter_hex(self, payload) -> Optional[str]:
        """Directory-width root key of the request's named adapter
        (``payload["adapter"]``), or None for base-model requests —
        the name alone determines the key (kvstore/adapters.py), so
        the router never needs the factor bytes."""
        if not payload or "adapter" not in payload:
            return None
        try:
            name = decode_value(payload["adapter"])
        except Exception:  # noqa: BLE001 - malformed → adapter-blind
            return None
        if not name:
            return None
        from ..kvstore.adapters import adapter_hex
        return adapter_hex(str(name))

    def _adapter_weights(self, candidates: List[str],
                         adapter_hex: str, now) -> Dict[str, float]:
        """Tier-weighted warmth of one adapter per candidate: 1.0 for
        factors advertised in HBM, ``host_prefix_weight`` /
        ``disk_prefix_weight`` for demoted / spilled copies, 0.0 when
        the replica has no paged copy at all."""
        tier_weight = (1.0, self.host_prefix_weight,
                       self.disk_prefix_weight)
        weights = {}
        for replica in candidates:
            tier = self.directory.adapter_tier(replica, adapter_hex,
                                               now)
            weights[replica] = tier_weight[tier] \
                if tier is not None and tier < 3 else 0.0
        return weights

    def _pick_prefix(self, candidates: List[str], payload,
                     adapter_weights: Optional[Dict[str, float]]
                     = None):
        """Score ``queue_depth − α·effective_matched_blocks −
        adapter_affinity·adapter_warmth`` (lower wins; ties break by
        replica order for determinism), where a matched block
        advertised in the HOST tier contributes
        ``host_prefix_weight`` of an HBM block and one in the DISK
        tier ``disk_prefix_weight`` — each rung of the tower is
        cheaper than a recompute but dearer than the rung above, and
        the placement decision should reflect that.  Returns
        ``(target, owner, owner_matched, target_matched,
        target_host_matched, target_disk_matched)`` or None when
        nothing matches — the caller falls back to an adapter-only
        pick (warm adapter, no prefix) and then EXACT P2C, so fleets
        without paged prefix caches see PR-4 routing unchanged."""
        if self.prefix_alpha <= 0 or not payload \
                or not self.directory.size:
            return None
        keys_by_bs = self._prompt_keys(payload)
        if not keys_by_bs:
            return None
        now = self.process.event.now()
        matched, host, disk = {}, {}, {}
        for replica in candidates:
            keys = keys_by_bs.get(self.directory.block_size(replica))
            # A replica mid-migration (digest ``/migrating`` flag) is
            # on its way OUT: plain P2C may still use it, but scoring
            # it for NEW prefix placement would anchor fresh chains to
            # a replica about to retire.
            if keys and self.directory.migrating(replica):
                keys = None
            matched[replica], host[replica], disk[replica] = \
                self.directory.matched_tiers(replica, keys, now) \
                if keys else (0, 0, 0)
        if not any(matched.values()):
            return None

        def effective(replica):
            return matched[replica] \
                - (1.0 - self.host_prefix_weight) * host[replica] \
                - (1.0 - self.disk_prefix_weight) * disk[replica]

        def score(replica):
            depth = self._loads.get(replica, {}).get("queue_depth", 0)
            warmth = adapter_weights.get(replica, 0.0) \
                if adapter_weights else 0.0
            return depth - self.prefix_alpha * effective(replica) \
                - self.adapter_affinity * warmth

        target = min(candidates, key=lambda r: (score(r), r))
        owner = max(candidates,
                    key=lambda r: (effective(r), matched[r], r))
        return (target, owner, matched[owner], matched[target],
                host[target], disk[target])

    def _saturated(self, candidates: List[str]) -> bool:
        """True only when EVERY candidate reports a queue at or past
        the shed threshold — unknown load never sheds."""
        if not candidates:
            return False
        return all(
            self._loads.get(r, {}).get("queue_depth", -1)
            >= self.shed_queue_depth for r in candidates)

    def _shed(self, request_id, response_topic, error: str,
              retry_after_ms: Optional[int] = None, parent=None):
        """Terminal rejection published straight to the client — a
        future must ALWAYS resolve; silent drops are the failure mode
        this PR exists to remove."""
        if error == "overloaded":
            self._bump("shed")
        elif error == "deadline_exceeded":
            self._bump("deadline_exceeded")
        outputs: Dict = {"error": error}
        if retry_after_ms is not None:
            outputs["retry_after_ms"] = int(retry_after_ms)
        if trace.TRACER is not None and parent is not None:
            span = trace.TRACER.start_span(
                "shed", parent=parent,
                attrs={"request_id": str(request_id), "error": error})
            trace.TRACER.finish(span)
            outputs["trace_spans"] = trace.encode_spans([span])
        if response_topic:
            self.process.message.publish(
                str(response_topic),
                generate("infer_response",
                         [str(request_id), encode_swag(outputs)]))

    def route(self, request_id, response_topic, payload=None) -> bool:
        """Dispatch one request to a live replica and begin tracking
        it.  Returns False when no replicas are live — the request
        then sheds with ``error="overloaded"`` so the caller's future
        resolves instead of hanging."""
        request_id = str(request_id)
        ctx = None
        if trace.TRACER is not None:
            ctx = self._trace_ctx(payload)
        if not self._replicas:
            self.logger.warning("%s: no live replicas for %s",
                                self.name, request_id)
            self._shed(request_id, response_topic, "overloaded",
                       retry_after_ms=1000, parent=ctx)
            return False
        candidates = self._candidates()
        if self._saturated(candidates):
            depths = [self._loads[r]["queue_depth"] for r in candidates]
            self._shed(request_id, response_topic, "overloaded",
                       retry_after_ms=min(5000, 50 * min(depths)),
                       parent=ctx)
            return False
        decode = self._decode_candidates(candidates)
        adapter_hex = self._request_adapter_hex(payload) \
            if self.adapter_affinity > 0 else None
        adapter_weights = None
        if adapter_hex is not None and self.directory.size:
            weights = self._adapter_weights(
                decode, adapter_hex, self.process.event.now())
            warm = [r for r in decode if weights.get(r, 0.0) > 0]
            if warm:
                # A cold landing is not a SLOW request but a FAILED
                # one (``unknown_adapter`` → the tenant re-uploads
                # factors), so adapter warmth is a hard preference,
                # not a score bonus load can outbid: restrict the
                # candidate set to warm replicas and let prefix
                # affinity, load, and tier order THEM — zero cold
                # starts whenever the adapter is warm anywhere.
                decode = warm
                adapter_weights = weights
            # Cold everywhere: route blind — any replica costs the
            # same upload.
        picked = self._pick_prefix(decode, payload, adapter_weights)
        if picked is None:
            if adapter_weights:
                # No prefix match: among the warm replicas, trade
                # queue depth against the copy's tier (an HBM-resident
                # adapter beats one needing a restore from host/disk).
                target = min(decode, key=lambda r: (
                    self._loads.get(r, {}).get("queue_depth", 0)
                    - self.adapter_affinity * adapter_weights[r], r))
            else:
                target = self._pick(decode)
            owner = owner_matched = target_matched = None
            target_host = target_disk = 0
        else:
            (target, owner, owner_matched, target_matched,
             target_host, target_disk) = picked
            self._bump("prefix_routed")
            if target_host:
                # The chosen target's match includes demoted blocks —
                # this request will trigger (or ride) a restore there.
                self._bump("prefix_routed_host")
            if target_disk:
                self._bump("prefix_routed_disk")
        if adapter_hex is not None:
            # Provenance of every adapter-tagged route: did the chosen
            # target already hold the factors (any tier), or does this
            # request pay the cold-start?  The loadgen A/B asserts the
            # aware router's cold count is ZERO when the adapter is
            # warm anywhere in the fleet.
            if adapter_weights is not None \
                    and adapter_weights.get(target, 0.0) > 0:
                self._bump("adapter_warm_routes")
            else:
                self._bump("adapter_cold_routes")
        send_payload = payload or {}
        if target_host or target_disk:
            # Tier-aware prefetch: tell the target NOW that this
            # request lands on a demoted/spilled chain, so it begins
            # the async promotion while the request rides the wire and
            # the queue — instead of at the admission walk's deferral.
            send_payload = dict(send_payload)
            send_payload["kv_tier_hint"] = "i:1"
            self._bump("kv_tier_hints")
        phase = "decode"
        if self.kv_transfer and owner is not None \
                and owner != target and owner_matched > (
                    target_matched or 0):
            # Load won over affinity — hint the target to PULL the
            # owner's blocks instead of recomputing the prefix.
            send_payload = dict(send_payload)
            send_payload["kv_source"] = f"s:{owner}"
            self._bump("kv_remote_hints")
        elif self.disaggregate and self.kv_transfer:
            prefill = [r for r in self._prefill_candidates()
                       if r in candidates]
            if prefill and target not in prefill:
                # Two-phase: prefill replica computes the prompt KV,
                # the decode target pulls it (see _begin_decode_phase).
                phase = "prefill"
                prefill_target = self._pick(prefill)
                send_payload = dict(send_payload)
                send_payload["prefill_only"] = "i:1"
                target = prefill_target
        route_span = None
        if trace.TRACER is not None:
            # The route span OPENS here and closes when the terminal
            # response passes back through the proxy — it measures the
            # request's routed lifetime; redispatch/shed spans nest
            # under it.
            route_span = trace.TRACER.start_span(
                "route", parent=ctx,
                attrs={"request_id": request_id, "target": target,
                       "phase": phase})
            if owner_matched:
                route_span.set_attr("prefix_matched",
                                    int(owner_matched))
            send_payload = dict(send_payload)
            send_payload["trace"] = f"s:{trace.inject(route_span)}"
        self._routed[request_id] = target
        while len(self._routed) > self._routed_limit:
            self._routed.popitem(last=False)
        self._inflight[request_id] = dict(
            replica=target, client_topic=str(response_topic),
            payload=payload or {}, attempts=0, delivered=0,
            replica_sent=0, routed_at=self.process.event.now(),
            deadline_ts=-1.0,    # -1 = not yet resolved from payload
            phase=phase, route_span=route_span,
            # Every token delivered to the client, in order — the
            # migration resume's carried context (len == delivered).
            tokens=[], migration=None,
            spans=[route_span] if route_span is not None else None)
        while len(self._inflight) > self._inflight_limit:
            dropped_id, _ = self._inflight.popitem(last=False)
            self.logger.warning(
                "%s: in-flight table full, dropping tracking for %s "
                "(request still routed; no re-dispatch protection)",
                self.name, dropped_id)
        self.process.message.publish(
            f"{target}/in",
            generate("infer", [request_id, self.topic_reply,
                               send_payload]))
        self.share["requests_routed"] += 1
        if self.ec_producer is not None:
            self.ec_producer.update("requests_routed",
                                    self.share["requests_routed"])
        return True

    # -- response proxy ---------------------------------------------- #

    def _on_reply(self, _topic: str, payload: str):
        """A replica answered on the reply topic: dedup + forward
        partials, intercept retriable errors, forward terminal
        responses and close out tracking."""
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command not in ("infer_partial", "infer_response") \
                or len(params) < 2:
            return
        entry = self._inflight.get(str(params[0]))
        if entry is None:
            return        # already terminal (late reply after re-dispatch)
        if command == "infer_partial":
            self._forward_partial(str(params[0]), entry, params[1])
            return
        try:
            outputs = decode_swag(params[1])
            error = outputs.get("error")
        except Exception:
            error = None  # corrupt swag: client resolves corrupt_response
        if entry.get("migration") is not None \
                and self.migration.absorb_source_final(str(params[0]),
                                                       entry):
            # Post-cutover: the destination owns the stream now — the
            # source's terminal (cancel ack or a racing finish) is the
            # double-delivery window's tail and must not reach the
            # client.  Pre-cutover the call aborted the migration and
            # returned False: the terminal proceeds normally below.
            return
        if error is not None and str(error) in RETRIABLE_ERRORS \
                and entry["attempts"] < self.max_redispatch:
            # The REPLICA failed, not the request — move the work.
            self._schedule_redispatch(str(params[0]), entry)
            return
        if entry.get("phase") == "prefill":
            if error is not None and str(error) != "cancelled":
                # Prefill leg failed terminally: decode from scratch
                # on a decode replica (no kv hint) — the request still
                # MUST resolve.
                self._begin_decode_phase(str(params[0]), entry, None)
            elif error is None:
                self._begin_decode_phase(str(params[0]), entry,
                                         entry["replica"])
            else:             # cancelled: terminal for the client too
                self._inflight.pop(str(params[0]), None)
                self.process.message.publish(entry["client_topic"],
                                             payload)
            return
        self._inflight.pop(str(params[0]), None)
        if entry.get("spans"):
            rebuilt = self._finish_trace(str(params[0]), entry,
                                         params[1])
            if rebuilt is not None:
                payload = rebuilt
        self.process.message.publish(entry["client_topic"], payload)

    def _begin_decode_phase(self, request_id: str, entry: Dict,
                            prefill_replica: Optional[str]):
        """Second leg of disaggregated serving: the prefill replica
        finished (its 1-token answer is DISCARDED — the decode leg
        regenerates it from the transferred KV), now route the full
        request to a decode replica with a ``kv_source`` hint at the
        warm prefill cache.  ``prefill_replica=None`` means the
        prefill leg failed and decode recomputes locally."""
        entry["phase"] = "decode"
        entry["replica_sent"] = 0
        candidates = self._decode_candidates(self._candidates())
        picked = self._pick_prefix(candidates, entry["payload"])
        target = picked[0] if picked else self._pick(candidates)
        send_payload = entry["payload"]
        if prefill_replica is not None and self.kv_transfer \
                and target != prefill_replica:
            send_payload = dict(send_payload)
            send_payload["kv_source"] = f"s:{prefill_replica}"
            self._bump("kv_remote_hints")
        if trace.TRACER is not None and \
                entry.get("route_span") is not None:
            span = trace.TRACER.start_span(
                "decode_phase", parent=entry["route_span"],
                attrs={"request_id": request_id, "target": target})
            trace.TRACER.finish(span)
            entry["spans"].append(span)
            send_payload = dict(send_payload)
            send_payload["trace"] = \
                f"s:{trace.inject(entry['route_span'])}"
        entry["replica"] = target
        self._routed[request_id] = target
        self.process.message.publish(
            f"{target}/in",
            generate("infer", [request_id, self.topic_reply,
                               send_payload]))

    def _forward_partial(self, request_id: str, entry: Dict, swag):
        """Token-offset dedup: a re-dispatched greedy request replays
        from the prompt, so the new replica re-streams tokens the
        client already has — forward only the suffix past what was
        delivered."""
        if entry.get("phase") == "prefill":
            return    # prefill leg's token is regenerated by decode
        try:
            increment = [int(t) for t in
                         np.asarray(decode_swag(swag)["tokens_out"])]
        except Exception:
            return              # corrupt partial: drop (final is authoritative)
        sent = entry["replica_sent"]
        entry["replica_sent"] = sent + len(increment)
        skip = max(0, entry["delivered"] - sent)
        fresh = increment[skip:]
        if not fresh:
            return
        entry["delivered"] += len(fresh)
        entry["tokens"].extend(fresh)
        self.process.message.publish(
            entry["client_topic"],
            generate("infer_partial",
                     [request_id,
                      encode_swag({"tokens_out":
                                   np.asarray(fresh, np.int32)})]))

    # -- live migration (drain-free replica replacement) -------------- #

    def migrate_request(self, request_id: str,
                        dest: Optional[str] = None) -> bool:
        """Migrate ONE in-flight request to ``dest`` (default: best
        live candidate that is not the source).  Returns False when
        the request is unknown or unmigratable — the original stream
        is untouched either way."""
        request_id = str(request_id)
        entry = self._inflight.get(request_id)
        if entry is None:
            return False
        source = entry.get("replica")
        if dest is None:
            others = [r for r in self._candidates() if r != source]
            if not others:
                return False
            dest = self._pick(self._decode_candidates(others))
        return self.migration.start(request_id, entry, str(dest))

    def migrate_replica(self, source: str,
                        dest: Optional[str] = None) -> int:
        """Drain-free evacuation: migrate every eligible in-flight
        request off ``source``.  Returns the number of migrations
        started (requests that cannot migrate — grammar-constrained,
        prefill-leg, unknown budget — stay put and finish in place,
        exactly like a graceful drain)."""
        source = str(source)
        started = 0
        for request_id, entry in list(self._inflight.items()):
            if entry.get("replica") == source:
                started += self.migrate_request(
                    request_id, dest=dest)
        return started

    def _wire_migrate(self, source, dest=None, response_topic=None):
        """Wire command ``(migrate source [dest] [reply_topic])`` —
        the autoscaler's migrate action and operators use this to
        evacuate a replica without a drain hole."""
        started = self.migrate_replica(
            str(source), dest=None if dest in (None, "", "-")
            else str(dest))
        if response_topic:
            self.process.message.publish(
                str(response_topic),
                generate("migrate_response",
                         [str(source),
                          encode_swag({"started": started})]))

    def _on_migrate_reply(self, _topic: str, payload: str):
        """Migration side-channel: source ``migrate_ready`` acks and
        the DESTINATION's resume stream (partials + terminal), all
        keyed by migration id."""
        try:
            command, params = parse(payload)
        except Exception:
            return
        if len(params) < 2:
            return
        mid = str(params[0])
        if command == "migrate_ready":
            self.migration.on_ready(mid, params[1])
        elif command == "infer_partial":
            self.migration.on_dest_partial(mid, params[1])
        elif command == "infer_response":
            self.migration.on_dest_final(mid, params[1])

    # -- re-dispatch -------------------------------------------------- #

    def _drain_replica(self, replica: str):
        """Re-dispatch every in-flight request the dead/unhealthy
        replica holds.  Migration-aware: a migration whose DESTINATION
        died aborts (the source never stopped serving); one whose
        SOURCE died mid-transfer promotes the destination instead of
        replaying."""
        self.migration.on_replica_down(replica)
        for request_id, entry in list(self._inflight.items()):
            if entry["replica"] == replica:
                if entry.get("migration") is not None \
                        and self.migration.on_owner_lost(
                            request_id, entry, replica):
                    continue     # destination promoted — no replay
                self._schedule_redispatch(request_id, entry)

    def _schedule_redispatch(self, request_id: str, entry: Dict):
        """Arm a once-timer with bounded exponential backoff + seeded
        jitter (0.5–1.5×): failures are correlated — a thundering herd
        of instant retries onto the one survivor is how cascades
        start."""
        if entry.get("migration") is not None:
            # Replay supersedes any in-flight migration: the new
            # replica regenerates everything, so the half-moved chain
            # is worthless — tear it down (idempotent).
            self.migration.abort(request_id, entry, "redispatch")
        entry["replica"] = None
        delay = min(self.backoff_cap_s,
                    self.backoff_base_s * (2 ** entry["attempts"]))
        delay *= 0.5 + self._rng.random()
        self.process.event.add_timer_handler(
            lambda: self._redispatch(request_id), delay, once=True)

    def _redispatch(self, request_id: str):
        entry = self._inflight.get(request_id)
        if entry is None or entry["replica"] is not None:
            return    # completed, or another path already re-routed it
        if entry["deadline_ts"] < 0:
            entry["deadline_ts"] = self._resolve_deadline(entry)
        if entry["deadline_ts"] is not None and \
                self.process.event.now() >= entry["deadline_ts"]:
            self._inflight.pop(request_id, None)
            self._shed(request_id, entry["client_topic"],
                       "deadline_exceeded",
                       parent=entry.get("route_span"))
            return
        if entry["attempts"] >= self.max_redispatch:
            self._inflight.pop(request_id, None)
            self._shed(request_id, entry["client_topic"],
                       "redispatch_failed",
                       parent=entry.get("route_span"))
            return
        entry["attempts"] += 1
        # Re-dispatch prefers non-retiring survivors; a fleet that is
        # ALL retiring still absorbs stranded work (drain ≠ dead).
        live = [r for r in self._replicas if r not in self._unhealthy
                and r not in self._retiring] or \
               [r for r in self._replicas if r not in self._unhealthy]
        if not live:
            # Nothing to route to YET — back off again; the attempt
            # budget above bounds how long we hope.
            self._schedule_redispatch(request_id, entry)
            return
        if entry.get("phase") == "prefill":
            # The prefill leg died: demote to a plain single-phase
            # request on a decode survivor (recompute, no kv hint) —
            # the zero-lost guarantee outranks disaggregation.
            entry["phase"] = "decode"
        live = self._decode_candidates(live)
        picked = self._pick_prefix(live, entry["payload"])
        target = picked[0] if picked else self._pick(live)
        entry["replica"] = target
        entry["replica_sent"] = 0     # new replica replays from prompt
        self._routed[request_id] = target
        self._bump("redispatches")
        send_payload = entry["payload"]
        if trace.TRACER is not None and \
                entry.get("route_span") is not None:
            span = trace.TRACER.start_span(
                "redispatch", parent=entry["route_span"],
                attrs={"request_id": request_id, "target": target,
                       "attempt": entry["attempts"]})
            trace.TRACER.finish(span)
            entry["spans"].append(span)
            # Re-point the propagated context at the route span so the
            # NEW replica's spans still join this request's tree.
            send_payload = dict(send_payload)
            send_payload["trace"] = \
                f"s:{trace.inject(entry['route_span'])}"
        self.logger.info("%s: re-dispatching %s to %s (attempt %d)",
                         self.name, request_id, target,
                         entry["attempts"])
        self.process.message.publish(
            f"{target}/in",
            generate("infer", [request_id, self.topic_reply,
                               send_payload]))

    def _resolve_deadline(self, entry: Dict) -> Optional[float]:
        """Lazily decode the original payload's ``deadline_ms`` (only
        on the failure path — the route hot path never decodes swag).
        Approximates the client's budget as starting at route time."""
        try:
            deadline_ms = decode_swag(entry["payload"]).get(
                "deadline_ms")
        except Exception:
            return None
        if deadline_ms is None:
            return None
        return entry["routed_at"] + float(np.asarray(deadline_ms)) / 1e3

    # -- cancel ------------------------------------------------------- #

    def _route_cancel(self, request_id, response_topic=None) -> None:
        """Forward ``(infer_cancel id [reply_topic])`` to the replica
        currently holding the request (the live in-flight record wins
        over the routed-affinity ring — a re-dispatch may have moved
        it).  An unknown or aged-out id resolves the caller's future
        with ``error="cancel_unrouted"`` when a reply topic rides
        along, instead of leaving it to time out.  Affinity entries are
        KEPT after forwarding so a cancel lost in transit can be
        retried; request ids must be unique per client
        (``InferClient`` guarantees this)."""
        request_id = str(request_id)
        entry = self._inflight.get(request_id)
        target = entry["replica"] if entry is not None \
            else self._routed.get(request_id)
        if target is None:
            self.logger.info("%s: infer_cancel for unrouted id %s",
                             self.name, request_id)
            self._bump("cancel_unrouted")
            if response_topic:
                self.process.message.publish(
                    str(response_topic),
                    generate("infer_response",
                             [request_id,
                              encode_swag({"error":
                                           "cancel_unrouted"})]))
            return
        if entry is not None and entry.get("migration") is not None:
            # Both legs of a migrating request must die — the
            # destination's resume runs under the migration id.
            self.migration.cancel_dest(entry)
        self.process.message.publish(
            f"{target}/in",
            generate("infer_cancel", [request_id]))


def _coerce_request(inputs: Dict, config, default_new: int):
    """Shared request scaffolding for the infer factories: coerce the
    token array to (batch, prompt), clamp the generation budget to the
    model's max_seq_len.  Returns (tokens, prompt_len, new) or an
    error payload dict."""
    import jax.numpy as jnp
    import numpy as np

    tokens = jnp.asarray(np.asarray(inputs["tokens"]), jnp.int32)
    if tokens.ndim == 1:
        tokens = tokens[None]
    prompt_len = tokens.shape[1]
    if prompt_len >= config.max_seq_len:
        # Reject cleanly: a cache shorter than the prompt would fail
        # deep inside prefill with an opaque trace error.
        return {"error": f"prompt_len {prompt_len} >= max_seq_len "
                         f"{config.max_seq_len}"}
    requested = int(np.asarray(inputs.get("max_new_tokens",
                                          default_new)))
    if requested <= 0:
        return {"error": f"max_new_tokens must be positive, got "
                         f"{requested}"}
    new = min(requested, config.max_seq_len - prompt_len)
    return tokens, prompt_len, new


def make_llama_infer(config_name: str = "tiny", quantize: bool = False,
                     max_new_tokens: int = 16, seed: int = 0,
                     quantize_kv: bool = False,
                     checkpoint: str = None) -> Callable:
    """Build a ModelReplica ``infer`` callable running the flagship
    Llama-architecture model: ``{"tokens": (batch, prompt)}`` →
    ``{"tokens_out": (batch, prompt+new)}``.

    ``checkpoint``: HF-layout safetensors path — serve TRAINED weights
    (config comes from its config.json; ``quantize`` applies on the
    fly).  Without it, random-init params under the named config (the
    shape/perf harness mode)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models import llama

    if checkpoint:
        from ..tools.import_weights import import_llama
        params, config = import_llama(
            checkpoint, bits=8 if quantize else None)
    else:
        config = llama.CONFIGS[config_name]
        params = llama.init_params(config, jax.random.PRNGKey(seed))
        if quantize:
            params = llama.quantize_params(params)

    def infer(inputs: Dict) -> Dict:
        request = _coerce_request(inputs, config, max_new_tokens)
        if isinstance(request, dict):
            return request
        tokens, prompt_len, new = request
        cache = llama.init_cache(config, tokens.shape[0],
                                 prompt_len + new,
                                 quantize_kv=quantize_kv)
        logits, cache = llama.prefill(params, tokens, cache, config)
        first = logits[:, -1].argmax(-1).astype(jnp.int32)[:, None]
        generated, _ = llama.generate_tokens(
            params, first, cache, jnp.int32(prompt_len), new - 1, config)
        return {"tokens_out": np.concatenate(
            [np.asarray(tokens), np.asarray(first),
             np.asarray(generated)], axis=1)}

    return infer


def make_speculative_infer(target_config="small", draft_config="tiny",
                           quantize: bool = False,
                           max_new_tokens: int = 16, k: int = 4,
                           seed: int = 0, draft_seed: int = 1) -> Callable:
    """Build a ModelReplica ``infer`` callable running GREEDY
    speculative decoding: a draft model proposes ``k`` tokens, the
    target verifies them in one chunked-prefill pass — output is
    IDENTICAL to target-only greedy decode (the exactness the tests
    assert), so a router can mix speculative and plain replicas freely.

    ``target_config``/``draft_config``: CONFIGS names or LlamaConfig
    instances; they must share a vocabulary.  Batch-1 requests only
    (speculation targets the low-batch latency regime; use
    ContinuousReplica for throughput batching).
    """
    import jax
    import numpy as np
    from ..models import llama
    from ..models.speculative import speculative_generate

    def resolve(config):
        return (llama.CONFIGS[config] if isinstance(config, str)
                else config)
    target_cfg = resolve(target_config)
    draft_cfg = resolve(draft_config)
    if target_cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    target_params = llama.init_params(target_cfg,
                                      jax.random.PRNGKey(seed))
    if quantize:
        target_params = llama.quantize_params(target_params)
    draft_params = llama.init_params(draft_cfg,
                                     jax.random.PRNGKey(draft_seed))

    def infer(inputs: Dict) -> Dict:
        prompt = np.asarray(inputs["tokens"], np.int32).reshape(-1)
        new = int(np.asarray(inputs.get("max_new_tokens",
                                        max_new_tokens)))
        # speculative_generate bounds by BOTH models' max_seq_len (the
        # draft runs the same positions).
        max_seq = min(target_cfg.max_seq_len, draft_cfg.max_seq_len)
        budget = max_seq - len(prompt) - k - 1
        if budget <= 0:
            return {"error": f"prompt_len {len(prompt)} too long for "
                             f"max_seq {max_seq} with k={k} "
                             "speculation"}
        new = min(new, budget)
        generated, stats = speculative_generate(
            target_params, draft_params, prompt, new, target_cfg,
            draft_cfg, k=k)
        return {"tokens_out": np.concatenate(
                    [prompt, np.asarray(generated, np.int32)])[None],
                "acceptance_rate": np.float32(stats.acceptance_rate),
                "tokens_per_target_pass": np.float32(
                    stats.tokens_per_target_pass)}

    return infer


def make_constrained_infer(config_name: str = "tiny", automaton=None,
                           quantize: bool = False,
                           max_new_tokens: int = 16, seed: int = 0,
                           temperature: float = 0.0) -> Callable:
    """Build a ModelReplica ``infer`` callable whose outputs are
    guaranteed grammatical: a token-DFA masks every decode step
    (:mod:`~..models.constrained`), so the replica can ONLY emit
    sequences the grammar accepts — the hard-guarantee upgrade of the
    reference's prompt-and-regex robot commanding.  Responses carry
    ``tokens_out`` (each row is the grammatical output followed by
    ``pad_token`` zeros once its state went terminal — trim at the
    grammar's end marker) and per-row ``accepted`` flags."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models import llama
    from ..models.constrained import constrained_generate

    if automaton is None:
        raise ValueError("make_constrained_infer requires automaton=")
    config = llama.CONFIGS[config_name]
    if automaton.vocab != config.vocab_size:
        raise ValueError(
            f"automaton vocab {automaton.vocab} != model vocab "
            f"{config.vocab_size}")
    params = llama.init_params(config, jax.random.PRNGKey(seed))
    if quantize:
        params = llama.quantize_params(params)
    # Device-resident once: re-uploading (n_states, vocab) masks per
    # request would put a host transfer on the serving hot path.
    allowed = jnp.asarray(automaton.allowed)
    next_state = jnp.asarray(automaton.next_state)

    def infer(inputs: Dict) -> Dict:
        request = _coerce_request(inputs, config, max_new_tokens)
        if isinstance(request, dict):
            return request
        tokens, prompt_len, new = request
        cache = llama.init_cache(config, tokens.shape[0],
                                 prompt_len + new)
        logits, cache = llama.prefill(params, tokens, cache, config)
        seed_req = int(np.asarray(inputs.get("seed", 0)))
        out, states, _ = constrained_generate(
            params, logits[:, -1], cache, jnp.int32(prompt_len), new,
            config, allowed, next_state, temperature=temperature,
            rng_key=jax.random.PRNGKey(seed_req))
        accepted = automaton.accepting[np.asarray(states)]
        return {"tokens_out": np.asarray(out),
                "accepted": accepted.astype(np.int32)}

    return infer

"""Data-parallel model replica serving.

The reference's LifeCycleManager runs fleets of identical clients
(SURVEY.md §2.6 maps that to data-parallel replica serving); this module
gives that shape a concrete model-serving form, matching the
BASELINE.md "multi-replica serving actors, DP over chips" workload:

- :class:`ModelReplica` — an Actor hosting one model instance (one chip
  / one mesh slice).  Wire protocol:
  ``(infer request_id response_topic (payload…))`` → runs the model,
  publishes ``(infer_response request_id (outputs…))`` to
  ``response_topic`` — the reference's response-topic idiom
  (main/storage.py:87-103).
- :class:`ReplicaRouter` — an Actor that discovers replicas through the
  ServicesCache (by protocol), load-balances requests round-robin, and
  prunes replicas the moment the Registrar evicts them (LWT death or
  lease expiry).  Routing is fire-and-forget pass-through: the
  *original* response topic rides along.  The only per-request state
  is the bounded id→replica affinity ring that lets ``infer_cancel``
  follow its request — so REPLICATED routers serve fine, but a cancel
  must reach the router that routed the request (sticky clients, or
  send cancels to every router instance).

Payloads are swag-codec dicts (numpy arrays travel as typed tags), so
token tensors cross process boundaries losslessly.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from ..pipeline.codec import decode_swag, encode_swag
from ..registry.services_cache import services_cache_create_singleton
from ..runtime.actor import Actor
from ..runtime.service import ServiceFilter
from ..utils.sexpr import generate

__all__ = ["ModelReplica", "ReplicaRouter", "REPLICA_PROTOCOL",
           "make_llama_infer", "make_speculative_infer",
           "make_constrained_infer", "serving_telemetry"]

REPLICA_PROTOCOL = "model_replica:0"

#: Server-stats keys worth broadcasting to operators.  Shared by
#: ContinuousReplica EC shares, dashboard rendering, and bench
#: reporting so all three show the SAME derived counters.
TELEMETRY_KEYS = (
    "slots_active", "queue_depth", "in_flight",
    "decode_steps_per_sec", "sync_stalls_per_100_steps",
    "admission_deferred", "state_uploads", "tokens_committed",
    "prefix_hits", "prefix_misses", "prefix_evictions",
    "decode_attention_path", "blocks_read_per_step",
    "prefill_tokens_per_sec", "prefill_queue_depth",
    "prefill_attention_path",
)


def serving_telemetry(stats: Dict) -> Dict:
    """Project a server's :meth:`stats` dict onto the operator
    telemetry keys (ints stay ints, rates stay floats, tags stay
    strings; absent keys — e.g. prefix counters on a non-paged server
    — are omitted)."""
    out = {}
    for key in TELEMETRY_KEYS:
        if key in stats:
            value = stats[key]
            if isinstance(value, str):
                out[key] = value
            elif isinstance(value, float):
                out[key] = round(float(value), 2)
            else:
                out[key] = int(value)
    return out


def _register_unsupported_adapter_commands(actor) -> None:
    """Adapter hot-deploy is a ContinuousReplica capability; other
    protocol speakers ACK with an error instead of silently dropping
    the command (a client future must always resolve)."""
    def unsupported(request_id, response_topic, payload=None):
        actor.process.message.publish(
            str(response_topic),
            generate("adapter_response",
                     [str(request_id),
                      encode_swag({"error": "unsupported_command"})]))

    actor._command_handlers["adapter_load"] = unsupported
    actor._command_handlers["adapter_unload"] = unsupported


class ModelReplica(Actor):
    """Hosts one model instance and serves ``infer`` requests."""

    def __init__(self, context, process=None,
                 infer: Optional[Callable[[Dict], Dict]] = None):
        context.protocol = context.protocol or REPLICA_PROTOCOL
        super().__init__(context, process)
        self._infer = infer or (lambda payload: payload)
        self._command_handlers["infer"] = self._wire_infer
        _register_unsupported_adapter_commands(self)
        self.share["requests_served"] = 0

    def _wire_infer(self, request_id, response_topic, payload=None):
        inputs = decode_swag(payload or {})
        try:
            outputs = self._infer(inputs)
        except Exception:  # noqa: BLE001 - a bad request must not kill us
            self.logger.exception("%s: infer failed for %s", self.name,
                                  request_id)
            outputs = {"error": "infer_failed"}
        self.share["requests_served"] += 1
        if self.ec_producer is not None:
            self.ec_producer.update("requests_served",
                                    self.share["requests_served"])
        self.process.message.publish(
            response_topic,
            generate("infer_response",
                     [str(request_id), encode_swag(outputs)]))


class ReplicaRouter(Actor):
    """Discovers :class:`ModelReplica` services and round-robins
    ``infer`` requests across the live set."""

    def __init__(self, context, process=None,
                 replica_protocol: str = REPLICA_PROTOCOL):
        super().__init__(context, process)
        self._replicas: List[str] = []   # replica topic paths, stable order
        self._next = 0
        self._command_handlers["infer"] = self.route
        self._command_handlers["infer_cancel"] = self._route_cancel
        _register_unsupported_adapter_commands(self)
        #: request_id -> replica topic path, so infer_cancel follows
        #: its request to the SAME replica.  Bounded ring evicting the
        #: OLDEST ROUTED id (liveness is invisible to a pass-through
        #: router): a cancel for an aged-out id is dropped with a log,
        #: so size the ring well above the maximum in-flight window
        #: (entries are two short strings each).
        self._routed: "OrderedDict[str, str]" = OrderedDict()
        self._routed_limit = 65536
        self.share["replicas"] = 0
        self._cache = services_cache_create_singleton(self.process)
        self._cache.add_handler(
            ServiceFilter(protocol=replica_protocol),
            self._replica_added, self._replica_removed)

    def _replica_added(self, fields):
        if fields.topic_path not in self._replicas:
            self._replicas.append(fields.topic_path)
            self._replicas.sort()
            self._update_share()
            self.logger.info("%s: replica up %s (%d live)", self.name,
                             fields.topic_path, len(self._replicas))

    def _replica_removed(self, fields):
        if fields.topic_path in self._replicas:
            self._replicas.remove(fields.topic_path)
            self._update_share()
            self.logger.info("%s: replica down %s (%d live)", self.name,
                             fields.topic_path, len(self._replicas))

    def _update_share(self):
        self.share["replicas"] = len(self._replicas)
        if self.ec_producer is not None:
            self.ec_producer.update("replicas", len(self._replicas))

    def route(self, request_id, response_topic, payload=None) -> bool:
        """Forward one request to the next live replica.  Returns False
        (and logs) when no replicas are live — the caller's retry is the
        recovery path, per the fire-and-forget idiom."""
        if not self._replicas:
            self.logger.warning("%s: no live replicas for %s",
                                self.name, request_id)
            return False
        target = self._replicas[self._next % len(self._replicas)]
        self._next += 1
        self._routed[str(request_id)] = target
        while len(self._routed) > self._routed_limit:
            self._routed.popitem(last=False)
        self.process.message.publish(
            f"{target}/in",
            generate("infer", [str(request_id), str(response_topic),
                               payload or {}]))
        return True

    def _route_cancel(self, request_id) -> None:
        """Forward ``(infer_cancel id)`` to the replica that holds the
        request (affinity recorded at route time); unknown or aged-out
        ids are logged only — their response may already be in
        flight.  The entry is KEPT after forwarding so a cancel lost in
        transit can be retried (the fire-and-forget idiom's recovery
        path); the router cannot see completions, so request ids must
        be unique per client (``InferClient`` guarantees this) — a
        hand-rolled client reusing an id would route its cancel to
        whatever replica last held that id until the affinity ring
        evicts it."""
        target = self._routed.get(str(request_id))
        if target is None:
            self.logger.info("%s: infer_cancel for unrouted id %s",
                             self.name, request_id)
            return
        self.process.message.publish(
            f"{target}/in",
            generate("infer_cancel", [str(request_id)]))


def _coerce_request(inputs: Dict, config, default_new: int):
    """Shared request scaffolding for the infer factories: coerce the
    token array to (batch, prompt), clamp the generation budget to the
    model's max_seq_len.  Returns (tokens, prompt_len, new) or an
    error payload dict."""
    import jax.numpy as jnp
    import numpy as np

    tokens = jnp.asarray(np.asarray(inputs["tokens"]), jnp.int32)
    if tokens.ndim == 1:
        tokens = tokens[None]
    prompt_len = tokens.shape[1]
    if prompt_len >= config.max_seq_len:
        # Reject cleanly: a cache shorter than the prompt would fail
        # deep inside prefill with an opaque trace error.
        return {"error": f"prompt_len {prompt_len} >= max_seq_len "
                         f"{config.max_seq_len}"}
    requested = int(np.asarray(inputs.get("max_new_tokens",
                                          default_new)))
    if requested <= 0:
        return {"error": f"max_new_tokens must be positive, got "
                         f"{requested}"}
    new = min(requested, config.max_seq_len - prompt_len)
    return tokens, prompt_len, new


def make_llama_infer(config_name: str = "tiny", quantize: bool = False,
                     max_new_tokens: int = 16, seed: int = 0,
                     quantize_kv: bool = False,
                     checkpoint: str = None) -> Callable:
    """Build a ModelReplica ``infer`` callable running the flagship
    Llama-architecture model: ``{"tokens": (batch, prompt)}`` →
    ``{"tokens_out": (batch, prompt+new)}``.

    ``checkpoint``: HF-layout safetensors path — serve TRAINED weights
    (config comes from its config.json; ``quantize`` applies on the
    fly).  Without it, random-init params under the named config (the
    shape/perf harness mode)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models import llama

    if checkpoint:
        from ..tools.import_weights import import_llama
        params, config = import_llama(
            checkpoint, bits=8 if quantize else None)
    else:
        config = llama.CONFIGS[config_name]
        params = llama.init_params(config, jax.random.PRNGKey(seed))
        if quantize:
            params = llama.quantize_params(params)

    def infer(inputs: Dict) -> Dict:
        request = _coerce_request(inputs, config, max_new_tokens)
        if isinstance(request, dict):
            return request
        tokens, prompt_len, new = request
        cache = llama.init_cache(config, tokens.shape[0],
                                 prompt_len + new,
                                 quantize_kv=quantize_kv)
        logits, cache = llama.prefill(params, tokens, cache, config)
        first = logits[:, -1].argmax(-1).astype(jnp.int32)[:, None]
        generated, _ = llama.generate_tokens(
            params, first, cache, jnp.int32(prompt_len), new - 1, config)
        return {"tokens_out": np.concatenate(
            [np.asarray(tokens), np.asarray(first),
             np.asarray(generated)], axis=1)}

    return infer


def make_speculative_infer(target_config="small", draft_config="tiny",
                           quantize: bool = False,
                           max_new_tokens: int = 16, k: int = 4,
                           seed: int = 0, draft_seed: int = 1) -> Callable:
    """Build a ModelReplica ``infer`` callable running GREEDY
    speculative decoding: a draft model proposes ``k`` tokens, the
    target verifies them in one chunked-prefill pass — output is
    IDENTICAL to target-only greedy decode (the exactness the tests
    assert), so a router can mix speculative and plain replicas freely.

    ``target_config``/``draft_config``: CONFIGS names or LlamaConfig
    instances; they must share a vocabulary.  Batch-1 requests only
    (speculation targets the low-batch latency regime; use
    ContinuousReplica for throughput batching).
    """
    import jax
    import numpy as np
    from ..models import llama
    from ..models.speculative import speculative_generate

    def resolve(config):
        return (llama.CONFIGS[config] if isinstance(config, str)
                else config)
    target_cfg = resolve(target_config)
    draft_cfg = resolve(draft_config)
    if target_cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    target_params = llama.init_params(target_cfg,
                                      jax.random.PRNGKey(seed))
    if quantize:
        target_params = llama.quantize_params(target_params)
    draft_params = llama.init_params(draft_cfg,
                                     jax.random.PRNGKey(draft_seed))

    def infer(inputs: Dict) -> Dict:
        prompt = np.asarray(inputs["tokens"], np.int32).reshape(-1)
        new = int(np.asarray(inputs.get("max_new_tokens",
                                        max_new_tokens)))
        # speculative_generate bounds by BOTH models' max_seq_len (the
        # draft runs the same positions).
        max_seq = min(target_cfg.max_seq_len, draft_cfg.max_seq_len)
        budget = max_seq - len(prompt) - k - 1
        if budget <= 0:
            return {"error": f"prompt_len {len(prompt)} too long for "
                             f"max_seq {max_seq} with k={k} "
                             "speculation"}
        new = min(new, budget)
        generated, stats = speculative_generate(
            target_params, draft_params, prompt, new, target_cfg,
            draft_cfg, k=k)
        return {"tokens_out": np.concatenate(
                    [prompt, np.asarray(generated, np.int32)])[None],
                "acceptance_rate": np.float32(stats.acceptance_rate),
                "tokens_per_target_pass": np.float32(
                    stats.tokens_per_target_pass)}

    return infer


def make_constrained_infer(config_name: str = "tiny", automaton=None,
                           quantize: bool = False,
                           max_new_tokens: int = 16, seed: int = 0,
                           temperature: float = 0.0) -> Callable:
    """Build a ModelReplica ``infer`` callable whose outputs are
    guaranteed grammatical: a token-DFA masks every decode step
    (:mod:`~..models.constrained`), so the replica can ONLY emit
    sequences the grammar accepts — the hard-guarantee upgrade of the
    reference's prompt-and-regex robot commanding.  Responses carry
    ``tokens_out`` (each row is the grammatical output followed by
    ``pad_token`` zeros once its state went terminal — trim at the
    grammar's end marker) and per-row ``accepted`` flags."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models import llama
    from ..models.constrained import constrained_generate

    if automaton is None:
        raise ValueError("make_constrained_infer requires automaton=")
    config = llama.CONFIGS[config_name]
    if automaton.vocab != config.vocab_size:
        raise ValueError(
            f"automaton vocab {automaton.vocab} != model vocab "
            f"{config.vocab_size}")
    params = llama.init_params(config, jax.random.PRNGKey(seed))
    if quantize:
        params = llama.quantize_params(params)
    # Device-resident once: re-uploading (n_states, vocab) masks per
    # request would put a host transfer on the serving hot path.
    allowed = jnp.asarray(automaton.allowed)
    next_state = jnp.asarray(automaton.next_state)

    def infer(inputs: Dict) -> Dict:
        request = _coerce_request(inputs, config, max_new_tokens)
        if isinstance(request, dict):
            return request
        tokens, prompt_len, new = request
        cache = llama.init_cache(config, tokens.shape[0],
                                 prompt_len + new)
        logits, cache = llama.prefill(params, tokens, cache, config)
        seed_req = int(np.asarray(inputs.get("seed", 0)))
        out, states, _ = constrained_generate(
            params, logits[:, -1], cache, jnp.int32(prompt_len), new,
            config, allowed, next_state, temperature=temperature,
            rng_key=jax.random.PRNGKey(seed_req))
        accepted = automaton.accepting[np.asarray(states)]
        return {"tokens_out": np.asarray(out),
                "accepted": accepted.astype(np.int32)}

    return infer

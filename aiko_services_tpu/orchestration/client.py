"""Client side of the serving wire protocol.

Every replica kind (:class:`~.serving.ModelReplica`,
:class:`~.continuous.ContinuousReplica`, a
:class:`~.serving.ReplicaRouter` front) speaks the same idiom:
``(infer request_id response_topic swag)`` in, ``(infer_response …)``
out, with optional ``(infer_partial …)`` streaming increments and
``(infer_cancel id)``.  :class:`InferClient` packages that idiom so an
application never hand-rolls S-expressions — the serving analog of the
reference's ``get_actor_mqtt`` reflection proxies
(reference main/transport/transport_mqtt.py:122-141; those are
fire-and-forget, while inference needs a response/streaming channel,
hence a dedicated client).

Futures, not blocking waits: the event engine may be driven by a
VirtualClock in tests or run in a thread in an application, so
``submit`` returns an :class:`InferFuture` that fills as messages
arrive; ``wait`` blocks on a condition variable that the response
handler wakes (real engines only).
"""

from __future__ import annotations

import itertools
import threading
import uuid
from typing import Callable, Dict, List, Optional

import numpy as np

from ..obs import trace
from ..pipeline.codec import decode_swag, encode_swag
from ..utils.sexpr import generate, parse

__all__ = ["InferClient", "InferFuture"]


class InferFuture:
    """Fills as the replica responds; readable at any time."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        #: tokens streamed so far (partials; equals the final sequence
        #: once done when the request streamed).
        self.partial_tokens: List[int] = []
        self.outputs: Optional[Dict] = None      # full response swag
        self.error: Optional[str] = None
        self.done = False
        self.on_partial: Optional[Callable[[List[int]], None]] = None
        #: Full request span tree (root + router + replica + kv
        #: source spans) when tracing was on at submit — the remote
        #: spans ride back on the response's ``trace_spans`` field.
        self.spans: List = []
        self._root_span = None
        self._event = threading.Event()

    def _resolve(self, outputs: Optional[Dict], error) -> None:
        """Terminal transition: set results, then wake waiters."""
        if self.done:
            return
        if outputs is not None:
            self.outputs = outputs
        self.error = str(error) if error is not None else None
        self.done = True
        self._event.set()

    @property
    def tokens(self) -> List[int]:
        """Final tokens when done, streamed prefix otherwise."""
        if self.outputs is not None and "tokens_out" in self.outputs:
            return [int(t) for t in
                    np.asarray(self.outputs["tokens_out"])]
        return list(self.partial_tokens)


class InferClient:
    """Submit inference requests to a replica (or router) topic and
    collect responses on a private reply topic."""

    def __init__(self, process, topic_in: str):
        self.process = process
        self.topic_in = topic_in
        self._futures: Dict[str, InferFuture] = {}
        # Globally unique client id: request ids must not collide
        # across OS processes sharing one replica, or a cancel from
        # one client could retire another's request.
        self._uid = uuid.uuid4().hex[:10]
        self._counter = itertools.count()
        self.response_topic = (f"{process.topic_path_process}"
                               f"/infer_client/{self._uid}")
        process.add_message_handler(self._on_message,
                                    self.response_topic)

    # ------------------------------------------------------------- #

    def submit(self, tokens, max_new_tokens: int = 16,
               stream: bool = False, adapter: Optional[str] = None,
               temperature: float = 0.0, top_p: float = 1.0,
               on_partial=None,
               deadline_s: Optional[float] = None,
               request_id: Optional[str] = None) -> InferFuture:
        """Send one ``(infer …)``; returns the future immediately.

        ``deadline_s`` is a client-relative budget: the replica rejects
        the request at admission or evicts it from its slot once the
        budget elapses (``error="deadline_exceeded"``), and routers
        stop re-dispatching it.
        """
        swag: Dict = {"tokens": np.asarray(tokens, np.int32),
                      "max_new_tokens": int(max_new_tokens)}
        if stream:
            swag["stream"] = 1
        if adapter:
            swag["adapter"] = adapter
        if temperature:
            swag["temperature"] = float(temperature)
            swag["top_p"] = float(top_p)
        if deadline_s is not None:
            swag["deadline_ms"] = int(float(deadline_s) * 1e3)
        return self._send("infer", swag, on_partial=on_partial,
                          request_id=request_id)

    def load_adapter(self, name: str, path: str) -> InferFuture:
        """Hot-deploy a PEFT-layout adapter checkpoint directory to
        the replica; the future resolves with the ack (``ok``/
        ``error`` and the loaded-adapter list).  ContinuousReplica
        only — other replica kinds ack with ``unsupported_command``."""
        return self._send("adapter_load", {"name": name,
                                           "path": path}, prefix="a")

    def unload_adapter(self, name: str) -> InferFuture:
        return self._send("adapter_unload", {"name": name},
                          prefix="a")

    def _send(self, command: str, swag: Dict, on_partial=None,
              request_id: Optional[str] = None,
              prefix: str = "c") -> InferFuture:
        """Register a future and publish ONE wire command carrying
        (request_id, reply topic, swag) — the shared tail of every
        request kind."""
        request_id = request_id or \
            f"{prefix}{self._uid}_{next(self._counter)}"
        future = InferFuture(request_id)
        future.on_partial = on_partial
        if trace.TRACER is not None and command == "infer":
            span = trace.TRACER.start_span(
                "infer", attrs={"request_id": request_id,
                                "target": self.topic_in})
            swag = dict(swag, trace=trace.inject(span))
            future._root_span = span
        self._futures[request_id] = future
        self.process.message.publish(
            self.topic_in,
            generate(command, [request_id, self.response_topic,
                               encode_swag(swag)]))
        return future

    def cancel(self, future: InferFuture) -> None:
        """``(infer_cancel …)`` — the cancelled response resolves the
        future with ``error="cancelled"`` and any partial tokens.  The
        reply topic rides along so a router can resolve cancels it no
        longer has a route for (``error="cancel_unrouted"``)."""
        self.process.message.publish(
            self.topic_in,
            generate("infer_cancel", [future.request_id,
                                      self.response_topic]))

    def wait(self, future: InferFuture, timeout: float = 30.0,
             poll: Optional[float] = None) -> InferFuture:
        """Block until done — for REAL engines (an engine thread is
        pumping); under a VirtualClock drive the engine instead.

        Sleeps on the future's event (woken by the response handler —
        no polling; ``poll`` is accepted for back-compat and ignored).
        On timeout the future resolves with ``error="timeout"`` —
        distinguishable from a replica-side ``error="cancelled"`` —
        and is forgotten, so a late reply is dropped rather than
        resolving an abandoned request.
        """
        del poll
        if not future._event.wait(timeout):
            # Lost the race vs. _on_message?  _resolve is idempotent:
            # whichever terminal state landed first stands.
            future._resolve(None, "timeout")
            self.forget(future)
        return future

    def forget(self, future: InferFuture) -> None:
        """Abandon a request: late replies/partials for it are dropped
        (the entry for a target that never responds otherwise lives as
        long as the client)."""
        self._futures.pop(future.request_id, None)

    # ------------------------------------------------------------- #

    def _on_message(self, _topic, payload):
        command, params = parse(payload)
        if command not in ("infer_response", "infer_partial",
                           "adapter_response") or len(params) < 2:
            return
        future = self._futures.get(str(params[0]))
        if future is None:
            return
        try:
            outputs = decode_swag(params[1])
        except Exception:
            # A mangled final response still resolves the future — a
            # corrupt partial is merely dropped (the final response
            # carries the authoritative token list anyway).
            if command == "infer_partial":
                return
            future._resolve({"error": "corrupt_response"},
                            "corrupt_response")
            self._futures.pop(future.request_id, None)
            return
        if command == "infer_partial":
            if future._root_span is not None and \
                    not future.partial_tokens:
                future._root_span.mark("client_first_token")
            increment = [int(t) for t in
                         np.asarray(outputs["tokens_out"])]
            future.partial_tokens.extend(increment)
            if future.on_partial is not None:
                future.on_partial(increment)
            return
        if future._root_span is not None:
            root = future._root_span
            if trace.TRACER is not None:
                trace.TRACER.finish(root)
            elif root.end is None:
                root.end = root.start
            remote = outputs.get("trace_spans")
            future.spans = [root] + (trace.decode_spans(remote)
                                     if remote else [])
        future._resolve(outputs, outputs.get("error"))
        # pop, not del: a concurrent forget() may have removed the
        # entry between the get() above and here (documented usage
        # after a wait() timeout).
        self._futures.pop(future.request_id, None)

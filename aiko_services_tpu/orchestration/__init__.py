from .process_manager import ProcessManager
from .lifecycle import (
    LifeCycleManager, LifeCycleClient,
    HANDSHAKE_LEASE_TIME, DELETION_LEASE_TIME,
)

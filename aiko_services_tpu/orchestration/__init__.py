from .process_manager import ProcessManager
from .lifecycle import (
    LifeCycleManager, LifeCycleClient,
    HANDSHAKE_LEASE_TIME, DELETION_LEASE_TIME,
)
from .serving import (
    ModelReplica, ReplicaRouter, REPLICA_PROTOCOL, make_llama_infer,
)
from .continuous import (
    ContinuousBatchingServer, ContinuousReplica, DecodeRequest,
)
from .paged import PagedContinuousServer
from .client import InferClient, InferFuture
from .trainer import TrainerActor, TRAINER_PROTOCOL
from .autoscaler import (
    FleetAutoscaler, AutoscalerPolicy, FleetSnapshot, ReplicaView,
    PendingView, DeathEvent, Action, ControllerState, decide,
    AUTOSCALER_PROTOCOL, manager_spawner, manager_terminator,
)

"""Serving load generator: drive replicas over the wire, report tails.

The reference's only load harness is multitude (pipelines at a fixed
frame rate, ``examples/pipeline/multitude``); the serving stack
(ModelReplica / ContinuousReplica / ReplicaRouter) needs its own:
open-loop request injection at a target rate with latency tails, the
standard way to expose queueing behavior that a closed loop hides.

    generator = LoadGenerator(process, target_topic="ns/h/1/0/in",
                              payload_fn=make_payload, rate_hz=50)
    report = generator.run(n_requests=500)
    report.p50_ms, report.p99_ms, report.throughput_rps, report.errors

Open-loop: requests are posted on schedule regardless of completions
(late responses still count; missing ones surface as ``timeouts``).
Works over any transport the process speaks (loopback in tests, the
built-in MQTT broker cross-process).
"""

from __future__ import annotations

import dataclasses
import os
import statistics
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import trace
from ..pipeline.codec import encode_swag
from ..utils.sexpr import generate, parse

__all__ = ["LoadGenerator", "LoadReport", "service_scale_sweep",
           "chaos_schedule", "run_chaos", "shared_prefix_payloads",
           "run_shared_prefix", "fleet_latency", "diurnal_trace",
           "elastic_chaos_schedule", "run_elastic",
           "run_elastic_chaos", "run_longtail", "run_restart",
           "run_restart_ab", "run_compile_cache_ab", "main"]

#: Per-phase latency keys the replicas stamp on responses, in report
#: order (``kv_restore`` is the cross-replica transfer phase).
PHASES = ("queue", "prefill", "decode", "kv_restore")


@dataclasses.dataclass
class LoadReport:
    sent: int
    completed: int
    errors: int
    timeouts: int
    elapsed_s: float
    latencies_ms: List[float]
    tokens_total: int = 0
    #: Server-reported time-to-first-token per completed request (the
    #: replica stamps ``ttft_ms`` on the wire response) — what SLOs
    #: watch; wire p50/p99 above includes full generation time.
    ttfts_ms: List[float] = dataclasses.field(default_factory=list)
    #: Optional server-side counters snapshot (``server.stats()`` or
    #: :func:`~..orchestration.serving.serving_telemetry` payload)
    #: attached by the harness after the run — ties the wire-level
    #: tails to the decode-attention path that produced them.
    server_stats: Optional[Dict] = None
    #: error string -> count.  ``errors`` alone can't distinguish a
    #: healthy shed (``overloaded``/``deadline_exceeded`` — the
    #: backpressure design working) from real failures.
    error_kinds: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: Fleet prefix-cache hit fraction over the run
    #: (``Σ prefix_hits / Σ (prefix_hits + prefix_misses)`` across
    #: replicas; None when the fleet has no prefix caches) — attached
    #: by the harness from server stats, like ``server_stats``.
    prefix_hit_rate: Optional[float] = None
    #: Fraction of prefix HITS that adopted blocks restored from the
    #: host tier (``Σ prefix_hits_host / Σ prefix_hits``; None when
    #: the fleet has no host tier or took no hits) — the tiered-KV
    #: number the longtail workload reports: hits the HBM pool alone
    #: would have lost.
    prefix_hit_rate_host: Optional[float] = None
    #: Total cross-replica KV bytes moved during the run (Σ replica
    #: ``kv_transfer_bytes`` deltas).
    kv_transfer_bytes: int = 0
    #: phase -> per-request latencies (ms) as stamped by the replicas
    #: (``queue_ms``/``prefill_ms``/``decode_ms``/``kv_restore_ms``)
    #: — the per-phase breakdown :meth:`phase_table` renders.
    phase_ms: Dict[str, List[float]] = dataclasses.field(
        default_factory=dict)
    #: Fleet-level quantiles from EXACT merges of the replicas'
    #: fixed-bucket histograms (phase -> {p50_ms, p95_ms, p99_ms,
    #: count}); attached by the harness via :func:`fleet_latency`.
    fleet_latency_ms: Optional[Dict[str, Dict[str, float]]] = None
    #: TTFT SLO (ms) goodput is judged against; None = goodput is raw
    #: throughput.  Attached by the harness (``run_elastic``).
    slo_ttft_ms: Optional[float] = None
    #: ∫ replica-count dt over the run — the denominator of
    #: :attr:`goodput_per_replica` (autoscaler share delta, or
    #: ``N * elapsed_s`` for a static fleet).
    replica_seconds: float = 0.0
    #: Final ``infer_response`` arriving for an already-completed
    #: request id — the double-delivery a drain/re-dispatch chaos run
    #: asserts is ZERO.
    duplicate_finals: int = 0
    #: replica topic/name -> TP degree (chips per replica), attached
    #: by the harness from fleet telemetry — per-chip efficiency needs
    #: the chip count, not the replica count, as denominator.
    replica_tp: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: request id -> final token list as delivered on the wire,
    #: attached by the harness — lets A/B runs over the same seeded
    #: payload sequence assert BIT-EXACT outputs (e.g. tier-on chaos
    #: vs tier-off chaos must produce identical greedy tokens).
    final_tokens: Dict[str, List[int]] = dataclasses.field(
        default_factory=dict)
    #: request id -> per-spec-round accepted-token counts as stamped
    #: by draft-enabled replicas; empty when the fleet runs no draft.
    #: An A/B run reports the acceptance distribution per request —
    #: the number that explains WHERE speculative decoding paid off
    #: (long accepted runs) and where it degraded to plain decode.
    spec_accept_hist: Dict[str, List[int]] = dataclasses.field(
        default_factory=dict)
    #: Fleet speculative counters (Σ over replicas of the server
    #: ``spec_*`` stats); None when no replica runs a draft.
    spec_stats: Optional[Dict] = None
    #: Warmup-vs-steady split (PR 14): the first-step compile tax
    #: reported SEPARATELY from steady throughput.  ``warmup_s`` is
    #: the harness-measured window before the compile ledger's fence
    #: dropped; ``warmup_compiles`` is what XLA compiled inside it;
    #: ``compiles_steady_state`` is what compiled AFTER it — the chaos
    #: gate asserts this stays ZERO on the paged path (any steady
    #: compile is a pow2 bucket-discipline regression).
    warmup_s: float = 0.0
    warmup_compiles: int = 0
    compiles_steady_state: int = 0
    #: Tokens/s measured over the steady window only (completed-token
    #: throughput with the warmup window excluded from the clock);
    #: 0.0 when the harness ran no ledger.
    steady_tokens_per_sec: float = 0.0
    #: Persistent compilation-cache counters over the run
    #: (hits/misses/saved_ms; None when no ledger was installed).
    compile_cache: Optional[Dict] = None
    #: tier -> high-water-mark bytes over the run, from the memory
    #: accountant's flow-integrated occupancy (PR 15) — a TRUE peak,
    #: not an end-of-run sample.  Empty when no ``pool_audit.AUDITOR``
    #: was installed for the run.
    peak_kv_bytes: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    #: End-of-run pool census from the (first paged) server —
    #: ``PagedContinuousServer.pool_census()``; None on non-paged
    #: fleets.  :meth:`pool_census` renders it.
    census: Optional[Dict] = None
    #: Multi-tenant adapters: client-observed cold starts — an
    #: ``unknown_adapter`` rejection is a request that landed on a
    #: replica without the tenant's factors and would force a factor
    #: re-upload before retry.  The adapter-aware arm of the
    #: multitenant A/B asserts this is ZERO whenever the adapter is
    #: warm anywhere in the fleet.
    adapter_cold_starts: int = 0
    #: Router's warm/cold split over adapter-tagged routes (mirrors
    #: ``router.counters``; both 0 under the adapter-blind baseline,
    #: which never inspects the adapter field).
    adapter_warm_routes: int = 0
    adapter_cold_routes: int = 0

    def pool_census(self) -> str:
        """Readable end-of-run memory summary: per-tier blocks/bytes
        (with the run's peak when the accountant tracked one) plus the
        pool state histogram."""
        if not self.census:
            return "(no pool census attached)"
        lines = [f"{'tier':<6}{'blocks':>9}{'bytes':>13}{'peak':>13}"]
        for tier in ("hbm", "host", "disk"):
            info = self.census.get("tiers", {}).get(tier, {})
            peak = self.peak_kv_bytes.get(tier)
            lines.append(
                f"{tier:<6}{int(info.get('blocks', 0)):>9}"
                f"{int(info.get('bytes', 0)):>13}"
                f"{peak if peak is not None else '-':>13}")
        states = self.census.get("states", {})
        if states:
            lines.append("states: " + ", ".join(
                f"{state}={count}" for state, count
                in sorted(states.items()) if count))
        return "\n".join(lines)

    @property
    def lost(self) -> int:
        """Requests neither completed nor error-terminal (hung or
        dropped) — the number a chaos run asserts is ZERO."""
        return self.sent - self.completed - self.errors

    @property
    def throughput_rps(self) -> float:
        return self.completed / self.elapsed_s if self.elapsed_s else 0.0

    @property
    def throughput_tps(self) -> float:
        """Generated tokens per second — the number the device-resident
        serving work moves; req/s alone hides per-request length."""
        return (self.tokens_total / self.elapsed_s
                if self.elapsed_s else 0.0)

    @property
    def good_completions(self) -> int:
        """Completions WITHIN the TTFT SLO (DistServe's goodput
        numerator).  Completions without a ``ttft_ms`` stamp count as
        good — only a measured breach disqualifies."""
        if self.slo_ttft_ms is None:
            return self.completed
        within = sum(1 for ttft in self.ttfts_ms
                     if ttft <= self.slo_ttft_ms)
        unstamped = self.completed - len(self.ttfts_ms)
        return within + max(0, unstamped)

    @property
    def goodput_rps(self) -> float:
        """SLO-attaining completions per second."""
        return (self.good_completions / self.elapsed_s
                if self.elapsed_s else 0.0)

    @property
    def avg_replicas(self) -> float:
        """Time-averaged fleet size over the run."""
        return (self.replica_seconds / self.elapsed_s
                if self.elapsed_s else 0.0)

    @property
    def goodput_per_replica(self) -> float:
        """Goodput divided by average fleet size — the efficiency
        number an autoscaled fleet must beat a static-peak fleet on
        (serving the valleys with fewer replicas is the whole
        point)."""
        average = self.avg_replicas
        return self.goodput_rps / average if average else 0.0

    @staticmethod
    def _quantile(values: List[float], q: float) -> float:
        if not values:
            return 0.0
        ordered = sorted(values)
        index = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[index]

    @property
    def p50_ms(self) -> float:
        return (statistics.median(self.latencies_ms)
                if self.latencies_ms else 0.0)

    @property
    def p99_ms(self) -> float:
        return self._quantile(self.latencies_ms, 0.99)

    @property
    def ttft_p50_ms(self) -> float:
        return (statistics.median(self.ttfts_ms)
                if self.ttfts_ms else 0.0)

    @property
    def ttft_p95_ms(self) -> float:
        return self._quantile(self.ttfts_ms, 0.95)

    def phase_table(self) -> str:
        """Per-phase latency breakdown (queue/prefill/decode/
        kv_restore) — WHERE a slow run spent its time, one line per
        phase with nearest-rank quantiles over this run's samples."""
        if not self.phase_ms:
            return "(no per-phase latency samples)"
        lines = [f"{'phase':<12}{'p50_ms':>9}{'p95_ms':>9}"
                 f"{'p99_ms':>9}{'n':>7}"]
        for phase in PHASES:
            values = self.phase_ms.get(phase)
            if not values:
                continue
            lines.append(
                f"{phase:<12}"
                f"{self._quantile(values, 0.5):>9.1f}"
                f"{self._quantile(values, 0.95):>9.1f}"
                f"{self._quantile(values, 0.99):>9.1f}"
                f"{len(values):>7}")
        return "\n".join(lines)

    def __repr__(self):
        attn = ""
        if self.server_stats and "decode_attention_path" in \
                self.server_stats:
            attn = (f", attn={self.server_stats['decode_attention_path']}"
                    f"/{self.server_stats.get('blocks_read_per_step', 0)}"
                    f" blk/step")
        ttft = (f", ttft_p50={self.ttft_p50_ms:.1f}/"
                f"p95={self.ttft_p95_ms:.1f} ms"
                if self.ttfts_ms else "")
        kinds = (", kinds=" + "/".join(
            f"{k}:{n}" for k, n in sorted(self.error_kinds.items()))
            if self.error_kinds else "")
        prefix = (f", prefix_hit={self.prefix_hit_rate:.0%}"
                  if self.prefix_hit_rate is not None else "")
        if self.prefix_hit_rate_host is not None:
            prefix += f" ({self.prefix_hit_rate_host:.0%} via host tier)"
        kv = (f", kv_xfer={self.kv_transfer_bytes}B"
              if self.kv_transfer_bytes else "")
        adapters = ""
        if (self.adapter_cold_starts or self.adapter_warm_routes
                or self.adapter_cold_routes):
            adapters = (f", adapters={self.adapter_warm_routes} warm"
                        f"/{self.adapter_cold_routes} cold routes, "
                        f"{self.adapter_cold_starts} cold starts")
        tp = ""
        if any(degree > 1 for degree in self.replica_tp.values()):
            tp = ", tp=" + "/".join(
                f"{name}:{degree}" for name, degree
                in sorted(self.replica_tp.items()))
        goodput = ""
        if self.slo_ttft_ms is not None:
            goodput = (f", goodput={self.goodput_rps:.1f} req/s"
                       f"@{self.slo_ttft_ms:g}ms")
            if self.replica_seconds:
                goodput += (f", {self.goodput_per_replica:.2f} "
                            f"req/s/replica (avg "
                            f"{self.avg_replicas:.2f})")
        compile_note = ""
        if self.warmup_compiles or self.compiles_steady_state:
            compile_note = (
                f", compiles={self.warmup_compiles} warmup"
                f"/{self.compiles_steady_state} steady"
                f" (warmup {self.warmup_s:.1f}s")
            if self.steady_tokens_per_sec:
                compile_note += (f", steady "
                                 f"{self.steady_tokens_per_sec:.1f} "
                                 f"tok/s")
            compile_note += ")"
        return (f"LoadReport(sent={self.sent}, done={self.completed}, "
                f"errors={self.errors}{kinds}, "
                f"timeouts={self.timeouts}, "
                f"{self.throughput_rps:.1f} req/s, "
                f"{self.throughput_tps:.1f} tok/s, "
                f"p50={self.p50_ms:.1f} ms, p99={self.p99_ms:.1f} ms"
                f"{ttft}{goodput}{prefix}{kv}{adapters}{tp}{attn}"
                f"{compile_note})")


class LoadGenerator:
    """Open-loop ``(infer …)`` load against a replica or router topic."""

    def __init__(self, process, target_topic: str,
                 payload_fn: Callable[[int], Dict], rate_hz: float = 50.0,
                 response_topic: Optional[str] = None,
                 clock=None, sleep=None):
        self.process = process
        self.target_topic = target_topic
        self.payload_fn = payload_fn
        self.rate_hz = rate_hz
        self.response_topic = response_topic or (
            f"loadgen/{uuid.uuid4().hex[:8]}/response")
        self._clock = clock or time.perf_counter
        self._sleep = sleep or time.sleep
        self._sent_at: Dict[str, float] = {}
        self._latencies: List[float] = []
        self._ttfts: List[float] = []
        self._phases: Dict[str, List[float]] = {}
        self._errors = 0
        self._error_kinds: Dict[str, int] = {}
        self._tokens = 0
        self._run_index = 0
        #: request_id -> concatenated streaming increments as
        #: delivered (``infer_partial``); public so chaos tests can
        #: assert partials == final tokens with no double-delivery.
        self.partial_tokens: Dict[str, List[int]] = {}
        #: request_id -> the final response's token list.
        self.final_tokens: Dict[str, List[int]] = {}
        #: request_id -> per-spec-round accepted-token counts as
        #: stamped by draft-enabled replicas (absent otherwise).
        self.spec_accept_hist: Dict[str, List[int]] = {}
        self._completed_ids: set = set()
        self._duplicate_finals = 0
        # Tracing (rides the global trace.TRACER switchboard): root
        # span per request, full ride-back tree kept per request id
        # for dump_traces().
        self._root_spans: Dict[str, object] = {}
        self._traces: List[Tuple[float, str, List]] = []
        process.add_message_handler(self._on_response,
                                    self.response_topic)

    def close(self):
        """Deregister the response handler (and its subscription) —
        required in long-lived processes doing rate sweeps, or dead
        generators keep receiving."""
        self.process.remove_message_handler(self._on_response,
                                            self.response_topic)

    def _on_response(self, _topic: str, payload: str):
        command, params = parse(payload)
        if command == "infer_partial" and len(params) > 1:
            self._on_partial(str(params[0]), params[1])
            return
        if command != "infer_response" or not params:
            return
        request_id = str(params[0])
        # Look up (don't pop yet): the drain loop in run_trace exits
        # the moment _sent_at goes empty, so the request must stay in
        # it until its latency/error is recorded — popping first lets
        # the report snapshot race ahead of the append and under-count
        # completions.  The pop happens at the end of this handler.
        started = self._sent_at.get(request_id)
        if started is None:
            if request_id in self._completed_ids:
                # A second FINAL for a finished request: the
                # double-delivery chaos runs must never see.
                self._duplicate_finals += 1
            return
        self._completed_ids.add(request_id)
        outputs = params[1] if len(params) > 1 else {}
        self._record_final_tokens(request_id, outputs)
        if isinstance(outputs, dict) and "spec_accepted_rounds" in outputs:
            try:
                from ..pipeline.codec import decode_value
                import numpy as np
                self.spec_accept_hist[request_id] = [
                    int(count) for count in np.asarray(decode_value(
                        outputs["spec_accepted_rounds"])).reshape(-1)]
            except Exception:  # noqa: BLE001 - telemetry only
                pass
        self._collect_trace(request_id, started, outputs)
        if isinstance(outputs, dict) and "error" in outputs:
            self._errors += 1
            # Values on the wire are codec-tagged ("s:overloaded") —
            # decode, so error_kinds keys match the error strings the
            # replicas publish.
            try:
                from ..pipeline.codec import decode_value
                kind = str(decode_value(outputs["error"]))
            except Exception:  # noqa: BLE001 - count it regardless
                kind = str(outputs["error"])
            self._error_kinds[kind] = \
                self._error_kinds.get(kind, 0) + 1
        else:
            self._latencies.append((self._clock() - started) * 1e3)
            if isinstance(outputs, dict) and "ttft_ms" in outputs:
                try:
                    from ..pipeline.codec import decode_value
                    self._ttfts.append(
                        float(decode_value(outputs["ttft_ms"])))
                except Exception:  # noqa: BLE001 - telemetry only
                    pass
            if isinstance(outputs, dict) and "tokens_out" in outputs:
                try:
                    from ..pipeline.codec import decode_value
                    import numpy as np
                    self._tokens += int(np.asarray(
                        decode_value(outputs["tokens_out"])).size)
                except Exception:  # noqa: BLE001 - telemetry only
                    pass
            if isinstance(outputs, dict):
                for phase in PHASES:
                    if f"{phase}_ms" not in outputs:
                        continue
                    try:
                        from ..pipeline.codec import decode_value
                        self._phases.setdefault(phase, []).append(
                            float(decode_value(outputs[f"{phase}_ms"])))
                    except Exception:  # noqa: BLE001 - telemetry only
                        pass
        # Everything recorded — only now mark the request finished so
        # run_trace cannot observe "done" before the stats landed.
        self._sent_at.pop(request_id, None)

    def _on_partial(self, request_id: str, outputs) -> None:
        """Accumulate a streaming increment (chaos tests assert the
        concatenation equals the final token list — a drained replica
        finishing in place must never re-stream)."""
        if not isinstance(outputs, dict) or "tokens_out" not in outputs:
            return
        try:
            from ..pipeline.codec import decode_value
            import numpy as np
            increment = [int(t) for t in
                         np.asarray(decode_value(outputs["tokens_out"]))
                         .reshape(-1)]
        except Exception:  # noqa: BLE001 - telemetry only
            return
        self.partial_tokens.setdefault(request_id, []).extend(increment)

    def _record_final_tokens(self, request_id: str, outputs) -> None:
        if not isinstance(outputs, dict) or "tokens_out" not in outputs:
            return
        try:
            from ..pipeline.codec import decode_value
            import numpy as np
            self.final_tokens[request_id] = [
                int(t) for t in
                np.asarray(decode_value(outputs["tokens_out"]))
                .reshape(-1)]
        except Exception:  # noqa: BLE001 - telemetry only
            pass

    def _collect_trace(self, request_id: str, started: float,
                       outputs) -> None:
        """Close this request's root span and keep the full ride-back
        tree (root + router + replica + kv source spans), keyed by
        wire latency so :meth:`dump_traces` can rank by slowest."""
        span = self._root_spans.pop(request_id, None)
        if span is None:
            return
        if trace.TRACER is not None:
            trace.TRACER.finish(span)
        elif span.end is None:
            span.end = span.start
        spans = [span]
        if isinstance(outputs, dict) and "trace_spans" in outputs:
            try:
                from ..pipeline.codec import decode_value
                spans.extend(trace.decode_spans(
                    str(decode_value(outputs["trace_spans"]))))
            except Exception:  # noqa: BLE001 - telemetry only
                pass
        self._traces.append(((self._clock() - started) * 1e3,
                             request_id, spans))

    def dump_traces(self, directory: str, top_k: int = 5) -> List[str]:
        """Export the ``top_k`` SLOWEST traced requests' span trees as
        Chrome trace-event JSON files (Perfetto-loadable), one file
        per request, named ``trace_<rank>_<request_id>.json``.
        Returns the written paths (empty when tracing was off)."""
        if not self._traces:
            return []
        os.makedirs(directory, exist_ok=True)
        ranked = sorted(self._traces,
                        key=lambda entry: -entry[0])[:top_k]
        paths = []
        for rank, (_total_ms, request_id, spans) in enumerate(ranked):
            path = os.path.join(
                directory, f"trace_{rank:02d}_{request_id}.json")
            trace.export_chrome(path, spans)
            paths.append(path)
        return paths

    def run(self, n_requests: int, drain_timeout_s: float = 30.0,
            pump: Optional[Callable[[], None]] = None) -> LoadReport:
        """Send ``n_requests`` at ``rate_hz``, then wait for stragglers.
        ``pump`` (optional) is called between waits — pass
        ``engine.drain`` when driving a VirtualClock engine in tests."""
        interval = 1.0 / self.rate_hz if self.rate_hz > 0 else 0.0
        return self.run_trace(
            [index * interval for index in range(n_requests)],
            drain_timeout_s=drain_timeout_s, pump=pump)

    def run_trace(self, send_offsets_s: List[float],
                  drain_timeout_s: float = 30.0,
                  pump: Optional[Callable[[], None]] = None
                  ) -> LoadReport:
        """Open-loop injection on an explicit schedule: request ``i``
        is sent ``send_offsets_s[i]`` seconds after the run starts
        (:func:`diurnal_trace` generates such schedules).  ``run()``
        is the constant-rate special case."""
        # Per-run state: runs are re-runnable (rate sweeps), and ids
        # are unique per run so a run-1 straggler cannot satisfy a
        # run-2 request.
        self._sent_at.clear()
        self._latencies = []
        self._ttfts = []
        self._phases = {}
        self._errors = 0
        self._error_kinds = {}
        self._tokens = 0
        self._root_spans.clear()
        self._traces = []
        self.partial_tokens = {}
        self.final_tokens = {}
        self.spec_accept_hist = {}
        self._completed_ids = set()
        self._duplicate_finals = 0
        self._run_index += 1
        run_tag = self._run_index
        started = self._clock()
        for index, offset in enumerate(send_offsets_s):
            delay = started + offset - self._clock()
            if delay > 0:
                self._sleep(delay)
            request_id = f"lg{run_tag}_{index}"
            swag = self.payload_fn(index)
            if trace.TRACER is not None:
                span = trace.TRACER.start_span(
                    "infer", attrs={"request_id": request_id,
                                    "target": self.target_topic})
                swag = dict(swag, trace=trace.inject(span))
                self._root_spans[request_id] = span
            self._sent_at[request_id] = self._clock()
            self.process.message.publish(
                self.target_topic,
                generate("infer",
                         [request_id, self.response_topic,
                          encode_swag(swag)]))
            if pump is not None:
                pump()
        deadline = self._clock() + drain_timeout_s
        while self._sent_at and self._clock() < deadline:
            if pump is not None:
                pump()
            self._sleep(0.01)
        elapsed = self._clock() - started
        return LoadReport(sent=len(send_offsets_s),
                          completed=len(self._latencies),
                          errors=self._errors,
                          timeouts=len(self._sent_at),
                          elapsed_s=elapsed,
                          latencies_ms=list(self._latencies),
                          tokens_total=self._tokens,
                          ttfts_ms=list(self._ttfts),
                          error_kinds=dict(self._error_kinds),
                          phase_ms={phase: list(values) for phase,
                                    values in self._phases.items()},
                          duplicate_finals=self._duplicate_finals)


def service_scale_sweep(services: int, broker: str = "scale-sweep",
                        namespace: str = "scale",
                        create_timeout_s: float = 120.0,
                        rpc_timeout_s: float = 120.0) -> dict:
    """Demonstrate the reference's aspirational service density
    (1,000-10,000 services/process, reference main/process.py:45-48,
    an untested TODO there): N actors in ONE process, all discovered
    by a registrar, one RPC each through the full parse→mailbox→
    dispatch path.  Raises AssertionError if discovery or any RPC is
    incomplete within its own (separate) timeout budget.

    Shared by ``tests/test_scale.py`` and the distributed-artifact
    capture (``scripts/capture_cpu_artifacts.py``)."""
    import time as time_module

    from ..registry import Registrar
    from ..runtime import Process, actor_args, compose_instance
    from ..runtime.actor import Actor
    from ..runtime.event import EventEngine

    class Echo(Actor):
        def echo(self, value):
            self.share["last"] = value

    engine = EventEngine()
    thread = engine.run_in_thread()
    process = Process(namespace=namespace, hostname="h", pid="1",
                      engine=engine, broker=broker)
    registrar = Registrar(process=process)
    deadline = time_module.time() + 15
    while registrar.state != "primary" \
            and time_module.time() < deadline:
        time_module.sleep(0.02)
    if registrar.state != "primary":
        # Fail HERE, not as a misleading discovery-count assertion
        # 2 minutes later: nothing registers without a primary.
        process.terminate()
        engine.terminate()
        thread.join(timeout=5)
        raise TimeoutError("scale sweep: registrar never went primary")
    try:
        t0 = time_module.perf_counter()
        actors = [compose_instance(Echo, actor_args(f"svc{i}"),
                                   process=process)
                  for i in range(services)]
        create_dt = time_module.perf_counter() - t0
        deadline = time_module.time() + create_timeout_s
        while len(registrar.services) < services + 1 \
                and time_module.time() < deadline:
            time_module.sleep(0.05)
        discovered = len(registrar.services) - 1
        assert discovered == services, \
            f"registrar discovered {discovered}/{services}"

        # RPC sweep gets its OWN budget — slow discovery must not
        # starve it into a flaky delivery failure.
        t0 = time_module.perf_counter()
        for i, actor in enumerate(actors):
            process.message.publish(actor.topic_in, f"(echo {i})")
        deadline = time_module.time() + rpc_timeout_s
        while any("last" not in a.share for a in actors) \
                and time_module.time() < deadline:
            time_module.sleep(0.05)
        rpc_dt = time_module.perf_counter() - t0
        assert all(a.share.get("last") == str(i)
                   for i, a in enumerate(actors)), "RPCs missing"
        return {
            "services": services,
            "create_per_sec": round(services / create_dt),
            "registrar_discovered": discovered,
            "rpc_sweep_per_sec": round(services / rpc_dt),
            "exact_indexed_topics": len(process._exact_handlers),
            "wildcard_patterns": len(process._wildcard_handlers),
        }
    finally:
        process.terminate()
        engine.terminate()
        thread.join(timeout=5)


def shared_prefix_payloads(n_conversations: int = 4, turns: int = 4,
                           system_len: int = 48, turn_len: int = 8,
                           max_new_tokens: int = 6, vocab: int = 1024,
                           seed: int = 0, stream: bool = True
                           ) -> Callable[[int], Dict]:
    """Multi-turn chat-style workload: ``n_conversations`` interleaved
    conversations of ``turns`` turns, ALL sharing one
    ``system_len``-token system prompt, each turn re-sending the
    conversation so far plus ``turn_len`` fresh tokens — the workload
    shape where a cluster-wide prefix cache pays (every request's
    prompt head is either the shared system prompt or a prior turn's
    whole prompt).

    ``payload_fn(index)``: conversation ``index % n_conversations``,
    turn ``(index // n_conversations) % turns`` — so concurrent
    requests hit DIFFERENT conversations (interleaving, like real
    traffic) while turn order within a conversation is preserved by
    send order.  Deterministic from ``seed``."""
    import numpy as np

    rng = np.random.RandomState(seed)
    system = rng.randint(1, vocab, size=system_len).astype(np.int32)
    turn_tokens = [[rng.randint(1, vocab,
                                size=turn_len).astype(np.int32)
                    for _ in range(turns)]
                   for _ in range(n_conversations)]

    def payload_fn(index: int) -> Dict:
        conversation = index % n_conversations
        turn = (index // n_conversations) % turns
        prompt = np.concatenate(
            [system] + turn_tokens[conversation][:turn + 1])
        payload = {"tokens": prompt, "max_new_tokens": max_new_tokens}
        if stream:
            payload["stream"] = 1
        return payload

    return payload_fn


def fleet_latency(servers) -> Dict[str, Dict[str, float]]:
    """Fleet-level latency quantiles by EXACTLY merging the replicas'
    fixed-bucket phase histograms (element-wise bucket adds — the
    same numbers a router derives from the ``hist.<phase>`` EC shares
    it watches).  phase -> {p50_ms, p95_ms, p99_ms, count}."""
    from ..obs.metrics import Histogram
    out: Dict[str, Dict[str, float]] = {}
    by_phase: Dict[str, List[Histogram]] = {}
    for server in servers:
        for phase, histogram in getattr(server, "latency_hists",
                                        {}).items():
            by_phase.setdefault(phase, []).append(histogram)
    for phase, histograms in sorted(by_phase.items()):
        merged = Histogram.merged(histograms)
        if merged.count:
            out[phase] = {"p50_ms": round(merged.quantile(0.5), 1),
                          "p95_ms": round(merged.quantile(0.95), 1),
                          "p99_ms": round(merged.quantile(0.99), 1),
                          "count": merged.count}
    return out


def _fleet_kv_stats(servers) -> Dict:
    """Aggregate the kvstore + tier counters a shared-prefix or
    longtail run reports."""
    totals = dict(prefix_hits=0, prefix_misses=0, kv_transfer_bytes=0,
                  prefix_remote_hits=0, kv_transfer_failures=0,
                  kv_demotions=0, kv_restores=0, kv_host_blocks=0,
                  kv_host_bytes=0, restore_queue_depth=0,
                  prefix_hits_host=0, kv_spills=0, kv_disk_blocks=0,
                  kv_disk_bytes=0, kv_disk_restores=0,
                  kv_checksum_failures=0, kv_adopted_chains=0,
                  kv_prefetch_promotions=0)
    for server in servers:
        stats = server.stats()
        for key in totals:
            totals[key] += int(stats.get(key, 0))
    return totals


def _attach_kv_rates(report: LoadReport, totals: Dict) -> None:
    """Derive the report's hit-rate fields from fleet totals."""
    lookups = totals["prefix_hits"] + totals["prefix_misses"]
    if lookups:
        report.prefix_hit_rate = totals["prefix_hits"] / lookups
    if totals["prefix_hits"] and (totals["kv_demotions"]
                                  or totals["prefix_hits_host"]):
        report.prefix_hit_rate_host = \
            totals["prefix_hits_host"] / totals["prefix_hits"]
    report.kv_transfer_bytes = totals["kv_transfer_bytes"]


def _attach_pool_census(report: LoadReport, servers) -> None:
    """Attach the end-of-run pool census (first paged server) and,
    when a memory accountant is installed, the flow-integrated per-tier
    peak bytes (PR 15)."""
    for server in servers:
        if hasattr(server, "pool_census"):
            try:
                report.census = server.pool_census()
            except Exception:  # noqa: BLE001 - census is best-effort
                pass
            break
    from ..obs import pool_audit
    if pool_audit.AUDITOR is not None:
        report.peak_kv_bytes = {
            tier: entry["bytes"] for tier, entry
            in pool_audit.AUDITOR.accountant.peak.items()}


def _fleet_spec_stats(servers) -> Optional[Dict]:
    """Σ the per-replica speculative counters (None when no replica
    runs a draft).  Rates are recomputed from the summed raw counts —
    averaging per-replica rates would weight idle replicas equally."""
    totals: Dict[str, float] = {}
    modes: set = set()
    k_effs: list = []
    for server in servers:
        stats = server.stats()
        if "spec_rounds" not in stats:
            continue
        for key in ("spec_rounds", "spec_proposed", "spec_accepted",
                    "spec_rollback_blocks", "spec_jump_forward_tokens",
                    "spec_ngram_hits"):
            totals[key] = totals.get(key, 0) + int(stats.get(key, 0))
        modes.add(str(stats.get("spec_draft_mode", "model")))
        k_eff = stats.get("spec_k_effective", "-")
        if k_eff not in (None, "-"):
            k_effs.append(str(k_eff))
    if not totals:
        return None
    totals["spec_draft_mode"] = "|".join(sorted(modes))
    totals["spec_k_effective"] = ";".join(k_effs) if k_effs else "-"
    proposed = totals["spec_proposed"]
    rounds = totals["spec_rounds"]
    totals["spec_acceptance_rate"] = round(
        totals["spec_accepted"] / proposed, 4) if proposed else 0.0
    totals["spec_tokens_per_target_pass"] = round(
        (totals["spec_accepted"] + rounds) / rounds, 4) \
        if rounds else 0.0
    return totals


def _enable_paired_draft(server, spec_k: int) -> None:
    """Alias the target weights in as the draft (the 'paired toy'):
    on the tiny CPU configs a real small draft is meaningless, and an
    identical draft gives the HIGH-acceptance regime — multi-token
    commits every round — while greedy outputs stay bitwise equal to
    the plain server by the verify construction (what the A/B run
    asserts).  Counters and histograms then show the mechanism at
    full stretch instead of degenerating to acceptance ≈ 0."""
    server._draft["params"] = server.params
    server._draft["config"] = server.config


def run_shared_prefix(n_requests: int = 24, rate_hz: float = 50.0,
                      n_conversations: int = 3, turns: int = 4,
                      system_len: int = 48,
                      prefix_routing: bool = True,
                      kv_transfer: bool = True,
                      drain_timeout_s: float = 90.0,
                      seed: int = 0,
                      trace_out: Optional[str] = None,
                      trace_top: int = 5,
                      spec_k: int = 0) -> LoadReport:
    """In-process 2-replica PAGED serving rig (prefix caches on)
    driven by :func:`shared_prefix_payloads` through a ReplicaRouter.
    ``prefix_routing=False`` degrades the router to pure
    least-loaded P2C (``prefix_alpha=0``) — the A/B baseline bench.py
    compares TTFT against.  The report carries ``prefix_hit_rate``,
    ``kv_transfer_bytes`` and histogram-merged ``fleet_latency_ms``
    aggregated across the fleet.  ``trace_out`` enables distributed
    tracing for the run and dumps the ``trace_top`` slowest requests'
    span trees as Chrome trace-event JSON into that directory."""
    from ..orchestration.continuous import ContinuousReplica
    from ..orchestration.paged import PagedContinuousServer
    from ..orchestration.serving import ReplicaRouter
    from ..registry import Registrar
    from ..runtime import Process, actor_args, compose_instance
    from ..runtime.event import EventEngine

    def wait_for(predicate, timeout_s: float, what: str):
        deadline = time.time() + timeout_s
        while not predicate():
            if time.time() > deadline:
                raise TimeoutError(f"shared-prefix rig: {what}")
            time.sleep(0.02)

    tracing = trace_out is not None and trace.TRACER is None
    if tracing:
        # One in-process rig → one tracer covers loadgen root spans
        # AND router spans; replicas synthesize theirs from the
        # propagated context without needing any tracer at all.
        trace.install(service="loadgen")
    engine = EventEngine()
    thread = engine.run_in_thread()
    broker = f"sharedpfx-{uuid.uuid4().hex[:6]}"
    processes = []

    def make_process(pid):
        process = Process(namespace="sharedpfx", hostname="h",
                          pid=str(pid), engine=engine, broker=broker)
        processes.append(process)
        return process

    generator = None
    servers = []
    try:
        registrar = Registrar(process=make_process(1))
        wait_for(lambda: registrar.state == "primary", 10,
                 "registrar primary")
        for index, name in enumerate(("replica_a", "replica_b")):
            server = PagedContinuousServer(
                config_name="tiny", slots=2, chunk_steps=4, seed=0,
                enable_prefix_cache=True, max_queue=256,
                watchdog_s=5.0,
                draft_config_name="tiny" if spec_k else None,
                spec_k=spec_k or 4)
            if spec_k:
                _enable_paired_draft(server, spec_k)
            servers.append(server)
            compose_instance(ContinuousReplica, actor_args(name),
                             process=make_process(2 + index),
                             server=server)
        router = compose_instance(
            ReplicaRouter, actor_args("router"),
            process=make_process(8),
            prefix_alpha=1.0 if prefix_routing else 0.0,
            kv_transfer=kv_transfer)
        wait_for(lambda: router.share["replicas"] == 2, 30,
                 "router discovery")
        generator = LoadGenerator(
            make_process(9), f"{router.topic_path}/in",
            payload_fn=shared_prefix_payloads(
                n_conversations=n_conversations, turns=turns,
                system_len=system_len, seed=seed),
            rate_hz=rate_hz)
        report = generator.run(n_requests,
                               drain_timeout_s=drain_timeout_s)
        totals = _fleet_kv_stats(servers)
        _attach_kv_rates(report, totals)
        _attach_pool_census(report, servers)
        report.fleet_latency_ms = fleet_latency(servers)
        report.final_tokens = dict(generator.final_tokens)
        report.spec_stats = _fleet_spec_stats(servers)
        report.spec_accept_hist = dict(generator.spec_accept_hist)
        report.server_stats = dict(
            router.counters, **totals,
            kv_directory_size=router.share.get("kv_directory_size", 0))
        if trace_out is not None:
            generator.dump_traces(trace_out, top_k=trace_top)
        return report
    finally:
        if tracing:
            trace.uninstall()
        if generator is not None:
            generator.close()
        for process in reversed(processes):
            try:
                process.terminate()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        engine.terminate()
        thread.join(timeout=5)


def longtail_payloads(n_prefixes: int = 8, prefix_len: int = 96,
                      tail_len: int = 8, max_new_tokens: int = 4,
                      vocab: int = 1024, seed: int = 0,
                      stream: bool = True) -> Callable[[int], Dict]:
    """Long-tail prefix workload: ``n_prefixes`` DISTINCT shared
    prefixes visited round-robin, each request re-sending its prefix
    plus ``tail_len`` fresh tokens.  The reuse distance is therefore
    ``n_prefixes`` requests — size the prefix working set
    (``n_prefixes × prefix_len/block_size`` blocks) past the HBM pool
    and an HBM-only cache thrashes (every hit evicted before its
    reuse), while a host tier holds the whole tail and serves it back
    through restores.  Deterministic from ``seed``."""
    import numpy as np

    rng = np.random.RandomState(seed)
    prefixes = [rng.randint(1, vocab, size=prefix_len).astype(np.int32)
                for _ in range(n_prefixes)]

    def payload_fn(index: int) -> Dict:
        which = index % n_prefixes
        tail = np.asarray(
            [1 + (7919 * (index + 1) + 31 * position) % (vocab - 1)
             for position in range(tail_len)], np.int32)
        payload = {"tokens": np.concatenate([prefixes[which], tail]),
                   "max_new_tokens": max_new_tokens}
        if stream:
            payload["stream"] = 1
        return payload

    return payload_fn


def run_longtail(n_requests: int = 36, rate_hz: float = 25.0,
                 n_prefixes: int = 6, prefix_len: int = 384,
                 tail_len: int = 8,
                 total_blocks: int = 52,
                 host_tier_blocks: int = 160,
                 restore_blocks_per_step: int = 24,
                 chunk_prefill_tokens: int = 64,
                 warmup_requests: int = 12,
                 drain_timeout_s: float = 180.0,
                 seed: int = 0,
                 spill_dir: Optional[str] = None,
                 spill_blocks: int = 1024) -> LoadReport:
    """Capacity A/B rig for the tiered KV cache: ONE paged replica
    whose HBM pool (``total_blocks``) is deliberately smaller than the
    longtail workload's prefix working set, behind a prefix-aware
    router.  ``host_tier_blocks=0`` is the HBM-only baseline — same
    pool, same workload, eviction deletes.  The tier-on run must beat
    it on BOTH ``prefix_hit_rate`` and mean TTFT (the capacity gate in
    tests/test_kv_tier.py; numbers in bench.py's ``kv_tier``
    section).  The report's ``prefix_hit_rate_host`` says how many of
    the hits only existed because demotion preserved them.

    Default sizing makes restore beat recompute in STEPS, which is
    what TTFT measures on any backend: a 384-token prefix is 24
    blocks, so a miss re-prefills 6 chunks of ``chunk_prefill_tokens``
    = 64 while a host hit defers one step, lands the whole chain in
    one batched scatter (``restore_blocks_per_step=24``) and prefills
    only the tail.

    ``spill_dir`` enables the SSD spill tier under the host tier
    (loadgen ``--disk-blocks``): host-RAM overflow demotes to disk
    instead of purging, so the comparison becomes a FOUR-way ladder —
    HBM hit, host restore, disk restore, recompute — and the report's
    ``kv_spills`` / ``kv_disk_restores`` counters say how much of the
    working set only survived on disk."""
    from ..orchestration.continuous import ContinuousReplica
    from ..orchestration.paged import PagedContinuousServer
    from ..orchestration.serving import ReplicaRouter
    from ..registry import Registrar
    from ..runtime import Process, actor_args, compose_instance
    from ..runtime.event import EventEngine

    def wait_for(predicate, timeout_s: float, what: str):
        deadline = time.time() + timeout_s
        while not predicate():
            if time.time() > deadline:
                raise TimeoutError(f"longtail rig: {what}")
            time.sleep(0.02)

    engine = EventEngine()
    thread = engine.run_in_thread()
    broker = f"longtail-{uuid.uuid4().hex[:6]}"
    processes = []

    def make_process(pid):
        process = Process(namespace="longtail", hostname="h",
                          pid=str(pid), engine=engine, broker=broker)
        processes.append(process)
        return process

    generator = None
    try:
        registrar = Registrar(process=make_process(1))
        wait_for(lambda: registrar.state == "primary", 10,
                 "registrar primary")
        prompt_len = prefix_len + tail_len
        max_seq = ((prompt_len + 8 + 15) // 16) * 16
        server = PagedContinuousServer(
            config_name="tiny", slots=2, max_seq=max_seq,
            chunk_steps=4, seed=0, enable_prefix_cache=True,
            total_blocks=total_blocks,
            host_tier_blocks=host_tier_blocks,
            restore_blocks_per_step=restore_blocks_per_step,
            chunk_prefill_tokens=chunk_prefill_tokens,
            spill_dir=spill_dir, spill_blocks=spill_blocks,
            max_queue=256, watchdog_s=10.0)
        compose_instance(ContinuousReplica, actor_args("replica_a"),
                         process=make_process(2), server=server)
        router = compose_instance(ReplicaRouter, actor_args("router"),
                                  process=make_process(8))
        wait_for(lambda: router.share["replicas"] == 1, 30,
                 "router discovery")
        generator = LoadGenerator(
            make_process(9), f"{router.topic_path}/in",
            payload_fn=longtail_payloads(
                n_prefixes=n_prefixes, prefix_len=prefix_len,
                tail_len=tail_len, seed=seed),
            rate_hz=rate_hz)
        if warmup_requests:
            # Same payload sequence both arms see in the measured
            # run: compiles every serve/gather/scatter shape and
            # brings each arm to ITS steady state (tier-on: working
            # set demoted to host; tier-off: pool thrashed) so the
            # A/B measures serving, not first-touch compilation.
            generator.run(warmup_requests,
                          drain_timeout_s=drain_timeout_s)
        report = generator.run(n_requests,
                               drain_timeout_s=drain_timeout_s)
        totals = _fleet_kv_stats([server])
        _attach_kv_rates(report, totals)
        _attach_pool_census(report, [server])
        report.fleet_latency_ms = fleet_latency([server])
        report.server_stats = dict(router.counters, **totals)
        return report
    finally:
        if generator is not None:
            generator.close()
        for process in reversed(processes):
            try:
                process.terminate()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        engine.terminate()
        thread.join(timeout=5)


def multitenant_payloads(n_adapters: int = 4, zipf_s: float = 1.2,
                         prompt_len: int = 12,
                         max_new_tokens: int = 4, vocab: int = 1024,
                         seed: int = 0, schedule_len: int = 4096
                         ) -> Callable[[int], Dict]:
    """Multi-tenant workload: every request names one of
    ``n_adapters`` tenants' adapters, drawn from a zipf-shaped
    popularity distribution (``weight ∝ 1/rank^zipf_s`` — a few hot
    tenants, a long tail of cold ones, the shape S-LoRA serves).
    Prompts are per-request random (NO shared prefix), so the A/B
    isolates ADAPTER locality from prefix locality.  Deterministic
    from ``seed``."""
    import numpy as np

    rng = np.random.RandomState(seed)
    weights = 1.0 / np.arange(1, n_adapters + 1) ** zipf_s
    weights /= weights.sum()
    schedule = rng.choice(n_adapters, size=schedule_len, p=weights)

    def payload_fn(index: int) -> Dict:
        which = int(schedule[index % schedule_len])
        prompt = np.asarray(
            [1 + (7919 * (index + 1) + 31 * position) % (vocab - 1)
             for position in range(prompt_len)], np.int32)
        return {"tokens": prompt, "max_new_tokens": max_new_tokens,
                "adapter": f"tenant-{which}"}

    return payload_fn


def _noisy_loadgen_adapter(config, lora_config, seed: int):
    """A host-side random adapter whose B factors are non-zero (a
    fresh-initialized adapter is an exact no-op) — numpy only, so the
    rig can mint tenants without touching the device."""
    import numpy as np

    rng = np.random.RandomState(seed)
    from ..models.lora import factor_dims
    in_dims, out_dims = factor_dims(config)
    layers = []
    for _ in range(config.n_layers):
        layer = {}
        for target in lora_config.targets:
            layer[target] = {
                "a": (rng.randn(in_dims[target], lora_config.rank)
                      * in_dims[target] ** -0.5).astype(np.float32),
                "b": (rng.randn(lora_config.rank, out_dims[target])
                      * 0.05).astype(np.float32)}
        layers.append(layer)
    return {"layers": layers}


def run_multitenant(n_requests: int = 32, rate_hz: float = 25.0,
                    n_adapters: int = 4, zipf_s: float = 1.2,
                    adapter_aware: bool = True,
                    warmup_requests: int = 8,
                    drain_timeout_s: float = 120.0,
                    seed: int = 0) -> LoadReport:
    """Warm-adapter-routing A/B rig: TWO paged replicas, each holding
    HALF the tenants' adapters (evens on A, odds on B — every adapter
    is warm on exactly one replica), behind either the adapter-aware
    router (``adapter_affinity=1``) or the adapter-blind baseline
    (``adapter_affinity=0`` — PR-4 P2C, never inspects the adapter
    field).  The blind router lands ~half the zipf-distributed
    requests on the WRONG replica, each an ``unknown_adapter``
    rejection the client must answer with a factor re-upload
    (``adapter_cold_starts``); the aware router reads adapter
    residency off the SAME prefix digests and must take ZERO cold
    starts — a warm adapter anywhere in the fleet is a warm adapter
    for every request that names it."""
    from ..kvstore.adapters import adapter_hex
    from ..models import llama
    from ..models.lora import LoRAConfig
    from ..orchestration.continuous import ContinuousReplica
    from ..orchestration.paged import PagedContinuousServer
    from ..orchestration.serving import ReplicaRouter
    from ..registry import Registrar
    from ..runtime import Process, actor_args, compose_instance
    from ..runtime.event import EventEngine

    def wait_for(predicate, timeout_s: float, what: str):
        deadline = time.time() + timeout_s
        while not predicate():
            if time.time() > deadline:
                raise TimeoutError(f"multitenant rig: {what}")
            time.sleep(0.02)

    engine = EventEngine()
    thread = engine.run_in_thread()
    broker = f"mtenant-{uuid.uuid4().hex[:6]}"
    processes = []

    def make_process(pid):
        process = Process(namespace="mtenant", hostname="h",
                          pid=str(pid), engine=engine, broker=broker)
        processes.append(process)
        return process

    lora_config = LoRAConfig(rank=4, alpha=8.0, targets=("wq", "wv"))
    config = llama.CONFIGS["tiny"]
    generator = None
    try:
        registrar = Registrar(process=make_process(1))
        wait_for(lambda: registrar.state == "primary", 10,
                 "registrar primary")
        servers = []
        for index, name in enumerate(("replica_a", "replica_b")):
            server = PagedContinuousServer(
                config_name="tiny", slots=4, max_seq=64,
                chunk_steps=4, seed=0, enable_prefix_cache=True,
                total_blocks=96, max_queue=256, watchdog_s=10.0)
            # Home placement: evens on A, odds on B — each tenant's
            # factors are paged (and digest-advertised) on exactly
            # one replica, so routing is the ONLY thing that decides
            # warm vs cold.
            for tenant in range(index, n_adapters, 2):
                server.load_adapter(
                    f"tenant-{tenant}",
                    _noisy_loadgen_adapter(config, lora_config,
                                           seed=100 + tenant),
                    lora_config)
            compose_instance(ContinuousReplica, actor_args(name),
                             process=make_process(2 + index),
                             server=server)
            servers.append(server)
        router = compose_instance(
            ReplicaRouter, actor_args("router"),
            process=make_process(8),
            adapter_affinity=1.0 if adapter_aware else 0.0)
        wait_for(lambda: router.share["replicas"] == 2, 30,
                 "router discovery")
        hexes = [adapter_hex(f"tenant-{t}") for t in range(n_adapters)]
        wait_for(lambda: all(
            router.directory.adapter_owners(
                h, router.process.event.now()) for h in hexes),
            30, "adapter residency in fleet digests")
        generator = LoadGenerator(
            make_process(9), f"{router.topic_path}/in",
            payload_fn=multitenant_payloads(
                n_adapters=n_adapters, zipf_s=zipf_s, seed=seed),
            rate_hz=rate_hz)
        if warmup_requests:
            generator.run(warmup_requests,
                          drain_timeout_s=drain_timeout_s)
            for counter in ("adapter_warm_routes",
                            "adapter_cold_routes"):
                router.counters[counter] = 0
        report = generator.run(n_requests,
                               drain_timeout_s=drain_timeout_s)
        report.adapter_cold_starts = \
            report.error_kinds.get("unknown_adapter", 0)
        report.adapter_warm_routes = \
            router.counters.get("adapter_warm_routes", 0)
        report.adapter_cold_routes = \
            router.counters.get("adapter_cold_routes", 0)
        totals = _fleet_kv_stats(servers)
        _attach_kv_rates(report, totals)
        _attach_pool_census(report, servers)
        report.server_stats = dict(router.counters, **{
            key: sum(server.stats().get(key, 0) for server in servers)
            for key in ("adapter_warm_loads", "adapter_cold_loads",
                        "adapter_pages_hbm", "adapter_pages_host",
                        "adapter_pages_disk")})
        return report
    finally:
        if generator is not None:
            generator.close()
        for process in reversed(processes):
            try:
                process.terminate()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        engine.terminate()
        thread.join(timeout=5)


def run_restart(n_requests: int = 12, rate_hz: float = 40.0,
                n_prefixes: int = 3, prefix_len: int = 192,
                tail_len: int = 8,
                total_blocks: int = 20,
                restore_blocks_per_step: int = 16,
                chunk_prefill_tokens: int = 64,
                warmup_requests: int = 6,
                recovery_batch: int = 4,
                hit_rate_floor: float = 0.34,
                drain_timeout_s: float = 180.0,
                seed: int = 0,
                spill_dir: Optional[str] = None,
                spill_blocks: int = 1024,
                adopt: bool = True) -> LoadReport:
    """Warm-replica-restart rig (loadgen ``--restart-replica``): ONE
    paged replica with ``host_tier_blocks=0`` and an SSD spill dir, so
    every demotion lands straight on disk — the durable working set.
    After a warmup phase that spills the longtail prefixes, the
    replica's PROCESS is killed mid-run (the LWT fires, the router
    sees it leave) and a fresh replica is composed on the same broker.
    ``adopt=True`` hands the respawn the same ``spill_dir`` (warm
    restart: the ctor scan re-adopts every intact chain and advertises
    tier 2); ``adopt=False`` is the cold-restart A/B baseline — same
    death, same respawn, same spill CONFIG (an empty sibling
    directory, so both arms pay the durability tax on eviction), but
    the pre-crash state is lost.  Adoption is the only variable.

    The measured phase runs in ``recovery_batch``-request sub-batches;
    per batch the rig computes the respawned replica's prefix hit rate
    from counter deltas and stamps ``restart_recovery_ms`` — time from
    respawn to the END of the first batch at or above
    ``hit_rate_floor`` — into ``report.server_stats`` (alongside the
    per-batch ``restart_hit_rates`` curve, ``None`` recovery when the
    floor is never reached).  :func:`run_restart_ab` asserts warm
    beats cold on hit rate AND mean TTFT with bit-exact greedy
    outputs."""
    from ..orchestration.continuous import ContinuousReplica
    from ..orchestration.paged import PagedContinuousServer
    from ..orchestration.serving import ReplicaRouter
    from ..registry import Registrar
    from ..runtime import Process, actor_args, compose_instance
    from ..runtime.event import EventEngine

    def wait_for(predicate, timeout_s: float, what: str):
        deadline = time.time() + timeout_s
        while not predicate():
            if time.time() > deadline:
                raise TimeoutError(f"restart rig: {what}")
            time.sleep(0.02)

    if spill_dir is None:
        raise ValueError("run_restart needs a spill_dir — the rig "
                         "exists to measure spill adoption")
    engine = EventEngine()
    thread = engine.run_in_thread()
    broker = f"restart-{uuid.uuid4().hex[:6]}"
    processes = []

    def make_process(pid):
        process = Process(namespace="restart", hostname="h",
                          pid=str(pid), engine=engine, broker=broker)
        processes.append(process)
        return process

    def make_server(directory: str):
        prompt_len = prefix_len + tail_len
        max_seq = ((prompt_len + 8 + 15) // 16) * 16
        return PagedContinuousServer(
            config_name="tiny", slots=2, max_seq=max_seq,
            chunk_steps=4, seed=0, enable_prefix_cache=True,
            total_blocks=total_blocks, host_tier_blocks=0,
            restore_blocks_per_step=restore_blocks_per_step,
            chunk_prefill_tokens=chunk_prefill_tokens,
            spill_dir=directory, spill_blocks=spill_blocks,
            max_queue=256, watchdog_s=10.0)

    generator = None
    try:
        registrar = Registrar(process=make_process(1))
        wait_for(lambda: registrar.state == "primary", 10,
                 "registrar primary")
        server_a = make_server(spill_dir)
        process_a = make_process(2)
        compose_instance(ContinuousReplica, actor_args("replica_a"),
                         process=process_a, server=server_a)
        router = compose_instance(ReplicaRouter, actor_args("router"),
                                  process=make_process(8))
        wait_for(lambda: router.share["replicas"] == 1, 30,
                 "router discovery")
        payloads = longtail_payloads(
            n_prefixes=n_prefixes, prefix_len=prefix_len,
            tail_len=tail_len, seed=seed)
        generator = LoadGenerator(
            make_process(9), f"{router.topic_path}/in",
            payload_fn=payloads, rate_hz=rate_hz)
        sent_total = 0
        if warmup_requests:
            generator.run(warmup_requests,
                          drain_timeout_s=drain_timeout_s)
            sent_total += warmup_requests
        spilled = int(server_a.stats().get("kv_spills", 0))

        # --- the restart: CRASH the only replica (LWT fires, the
        # registrar evicts it), then respawn it fresh ---
        process_a.kill()
        wait_for(lambda: router.share["replicas"] == 0, 30,
                 "dead replica leaving the fleet")
        server_b = make_server(spill_dir if adopt
                               else spill_dir + "-cold")
        respawned_at = time.time()
        compose_instance(ContinuousReplica, actor_args("replica_b"),
                         process=make_process(3), server=server_b)
        wait_for(lambda: router.share["replicas"] == 1, 30,
                 "respawn discovery")

        # --- measured phase: sub-batched so the hit-rate RECOVERY
        # curve is observable, payload index offset so batches keep
        # walking the same longtail instead of replaying batch one ---
        batches: List[LoadReport] = []
        final_tokens: Dict[str, List[int]] = {}
        hit_rates: List[float] = []
        recovery_ms: Optional[float] = None
        remaining = n_requests
        while remaining > 0:
            batch_n = min(recovery_batch, remaining)
            before = server_b.stats()
            generator.payload_fn = \
                lambda i, base=sent_total: payloads(base + i)
            batch = generator.run(batch_n,
                                  drain_timeout_s=drain_timeout_s)
            for request_id, tokens in generator.final_tokens.items():
                final_tokens[f"r{sent_total}_{request_id}"] = tokens
            after = server_b.stats()
            hits = int(after["prefix_hits"]) - int(before["prefix_hits"])
            lookups = hits + (int(after["prefix_misses"])
                              - int(before["prefix_misses"]))
            rate = hits / lookups if lookups else 0.0
            hit_rates.append(round(rate, 4))
            if recovery_ms is None and rate >= hit_rate_floor:
                recovery_ms = round(
                    (time.time() - respawned_at) * 1000.0, 1)
            batches.append(batch)
            sent_total += batch_n
            remaining -= batch_n
        report = LoadReport(
            sent=sum(b.sent for b in batches),
            completed=sum(b.completed for b in batches),
            errors=sum(b.errors for b in batches),
            timeouts=sum(b.timeouts for b in batches),
            elapsed_s=sum(b.elapsed_s for b in batches),
            latencies_ms=[v for b in batches for v in b.latencies_ms],
            tokens_total=sum(b.tokens_total for b in batches),
            ttfts_ms=[v for b in batches for v in b.ttfts_ms],
            duplicate_finals=sum(b.duplicate_finals for b in batches))
        for batch in batches:
            for phase, values in batch.phase_ms.items():
                report.phase_ms.setdefault(phase, []).extend(values)
            for kind, count in batch.error_kinds.items():
                report.error_kinds[kind] = \
                    report.error_kinds.get(kind, 0) + count
        report.final_tokens = final_tokens
        totals = _fleet_kv_stats([server_b])
        _attach_kv_rates(report, totals)
        report.fleet_latency_ms = fleet_latency([server_b])
        report.server_stats = dict(
            router.counters, **totals,
            warmup_spills=spilled,
            restart_recovery_ms=recovery_ms,
            restart_hit_rates=hit_rates)
        return report
    finally:
        if generator is not None:
            generator.close()
        for process in reversed(processes):
            try:
                process.terminate()
            except Exception:  # noqa: BLE001 - replica_a is already
                pass           # dead by design
        engine.terminate()
        thread.join(timeout=5)


def run_restart_ab(n_requests: int = 18, rate_hz: float = 25.0,
                   seed: int = 0,
                   drain_timeout_s: float = 180.0,
                   **kwargs) -> Tuple[LoadReport, LoadReport]:
    """Warm-restart A/B gate: the SAME seeded longtail sequence
    through :func:`run_restart` twice — cold (respawn spills to an
    empty sibling directory) then warm (respawn adopts the dead
    replica's) — each arm rooted in its own fresh temp dir so the
    warmup phases are identical.  Asserts
    the greedy outputs are BIT-EXACT request for request (a restored
    block may never change a token), then returns ``(cold, warm)``;
    the caller (bench.py's ``kv_tier`` section, tests/test_kv_spill)
    checks warm strictly beats cold on measured-phase hit rate and
    mean TTFT."""
    import tempfile

    reports = []
    for adopt in (False, True):
        with tempfile.TemporaryDirectory(prefix="kvspill-ab-") as root:
            reports.append(run_restart(
                n_requests=n_requests, rate_hz=rate_hz, seed=seed,
                drain_timeout_s=drain_timeout_s,
                spill_dir=os.path.join(root, "spill"),
                adopt=adopt, **kwargs))
    cold, warm = reports
    both = set(cold.final_tokens) & set(warm.final_tokens)
    mismatched = [request_id for request_id in sorted(both)
                  if cold.final_tokens[request_id]
                  != warm.final_tokens[request_id]]
    if mismatched:
        raise AssertionError(
            f"restart A/B not bit-exact (seed={seed}): "
            f"{len(mismatched)}/{len(both)} requests diverged, first "
            f"{mismatched[0]}")
    if not both:
        raise AssertionError(
            "restart A/B compared zero requests — the gate proved "
            "nothing")
    return cold, warm


def run_compile_cache_ab(cache_dir: Optional[str] = None,
                         prompt_len: int = 24,
                         max_new_tokens: int = 4, seed: int = 0,
                         config_name: str = "tiny"
                         ) -> Tuple[LoadReport, LoadReport]:
    """Persistent-compilation-cache A/B gate — the PR-12 warm-restart
    gate extended to COMPILE time.  The same single-request greedy
    decode through two freshly constructed paged engines sharing ONE
    persistent cache directory: arm 1 COLD (empty directory — every
    program really compiles and populates the cache), then
    ``jax.clear_caches()`` drops the in-memory jit caches (the honest
    in-process stand-in for a process restart), arm 2 WARM (same
    directory — every lookup should retrieve instead of compile).
    Asserts the warm arm strictly beats the cold arm on
    time-to-first-compiled-step, saw > 0 persistent-cache hits, and
    produced bit-exact greedy tokens.  Returns ``(cold, warm)``
    LoadReports whose ``compile_cache`` dict carries the per-arm
    ledger deltas; ``elapsed_s`` IS the time-to-first-compiled-step.

    ``cache_dir=None`` (the default) uses a fresh temp directory —
    pass a directory only if you can guarantee it starts empty, or
    the cold arm is not cold and the gate proves nothing."""
    import tempfile

    import jax
    import numpy as np

    from ..obs import compiles
    from ..orchestration.continuous import DecodeRequest
    from ..orchestration.paged import PagedContinuousServer

    ledger_owned = compiles.LEDGER is None
    ledger = compiles.install(service="cache-ab")
    rng = np.random.RandomState(seed)
    prompt = rng.randint(1, 256, size=prompt_len).astype(np.int32)
    tmp = None
    if cache_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="compile-cache-ab-")
        cache_dir = tmp.name
    reports = []
    try:
        for arm in ("cold", "warm"):
            jax.clear_caches()
            base = ledger.snapshot()
            began = time.monotonic()
            server = PagedContinuousServer(
                config_name=config_name, slots=2, chunk_steps=4,
                seed=0, compilation_cache_dir=cache_dir)
            server.submit(DecodeRequest(
                request_id=f"ab_{arm}", prompt=prompt,
                max_new_tokens=max_new_tokens))
            done = []
            for _ in range(512):
                done.extend(server.step())
                if done:
                    break
            else:
                raise AssertionError(
                    f"cache A/B: {arm} arm request never completed")
            ttfs_s = time.monotonic() - began
            if done[0].error is not None:
                raise AssertionError(
                    f"cache A/B: {arm} arm errored: {done[0].error}")
            after = ledger.snapshot()
            delta = {key: after[key] - base[key]
                     for key in ("compiles", "cache_hits",
                                 "cache_misses")}
            delta["cache_saved_ms"] = round(
                after["cache_saved_ms"] - base["cache_saved_ms"], 3)
            delta["time_to_first_step_s"] = round(ttfs_s, 4)
            report = LoadReport(
                sent=1, completed=1, errors=0, timeouts=0,
                elapsed_s=ttfs_s, latencies_ms=[ttfs_s * 1e3],
                tokens_total=len(done[0].tokens or []),
                compile_cache=delta)
            report.final_tokens = {
                done[0].request_id:
                [int(t) for t in (done[0].tokens or [])]}
            reports.append(report)
    finally:
        if ledger_owned:
            compiles.uninstall()
        compiles.disable_persistent_cache()
        if tmp is not None:
            tmp.cleanup()
    cold, warm = reports
    cold_tokens = next(iter(cold.final_tokens.values()))
    warm_tokens = next(iter(warm.final_tokens.values()))
    if cold_tokens != warm_tokens:
        raise AssertionError(
            f"cache A/B not bit-exact (seed={seed}): a cached program "
            f"may never change a token — cold {cold_tokens} vs warm "
            f"{warm_tokens}")
    if warm.compile_cache["cache_hits"] <= 0:
        raise AssertionError(
            "cache A/B: warm arm saw ZERO persistent-cache hits — the "
            "cache directory wiring is dead")
    if not warm.elapsed_s < cold.elapsed_s:
        raise AssertionError(
            f"cache A/B: warm restart must strictly beat cold on "
            f"time-to-first-compiled-step, got cold "
            f"{cold.elapsed_s:.3f}s vs warm {warm.elapsed_s:.3f}s")
    return cold, warm


def chaos_schedule(seed: int):
    """The canonical seeded fault schedule for ``loadgen --chaos``:
    one replica death mid-decode, streaming-increment message drops,
    and a device-step stall — the three failure classes the serving
    robustness machinery covers (re-dispatch, dedup-tolerant
    streaming, watchdog/latency).  Deriving the plan purely from
    ``seed`` is what makes a chaos run reproducible."""
    from ..runtime import faults
    return (
        faults.FaultPlan(seed=seed)
        # replica_a dies on its Nth pump — mid-decode under load.
        .add("kill_replica", nth=6 + seed % 5, match="replica_a")
        # Streamed increments are droppable by design (the final
        # response is authoritative); finals are NOT dropped — nothing
        # retries a silently-eaten terminal response.
        .add("drop_message", nth=4, match="infer_partial")
        .add("drop_message", nth=9, match="infer_partial")
        # Latency blip well under the watchdog threshold: chaos runs
        # exercise the stall POINT; the watchdog trip itself is
        # unit-tested deterministically.
        .add("stall_step", nth=7 + seed % 3, ms=40))


def run_chaos(seed: int = 0, n_requests: int = 40,
              rate_hz: float = 100.0,
              drain_timeout_s: float = 90.0,
              total_blocks: Optional[int] = None,
              host_tier_blocks: int = 0,
              restore_blocks_per_step: int = 2,
              spill_dir: Optional[str] = None,
              spill_blocks: int = 1024,
              spec_k: int = 0,
              compile_gate: bool = False,
              warmup_requests: Optional[int] = None) -> LoadReport:
    """Run an in-process 2-replica serving rig (loopback broker, real
    event engine, Registrar + router) under :func:`chaos_schedule` and
    return the LoadReport.  The invariant a chaos run checks:
    ``report.lost == 0 and report.timeouts == 0`` — every request
    reaches a terminal state (completed, or an explicit error like
    ``deadline_exceeded``/``overloaded``) no matter which replica died
    or which messages vanished.  CPU-friendly (tiny config); set
    ``JAX_PLATFORMS=cpu`` when no accelerator is wanted.

    Replicas run the PAGED backend with prefix caches on and the
    router routes prefix-aware with KV transfer enabled — the chaos
    gate covers the kvstore path too: killing a directory-advertised
    prefix owner mid-stream must still lose ZERO requests (directory
    eviction + fetch-timeout fallback to local prefill).

    ``spill_dir`` gives each replica its OWN subdirectory of it as an
    SSD spill tier (spill dirs are single-owner by design — the
    signature/lease story is per-replica), so a chaos kill lands
    mid-spill: the crash gate in tests/test_chaos.py asserts zero
    lost requests AND that a fresh server adopting the dead replica's
    directory serves bit-exact tokens — torn writes never surface.

    ``compile_gate=True`` adds the compile-ledger steady-state gate:
    a warmup wave of ``warmup_requests`` (default 12 = one full
    period of the shared-prefix payload cycle, so every distinct
    prompt shape the measured wave will send compiles once) runs
    BEFORE the fault plan is armed, the ledger's warmup fence drops,
    and the measured chaos wave must then record ZERO steady-state
    compiles — a replica dying mid-decode and re-dispatching its work
    may never cost the fleet a recompile.  Two mechanisms make that
    true together: pow2 bucketing keeps the survivor's shapes a
    subset of the warmed set, and the replicas SHARE one persistent
    compilation cache directory — prefix-aware routing concentrates
    warmup on the prefix owner, so the failover target can be
    compile-COLD when the kill lands, and its first-touch programs
    must come back as ~ms cache retrievals (booked as hits, never as
    steady compiles).  The report carries the warmup/steady split
    (``warmup_s``, ``warmup_compiles``, ``compiles_steady_state``,
    ``steady_tokens_per_sec``)."""
    import tempfile

    from ..obs import compiles
    from ..orchestration.continuous import ContinuousReplica
    from ..orchestration.paged import PagedContinuousServer
    from ..orchestration.serving import ReplicaRouter
    from ..registry import Registrar
    from ..runtime import (Process, actor_args, compose_instance,
                           faults)
    from ..runtime.event import EventEngine

    def wait_for(predicate, timeout_s: float, what: str):
        deadline = time.time() + timeout_s
        while not predicate():
            if time.time() > deadline:
                raise TimeoutError(f"chaos rig: {what}")
            time.sleep(0.02)

    warmup_began = time.time()
    ledger = None
    ledger_owned = False
    cache_tmp = None
    if compile_gate:
        ledger_owned = compiles.LEDGER is None
        ledger = compiles.install(service="chaos-gate")
        cache_tmp = tempfile.TemporaryDirectory(
            prefix="chaos-compile-cache-")
    # The fault plan arms AFTER the warmup wave when gating compiles —
    # warmup pumps must not consume the schedule's nth counters, or
    # the kill would land mid-warmup instead of mid-measured-decode.
    plan = faults.install(chaos_schedule(seed)) \
        if not compile_gate else None
    engine = EventEngine()
    thread = engine.run_in_thread()
    broker = f"chaos-{uuid.uuid4().hex[:6]}"
    processes = []

    def make_process(pid):
        process = Process(namespace="chaos", hostname="h",
                          pid=str(pid), engine=engine, broker=broker)
        processes.append(process)
        return process

    generator = None
    servers = []
    try:
        registrar = Registrar(process=make_process(1))
        wait_for(lambda: registrar.state == "primary", 10,
                 "registrar primary")
        for index, name in enumerate(("replica_a", "replica_b")):
            # Same config+seed on purpose: greedy decode is replica-
            # independent, so re-dispatched requests finish with the
            # exact tokens the dead replica would have produced.
            server = PagedContinuousServer(
                config_name="tiny", slots=2, chunk_steps=4, seed=0,
                enable_prefix_cache=True, max_queue=256,
                watchdog_s=5.0, total_blocks=total_blocks,
                host_tier_blocks=host_tier_blocks,
                restore_blocks_per_step=restore_blocks_per_step,
                spill_dir=(os.path.join(spill_dir, name)
                           if spill_dir else None),
                spill_blocks=spill_blocks,
                draft_config_name="tiny" if spec_k else None,
                spec_k=spec_k or 4,
                compilation_cache_dir=(cache_tmp.name if cache_tmp
                                       else None))
            if spec_k:
                # Kill-mid-spec-round coverage: greedy determinism +
                # idempotent replay must hold through rejected-tail
                # rollbacks exactly as through plain decode.
                _enable_paired_draft(server, spec_k)
            servers.append(server)
            compose_instance(ContinuousReplica, actor_args(name),
                             process=make_process(2 + index),
                             server=server,
                             # Dead-owner fallback must fire well
                             # inside the drain budget.
                             kv_fetch_timeout_s=2.0)
        router = compose_instance(ReplicaRouter, actor_args("router"),
                                  process=make_process(8),
                                  kv_transfer=True)
        wait_for(lambda: router.share["replicas"] == 2, 30,
                 "router discovery")
        generator = LoadGenerator(
            make_process(9), f"{router.topic_path}/in",
            # Shared 32-token system prefix: the fault schedule then
            # kills a replica the directory advertises as an owner.
            payload_fn=shared_prefix_payloads(
                n_conversations=3, turns=4, system_len=32,
                seed=seed),
            rate_hz=rate_hz)
        warmup_s = 0.0
        warmup_compiles = 0
        if compile_gate:
            # One full payload period: every distinct prompt the
            # measured wave will send compiles (or cache-hits) here.
            generator.run(12 if warmup_requests is None
                          else int(warmup_requests),
                          drain_timeout_s=drain_timeout_s)
            warmup_compiles = ledger.compiles
            ledger.fence()
            warmup_s = time.time() - warmup_began
            plan = faults.install(chaos_schedule(seed))
        report = generator.run(n_requests,
                               drain_timeout_s=drain_timeout_s)
        totals = _fleet_kv_stats(servers)
        _attach_kv_rates(report, totals)
        report.final_tokens = dict(generator.final_tokens)
        report.fleet_latency_ms = fleet_latency(servers)
        report.spec_stats = _fleet_spec_stats(servers)
        report.spec_accept_hist = dict(generator.spec_accept_hist)
        report.server_stats = dict(
            router.counters, **totals,
            replicas_live=router.share["replicas"],
            faults_fired=len(plan.fired))
        if compile_gate:
            report.warmup_s = round(warmup_s, 3)
            report.warmup_compiles = warmup_compiles
            report.compiles_steady_state = ledger.steady_compiles
            if report.elapsed_s > 0:
                report.steady_tokens_per_sec = round(
                    report.tokens_total / report.elapsed_s, 2)
            if ledger.steady_compiles:
                offenders = sorted({
                    (entry["program"], entry["signature"])
                    for entry in ledger.snapshot()["records"]
                    if entry["steady"]})
                raise AssertionError(
                    f"chaos compile gate: {ledger.steady_compiles} "
                    f"steady-state compile(s) after the warmup fence "
                    f"— pow2 bucket discipline regressed: {offenders}")
        return report
    finally:
        faults.uninstall()
        if generator is not None:
            generator.close()
        for process in reversed(processes):
            try:
                process.terminate()
            except Exception:  # noqa: BLE001 - the chaos run may have
                pass           # already killed this process
        engine.terminate()
        thread.join(timeout=5)
        if ledger is not None:
            ledger.lift_fence()
            if ledger_owned:
                compiles.uninstall()
        if cache_tmp is not None:
            compiles.disable_persistent_cache()
            cache_tmp.cleanup()


def run_spec_ab(spec_k: int = 4, n_requests: int = 24,
                rate_hz: float = 50.0, seed: int = 0,
                chaos: bool = False,
                drain_timeout_s: float = 90.0
                ) -> Tuple[LoadReport, LoadReport]:
    """A/B gate for speculative decoding on the serving path: the SAME
    seeded payload sequence through the same 2-replica paged rig, once
    plain and once with a ``spec_k``-token paired draft, asserting the
    greedy outputs are BIT-EXACT request for request.  ``chaos=True``
    runs both sides under :func:`chaos_schedule` instead — a replica
    dying mid-spec-round must re-dispatch idempotently (zero lost,
    zero duplicate finals) and still match the plain side token for
    token, which rules out half-committed speculative state leaking
    across the replay.  Returns ``(base_report, spec_report)``; the
    spec report carries the fleet ``spec_stats`` counters and the
    per-request ``spec_accept_hist`` acceptance histograms."""
    if chaos:
        base = run_chaos(seed=seed, n_requests=n_requests,
                         rate_hz=rate_hz,
                         drain_timeout_s=drain_timeout_s)
        spec = run_chaos(seed=seed, n_requests=n_requests,
                         rate_hz=rate_hz,
                         drain_timeout_s=drain_timeout_s,
                         spec_k=spec_k)
    else:
        base = run_shared_prefix(n_requests=n_requests,
                                 rate_hz=rate_hz, seed=seed,
                                 drain_timeout_s=drain_timeout_s)
        spec = run_shared_prefix(n_requests=n_requests,
                                 rate_hz=rate_hz, seed=seed,
                                 drain_timeout_s=drain_timeout_s,
                                 spec_k=spec_k)
    both = set(base.final_tokens) & set(spec.final_tokens)
    mismatched = [request_id for request_id in sorted(both)
                  if base.final_tokens[request_id]
                  != spec.final_tokens[request_id]]
    if mismatched:
        raise AssertionError(
            f"spec A/B not bit-exact (spec_k={spec_k}, seed={seed}): "
            f"{len(mismatched)}/{len(both)} requests diverged, first "
            f"{mismatched[0]}")
    if not both:
        raise AssertionError(
            "spec A/B compared zero requests — both runs completed "
            "disjoint id sets, the gate proved nothing")
    return base, spec


def command_automaton(vocab: int = 1024):
    """Token grammar for the structured workload's agentic "tool
    call" — a JSON-shaped command ``{ "action" : VERB , "args" : [
    ARG{0..2} ] }`` where every skeleton token (braces, key names,
    colons, commas) is the SOLE legal token in its state.  Those
    single-token states chain into deterministic segments the
    jump-forward path drafts for free: of the 8-11 generated tokens
    only the verb and args are model choices."""
    from ..models.constrained import automaton_from_rules

    LBRACE, KEY_ACTION, COLON, COMMA = 10, 11, 12, 13
    KEY_ARGS, LBRACK, RBRACK, RBRACE = 14, 15, 16, 17
    VERBS, ARGS = (3, 4, 5), (6, 7, 8, 9)
    return automaton_from_rules(
        vocab=vocab,
        rules={
            0: [((LBRACE,), 1)],
            1: [((KEY_ACTION,), 2)],      # ── forced: "action"
            2: [((COLON,), 3)],           # ── forced: :
            3: [(VERBS, 4)],              #    model picks the verb
            4: [((COMMA,), 5)],           # ── forced: ,
            5: [((KEY_ARGS,), 6)],        # ── forced: "args"
            6: [((COLON,), 7)],           # ── forced: :
            7: [((LBRACK,), 8)],          # ── forced: [
            8: [(ARGS, 9), ((RBRACK,), 10)],
            9: [(ARGS, 11), ((RBRACK,), 10)],
            11: [((RBRACK,), 10)],        # ── forced: ] (args capped)
            10: [((RBRACE,), 12)],        # ── forced: }
            12: [],                       # terminal
        },
        accepting=[12])


def structured_payloads(n_contexts: int = 3, context_len: int = 32,
                        tail_len: int = 8, max_new_tokens: int = 16,
                        vocab: int = 1024, seed: int = 0,
                        constrained: bool = True
                        ) -> Callable[[int], Dict]:
    """Agentic structured-output traffic: ``n_contexts`` shared "tool
    context" prefixes (the agent scaffold every turn re-sends — prefix
    cache food) each followed by a fresh per-request observation tail,
    answered with a grammar-constrained command (``automaton="cmd"``).
    Greedy on purpose: the constrained-vs-unconstrained A/B compares
    goodput over IDENTICAL deterministic payloads.  ``constrained=
    False`` emits the same sequence without the automaton field — the
    B side of the goodput A/B."""
    import numpy as np

    rng = np.random.RandomState(seed)
    contexts = [rng.randint(1, vocab, size=context_len)
                .astype(np.int32) for _ in range(n_contexts)]

    def payload_fn(index: int) -> Dict:
        context = contexts[index % n_contexts]
        tail = np.asarray(
            [1 + (7451 * (index + 1) + 17 * position) % (vocab - 1)
             for position in range(tail_len)], np.int32)
        payload = {"tokens": np.concatenate([context, tail]),
                   "max_new_tokens": max_new_tokens,
                   "temperature": 0.0}
        if constrained:
            payload["automaton"] = "cmd"
        return payload

    return payload_fn


def run_structured(n_requests: int = 24, rate_hz: float = 50.0,
                   spec_k: int = 4, draft_mode: str = "ngram",
                   chaos: bool = False,
                   drain_timeout_s: float = 90.0,
                   seed: int = 0
                   ) -> Tuple[LoadReport, LoadReport]:
    """Structured-output workload gate: the SAME seeded agentic
    payload sequence through an automaton-equipped 2-replica paged
    rig, once grammar-constrained and once free-running, returning
    ``(constrained_report, unconstrained_report)``.  Three checks ride
    on it: every constrained final is accepted by the grammar (chaos
    replays included — half-committed automaton state leaking across a
    re-dispatch would surface here as an ungrammatical final), the
    fleet counters carry non-zero ``spec_jump_forward_tokens`` (the
    skeleton segments really were drafted, not decoded), and the pair
    of reports gives the constrained-vs-unconstrained goodput A/B
    (``tokens_total / elapsed_s``; constrained wins when jump-forward
    commits the skeleton in bulk).  ``chaos=True`` arms the standard
    :func:`chaos_schedule` for BOTH sides.  ``draft_mode="ngram"``
    (default) runs model-free — the structured gate composes with
    self-drafting and needs no second model."""
    from ..orchestration.continuous import ContinuousReplica
    from ..orchestration.paged import PagedContinuousServer
    from ..orchestration.serving import ReplicaRouter
    from ..registry import Registrar
    from ..runtime import (Process, actor_args, compose_instance,
                           faults)
    from ..runtime.event import EventEngine

    automaton = command_automaton()

    def one_pass(constrained: bool) -> LoadReport:
        def wait_for(predicate, timeout_s: float, what: str):
            deadline = time.time() + timeout_s
            while not predicate():
                if time.time() > deadline:
                    raise TimeoutError(f"structured rig: {what}")
                time.sleep(0.02)

        plan = faults.install(chaos_schedule(seed)) if chaos else None
        engine = EventEngine()
        thread = engine.run_in_thread()
        broker = f"structured-{uuid.uuid4().hex[:6]}"
        processes = []

        def make_process(pid):
            process = Process(namespace="structured", hostname="h",
                              pid=str(pid), engine=engine,
                              broker=broker)
            processes.append(process)
            return process

        generator = None
        servers = []
        try:
            registrar = Registrar(process=make_process(1))
            wait_for(lambda: registrar.state == "primary", 10,
                     "registrar primary")
            for index, name in enumerate(("replica_a", "replica_b")):
                server = PagedContinuousServer(
                    config_name="tiny", slots=2, chunk_steps=4,
                    seed=0, enable_prefix_cache=True, max_queue=256,
                    watchdog_s=5.0,
                    draft_mode=draft_mode,
                    draft_config_name=("tiny" if draft_mode == "model"
                                       else None),
                    spec_k=spec_k,
                    automata={"cmd": automaton})
                if draft_mode == "model":
                    _enable_paired_draft(server, spec_k)
                servers.append(server)
                compose_instance(ContinuousReplica, actor_args(name),
                                 process=make_process(2 + index),
                                 server=server)
            router = compose_instance(
                ReplicaRouter, actor_args("router"),
                process=make_process(8), kv_transfer=True)
            wait_for(lambda: router.share["replicas"] == 2, 30,
                     "router discovery")
            generator = LoadGenerator(
                make_process(9), f"{router.topic_path}/in",
                payload_fn=structured_payloads(
                    seed=seed, constrained=constrained),
                rate_hz=rate_hz)
            report = generator.run(n_requests,
                                   drain_timeout_s=drain_timeout_s)
            report.final_tokens = dict(generator.final_tokens)
            report.fleet_latency_ms = fleet_latency(servers)
            report.spec_stats = _fleet_spec_stats(servers)
            report.spec_accept_hist = dict(generator.spec_accept_hist)
            report.server_stats = dict(router.counters)
            if plan is not None:
                report.server_stats["faults_fired"] = len(plan.fired)
            return report
        finally:
            if chaos:
                faults.uninstall()
            if generator is not None:
                generator.close()
            for process in reversed(processes):
                try:
                    process.terminate()
                except Exception:  # noqa: BLE001 - teardown (chaos may
                    pass           # have killed this process already)
            engine.terminate()
            thread.join(timeout=5)

    cons = one_pass(constrained=True)
    free = one_pass(constrained=False)
    bad = [request_id for request_id, tokens
           in sorted(cons.final_tokens.items())
           if not automaton.accepts(list(tokens))]
    if bad:
        raise AssertionError(
            f"structured workload: {len(bad)}/{len(cons.final_tokens)}"
            f" constrained finals ungrammatical (seed={seed}, "
            f"chaos={chaos}), first {bad[0]}")
    if not cons.final_tokens:
        raise AssertionError(
            "structured workload: zero constrained finals — the "
            "grammar gate proved nothing")
    return cons, free


def diurnal_trace(duration_s: float, base_hz: float = 2.0,
                  peak_hz: float = 12.0, period_s: float = 8.0,
                  burst_hz: float = 0.0, burst_every_s: float = 0.0,
                  burst_len_s: float = 1.0,
                  seed: int = 0) -> List[float]:
    """Seeded diurnal arrival schedule: send offsets (seconds) for a
    sinusoidal base rate — ``base_hz`` in the valley, ``peak_hz`` at
    the crest, period ``period_s`` — with optional Poisson-arriving
    bursts (``burst_hz`` extra for ``burst_len_s``, mean gap
    ``burst_every_s``).  Arrivals are a non-homogeneous Poisson
    process generated by thinning, fully deterministic per ``seed`` —
    the workload shape an autoscaler must track (valleys are where a
    static peak-sized fleet wastes replicas; bursts are what hysteresis
    must not overreact to).  Feed to :meth:`LoadGenerator.run_trace`."""
    import math
    import random

    rng = random.Random(seed)
    bursts: List[Tuple[float, float]] = []
    if burst_hz > 0 and burst_every_s > 0:
        t = rng.expovariate(1.0 / burst_every_s)
        while t < duration_s:
            bursts.append((t, t + burst_len_s))
            t += burst_len_s + rng.expovariate(1.0 / burst_every_s)

    def rate_at(t: float) -> float:
        wave = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / period_s))
        rate = base_hz + (peak_hz - base_hz) * wave
        if any(start <= t < end for start, end in bursts):
            rate += burst_hz
        return rate

    rate_max = max(base_hz, peak_hz) + burst_hz
    times: List[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate_max)
        if t >= duration_s:
            return times
        if rng.random() * rate_max < rate_at(t):
            times.append(t)


def elastic_chaos_schedule(seed: int):
    """The seeded fault schedule gating elastic scale-down: during a
    scripted ``scale_target`` 3→2 scale-down (victim: the idlest
    replica — lexicographically ``decode1`` in the early-run valley),
    ``decode3`` is killed outright, its replacement's first spawn
    attempt fails, and the retry is slow-started.  The invariant: the
    fleet still converges to the target with zero lost and zero
    double-delivered requests.  The rig installs this plan AFTER its
    warmup phase, so ``nth`` counts start with the measured run."""
    from ..runtime import faults
    return (
        faults.FaultPlan(seed=seed)
        # In-process kill (no hard=1: os._exit would take the whole
        # rig); pump count puts it mid-load, after the scale-down.
        .add("kill_replica", nth=6 + seed % 5, match="decode3")
        # The post-kill REPLACEMENT spawn fails outright (bootstrap
        # spawns happened before the plan was installed).
        .add("fail_spawn", nth=1, match="decode3")
        # The retry after the failed replacement announces late
        # (pending-spawn accounting covers the gap — no spawn storm).
        .add("slow_start", nth=1, match="decode3", ms=300))


def _elastic_payloads(seed: int = 0, prompt_len: int = 12,
                      max_new_tokens: int = 4, vocab: int = 1024,
                      stream: bool = False) -> Callable[[int], Dict]:
    """Independent random prompts (no shared prefix — elasticity, not
    cache locality, is under test)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    prompts = rng.randint(1, vocab,
                          size=(32, prompt_len)).astype(np.int32)

    def payload_fn(index: int) -> Dict:
        payload = {"tokens": prompts[index % len(prompts)],
                   "max_new_tokens": max_new_tokens}
        if stream:
            payload["stream"] = 1
        return payload

    return payload_fn


def run_elastic(duration_s: float = 10.0, seed: int = 0,
                base_hz: float = 2.0, peak_hz: float = 12.0,
                period_s: float = 8.0, burst_hz: float = 0.0,
                burst_every_s: float = 0.0, burst_len_s: float = 1.0,
                slo_ttft_ms: float = 500.0,
                static_replicas: Optional[int] = None,
                policy=None, stream: bool = False,
                max_new_tokens: int = 4,
                drain_timeout_s: float = 90.0,
                fault_plan=None,
                scale_script: Tuple[Tuple[float, int], ...] = (),
                command_script: Tuple[Tuple[float, str], ...] = (),
                converge_s: float = 0.0,
                warmup: int = 0) -> LoadReport:
    """In-process ELASTIC serving rig: a :class:`FleetAutoscaler`
    owns the replica fleet (in-process spawner building tiny PAGED
    servers on background threads; terminator kills the replica's
    Process so the Registrar LWT path runs for real) behind a
    ReplicaRouter, driven by a :func:`diurnal_trace` schedule.

    ``static_replicas=N`` instead pins a fixed N-replica fleet with no
    autoscaler — the A/B baseline: the autoscaled fleet must beat the
    static PEAK-sized fleet on ``goodput_per_replica`` over a diurnal
    day (bench.py's ``serving_autoscale`` section and the slow gate).

    ``scale_script`` is a sequence of ``(delay_s, target)`` operator
    ``(scale_target …)`` commands fired mid-run (the chaos gate's
    scripted scale-down); ``command_script`` fires arbitrary raw
    operator s-exprs at the autoscaler (e.g. ``(rolling_upgrade)``
    for the zero-downtime upgrade rig); ``fault_plan`` installs a
    :mod:`~..runtime.faults` plan for the run; ``converge_s`` waits
    after the load for the fleet to settle (live == target, nothing
    pending or draining) and records ``converged`` in
    ``server_stats``.

    ``warmup`` sends that many throwaway requests BEFORE the measured
    run (and before the fault plan installs): the first decode step
    JIT-compiles on the engine thread, a multi-second stall that would
    otherwise smear the scale/fault timeline into one wakeup."""
    import threading

    from ..orchestration.autoscaler import (AutoscalerPolicy,
                                            FleetAutoscaler)
    from ..orchestration.continuous import ContinuousReplica
    from ..orchestration.paged import PagedContinuousServer
    from ..orchestration.serving import ReplicaRouter
    from ..registry import Registrar
    from ..runtime import (Process, actor_args, compose_instance,
                           faults)
    from ..runtime.event import EventEngine

    def wait_for(predicate, timeout_s: float, what: str):
        deadline = time.time() + timeout_s
        while not predicate():
            if time.time() > deadline:
                raise TimeoutError(f"elastic rig: {what}")
            time.sleep(0.02)

    engine = EventEngine()
    thread = engine.run_in_thread()
    broker = f"elastic-{uuid.uuid4().hex[:6]}"
    processes: List = []
    pid_lock = threading.Lock()
    next_pid = [1]

    def make_process():
        with pid_lock:
            pid = next_pid[0]
            next_pid[0] += 1
        process = Process(namespace="elastic", hostname="h",
                          pid=str(pid), engine=engine, broker=broker)
        processes.append(process)
        return process

    #: slot -> {"process", "server"} for every replica ever built.
    fleet: Dict[str, Dict] = {}
    fleet_lock = threading.Lock()
    servers: List = []

    def build_replica(slot: str):
        # Heavy JAX construction runs OFF the engine thread (the
        # autoscaler calls the spawner from its tick timer; blocking
        # the engine would stall every announcement and drain).
        server = PagedContinuousServer(
            config_name="tiny", slots=2, chunk_steps=4, seed=0,
            enable_prefix_cache=True, max_queue=256, watchdog_s=5.0)
        process = make_process()
        compose_instance(ContinuousReplica, actor_args(slot),
                         process=process, server=server)
        with fleet_lock:
            fleet[slot] = {"process": process, "server": server}
            servers.append(server)

    def spawner(slot: str, _role: str):
        threading.Thread(target=build_replica, args=(slot,),
                         daemon=True).start()

    def terminator(slot: str, _mode: str):
        with fleet_lock:
            entry = fleet.get(slot)
        if entry is None:
            return
        # Non-graceful: the LWT (absent) fires, exactly the eviction
        # path a real dead OS process takes.  Off the engine thread —
        # terminate pumps the transport.
        threading.Thread(target=entry["process"].terminate,
                         kwargs=dict(graceful=False),
                         daemon=True).start()

    generator = None
    autoscaler = None
    timers: List = []
    try:
        registrar = Registrar(process=make_process())
        wait_for(lambda: registrar.state == "primary", 10,
                 "registrar primary")
        router = compose_instance(ReplicaRouter, actor_args("router"),
                                  process=make_process(),
                                  kv_transfer=True)
        if static_replicas is not None:
            expected = static_replicas
            for index in range(static_replicas):
                build_replica(f"static{index + 1}")
        else:
            if policy is None:
                policy = AutoscalerPolicy(
                    target=1, max_replicas=3, ttft_slo_ms=slo_ttft_ms,
                    breach_windows=2, clear_windows=8,
                    cooldown_s=2.0, spawn_timeout_s=60.0,
                    drain_timeout_s=15.0)
            expected = policy.initial_targets().get("decode", 1)
            autoscaler = compose_instance(
                FleetAutoscaler, actor_args("autoscaler"),
                process=make_process(), spawner=spawner,
                terminator=terminator, policy=policy, tick_s=0.25)
        wait_for(lambda: router.share["replicas"] >= expected, 90,
                 f"router discovery of {expected} replicas")
        generator = LoadGenerator(
            make_process(), f"{router.topic_path}/in",
            payload_fn=_elastic_payloads(
                seed=seed, max_new_tokens=max_new_tokens,
                stream=stream),
            rate_hz=0)
        if warmup:
            # Throwaway compile-warming burst; spacing gives P2C a
            # chance to touch every replica.
            generator.run_trace([0.1 * i for i in range(warmup)],
                                drain_timeout_s=30.0)
        if fault_plan is not None:
            faults.install(fault_plan)
        commands = [(delay_s, f"(scale_target {target})")
                    for delay_s, target in scale_script]
        commands += [(delay_s, command)
                     for delay_s, command in command_script]
        for delay_s, command in (commands if autoscaler is not None
                                 else ()):
            timer = threading.Timer(
                delay_s,
                lambda c=command: autoscaler.process.message.publish(
                    f"{autoscaler.topic_path}/in", c))
            timer.daemon = True
            timer.start()
            timers.append(timer)
        times = diurnal_trace(
            duration_s, base_hz=base_hz, peak_hz=peak_hz,
            period_s=period_s, burst_hz=burst_hz,
            burst_every_s=burst_every_s, burst_len_s=burst_len_s,
            seed=seed)
        replica_seconds_0 = (
            float(autoscaler.share["replica_seconds"])
            if autoscaler is not None else 0.0)
        report = generator.run_trace(times,
                                     drain_timeout_s=drain_timeout_s)
        report.slo_ttft_ms = slo_ttft_ms
        # Stream-consistency audit: for every streamed request the
        # concatenated partials must equal the final token sequence —
        # a drain/kill/re-dispatch that re-streams or drops a token
        # shows up here as a mismatch.
        stream_mismatches = sum(
            1 for request_id, partials in generator.partial_tokens.items()
            if request_id in generator.final_tokens
            and partials != generator.final_tokens[request_id])
        converged = None
        if autoscaler is not None:
            if converge_s:
                want = sum(autoscaler.state.targets.values())

                def settled():
                    return (autoscaler.share["replicas_live"] == want
                            and autoscaler.share["replicas_pending"]
                            == 0
                            and autoscaler.share["replicas_draining"]
                            == 0)

                deadline = time.time() + converge_s
                while not settled() and time.time() < deadline:
                    time.sleep(0.05)
                converged = settled()
            report.replica_seconds = (
                float(autoscaler.share["replica_seconds"])
                - replica_seconds_0)
            report.server_stats = dict(
                autoscaler.stats(),
                router_shed=router.counters["shed"],
                redispatches=router.counters["redispatches"],
                migrations_started=router.counters[
                    "migrations_started"],
                migrations_completed=router.counters[
                    "migrations_completed"],
                migrations_aborted=router.counters[
                    "migrations_aborted"],
                migration_cutover_ms=list(router.migration.cutover_ms),
                stream_mismatches=stream_mismatches,
                faults_fired=(len(fault_plan.fired)
                              if fault_plan is not None else 0))
            if converged is not None:
                report.server_stats["converged"] = converged
        else:
            report.replica_seconds = static_replicas * report.elapsed_s
            report.server_stats = dict(
                replicas_live=router.share["replicas"],
                router_shed=router.counters["shed"],
                redispatches=router.counters["redispatches"],
                stream_mismatches=stream_mismatches)
        report.fleet_latency_ms = fleet_latency(servers)
        return report
    finally:
        if fault_plan is not None:
            faults.uninstall()
        for timer in timers:
            timer.cancel()
        if generator is not None:
            generator.close()
        for process in reversed(processes):
            try:
                process.terminate()
            except Exception:  # noqa: BLE001 - the chaos run may have
                pass           # already killed this process
        engine.terminate()
        thread.join(timeout=5)


def run_elastic_chaos(seed: int = 0, duration_s: float = 8.0,
                      **kwargs) -> LoadReport:
    """The chaos gate for elastic scale-down (ISSUE acceptance): a
    3-replica autoscaled fleet under streaming load gets a scripted
    ``scale_target 2`` (graceful drain) while
    :func:`elastic_chaos_schedule` kills a NON-draining replica and
    fails its replacement's first spawn.  The run must converge to the
    target with ``lost == 0`` and ``duplicate_finals == 0`` — the
    hard invariant of the drain design.  SLO-driven scaling is frozen
    (huge windows) so only the scripted scale-in and self-healing
    move the fleet."""
    from ..orchestration.autoscaler import AutoscalerPolicy

    policy = AutoscalerPolicy(
        target=3, min_replicas=1, max_replicas=4,
        breach_windows=10 ** 6, clear_windows=10 ** 6,
        cooldown_s=3600.0, spawn_timeout_s=60.0,
        drain_timeout_s=10.0, backoff_base_s=0.5,
        crash_loop_threshold=3, crash_loop_window_s=60.0)
    kwargs.setdefault("scale_script", ((max(0.6, duration_s * 0.1),
                                        2),))
    kwargs.setdefault("converge_s", 30.0)
    kwargs.setdefault("stream", True)
    kwargs.setdefault("warmup", 6)
    return run_elastic(duration_s=duration_s, seed=seed,
                       policy=policy,
                       fault_plan=elastic_chaos_schedule(seed),
                       **kwargs)


def migration_chaos_schedule(seed: int, phase: str = "none"):
    """Seeded fault schedule for the live-migration chaos gate — one
    fault class per ``phase`` so a run exercises exactly one migration
    failure point (each phase is a separate loadgen invocation / test
    parametrization):

    * ``transfer``  — ``drop_migration_block``: the source drops the
      last exported KV block; the destination resumes one block colder
      and recomputes the tail (still bit-exact).
    * ``cutover``   — ``stall_cutover``: wedge the router inside the
      double-delivery window, forcing the token-offset dedup to earn
      its keep.
    * ``source``    — ``kill_source_mid_migration``: the source
      replica dies while migrations are in flight; TRANSFER-phase
      requests promote to the destination, earlier phases abort into
      the normal re-dispatch path.
    * ``none``      — no faults: the clean-migration control.
    """
    from ..runtime import faults
    plan = faults.FaultPlan(seed=seed)
    if phase == "transfer":
        plan.add("drop_migration_block", nth=1)
    elif phase == "cutover":
        plan.add("stall_cutover", nth=1, ms=60)
    elif phase == "source":
        plan.add("kill_source_mid_migration", nth=2,
                 match="replica_a")
    elif phase != "none":
        raise ValueError(f"unknown migration chaos phase: {phase}")
    return plan


def run_migration_chaos(seed: int = 0, n_requests: int = 10,
                        rate_hz: float = 60.0,
                        phase: str = "none",
                        migrate_delay_s: float = 0.05,
                        max_new_tokens: int = 48,
                        drain_timeout_s: float = 90.0
                        ) -> Tuple[LoadReport, LoadReport]:
    """Drain-free live-migration chaos gate: an in-process 2-replica
    rig streams requests while a mid-run ``(migrate replica_a)``
    operator command evacuates replica_a's whole in-flight population
    to replica_b mid-decode, under the :func:`migration_chaos_schedule`
    fault ``phase``.  Returns ``(control, migrated)`` where control is
    the identical seeded run WITHOUT the migration.

    The invariants (asserted by tests/test_migration.py and the CLI):
    zero lost, zero hung, zero duplicated finals, zero stream
    mismatches (concatenated partials == final sequence, i.e. the
    double-delivery window deduped exactly), and BIT-EXACT final
    tokens against the unmigrated control for every request both runs
    completed — migration must be invisible to the token stream."""

    from ..orchestration.continuous import ContinuousReplica
    from ..orchestration.paged import PagedContinuousServer
    from ..orchestration.serving import ReplicaRouter
    from ..registry import Registrar
    from ..runtime import (Process, actor_args, compose_instance,
                           faults)
    from ..runtime.event import EventEngine

    import threading

    def wait_for(predicate, timeout_s: float, what: str):
        deadline = time.time() + timeout_s
        while not predicate():
            if time.time() > deadline:
                raise TimeoutError(f"migration rig: {what}")
            time.sleep(0.02)

    def one_run(migrate: bool) -> LoadReport:
        plan = None
        engine = EventEngine()
        thread = engine.run_in_thread()
        broker = f"migrate-{uuid.uuid4().hex[:6]}"
        processes = []

        def make_process(pid):
            process = Process(namespace="migrate", hostname="h",
                              pid=str(pid), engine=engine,
                              broker=broker)
            processes.append(process)
            return process

        generator = None
        timer = None
        try:
            registrar = Registrar(process=make_process(1))
            wait_for(lambda: registrar.state == "primary", 10,
                     "registrar primary")
            replicas = {}
            for index, name in enumerate(("replica_a", "replica_b")):
                # Same config+seed: greedy decode is replica-
                # independent, so a migrated request's destination
                # continues the exact sequence the source started.
                server = PagedContinuousServer(
                    config_name="tiny", slots=4, chunk_steps=2,
                    seed=0, enable_prefix_cache=True, max_queue=256,
                    watchdog_s=5.0)
                replicas[name] = compose_instance(
                    ContinuousReplica, actor_args(name),
                    process=make_process(2 + index), server=server,
                    kv_fetch_timeout_s=2.0)
            router = compose_instance(
                ReplicaRouter, actor_args("router"),
                process=make_process(8), kv_transfer=True)
            wait_for(lambda: router.share["replicas"] == 2, 30,
                     "router discovery")
            generator = LoadGenerator(
                make_process(9), f"{router.topic_path}/in",
                payload_fn=_elastic_payloads(
                    seed=seed, prompt_len=18,
                    max_new_tokens=max_new_tokens, stream=True),
                rate_hz=rate_hz)
            # Warm the decode programs first (both arms identically):
            # the measured wave then runs at steady speed, so the
            # migration trigger really lands mid-decode instead of
            # after a compile-stretched drain.
            generator.run(2, drain_timeout_s=drain_timeout_s)
            if migrate:
                plan = faults.install(migration_chaos_schedule(
                    seed, phase))
                source_topic = replicas["replica_a"].topic_path

                def fire_when_mid_decode():
                    # Deterministic trigger: wait until the source
                    # owns a request that has already streamed at
                    # least one token, then evacuate the source.
                    deadline = time.time() + migrate_delay_s + 30.0
                    time.sleep(migrate_delay_s)
                    while time.time() < deadline:
                        inflight = list(router._inflight.values())
                        if any(entry.get("replica") == source_topic
                               and entry.get("delivered", 0) > 0
                               for entry in inflight):
                            router.process.message.publish(
                                f"{router.topic_path}/in",
                                f"(migrate {source_topic})")
                            return
                        time.sleep(0.002)

                timer = threading.Thread(target=fire_when_mid_decode,
                                         daemon=True)
                timer.start()
            report = generator.run(n_requests,
                                   drain_timeout_s=drain_timeout_s)
            report.final_tokens = dict(generator.final_tokens)
            stream_mismatches = sum(
                1 for request_id, partials
                in generator.partial_tokens.items()
                if request_id in generator.final_tokens
                and partials != generator.final_tokens[request_id])
            report.server_stats = dict(
                router.counters,
                stream_mismatches=stream_mismatches,
                migration_cutover_ms=list(
                    router.migration.cutover_ms),
                faults_fired=(len(plan.fired) if plan else 0),
                replicas_live=router.share["replicas"])
            return report
        finally:
            faults.uninstall()
            if generator is not None:
                generator.close()
            for process in reversed(processes):
                try:
                    process.terminate()
                except Exception:  # noqa: BLE001 - the kill phase may
                    pass           # have taken this process already
            engine.terminate()
            thread.join(timeout=5)

    control = one_run(migrate=False)
    migrated = one_run(migrate=True)
    return control, migrated


def run_rolling_upgrade(duration_s: float = 10.0, seed: int = 0,
                        replicas: int = 4,
                        drain_based: bool = False,
                        **kwargs) -> LoadReport:
    """Zero-downtime rolling upgrade goodput trace: a ``replicas``-
    strong autoscaled fleet under streaming diurnal load receives a
    mid-run ``(rolling_upgrade)`` — every replica is replaced one at a
    time with its in-flight population LIVE-MIGRATED onto the
    successor.  ``drain_based=True`` is the A/B control: the same
    replacement loop but each predecessor drains its tail instead of
    migrating it (``policy.migrate_drains`` off).  The bench section
    compares goodput and total upgrade wall-time between the two."""
    from ..orchestration.autoscaler import AutoscalerPolicy

    policy = AutoscalerPolicy(
        target=replicas, min_replicas=1, max_replicas=replicas + 2,
        breach_windows=10 ** 6, clear_windows=10 ** 6,
        cooldown_s=3600.0, spawn_timeout_s=60.0,
        drain_timeout_s=20.0,
        migrate_drains=not drain_based)
    kwargs.setdefault("command_script",
                      ((max(0.6, duration_s * 0.15),
                        "(rolling_upgrade)"),))
    kwargs.setdefault("converge_s", 60.0)
    kwargs.setdefault("stream", True)
    kwargs.setdefault("warmup", 6)
    # Dense enough that every replica holds live streams at any
    # instant: each handoff then really carries an in-flight
    # population instead of landing in a gap between requests.
    kwargs.setdefault("base_hz", 8.0)
    kwargs.setdefault("peak_hz", 12.0)
    kwargs.setdefault("max_new_tokens", 48)
    return run_elastic(duration_s=duration_s, seed=seed,
                       policy=policy, **kwargs)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m aiko_services_tpu.tools.loadgen --chaos`` (seeded
    fault schedule; exit 1 if any request was lost or hung) or
    ``--workload shared_prefix`` (multi-turn shared-system-prompt
    profile through the prefix-aware router)."""
    import argparse

    parser = argparse.ArgumentParser(
        description="Serving load generator (chaos mode: seeded "
                    "fault-injection run asserting zero lost "
                    "requests; shared_prefix workload: multi-turn "
                    "conversations against the prefix-aware router)")
    parser.add_argument("--chaos", action="store_true",
                        help="run the seeded fault schedule against "
                             "an in-process 2-replica rig")
    parser.add_argument("--elastic-chaos", action="store_true",
                        help="run the elastic scale-down chaos gate "
                             "(drain + kill-during-drain + failed "
                             "replacement spawn; exit 1 unless zero "
                             "lost/duplicated and converged)")
    parser.add_argument("--migrate-mid-stream", action="store_true",
                        help="live-migration chaos gate: evacuate one "
                             "replica's in-flight population to the "
                             "other mid-decode under a seeded fault "
                             "phase; exit 1 unless zero lost/"
                             "duplicated/mismatched and bit-exact vs "
                             "the unmigrated control")
    parser.add_argument("--migration-phase", default="none",
                        choices=["none", "transfer", "cutover",
                                 "source"],
                        help="--migrate-mid-stream: which migration "
                             "phase the seeded fault hits")
    parser.add_argument("--rolling-upgrade", action="store_true",
                        help="zero-downtime rolling upgrade trace: "
                             "replace every replica one at a time "
                             "with live migration, vs the drain-based "
                             "control")
    parser.add_argument("--workload",
                        choices=["shared_prefix", "diurnal",
                                 "longtail", "structured",
                                 "multitenant"],
                        help="named workload profile (in-process rig)")
    parser.add_argument("--tenants", type=int, default=4,
                        help="multitenant: distinct LoRA adapters "
                             "(zipf-popular tenants split across two "
                             "replicas)")
    parser.add_argument("--zipf-s", type=float, default=1.2,
                        help="multitenant: zipf exponent of adapter "
                             "popularity (higher = hotter head)")
    parser.add_argument("--draft-mode", default="ngram",
                        choices=["ngram", "model"],
                        help="structured workload: proposer for the "
                             "speculative path (ngram = model-free "
                             "self-drafting)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--requests", type=int, default=40)
    parser.add_argument("--rate-hz", type=float, default=100.0)
    parser.add_argument("--duration", type=float, default=12.0,
                        help="diurnal/elastic: run length (seconds)")
    parser.add_argument("--base-hz", type=float, default=2.0,
                        help="diurnal: valley request rate")
    parser.add_argument("--peak-hz", type=float, default=12.0,
                        help="diurnal: crest request rate")
    parser.add_argument("--period", type=float, default=8.0,
                        help="diurnal: sinusoid period (seconds)")
    parser.add_argument("--slo-ttft-ms", type=float, default=500.0,
                        help="diurnal: TTFT SLO goodput is judged "
                             "against")
    parser.add_argument("--static-replicas", type=int, default=None,
                        help="diurnal: pin a fixed fleet (A/B "
                             "baseline) instead of autoscaling")
    parser.add_argument("--conversations", type=int, default=3,
                        help="shared_prefix: interleaved conversations")
    parser.add_argument("--turns", type=int, default=4,
                        help="shared_prefix: turns per conversation")
    parser.add_argument("--system-len", type=int, default=48,
                        help="shared_prefix: shared system prompt "
                             "tokens")
    parser.add_argument("--no-prefix-routing", action="store_true",
                        help="shared_prefix: disable prefix-aware "
                             "scoring (A/B baseline)")
    parser.add_argument("--prefixes", type=int, default=6,
                        help="longtail: distinct shared prefixes "
                             "(working set = prefixes x prefix-len "
                             "blocks)")
    parser.add_argument("--prefix-len", type=int, default=384,
                        help="longtail: tokens per shared prefix")
    parser.add_argument("--hbm-blocks", type=int, default=52,
                        help="longtail: HBM pool size in blocks "
                             "(deliberately smaller than the prefix "
                             "working set)")
    parser.add_argument("--host-blocks", type=int, default=160,
                        help="longtail: host-RAM tier capacity in "
                             "blocks (0 = tier off, the A/B baseline)")
    parser.add_argument("--tier-off", action="store_true",
                        help="longtail: shorthand for --host-blocks 0")
    parser.add_argument("--disk-blocks", type=int, default=0,
                        help="longtail: SSD spill tier capacity in "
                             "blocks under a fresh temp directory "
                             "(0 = no spill tier); host overflow "
                             "demotes to disk instead of purging")
    parser.add_argument("--restart-replica", action="store_true",
                        help="warm-restart chaos A/B: kill the only "
                             "replica mid-run and respawn it cold vs "
                             "spill-adopting; exit 1 unless greedy "
                             "outputs are bit-exact and the warm arm "
                             "beats cold on hit rate and mean TTFT")
    parser.add_argument("--trace-out", metavar="DIR",
                        help="enable distributed tracing and dump the "
                             "slowest requests' span trees as Chrome "
                             "trace-event JSON (Perfetto-loadable) "
                             "into DIR")
    parser.add_argument("--trace-top", type=int, default=5,
                        help="how many slowest requests --trace-out "
                             "dumps")
    parser.add_argument("--spec-k", type=int, default=0,
                        help="speculative A/B gate: run the seeded "
                             "payload sequence plain AND with a "
                             "k-token paired draft, assert BIT-EXACT "
                             "outputs, report acceptance histograms "
                             "(composes with --chaos: both sides run "
                             "the fault schedule)")
    args = parser.parse_args(argv)
    if args.migrate_mid_stream:
        control, migrated = run_migration_chaos(
            seed=args.seed,
            n_requests=args.requests if args.requests != 40 else 10,
            rate_hz=args.rate_hz, phase=args.migration_phase)
        print("control: ", control)
        print("migrated:", migrated)
        stats = migrated.server_stats
        print(f"router counters: {stats}")
        both = set(control.final_tokens) & set(migrated.final_tokens)
        mismatched = [request_id for request_id in both
                      if control.final_tokens[request_id]
                      != migrated.final_tokens[request_id]]
        ok = (not migrated.lost and not migrated.timeouts
              and not migrated.duplicate_finals
              and not stats.get("stream_mismatches")
              and stats.get("migrations_started", 0) > 0
              and not mismatched and both)
        if not ok:
            print(f"MIGRATION CHAOS FAIL (seed={args.seed}, "
                  f"phase={args.migration_phase}): {migrated.lost} "
                  f"lost, {migrated.timeouts} hung, "
                  f"{migrated.duplicate_finals} duplicated, "
                  f"{stats.get('stream_mismatches')} stream "
                  f"mismatches, {len(mismatched)} diverged vs "
                  f"control")
            return 1
        cutovers = stats.get("migration_cutover_ms", [])
        print(f"MIGRATION CHAOS OK (seed={args.seed}, "
              f"phase={args.migration_phase}): "
              f"{stats.get('migrations_completed')} migrated / "
              f"{stats.get('migrations_aborted')} aborted, "
              f"{len(cutovers)} cutovers, bit-exact vs control")
        return 0
    if args.rolling_upgrade:
        migrated = run_rolling_upgrade(duration_s=args.duration,
                                       seed=args.seed)
        drained = run_rolling_upgrade(duration_s=args.duration,
                                      seed=args.seed,
                                      drain_based=True)
        for label, report in (("live-migrated", migrated),
                              ("drain-based ", drained)):
            stats = report.server_stats
            print(f"{label}: goodput={report.goodput_rps:.2f} req/s, "
                  f"upgrades={stats.get('upgrades_completed')}, "
                  f"migrations={stats.get('migrations_completed')}, "
                  f"lost={report.lost}")
        stats = migrated.server_stats
        ok = (not migrated.lost and not migrated.timeouts
              and not migrated.duplicate_finals
              and not stats.get("stream_mismatches")
              and stats.get("upgrades_completed", 0) > 0
              and stats.get("converged"))
        if not ok:
            print(f"ROLLING UPGRADE FAIL (seed={args.seed}): "
                  f"{migrated.lost} lost, {migrated.timeouts} hung, "
                  f"{migrated.duplicate_finals} duplicated, "
                  f"converged={stats.get('converged')}")
            return 1
        print(f"ROLLING UPGRADE OK (seed={args.seed}): fleet "
              f"replaced with zero lost/duplicated tokens")
        return 0
    if args.workload == "structured":
        cons, free = run_structured(
            n_requests=args.requests, rate_hz=args.rate_hz,
            spec_k=args.spec_k or 4, draft_mode=args.draft_mode,
            chaos=args.chaos, seed=args.seed)
        print("constrained:  ", cons)
        print("unconstrained:", free)
        stats = cons.spec_stats or {}
        cons_tps = (cons.tokens_total / cons.elapsed_s
                    if cons.elapsed_s else 0.0)
        free_tps = (free.tokens_total / free.elapsed_s
                    if free.elapsed_s else 0.0)
        print(f"fleet spec counters: {stats}")
        print(f"goodput A/B: constrained {cons_tps:.1f} tok/s "
              f"({stats.get('spec_jump_forward_tokens', 0)} "
              f"jump-forward tok) vs unconstrained {free_tps:.1f} "
              f"tok/s")
        failed = (cons.lost or cons.timeouts or free.lost
                  or free.timeouts
                  or (args.chaos and (cons.duplicate_finals
                                      or free.duplicate_finals)))
        if failed:
            print(f"STRUCTURED FAIL (seed={args.seed}): "
                  f"{cons.lost}+{free.lost} lost, "
                  f"{cons.timeouts}+{free.timeouts} hung, "
                  f"{cons.duplicate_finals}+{free.duplicate_finals} "
                  f"duplicated")
            return 1
        mode = "chaos" if args.chaos else "steady"
        print(f"STRUCTURED OK ({mode}, seed={args.seed}): all "
              f"constrained finals grammatical, "
              f"{stats.get('spec_jump_forward_tokens', 0)} skeleton "
              f"tokens jump-forwarded")
        return 0
    if args.spec_k:
        base, spec = run_spec_ab(
            spec_k=args.spec_k, n_requests=args.requests,
            rate_hz=args.rate_hz, seed=args.seed, chaos=args.chaos)
        print("base:", base)
        print("spec:", spec)
        print(f"fleet spec counters: {spec.spec_stats}")
        lengths = sorted(len(hist) for hist
                         in spec.spec_accept_hist.values())
        accepted = [count for hist in spec.spec_accept_hist.values()
                    for count in hist]
        mean_accept = (statistics.fmean(accepted) if accepted else 0.0)
        print(f"accept histograms: {len(lengths)} requests, "
              f"rounds/request p50="
              f"{lengths[len(lengths) // 2] if lengths else 0}, "
              f"mean accepted/round={mean_accept:.2f}")
        if args.chaos and (spec.lost or spec.timeouts
                           or spec.duplicate_finals):
            print(f"SPEC CHAOS FAIL (seed={args.seed}): "
                  f"{spec.lost} lost, {spec.timeouts} hung, "
                  f"{spec.duplicate_finals} duplicated")
            return 1
        mode = "chaos" if args.chaos else "shared_prefix"
        print(f"SPEC A/B OK (k={args.spec_k}, {mode}, "
              f"seed={args.seed}): bit-exact, "
              f"tokens/target-pass="
              f"{(spec.spec_stats or {}).get('spec_tokens_per_target_pass')}")
        return 0
    if args.elastic_chaos:
        report = run_elastic_chaos(seed=args.seed,
                                   duration_s=args.duration,
                                   base_hz=args.base_hz,
                                   peak_hz=args.peak_hz,
                                   period_s=args.period)
        print(report)
        print(f"autoscaler: {report.server_stats}")
        ok = (not report.lost and not report.timeouts
              and not report.duplicate_finals
              and not report.server_stats.get("stream_mismatches")
              and report.server_stats.get("converged"))
        if not ok:
            print(f"ELASTIC CHAOS FAIL (seed={args.seed}): "
                  f"{report.lost} lost, {report.timeouts} hung, "
                  f"{report.duplicate_finals} duplicated, "
                  f"{report.server_stats.get('stream_mismatches')} "
                  f"stream mismatches, "
                  f"converged={report.server_stats.get('converged')}")
            return 1
        print(f"ELASTIC CHAOS OK (seed={args.seed}): drain + kill + "
              f"failed respawn, nothing lost, fleet converged")
        return 0
    if args.workload == "diurnal":
        report = run_elastic(duration_s=args.duration, seed=args.seed,
                             base_hz=args.base_hz,
                             peak_hz=args.peak_hz,
                             period_s=args.period,
                             slo_ttft_ms=args.slo_ttft_ms,
                             static_replicas=args.static_replicas)
        print(report)
        print(f"fleet: {report.server_stats}")
        print(f"goodput {report.goodput_rps:.2f} req/s over avg "
              f"{report.avg_replicas:.2f} replicas = "
              f"{report.goodput_per_replica:.2f} req/s/replica")
        return 1 if (report.lost or report.timeouts) else 0
    if args.restart_replica:
        cold, warm = run_restart_ab(n_requests=args.requests
                                    if args.requests != 40 else 18,
                                    seed=args.seed)
        for label, report in (("cold", cold), ("warm", warm)):
            stats = report.server_stats or {}
            mean_ttft = (statistics.fmean(report.ttfts_ms)
                         if report.ttfts_ms else 0.0)
            print(f"{label}: hit_rate={report.prefix_hit_rate}, "
                  f"mean TTFT={mean_ttft:.1f}ms, "
                  f"recovery={stats.get('restart_recovery_ms')}ms, "
                  f"batch hit rates="
                  f"{stats.get('restart_hit_rates')}, "
                  f"adopted={stats.get('kv_adopted_chains')}, "
                  f"disk restores={stats.get('kv_disk_restores')}")
        cold_ttft = statistics.fmean(cold.ttfts_ms or [0.0])
        warm_ttft = statistics.fmean(warm.ttfts_ms or [0.0])
        ok = (not cold.lost and not warm.lost
              and not cold.timeouts and not warm.timeouts
              and (warm.prefix_hit_rate or 0.0)
              > (cold.prefix_hit_rate or 0.0)
              and warm_ttft < cold_ttft)
        if not ok:
            print(f"RESTART A/B FAIL (seed={args.seed}): warm must "
                  f"beat cold on hit rate and mean TTFT with zero "
                  f"lost")
            return 1
        print(f"RESTART A/B OK (seed={args.seed}): bit-exact, warm "
              f"restart adopted the spill tier and recovered first")
        return 0
    if args.workload == "longtail":
        import contextlib
        import tempfile

        host_blocks = 0 if args.tier_off else args.host_blocks
        with contextlib.ExitStack() as stack:
            spill_dir = None
            if args.disk_blocks:
                spill_dir = os.path.join(stack.enter_context(
                    tempfile.TemporaryDirectory(prefix="kvspill-")),
                    "spill")
            report = run_longtail(
                n_requests=args.requests, rate_hz=args.rate_hz,
                n_prefixes=args.prefixes, prefix_len=args.prefix_len,
                total_blocks=args.hbm_blocks,
                host_tier_blocks=host_blocks, seed=args.seed,
                spill_dir=spill_dir,
                spill_blocks=args.disk_blocks or 1024)
        print(report)
        print(report.phase_table())
        print(f"fleet counters: {report.server_stats}")
        tier = f"host tier {host_blocks} blocks" if host_blocks \
            else "host tier OFF"
        if args.disk_blocks:
            tier += f" + disk tier {args.disk_blocks} blocks"
        mean_ttft = (statistics.fmean(report.ttfts_ms)
                     if report.ttfts_ms else 0.0)
        print(f"longtail ({args.prefixes} prefixes x "
              f"{args.prefix_len} tok over {args.hbm_blocks} HBM "
              f"blocks, {tier}): "
              f"prefix_hit_rate={report.prefix_hit_rate}, "
              f"host share={report.prefix_hit_rate_host}, "
              f"mean TTFT={mean_ttft:.1f}ms")
        return 1 if (report.lost or report.timeouts) else 0
    if args.workload == "multitenant":
        aware = run_multitenant(
            n_requests=args.requests, rate_hz=args.rate_hz,
            n_adapters=args.tenants, zipf_s=args.zipf_s,
            adapter_aware=True, seed=args.seed)
        blind = run_multitenant(
            n_requests=args.requests, rate_hz=args.rate_hz,
            n_adapters=args.tenants, zipf_s=args.zipf_s,
            adapter_aware=False, seed=args.seed)
        print("adapter-aware:", aware)
        print("adapter-blind:", blind)
        print(f"fleet counters (aware): {aware.server_stats}")
        print(f"warm-routing A/B ({args.tenants} tenants, zipf "
              f"s={args.zipf_s}): aware {aware.adapter_cold_starts} "
              f"cold starts ({aware.adapter_warm_routes} warm "
              f"routes) vs blind {blind.adapter_cold_starts} cold "
              f"starts")
        failed = (aware.adapter_cold_starts or aware.lost
                  or aware.timeouts
                  or aware.adapter_warm_routes < aware.completed
                  or blind.adapter_cold_starts == 0)
        if failed:
            print(f"MULTITENANT FAIL (seed={args.seed}): aware arm "
                  f"{aware.adapter_cold_starts} cold starts / "
                  f"{aware.lost} lost / {aware.timeouts} hung; blind "
                  f"arm {blind.adapter_cold_starts} cold starts "
                  f"(expected > 0)")
            return 1
        print(f"MULTITENANT OK (seed={args.seed}): every warm "
              f"adapter routed warm; adapter-blind baseline paid "
              f"{blind.adapter_cold_starts} re-uploads")
        return 0
    if args.workload == "shared_prefix":
        report = run_shared_prefix(
            n_requests=args.requests, rate_hz=args.rate_hz,
            n_conversations=args.conversations, turns=args.turns,
            system_len=args.system_len,
            prefix_routing=not args.no_prefix_routing,
            seed=args.seed, trace_out=args.trace_out,
            trace_top=args.trace_top)
        print(report)
        print(report.phase_table())
        if report.fleet_latency_ms:
            print(f"fleet latency (merged histograms): "
                  f"{report.fleet_latency_ms}")
        print(f"fleet counters: {report.server_stats}")
        if args.trace_out:
            print(f"trace-event JSON for the {args.trace_top} slowest "
                  f"requests written to {args.trace_out}")
        return 1 if (report.lost or report.timeouts) else 0
    if not args.chaos:
        parser.error("API runs use LoadGenerator directly; the CLI "
                     "wires --chaos and --workload shared_prefix")
    report = run_chaos(seed=args.seed, n_requests=args.requests,
                       rate_hz=args.rate_hz)
    print(report)
    print(report.phase_table())
    print(f"router counters: {report.server_stats}")
    if report.lost or report.timeouts:
        print(f"CHAOS FAIL (seed={args.seed}): {report.lost} lost, "
              f"{report.timeouts} hung")
        return 1
    print(f"CHAOS OK (seed={args.seed}): {report.sent}/{report.sent} "
          "requests terminal under kill + drop + stall schedule")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

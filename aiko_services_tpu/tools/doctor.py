"""Capture-bundle doctor: one readable report per flight recording.

``obs/flight.py`` dumps self-contained JSON capture bundles (span
window + step-log slice + counter snapshot + manifest, all stamped
with one trace id).  This CLI is the consumer: it loads one or more
bundles — files, directories, or a whole fleet's worth — and renders
each as a single report:

- the **manifest** header (trigger, reason, trace id, service, time);
- the **span tree**, indented parent→child with durations, filtered
  to the bundle's trace id when spans match it;
- the **tax table** — the step-log slice run through
  :func:`obs.attrib.attribute_steps`; when the bundle carries a
  device-profile manifest its MEASURED ``device_step_ms`` feeds the
  attribution (the probe estimate is only a fallback), so a watchdog
  bundle directly shows where the stalled step's time went;
- the **compile ledger** section (PR 14) — compile counts, cache
  hit/miss/saved-ms, and the recent per-compile records with their
  shape-bucket signatures (steady-state compiles flagged);
- the **profile manifest** (PR 14) — artifact paths + sizes,
  per-chunk device ms, and the span-annotation scheme that stitches
  device kernels to the request span tree;
- the **pool census** (PR 15) — the memory accountant's per-tier
  block/byte table, flow integrals, audit sweep/violation counters,
  and the auditor's last violation list, so a ``pool_audit`` capture
  reads as "what the books said vs what the pool held";
- **counter diffs** against the recorder's install-time baseline
  (what moved since the process started flying).

Bundles sharing a trace id (the router's fleet fan-out) group into
one fleet section, so "one slow request" reads as one record across
every process that touched it; census-carrying fleet groups get a
fleet memory total line summing every process's tiers.

``--json`` renders the same content machine-readable: one summary
object per bundle under a pinned schema (:data:`JSON_FORMAT`,
``tests/test_compiles.py`` pins the keys) — the CI/scripting face of
the same reports.

Usage::

    python -m aiko_services_tpu.tools.doctor /tmp/flight/           # dir
    python -m aiko_services_tpu.tools.doctor capture_watchdog_*.json
    python -m aiko_services_tpu.tools.doctor --json /tmp/flight/

Host-side, stdlib + ``obs`` only — running the doctor never imports
a backend.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, Iterable, List, Optional

from ..obs import attrib
from ..obs.flight import FORMAT_VERSION

__all__ = ["load_bundle", "collect_paths", "span_tree_lines",
           "counter_diff_lines", "census_lines", "render_report",
           "render_fleet", "bundle_summary", "JSON_FORMAT", "main"]

#: ``--json`` output schema version — tests pin the per-bundle keys.
JSON_FORMAT = 1


def load_bundle(path: str) -> Dict:
    """Parse + validate one bundle file.  Raises ``ValueError`` on a
    bundle the doctor cannot read (wrong shape / future format)."""
    with open(path) as handle:
        bundle = json.load(handle)
    manifest = bundle.get("manifest")
    if not isinstance(manifest, dict):
        raise ValueError(f"{path}: not a capture bundle (no manifest)")
    version = manifest.get("format")
    if version != FORMAT_VERSION:
        raise ValueError(f"{path}: bundle format {version!r}, "
                         f"this doctor reads {FORMAT_VERSION}")
    bundle["_path"] = path
    return bundle


def collect_paths(arguments: Iterable[str]) -> List[str]:
    """Expand files / directories / globs into bundle file paths."""
    paths: List[str] = []
    for argument in arguments:
        if os.path.isdir(argument):
            paths.extend(sorted(
                glob.glob(os.path.join(argument, "capture_*.json"))))
        elif os.path.exists(argument):
            paths.append(argument)
        else:
            paths.extend(sorted(glob.glob(argument)))
    return paths


# -- span tree ---------------------------------------------------------------- #

def span_tree_lines(span_dicts: List[Dict]) -> List[str]:
    """Indented parent→child rendering of span dicts (the
    ``Span.to_dict`` form).  Orphans (parent outside the window)
    render as roots — a bounded ring legitimately loses ancestors."""
    by_id = {span["sid"]: span for span in span_dicts
             if isinstance(span, dict) and "sid" in span}
    children: Dict[str, List[Dict]] = {}
    roots: List[Dict] = []
    for span in by_id.values():
        parent = span.get("pid")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(span)
        else:
            roots.append(span)
    lines: List[str] = []

    def walk(span: Dict, depth: int):
        duration_ms = (span.get("t1", span["t0"]) - span["t0"]) * 1e3
        marks = span.get("marks") or []
        note = (" [" + ", ".join(name for name, _ in marks) + "]"
                if marks else "")
        lines.append(f"  {'  ' * depth}{span.get('name', '?'):<24} "
                     f"{duration_ms:>9.2f} ms  "
                     f"({span.get('svc', '?')}){note}")
        for child in sorted(children.get(span["sid"], []),
                            key=lambda s: s.get("t0", 0.0)):
            walk(child, depth + 1)

    for root in sorted(roots, key=lambda s: s.get("t0", 0.0)):
        walk(root, 0)
    return lines


# -- counters ----------------------------------------------------------------- #

def _fmt(value) -> str:
    return f"{value:g}" if isinstance(value, (int, float)) \
        else str(value)


def counter_diff_lines(counters: Dict, limit: int = 40) -> List[str]:
    """What moved between the recorder's install-time baseline and the
    capture — the "what was the process doing" section."""
    current = counters.get("metrics", {}) or {}
    baseline = counters.get("baseline", {}) or {}
    lines: List[str] = []
    for key in sorted(current):
        now_value, then_value = current[key], baseline.get(key)
        if now_value == then_value:
            continue
        if isinstance(now_value, dict):
            # Histogram snapshot entries: diff on the sample count.
            now_count = now_value.get("count", 0)
            then_count = (then_value or {}).get("count", 0) \
                if isinstance(then_value, dict) else 0
            if now_count == then_count:
                continue
            lines.append(
                f"  {key:<56} n {then_count} -> {now_count} "
                f"(p95 {now_value.get('p95', 0):g} ms)")
        else:
            lines.append(
                f"  {key:<56} "
                f"{_fmt(then_value if then_value is not None else 0)}"
                f" -> {_fmt(now_value)}")
    if len(lines) > limit:
        lines = lines[:limit] + [f"  … {len(lines) - limit} more"]
    return lines


# -- pool census -------------------------------------------------------------- #

def _mib(nbytes) -> str:
    return f"{int(nbytes) / (1024 * 1024):.2f} MiB"


def census_lines(census: Dict) -> List[str]:
    """Render one bundle's ``census`` section (the auditor snapshot):
    per-tier occupancy table, flow integrals, state histogram, audit
    counters, and the most recent violations."""
    lines = [
        f"pool census: {census.get('sweeps', 0)} audit sweeps, "
        f"{census.get('violations_total', 0)} violations"]
    snap = census.get("census") or {}
    tiers = snap.get("tiers") or {}
    integrated = census.get("integrated_bytes") or {}
    if tiers:
        lines.append(f"  {'tier':<6} {'blocks':>8} {'bytes':>14} "
                     f"{'flow integral':>14}")
        for tier in ("hbm", "host", "disk"):
            info = tiers.get(tier, {})
            lines.append(
                f"  {tier:<6} {int(info.get('blocks', 0)):>8} "
                f"{_mib(info.get('bytes', 0)):>14} "
                f"{_mib(integrated.get(tier, 0)):>14}")
    states = snap.get("states") or {}
    if states:
        lines.append("  states: " + ", ".join(
            f"{state}={count}" for state, count
            in sorted(states.items()) if count))
    flows = census.get("flows") or {}
    moved = {name: entry for name, entry in flows.items()
             if entry.get("blocks")}
    if moved:
        lines.append("  flows:  " + ", ".join(
            f"{name}={entry['blocks']}" for name, entry
            in sorted(moved.items())))
    for violation in (census.get("last_violations") or [])[:8]:
        lines.append(f"  VIOLATION: {violation}")
    return lines


# -- report ------------------------------------------------------------------- #

def render_report(bundle: Dict) -> str:
    manifest = bundle["manifest"]
    lines = [
        "=" * 72,
        f"capture: {manifest.get('trigger', '?')} — "
        f"{manifest.get('reason') or '(no reason recorded)'}",
        f"  trace_id: {manifest.get('trace_id', '?')}",
        f"  service:  {manifest.get('service', '?')} "
        f"(pid {manifest.get('pid', '?')})  "
        f"at {manifest.get('captured', '?')}",
    ]
    if bundle.get("_path"):
        lines.append(f"  bundle:   {bundle['_path']}")

    spans = (bundle.get("spans") or {}).get("spans") or []
    lines.append("")
    if spans:
        matched = (bundle.get("spans") or {}).get("matched")
        lines.append(f"span tree ({len(spans)} spans"
                     + (", matched trace" if matched else "") + "):")
        lines.extend(span_tree_lines(spans))
    else:
        lines.append("span tree: (no spans in the window)")

    steplog = bundle.get("steplog") or {}
    events = steplog.get("events") or []
    profile = bundle.get("profile") or {}
    device_step_ms = profile.get("device_step_ms") or None
    lines.append("")
    if len(events) >= 2:
        table = attrib.attribute_steps(
            [(row[0], row[1], row[2]) for row in events],
            device_step_ms=device_step_ms)
        lines.append(table.render())
        if device_step_ms:
            lines.append(f"  (device_step_ms {device_step_ms:g} "
                         f"MEASURED by the profile bracket below)")
        if steplog.get("dropped"):
            lines.append(f"  (ring dropped {steplog['dropped']} "
                         f"older rows)")
    else:
        lines.append("step log: (empty — no engine loop in this "
                     "process, or recorder off)")

    compiles = bundle.get("compiles") or {}
    if compiles:
        lines.append("")
        lines.append(
            f"compile ledger: {compiles.get('compiles', 0)} compiles "
            f"({compiles.get('compiles_steady_state', 0)} steady-state)"
            f", cache {compiles.get('cache_hits', 0)} hit / "
            f"{compiles.get('cache_misses', 0)} miss, "
            f"saved {compiles.get('cache_saved_ms', 0):g} ms"
            + (", FENCED" if compiles.get("fenced") else ""))
        for record in (compiles.get("records") or [])[-12:]:
            flag = "  << STEADY-STATE" if record.get("steady") else (
                "  (cache hit)" if record.get("cache_hit") else "")
            lines.append(
                f"  {record.get('program', '?'):<16} "
                f"{record.get('signature', '') or '-':<12} "
                f"{record.get('wall_ms', 0):>9.2f} ms{flag}")

    if profile:
        lines.append("")
        status = "ok" if profile.get("ok") else \
            f"FAILED: {profile.get('error', '?')}"
        lines.append(
            f"device profile ({status}): {profile.get('steps', 0)} "
            f"steps bracketed, device_step_ms "
            f"{profile.get('device_step_ms', 0):g}"
            + (f" — {profile.get('reason')}" if profile.get("reason")
               else ""))
        lines.append(f"  trace_dir: {profile.get('trace_dir', '?')}  "
                     f"(annotations: "
                     f"{profile.get('annotation_scheme', '?')})")
        for artifact in (profile.get("artifacts") or [])[:8]:
            lines.append(f"  artifact: {artifact.get('path', '?')} "
                         f"({artifact.get('bytes', 0)} bytes)")
        if profile.get("live_trace_ids"):
            lines.append("  live requests during bracket: "
                         + ", ".join(profile["live_trace_ids"][:6]))

    census = bundle.get("census") or {}
    if census:
        lines.append("")
        lines.extend(census_lines(census))

    diff = counter_diff_lines(bundle.get("counters") or {})
    lines.append("")
    if diff:
        lines.append("counters (baseline -> capture):")
        lines.extend(diff)
    else:
        lines.append("counters: (nothing moved since baseline)")

    providers = ((bundle.get("counters") or {}).get("providers")
                 or {})
    for name, payload in sorted(providers.items()):
        interesting = {key: value for key, value in payload.items()
                       if isinstance(value, (int, float)) and value}
        if interesting:
            lines.append(f"  provider {name}: " + ", ".join(
                f"{key}={value:g}" for key, value
                in sorted(interesting.items())[:12]))
    return "\n".join(lines)


def bundle_summary(bundle: Dict) -> Dict:
    """Machine-readable per-bundle summary — the ``--json`` schema
    (version :data:`JSON_FORMAT`; tests pin these keys)."""
    manifest = bundle.get("manifest") or {}
    spans = bundle.get("spans") or {}
    steplog = bundle.get("steplog") or {}
    events = steplog.get("events") or []
    profile = bundle.get("profile") or {}
    compiles = bundle.get("compiles") or {}
    tax = None
    if len(events) >= 2:
        tax = attrib.attribute_steps(
            [(row[0], row[1], row[2]) for row in events],
            device_step_ms=profile.get("device_step_ms") or None
        ).to_dict()
    summary = {
        "path": bundle.get("_path", ""),
        "trigger": manifest.get("trigger", ""),
        "reason": manifest.get("reason", ""),
        "trace_id": manifest.get("trace_id", ""),
        "service": manifest.get("service", ""),
        "pid": manifest.get("pid", 0),
        "captured_unix": manifest.get("captured_unix", 0.0),
        "spans": {"count": len(spans.get("spans") or []),
                  "matched": bool(spans.get("matched"))},
        "steplog": {"events": len(events),
                    "dropped": steplog.get("dropped", 0)},
        "tax_table": tax,
        "counters_moved": len(
            counter_diff_lines(bundle.get("counters") or {},
                               limit=10_000)),
        "compiles": None,
        "profile": None,
        "census": None,
    }
    if compiles:
        summary["compiles"] = {
            "compiles": compiles.get("compiles", 0),
            "compiles_steady_state":
                compiles.get("compiles_steady_state", 0),
            "cache_hits": compiles.get("cache_hits", 0),
            "cache_misses": compiles.get("cache_misses", 0),
            "cache_saved_ms": compiles.get("cache_saved_ms", 0.0),
            "fenced": bool(compiles.get("fenced")),
            "records": len(compiles.get("records") or []),
        }
    if profile:
        summary["profile"] = {
            "ok": bool(profile.get("ok")),
            "steps": profile.get("steps", 0),
            "device_step_ms": profile.get("device_step_ms", 0.0),
            "trace_dir": profile.get("trace_dir", ""),
            "artifacts": len(profile.get("artifacts") or []),
        }
    census = bundle.get("census") or {}
    if census:
        snap = census.get("census") or {}
        summary["census"] = {
            "sweeps": census.get("sweeps", 0),
            "violations_total": census.get("violations_total", 0),
            "last_violations": len(census.get("last_violations")
                                   or []),
            "tiers": {tier: dict(info) for tier, info
                      in (snap.get("tiers") or {}).items()},
        }
    return summary


def render_fleet(bundles: List[Dict]) -> str:
    """Group bundles by trace id: the router fan-out makes one
    incident → N bundles → ONE fleet section here."""
    groups: Dict[str, List[Dict]] = {}
    for bundle in bundles:
        groups.setdefault(
            bundle["manifest"].get("trace_id", "?"), []).append(bundle)
    sections: List[str] = []
    for trace_id, group in sorted(
            groups.items(),
            key=lambda item: item[1][0]["manifest"].get(
                "captured_unix", 0.0)):
        if len(group) > 1:
            services = ", ".join(sorted(
                b["manifest"].get("service", "?") for b in group))
            sections.append(f"\n### fleet capture {trace_id} "
                            f"({len(group)} processes: {services})")
            totals = {"hbm": 0, "host": 0, "disk": 0}
            carrying = 0
            for bundle in group:
                tiers = ((bundle.get("census") or {}).get("census")
                         or {}).get("tiers") or {}
                if tiers:
                    carrying += 1
                    for tier in totals:
                        totals[tier] += int(
                            tiers.get(tier, {}).get("bytes", 0))
            if carrying:
                sections.append(
                    f"fleet memory ({carrying} censuses): " + ", ".join(
                        f"{tier} {_mib(totals[tier])}"
                        for tier in ("hbm", "host", "disk")))
        for bundle in sorted(
                group, key=lambda b: b["manifest"].get(
                    "captured_unix", 0.0)):
            sections.append(render_report(bundle))
    return "\n".join(sections)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m aiko_services_tpu.tools.doctor",
        description="Render flight-recorder capture bundles as "
                    "readable reports (grouped by trace id).")
    parser.add_argument("paths", nargs="+",
                        help="bundle files, globs, or directories")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable summaries (pinned "
                             "schema) instead of the text report")
    arguments = parser.parse_args(argv)
    paths = collect_paths(arguments.paths)
    if not paths:
        print("doctor: no capture bundles found", file=sys.stderr)
        return 1
    bundles: List[Dict] = []
    failed = 0
    for path in paths:
        try:
            bundles.append(load_bundle(path))
        except (OSError, ValueError, json.JSONDecodeError) as error:
            print(f"doctor: skipping {path}: {error}", file=sys.stderr)
            failed += 1
    if not bundles:
        return 1
    if arguments.json:
        print(json.dumps(
            {"format": JSON_FORMAT,
             "bundles": [bundle_summary(b) for b in bundles]},
            indent=1, sort_keys=True))
    else:
        print(render_fleet(bundles))
    return 0 if not failed else 2


if __name__ == "__main__":
    raise SystemExit(main())

"""Dashboard plugin frames.

Reference parity: ``/root/reference/src/aiko_services/main/
dashboard_plugins.py:7-52`` — custom dashboard pages keyed by service
name or protocol.  A plugin renders the selected service's live EC
variables into service-specific lines; the dashboard shows those lines
instead of the raw ``VARIABLE = VALUE`` dump when a plugin matches.

Register with::

    @dashboard_plugin(protocol="pipeline")
    def my_plugin(fields, variables) -> list[str]: ...
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

PluginFn = Callable[[object, Dict], List[str]]

_PLUGINS_BY_NAME: Dict[str, PluginFn] = {}
_PLUGINS_BY_PROTOCOL: Dict[str, PluginFn] = {}


def dashboard_plugin(name: Optional[str] = None,
                     protocol: Optional[str] = None):
    """Decorator registering a plugin for a service name and/or a
    protocol substring (reference keys plugins the same two ways)."""
    def register(fn: PluginFn) -> PluginFn:
        if name:
            _PLUGINS_BY_NAME[name] = fn
        if protocol:
            _PLUGINS_BY_PROTOCOL[protocol] = fn
        return fn
    return register


def find_plugin(fields) -> Optional[PluginFn]:
    """Name match wins over protocol-substring match."""
    plugin = _PLUGINS_BY_NAME.get(fields.name)
    if plugin is not None:
        return plugin
    protocol = fields.protocol or ""
    for key, fn in _PLUGINS_BY_PROTOCOL.items():
        if key in protocol:
            return fn
    return None


def _get(variables: Dict, *path, default="-"):
    node = variables
    for key in path:
        if not isinstance(node, dict) or key not in node:
            return default
        node = node[key]
    return node


@dashboard_plugin(protocol="pipeline")
def pipeline_plugin(fields, variables) -> List[str]:
    """Streams/frames counters published by the pipeline's 3 s status
    timer into its EC share."""
    lines = [
        f"Pipeline: {fields.name}",
        f"  lifecycle: {_get(variables, 'lifecycle')}",
        f"  streams:   {_get(variables, 'streams')}",
        f"  frames:    {_get(variables, 'frames_processed')}",
    ]
    elements = _get(variables, "elements", default={})
    if isinstance(elements, dict) and elements:
        lines += ["", "  elements:"]
        for name, state in sorted(elements.items()):
            lines.append(f"    {name:24} {state}")
    return lines


@dashboard_plugin(protocol="lifecycle_manager")
def lifecycle_manager_plugin(fields, variables) -> List[str]:
    lines = [
        f"LifeCycleManager: {fields.name}",
        f"  lifecycle: {_get(variables, 'lifecycle')}",
        f"  clients:   {_get(variables, 'client_count')}",
        "",
        "  clients:",
    ]
    clients = _get(variables, "clients", default={})
    if isinstance(clients, dict):
        for client_id, topic in sorted(clients.items()):
            lines.append(f"    {client_id:12} {topic}")
    return lines


@dashboard_plugin(protocol="registrar")
def registrar_plugin(fields, variables) -> List[str]:
    return [
        f"Registrar: {fields.name}",
        f"  lifecycle:     {_get(variables, 'lifecycle')}",
        f"  service_count: {_get(variables, 'service_count')}",
    ]

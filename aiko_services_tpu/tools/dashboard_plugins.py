"""Dashboard plugin frames.

Reference parity: ``/root/reference/src/aiko_services/main/
dashboard_plugins.py:7-52`` — custom dashboard pages keyed by service
name or protocol.  A plugin renders the selected service's live EC
variables into service-specific lines; the dashboard shows those lines
instead of the raw ``VARIABLE = VALUE`` dump when a plugin matches.

Register with::

    @dashboard_plugin(protocol="pipeline")
    def my_plugin(fields, variables) -> list[str]: ...
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

PluginFn = Callable[[object, Dict], List[str]]
#: Action: key char -> (label, fn(process, fields, variables)).
ActionMap = Dict[str, tuple]

_PLUGINS_BY_NAME: Dict[str, PluginFn] = {}
_PLUGINS_BY_PROTOCOL: Dict[str, PluginFn] = {}
#: Keyed by the plugin function itself, so a service's actions always
#: belong to the SAME plugin whose view is rendered.
_ACTIONS_BY_PLUGIN: Dict[PluginFn, ActionMap] = {}


def dashboard_plugin(name: Optional[str] = None,
                     protocol: Optional[str] = None,
                     actions: Optional[ActionMap] = None):
    """Decorator registering a plugin for a service name and/or a
    protocol substring (reference keys plugins the same two ways).
    ``actions`` maps a keystroke to ``(label, fn)``; the dashboard runs
    ``fn(process, fields, variables)`` when the key is pressed on the
    plugin page (reference dashboard.py:726-730 action hooks)."""
    def register(fn: PluginFn) -> PluginFn:
        if name:
            _PLUGINS_BY_NAME[name] = fn
        if protocol:
            _PLUGINS_BY_PROTOCOL[protocol] = fn
        if actions:
            _ACTIONS_BY_PLUGIN[fn] = dict(actions)
        return fn
    return register


def find_plugin(fields) -> Optional[PluginFn]:
    """Name match wins over protocol-substring match."""
    plugin = _PLUGINS_BY_NAME.get(fields.name)
    if plugin is not None:
        return plugin
    protocol = fields.protocol or ""
    for key, fn in _PLUGINS_BY_PROTOCOL.items():
        if key in protocol:
            return fn
    return None


def find_plugin_actions(fields) -> ActionMap:
    plugin = find_plugin(fields)
    if plugin is None:
        return {}
    return _ACTIONS_BY_PLUGIN.get(plugin, {})


def _get(variables: Dict, *path, default="-"):
    node = variables
    for key in path:
        if not isinstance(node, dict) or key not in node:
            return default
        node = node[key]
    return node


def _get_hist(variables: Dict, phase: str):
    """Replica ``hist.<phase>`` shares arrive either nested (ECProducer
    expands dotted paths) or flat, depending on the consumer's cache
    shape — accept both and decode to a Histogram, or None."""
    encoded = _get(variables, "hist", phase, default=None)
    if encoded in (None, "-"):
        encoded = _get(variables, f"hist.{phase}", default=None)
    if encoded in (None, "-"):
        return None
    from ..obs.metrics import Histogram
    try:
        return Histogram.decode(str(encoded))
    except (ValueError, IndexError):
        return None


def _fmt_bytes(value) -> str:
    try:
        nbytes = int(value)
    except (TypeError, ValueError):
        return str(value)
    if nbytes >= 1024 * 1024:
        return f"{nbytes / (1024 * 1024):.2f} MiB"
    if nbytes >= 1024:
        return f"{nbytes / 1024:.1f} KiB"
    return f"{nbytes} B"


def _memory_pane(variables: Dict) -> List[str]:
    """The replica's KV memory pane — ONE table for all three tiers,
    fed by the memory accountant's per-tier counters (PR 15; the
    scattered ``kv tier:`` / ``kv disk:`` lines folded here).  Renders
    only when tiering telemetry is present at all."""
    host_blocks = _get(variables, "kv_host_blocks", default=None)
    hbm_blocks = _get(variables, "kv_hbm_blocks", default=None)
    if host_blocks in (None, "-") and hbm_blocks in (None, "-"):
        return []
    lines = ["", "  kv memory (accountant):",
             f"    {'tier':<6} {'blocks':>8} {'bytes':>12}  flows"]
    lines.append(
        f"    {'hbm':<6} {hbm_blocks if hbm_blocks not in (None, '-') else 0:>8} "
        f"{_fmt_bytes(_get(variables, 'kv_hbm_bytes', default=0)):>12}")
    lines.append(
        f"    {'host':<6} {host_blocks or 0:>8} "
        f"{_fmt_bytes(_get(variables, 'kv_host_bytes', default=0)):>12}"
        f"  {_get(variables, 'kv_demotions', default=0)} demoted / "
        f"{_get(variables, 'kv_restores', default=0)} restored, "
        f"{_get(variables, 'restore_queue_depth', default=0)}"
        f" restoring, "
        f"{_get(variables, 'prefix_hits_host', default=0)} hits")
    lines.append(
        f"    {'disk':<6} "
        f"{_get(variables, 'kv_disk_blocks', default=0):>8} "
        f"{_fmt_bytes(_get(variables, 'kv_disk_bytes', default=0)):>12}"
        f"  {_get(variables, 'kv_spills', default=0)} spilled / "
        f"{_get(variables, 'kv_disk_restores', default=0)} restored, "
        f"{_get(variables, 'kv_adopted_chains', default=0)} adopted, "
        f"{_get(variables, 'kv_checksum_failures', default=0)}"
        f" checksum fails")
    sweeps = _get(variables, "kv_audit_sweeps", default=None)
    if sweeps not in (None, "-"):
        violations = _get(variables, "kv_audit_violations", default=0)
        flag = "  << VIOLATIONS" if violations not in (
            None, "-", 0, "0") else ""
        lines.append(f"    audit: {sweeps} sweeps, "
                     f"{violations} violations{flag}")
    return lines


def _adapter_pane(variables: Dict) -> List[str]:
    """Multi-tenant adapter pane: loaded adapter names, paged factor
    residency per tier (the pages live in the SAME audited pool as
    KV), warm-vs-cold load provenance, and per-adapter live slot
    occupancy off the ``adapter_slots`` share.  Renders only when the
    replica serves adapters at all."""
    adapters = _get(variables, "adapters", default=None)
    if adapters in (None, "-", ""):
        return []
    lines = [f"  adapters:  {adapters}"]
    pages_hbm = _get(variables, "adapter_pages_hbm", default=None)
    if pages_hbm not in (None, "-"):
        lines.append(
            f"    pages:   {pages_hbm or 0} hbm / "
            f"{_get(variables, 'adapter_pages_host', default=0)}"
            f" host / "
            f"{_get(variables, 'adapter_pages_disk', default=0)}"
            f" disk (shared kv pool)")
        lines.append(
            f"    loads:   "
            f"{_get(variables, 'adapter_warm_loads', default=0)}"
            f" warm / "
            f"{_get(variables, 'adapter_cold_loads', default=0)}"
            f" cold uploads")
    slots = _get(variables, "adapter_slots", default=None)
    if slots not in (None, "-", ""):
        lines.append("    slots:   " + str(slots))
    return lines


#: Bar width for the slowest-requests phase breakdown.
_BAR_CELLS = 20
_PHASE_ORDER = ("queue", "kv_restore", "prefill", "decode")


def _slow_request_lines(raw: str) -> List[str]:
    """Render the ``slow_requests`` share — space-joined entries of
    ``<request_id>:<total_ms>:<phase>=<ms>,…`` — as one line per
    request with a proportional per-phase bar."""
    lines: List[str] = []
    for entry in str(raw).split():
        try:
            request_id, total, breakdown = entry.split(":", 2)
            total_ms = float(total)
            phases = {}
            if breakdown:
                for pair in breakdown.split(","):
                    phase, value = pair.split("=", 1)
                    phases[phase] = float(value)
        except (ValueError, IndexError):
            continue
        bar = ""
        if total_ms > 0:
            for phase in _PHASE_ORDER:
                cells = round(phases.get(phase, 0.0)
                              / total_ms * _BAR_CELLS)
                bar += phase[0] * cells
        bar = (bar[:_BAR_CELLS]).ljust(_BAR_CELLS, ".")
        detail = " ".join(f"{phase}={phases[phase]:.0f}"
                          for phase in _PHASE_ORDER if phase in phases)
        lines.append(f"    {request_id:12} {total_ms:8.1f} ms "
                     f"[{bar}] {detail}")
    return lines


def _pipeline_stop_action(process, fields, variables):
    """Operator stop: Pipeline.stop() destroys all streams and halts
    the elements (dispatched by the actor's command path)."""
    process.message.publish(f"{fields.topic_path}/in", "(stop)")


@dashboard_plugin(protocol="pipeline",
                  actions={"s": ("stop pipeline", _pipeline_stop_action)})
def pipeline_plugin(fields, variables) -> List[str]:
    """Streams/frames counters published by the pipeline's 3 s status
    timer into its EC share."""
    lines = [
        f"Pipeline: {fields.name}",
        f"  lifecycle: {_get(variables, 'lifecycle')}",
        f"  streams:   {_get(variables, 'streams')}",
        f"  frames:    {_get(variables, 'frames_processed')}",
    ]
    elements = _get(variables, "elements", default={})
    if isinstance(elements, dict) and elements:
        lines += ["", "  elements:"]
        for name, state in sorted(elements.items()):
            lines.append(f"    {name:24} {state}")
    return lines


@dashboard_plugin(protocol="lifecycle_manager")
def lifecycle_manager_plugin(fields, variables) -> List[str]:
    lines = [
        f"LifeCycleManager: {fields.name}",
        f"  lifecycle: {_get(variables, 'lifecycle')}",
        f"  clients:   {_get(variables, 'client_count')}",
        "",
        "  clients:",
    ]
    clients = _get(variables, "clients", default={})
    if isinstance(clients, dict):
        for client_id, topic in sorted(clients.items()):
            lines.append(f"    {client_id:12} {topic}")
    return lines


@dashboard_plugin(protocol="registrar")
def registrar_plugin(fields, variables) -> List[str]:
    return [
        f"Registrar: {fields.name}",
        f"  lifecycle:     {_get(variables, 'lifecycle')}",
        f"  service_count: {_get(variables, 'service_count')}",
    ]


def _replica_terminate_action(process, fields, variables):
    """Operator kill: terminate the replica's process gracefully (its
    LWT then prunes it from every router)."""
    process.message.publish(f"{fields.topic_path}/in", "(terminate)")


@dashboard_plugin(protocol="model_replica",
                  actions={"k": ("kill replica",
                                 _replica_terminate_action)})
def model_replica_plugin(fields, variables) -> List[str]:
    """Serving view: request counters for ModelReplica and (when the
    replica is a ContinuousReplica) live slot occupancy."""
    lines = [
        f"ModelReplica: {fields.name}",
        f"  lifecycle: {_get(variables, 'lifecycle')}",
        f"  served:    {_get(variables, 'requests_served')}",
    ]
    slots = _get(variables, "slots", default=None)
    if slots not in (None, "-"):
        lines.append(f"  slots:     "
                     f"{_get(variables, 'slots_active', default=0)}"
                     f"/{slots} active (continuous batching)")
        lines.append(f"  queued:    "
                     f"{_get(variables, 'queue_depth', default=0)}")
        tp = _get(variables, "tp_degree", default=None)
        if tp not in (None, "-", 0, 1, "1"):
            mesh_shape = _get(variables, "mesh_shape", default="")
            lines.append(
                f"  mesh:      TP={tp}"
                + (f" ({mesh_shape})"
                   if mesh_shape not in (None, "-", "") else ""))
        steps_sec = _get(variables, "decode_steps_per_sec",
                         default=None)
        if steps_sec not in (None, "-"):
            lines.append(
                f"  decode:    {steps_sec} steps/s, "
                f"{_get(variables, 'sync_stalls_per_100_steps', default=0)}"
                f" stalls/100, "
                f"{_get(variables, 'in_flight', default=0)} in flight")
        ring_depth = _get(variables, "ring_depth", default=None)
        if ring_depth not in (None, "-"):
            lines.append(
                f"  ring:      depth {ring_depth}, "
                f"{_get(variables, 'ring_starved_steps', default=0)}"
                f" starved steps, "
                f"{_get(variables, 'dirty_rows_uploaded', default=0)}"
                f" dirty rows up")
        deferred = _get(variables, "admission_deferred", default=None)
        if deferred not in (None, "-", 0):
            lines.append(f"  deferred:  {deferred} admissions")
        attn_path = _get(variables, "decode_attention_path",
                         default=None)
        if attn_path not in (None, "-", ""):
            lines.append(
                f"  attn:      {attn_path} path, "
                f"{_get(variables, 'blocks_read_per_step', default=0)}"
                f" blocks/step")
        prefill_tps = _get(variables, "prefill_tokens_per_sec",
                           default=None)
        if prefill_tps not in (None, "-"):
            lines.append(
                f"  prefill:   {prefill_tps} tok/s, "
                f"{_get(variables, 'prefill_queue_depth', default=0)}"
                f" chunking"
                + (f" ({_get(variables, 'prefill_attention_path')}"
                   f" path)"
                   if _get(variables, "prefill_attention_path",
                           default=None) not in (None, "-", "")
                   else ""))
        hits = _get(variables, "prefix_hits", default=None)
        if hits not in (None, "-"):
            lines.append(
                f"  prefix:    {hits} hits / "
                f"{_get(variables, 'prefix_misses', default=0)} misses, "
                f"{_get(variables, 'prefix_evictions', default=0)}"
                f" evicted")
        remote = _get(variables, "prefix_remote_hits", default=None)
        xfer_bytes = _get(variables, "kv_transfer_bytes", default=None)
        if remote not in (None, "-") or \
                xfer_bytes not in (None, "-", 0):
            lines.append(
                f"  kv xfer:   {remote or 0} remote hits, "
                f"{xfer_bytes or 0} B in "
                f"{_get(variables, 'kv_transfer_ms', default=0)} ms, "
                f"{_get(variables, 'kv_transfer_failures', default=0)}"
                f" failed")
        lines += _memory_pane(variables)
        spec_rounds = _get(variables, "spec_rounds", default=None)
        if spec_rounds not in (None, "-"):
            lines.append(
                f"  spec:      k={_get(variables, 'spec_k', default='?')}, "
                f"{spec_rounds} rounds, "
                f"{_get(variables, 'spec_accepted', default=0)}"
                f"/{_get(variables, 'spec_proposed', default=0)}"
                f" accepted "
                f"({_get(variables, 'spec_acceptance_rate', default=0)}"
                f" rate), "
                f"{_get(variables, 'spec_tokens_per_target_pass', default=0)}"
                f" tok/pass, "
                f"{_get(variables, 'spec_rollback_blocks', default=0)}"
                f" rollback blocks")
            mode = _get(variables, "spec_draft_mode", default=None)
            if mode not in (None, "-"):
                lines.append(
                    f"  spec v2:   mode={mode}, "
                    f"k_eff {_get(variables, 'spec_k_effective', default='-')}, "
                    f"{_get(variables, 'spec_jump_forward_tokens', default=0)}"
                    f" jump-forward tok, "
                    f"{_get(variables, 'spec_ngram_hits', default=0)}"
                    f" ngram hits")
    lines += _adapter_pane(variables)
    ttft = _get(variables, "ttft_p50_ms", default=None)
    ttft95 = _get(variables, "ttft_p95_ms", default=None)
    total = _get(variables, "total_p50_ms", default=None)
    if any(value not in (None, "-", "")
           for value in (ttft, ttft95, total)):
        lines.append(f"  latency:   ttft p50 {ttft or '?'}"
                     f"/p95 {ttft95 or '?'} ms, "
                     f"total p50 {total or '?'} ms")
    phase_lines = []
    for phase in ("ttft", "total") + _PHASE_ORDER:
        hist = _get_hist(variables, phase)
        if hist is None or not hist.count:
            continue
        phase_lines.append(
            f"    {phase:10} p50 {hist.quantile(0.50):8.1f}  "
            f"p95 {hist.quantile(0.95):8.1f}  "
            f"p99 {hist.quantile(0.99):8.1f}  n={hist.count}")
    if phase_lines:
        lines += ["", "  phase latency (ms, mergeable histograms):"]
        lines += phase_lines
    slow = _get(variables, "slow_requests", default=None)
    if slow not in (None, "-", ""):
        slow_lines = _slow_request_lines(slow)
        if slow_lines:
            lines += ["", "  slowest requests "
                          "(q=queue k=kv_restore p=prefill d=decode):"]
            lines += slow_lines
    healthy = _get(variables, "healthy", default=None)
    if healthy not in (None, "-"):
        state = "ok" if str(healthy) not in ("0", "False") else "STALLED"
        lines.append(
            f"  health:    {state}, "
            f"{_get(variables, 'watchdog_trips', default=0)}"
            f" watchdog trips, "
            f"{_get(variables, 'free_slots', default='-')} free slots")
    rejected = [(label, _get(variables, key, default=None))
                for label, key in (("deadline", "deadline_exceeded"),
                                   ("shed", "shed"))]
    if any(value not in (None, "-", 0) for _, value in rejected):
        lines.append("  rejected:  " + ", ".join(
            f"{value or 0} {label}" for label, value in rejected))
    captures = _get(variables, "flight_captures", default=None)
    if captures not in (None, "-", 0):
        lines.append(
            f"  flight:    {captures} capture bundles, recent: "
            f"{_get(variables, 'last_capture', default='-')}")
    compiles = _get(variables, "compiles", default=None)
    if compiles not in (None, "-"):
        steady = _get(variables, "compiles_steady_state", default=0)
        steady_note = (f", {steady} STEADY-STATE"
                       if steady not in (None, "-", 0, "0") else "")
        lines.append(
            f"  compiles:  {compiles} total "
            f"({_get(variables, 'compile_wall_ms', default=0)} ms)"
            f"{steady_note}, cache "
            f"{_get(variables, 'compile_cache_hits', default=0)} hit/"
            f"{_get(variables, 'compile_cache_misses', default=0)}"
            f" miss")
    device_ms = _get(variables, "device_step_ms", default=None)
    if device_ms not in (None, "-"):
        lines.append(
            f"  profile:   device step {device_ms} ms measured "
            f"({_get(variables, 'profiles', default=0)} brackets)")
    return lines


@dashboard_plugin(protocol="replica_router")
def replica_router_plugin(fields, variables) -> List[str]:
    """Router view: fleet size plus the robustness counters (failure
    re-dispatches, observed replica deaths, load sheds)."""
    lines = [
        f"ReplicaRouter: {fields.name}",
        f"  lifecycle:  {_get(variables, 'lifecycle')}",
        f"  replicas:   {_get(variables, 'replicas')}",
        f"  routed:     {_get(variables, 'requests_routed')}",
        f"  redispatch: {_get(variables, 'redispatches', default=0)}"
        f" ({_get(variables, 'replica_deaths_observed', default=0)}"
        f" deaths observed)",
        f"  shed:       {_get(variables, 'shed', default=0)} overload, "
        f"{_get(variables, 'deadline_exceeded', default=0)} deadline",
    ]
    unrouted = _get(variables, "cancel_unrouted", default=None)
    if unrouted not in (None, "-", 0):
        lines.append(f"  cancels:    {unrouted} unrouted")
    # Live-migration pane (PR 19): drain-free mid-decode handoffs.
    migrations = _get(variables, "migrations_started", default=None)
    if migrations not in (None, "-", 0):
        lines.append(
            f"  migrate:    {migrations} started, "
            f"{_get(variables, 'migrations_completed', default=0)}"
            f" cut over / "
            f"{_get(variables, 'migrations_aborted', default=0)}"
            f" aborted, "
            f"{_get(variables, 'migration_blocks_streamed', default=0)}"
            f" blocks streamed, last cutover "
            f"{_get(variables, 'migration_cutover_ms', default=0)} ms")
    directory = _get(variables, "kv_directory_size", default=None)
    if directory not in (None, "-"):
        lines.append(
            f"  kv dir:     {directory} advertised blocks, "
            f"{_get(variables, 'kv_remote_hints', default=0)}"
            f" transfer hints")
    # Adapter-aware routing (multi-tenant LoRA): warm-vs-cold split
    # over adapter-tagged routes.
    warm_routes = _get(variables, "adapter_warm_routes", default=None)
    cold_routes = _get(variables, "adapter_cold_routes", default=None)
    if any(value not in (None, "-", 0)
           for value in (warm_routes, cold_routes)):
        lines.append(
            f"  adapters:   {warm_routes or 0} warm-routed / "
            f"{cold_routes or 0} cold (no paged copy in fleet)")
    # Fleet memory pane (PR 15): per-tier byte totals folded from
    # every replica's accountant broadcast, plus the prefix-routing
    # hbm/host split that used to live on the kv dir line.
    fleet_hbm = _get(variables, "fleet_kv_hbm_bytes", default=None)
    routed = _get(variables, "prefix_routed", default=None)
    if fleet_hbm not in (None, "-") or routed not in (None, "-", 0):
        lines += ["", "  fleet kv memory (summed accountants):"]
        lines.append(
            f"    hbm {_fmt_bytes(_get(variables, 'fleet_kv_hbm_bytes', default=0))}"
            f" / host "
            f"{_fmt_bytes(_get(variables, 'fleet_kv_host_bytes', default=0))}"
            f" / disk "
            f"{_fmt_bytes(_get(variables, 'fleet_kv_disk_bytes', default=0))}")
        routed_host = _get(variables, "prefix_routed_host", default=0)
        try:
            hbm_routed = int(routed or 0) - int(routed_host)
        except (TypeError, ValueError):
            hbm_routed = routed or 0
        lines.append(
            f"    routed: {routed or 0} prefix-routed "
            f"({hbm_routed} hbm / {routed_host} host / "
            f"{_get(variables, 'prefix_routed_disk', default=0)} disk)")
        censuses = _get(variables, "fleet_censuses", default=None)
        audit = _get(variables, "fleet_audit_violations", default=None)
        if censuses not in (None, "-", 0) or \
                audit not in (None, "-", 0):
            lines.append(
                f"    audit:  {censuses or 0} census fan-outs, "
                f"{audit or 0} fleet audit violations")
    fleet_lines = []
    for phase in ("ttft", "total") + _PHASE_ORDER:
        p50 = _get(variables, f"fleet_{phase}_p50_ms", default=None)
        if p50 in (None, "-"):
            continue
        fleet_lines.append(
            f"    {phase:10} p50 {p50:>8}  "
            f"p95 {_get(variables, f'fleet_{phase}_p95_ms'):>8}  "
            f"p99 {_get(variables, f'fleet_{phase}_p99_ms'):>8}")
    if fleet_lines:
        lines += ["", "  fleet latency (ms, merged across replicas):"]
        lines += fleet_lines
    anomalies = _get(variables, "anomaly_flags", default=None)
    if anomalies not in (None, "-", 0):
        lines.append(
            f"  anomaly:    {anomalies} anomaly flags "
            f"(p95 drift, steady compiles, pool audits), "
            f"{_get(variables, 'fleet_captures', default=0)}"
            f" fleet captures")
        last = _get(variables, "last_anomaly", default=None)
        if last not in (None, "-", ""):
            lines.append(f"    last: {last}")
    steady = _get(variables, "fleet_steady_compiles", default=None)
    profiles = _get(variables, "fleet_profiles", default=None)
    if any(value not in (None, "-", 0) for value in (steady,
                                                     profiles)):
        lines.append(
            f"  compiles:   {steady or 0} steady-state across fleet, "
            f"{profiles or 0} fleet profile fan-outs")
    return lines


@dashboard_plugin(protocol="autoscaler")
def autoscaler_plugin(fields, variables) -> List[str]:
    """Elastic-fleet view: replica counts against targets, the last
    scaling action, crash-loop quarantine, and SLO headroom."""
    targets = ", ".join(
        f"{key[len('target_'):]}={value}"
        for key, value in sorted(variables.items())
        if key.startswith("target_")) or "-"
    lines = [
        f"FleetAutoscaler: {fields.name}",
        f"  lifecycle:  {_get(variables, 'lifecycle')}",
        f"  fleet:      {_get(variables, 'replicas_live', default=0)}"
        f" live / {_get(variables, 'replicas_pending', default=0)}"
        f" pending / {_get(variables, 'replicas_draining', default=0)}"
        f" draining  (targets: {targets})",
        f"  scaling:    {_get(variables, 'scale_out', default=0)} out, "
        f"{_get(variables, 'scale_in', default=0)} in, "
        f"last: {_get(variables, 'last_action')}",
        f"  healing:    {_get(variables, 'respawns', default=0)}"
        f" respawns, {_get(variables, 'spawn_failures', default=0)}"
        f" spawn failures, "
        f"{_get(variables, 'deaths_observed', default=0)} deaths",
        f"  drains:     {_get(variables, 'drains', default=0)} begun, "
        f"{_get(variables, 'drain_completed', default=0)} completed, "
        f"{_get(variables, 'drain_timeouts', default=0)} timed out",
    ]
    migrates = _get(variables, "migrates", default=None)
    upgrades = _get(variables, "upgrades_started", default=None)
    if any(value not in (None, "-", 0) for value in (migrates,
                                                     upgrades)):
        lines.append(
            f"  migrate:    {migrates or 0} live-migrations asked, "
            f"{_get(variables, 'upgrades_completed', default=0)}"
            f"/{upgrades or 0} rolling upgrades done")
    quarantine = _get(variables, "quarantine", default="")
    if quarantine not in ("", "-", None):
        lines.append(f"  QUARANTINE: {quarantine} "
                     f"({_get(variables, 'quarantines', default=0)}"
                     f" total)")
    headroom = _get(variables, "slo_headroom_ms", default=None)
    if headroom not in (None, "-", ""):
        lines.append(f"  slo:        {headroom} ms TTFT headroom")
    replica_seconds = _get(variables, "replica_seconds", default=None)
    if replica_seconds not in (None, "-", ""):
        lines.append(f"  usage:      {replica_seconds}"
                     f" replica-seconds")
    return lines


def _trainer_pause_action(process, fields, variables):
    process.message.publish(f"{fields.topic_path}/in", "(pause)")


def _trainer_resume_action(process, fields, variables):
    process.message.publish(f"{fields.topic_path}/in", "(resume)")


def _trainer_save_action(process, fields, variables):
    process.message.publish(f"{fields.topic_path}/in", "(save)")


@dashboard_plugin(protocol="trainer",
                  actions={"p": ("pause", _trainer_pause_action),
                           "r": ("resume", _trainer_resume_action),
                           "c": ("checkpoint", _trainer_save_action)})
def trainer_plugin(fields, variables) -> List[str]:
    """Training-job view: live step/loss/throughput from the
    TrainerActor's EC share, with pause/resume/checkpoint controls."""
    return [
        f"Trainer: {fields.name}",
        f"  state:      {_get(variables, 'state')}",
        f"  step:       {_get(variables, 'step')}",
        f"  loss:       {_get(variables, 'loss')}",
        f"  tokens/sec: {_get(variables, 'tokens_per_sec')}",
    ]


@dashboard_plugin(protocol="profiler")
def profiler_plugin(fields, variables) -> List[str]:
    lines = [
        f"Profiler: {fields.name}",
        f"  profiling:  {_get(variables, 'profiling')}",
        f"  last trace: {_get(variables, 'last_trace_dir')}",
    ]
    seconds = _get(variables, "last_trace_seconds", default=None)
    if seconds not in (None, "-"):
        lines.append(f"  duration:   {seconds}s")
    return lines

"""Storage actor: sqlite-backed persistent key/value store with the
framework's request/response idiom.

Reference parity: ``/root/reference/src/aiko_services/main/storage.py:
49-103``.  Request: publish ``(put key value)`` / ``(get response_topic
key)`` / ``(keys response_topic)`` to the actor's ``…/in``; responses
arrive on the caller-chosen response topic as ``(item_count N)`` followed
by N ``(item key value)`` messages — the same shape the EC share and
registrar queries use.
"""

from __future__ import annotations

import sqlite3
from typing import Optional

from ..utils.sexpr import generate
from ..runtime.actor import Actor
from ..runtime.context import actor_args

__all__ = ["Storage"]


class Storage(Actor):
    def __init__(self, context=None, process=None,
                 database_pathname: str = ":memory:"):
        context = context or actor_args("storage", protocol="storage:0")
        super().__init__(context, process)
        self._connection = sqlite3.connect(database_pathname,
                                           check_same_thread=False)
        self._connection.execute(
            "CREATE TABLE IF NOT EXISTS store "
            "(key TEXT PRIMARY KEY, value TEXT)")
        self.share["database"] = database_pathname

    # -- wire commands -------------------------------------------------------- #

    def put(self, key, value):
        with self._connection:
            self._connection.execute(
                "INSERT INTO store (key, value) VALUES (?, ?) "
                "ON CONFLICT(key) DO UPDATE SET value = excluded.value",
                (str(key), str(value)))

    def delete(self, key):
        with self._connection:
            self._connection.execute("DELETE FROM store WHERE key = ?",
                                     (str(key),))

    def get(self, response_topic, key):
        row = self._connection.execute(
            "SELECT value FROM store WHERE key = ?",
            (str(key),)).fetchone()
        publish = self.process.message.publish
        if row is None:
            publish(str(response_topic), generate("item_count", ["0"]))
        else:
            publish(str(response_topic), generate("item_count", ["1"]))
            publish(str(response_topic),
                    generate("item", [str(key), row[0]]))

    def keys(self, response_topic):
        rows = self._connection.execute(
            "SELECT key FROM store ORDER BY key").fetchall()
        publish = self.process.message.publish
        publish(str(response_topic),
                generate("item_count", [str(len(rows))]))
        for (key,) in rows:
            publish(str(response_topic), generate("item", [key]))

    def stop(self):
        self._connection.close()
        super().stop()

"""Recorder: aggregates distributed log topics into ring buffers exposed
as an EC share.

Reference parity: ``/root/reference/src/aiko_services/main/recorder.py:
50-96``.  Subscribes ``{namespace}/+/+/+/log``, keeps an LRU of
per-topic rings, republishes counts/last-lines into its own share for
the Dashboard.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict

from ..utils.lru_cache import LRUCache
from ..runtime.actor import Actor
from ..runtime.context import actor_args

__all__ = ["Recorder"]

RING_SIZE = 128
TOPIC_CACHE_SIZE = 64


class Recorder(Actor):
    def __init__(self, context=None, process=None):
        context = context or actor_args("recorder", protocol="recorder:0")
        super().__init__(context, process)
        self.rings: LRUCache = LRUCache(TOPIC_CACHE_SIZE)
        self._log_pattern = f"{self.process.namespace}/+/+/+/log"
        self.process.add_message_handler(self._log_handler,
                                         self._log_pattern)
        self.share["log_topics"] = 0

    def _log_handler(self, topic: str, payload: str):
        ring: Deque = self.rings.get(topic)
        if ring is None:
            ring = deque(maxlen=RING_SIZE)
            self.rings.put(topic, ring)
            if self.ec_producer:
                self.ec_producer.update("log_topics", len(self.rings))
        ring.append(payload)
        if self.ec_producer:
            # Terse topic: host/pid/sid.
            terse = "/".join(topic.split("/")[1:4])
            self.ec_producer.update(f"last_log.{terse.replace('/', '_')}",
                                    payload[-120:])

    def get_log(self, topic: str) -> list:
        ring = self.rings.get(topic)
        return list(ring) if ring else []

    def stop(self):
        self.process.remove_message_handler(self._log_handler,
                                            self._log_pattern)
        super().stop()

"""Profiler actor: remote-controlled XLA/JAX trace capture.

SURVEY.md §5.1's TPU answer to the reference's wall-clock frame metrics
(reference main/pipeline.py:1278-1290): per-stage device timings come
from the XLA profiler, not host stopwatches.  The fused pipeline stages
already annotate their device ops (``jax.profiler.TraceAnnotation`` in
``pipeline/tpu_stage.py``); this actor turns capture on/off over the
standard actor wire protocol so an operator (or the dashboard) can grab
a trace from ANY running process in the fleet without restarting it:

    (profile_start /tmp/trace_dir)   → jax.profiler.start_trace
    (profile_stop)                   → stop_trace; share lists the dir
    (profile_status)                 → echo state to topic_out

Traces are TensorBoard-loadable (``tensorboard --logdir <dir>``) and
include per-op device time, HBM traffic, and the stage:<name>
annotations.  A ``ProfilerMixin`` is also provided so any Actor can
adopt the same commands.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from ..runtime.actor import Actor

__all__ = ["ProfilerActor", "ProfilerMixin"]


class ProfilerMixin:
    """Adds profile_start/profile_stop/profile_status commands to an
    Actor subclass (call :meth:`_init_profiler` after Actor.__init__)."""

    def _init_profiler(self):
        self._command_handlers["profile_start"] = self.profile_start
        self._command_handlers["profile_stop"] = self.profile_stop
        self._command_handlers["profile_status"] = self.profile_status
        self._command_handlers["profile_reset"] = self.profile_reset
        self._trace_dir: Optional[str] = None
        self._trace_started: Optional[float] = None
        self._share_update("profiling", False)

    def profile_start(self, trace_dir: str = ""):
        """Begin an XLA trace capture into ``trace_dir``."""
        import jax
        if self._trace_dir is not None:
            self.logger.warning("%s: trace already running in %s",
                                self.name, self._trace_dir)
            return
        trace_dir = str(trace_dir) or os.path.join(
            "/tmp", f"aiko_trace_{os.getpid()}_{int(time.time())}")
        os.makedirs(trace_dir, exist_ok=True)
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception as error:  # noqa: BLE001 - backend may lack it
            # Do NOT stop_trace here: an "already active" failure means
            # SOMEONE ELSE owns the process-global session and killing
            # it would wedge their capture.  Operators can force-clear
            # a known-orphaned session with (profile_reset).
            self.logger.error("%s: start_trace failed: %r", self.name,
                              error)
            return
        self._trace_dir = trace_dir
        self._trace_started = time.time()
        self._share_update("profiling", True)
        self.logger.info("%s: tracing to %s", self.name, trace_dir)

    def profile_stop(self):
        """End the capture; the trace dir lands in the EC share so the
        dashboard / remote callers can find it."""
        import jax
        if self._trace_dir is None:
            self.logger.warning("%s: no trace running", self.name)
            return
        try:
            jax.profiler.stop_trace()
        except Exception as error:  # noqa: BLE001
            # Keep _trace_dir so the operator can retry profile_stop —
            # the process-global profiler session may still be open, and
            # clearing our state here would wedge profiling forever.
            self.logger.error("%s: stop_trace failed (retryable): %r",
                              self.name, error)
            return
        duration = time.time() - (self._trace_started or time.time())
        self._share_update("profiling", False)
        self._share_update("last_trace_dir", self._trace_dir)
        self._share_update("last_trace_seconds", round(duration, 3))
        self.logger.info("%s: trace (%.1fs) written to %s", self.name,
                         duration, self._trace_dir)
        self._trace_dir = None
        self._trace_started = None

    def profile_reset(self):
        """Operator escape hatch: force-stop the process-global profiler
        session (e.g. orphaned by a crashed owner) and clear state."""
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception as error:  # noqa: BLE001
            self.logger.warning("%s: reset stop_trace: %r", self.name,
                                error)
        self._trace_dir = None
        self._trace_started = None
        self._share_update("profiling", False)

    def profile_status(self):
        self.publish_out("profile_status",
                         ["running" if self._trace_dir else "idle",
                          self._trace_dir or
                          self.share.get("last_trace_dir", "")])

    def _share_update(self, key, value):
        """Share write + EC broadcast (ECProducer.update already sets
        the share dict; the direct write is only the no-producer
        fallback)."""
        if getattr(self, "ec_producer", None) is not None:
            self.ec_producer.update(key, value)
        else:
            self.share[key] = value


class ProfilerActor(ProfilerMixin, Actor):
    """Standalone profiler service: run one per process to capture that
    process's device traces on demand."""

    def __init__(self, context, process=None):
        context.protocol = context.protocol or "profiler:0"
        super().__init__(context, process)
        self._init_profiler()

"""Dashboard: terminal UI over the live service directory.

Reference parity: ``/root/reference/src/aiko_services/main/dashboard.py:
286-760`` — a services table fed by the ServicesCache, a live variable
view via an ECConsumer on the selected service, and a log page fed by
the service's ``…/log`` topic.  The reference uses asciimatics (not in
this image); this implementation uses stdlib ``curses`` with the same
page structure, plus a ``--headless`` snapshot mode that prints the
directory once (scriptable, and usable in tests).

Keys: ↑/↓ select service · ENTER variables page · L log page ·
ESC/q back/quit.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import click

from ..runtime.process import default_process
from ..runtime.service import ServiceFilter
from ..registry.services_cache import services_cache_create_singleton
from ..registry.share import ECConsumer
from .dashboard_plugins import find_plugin

REFRESH_SECONDS = 0.25   # 4 Hz, reference dashboard.py:224-226


class DashboardState:
    def __init__(self, process):
        self.process = process
        self.cache = services_cache_create_singleton(process)
        self.selected = 0
        self.page = "services"
        self.variables: Dict = {}
        self.logs: List[str] = []
        self.plugin = None
        self.plugin_fields = None
        self._consumer: Optional[ECConsumer] = None
        self._log_topic: Optional[str] = None

    def services(self):
        return list(self.cache.services)

    def select(self, index: int):
        services = self.services()
        if not services:
            return
        self.selected = max(0, min(index, len(services) - 1))

    def open_variables(self):
        fields = self._selected_fields()
        if fields is None:
            return
        self.close_views()
        self.variables = {}
        self.plugin = find_plugin(fields)
        self.plugin_fields = fields
        self._consumer = ECConsumer(
            self.process, self.variables, f"{fields.topic_path}/control")
        self.page = "variables"

    def open_log(self):
        fields = self._selected_fields()
        if fields is None:
            return
        self.close_views()
        self.logs = []
        self._log_topic = f"{fields.topic_path}/log"
        self.process.add_message_handler(self._on_log, self._log_topic)
        self.page = "log"

    def _on_log(self, topic, payload):
        self.logs.append(str(payload))
        del self.logs[:-200]

    def close_views(self):
        if self._consumer is not None:
            self._consumer.terminate()
            self._consumer = None
        if self._log_topic is not None:
            self.process.remove_message_handler(self._on_log,
                                                self._log_topic)
            self._log_topic = None
        self.plugin = None
        self.plugin_fields = None
        self.page = "services"

    # -- operator controls (reference dashboard.py:565-648) ----------------- #

    def _selected_fields(self):
        services = self.services()
        if not services:
            return None
        return services[min(self.selected, len(services) - 1)]

    def kill_selected(self) -> Optional[str]:
        """Publish ``(terminate)`` to the selected service's topic_in
        (Actors dispatch it to ``Actor.terminate``)."""
        fields = self._selected_fields()
        if fields is None:
            return None
        self.process.message.publish(f"{fields.topic_path}/in",
                                     "(terminate)")
        return fields.topic_path

    def set_log_level(self, level: str) -> Optional[str]:
        """Publish ``(log_level LEVEL)`` to the selected service; the
        service echoes the new level into its EC share."""
        fields = self._selected_fields()
        if fields is None:
            return None
        self.process.message.publish(
            f"{fields.topic_path}/in", f"(log_level {level.upper()})")
        return fields.topic_path

    def plugin_actions(self):
        """Actions the current plugin exposes: {key: (label, fn)}."""
        from .dashboard_plugins import find_plugin_actions
        if self.plugin_fields is None:
            return {}
        return find_plugin_actions(self.plugin_fields)

    def run_plugin_action(self, key: str) -> bool:
        action = self.plugin_actions().get(key)
        if action is None:
            return False
        _label, fn = action
        fn(self.process, self.plugin_fields, self.variables)
        return True


def _render(stdscr, state: DashboardState):
    import curses
    stdscr.erase()
    height, width = stdscr.getmaxyx()
    title = (f" aiko_services_tpu dashboard — {state.process.namespace} "
             f"— {state.cache.state} ")
    stdscr.addnstr(0, 0, title.ljust(width), width - 1,
                   curses.A_REVERSE)
    if state.page == "services":
        header = f"  {'SERVICE':24} {'PROTOCOL':20} {'TOPIC PATH':30}"
        stdscr.addnstr(1, 0, header, width - 1, curses.A_BOLD)
        for i, fields in enumerate(state.services()[:height - 3]):
            attr = curses.A_REVERSE if i == state.selected else 0
            line = (f"  {fields.name:24.24} "
                    f"{(fields.protocol or '-'):20.20} "
                    f"{fields.topic_path:30.30}")
            stdscr.addnstr(2 + i, 0, line, width - 1, attr)
        footer = (" ↑/↓ select · ENTER variables · L log · K kill · "
                  "D/I log-level DEBUG/INFO · Q quit")
    elif state.page == "variables":
        if state.plugin is not None:
            stdscr.addnstr(1, 0, "  PLUGIN VIEW", width - 1,
                           curses.A_BOLD)
            lines = state.plugin(state.plugin_fields,
                                 state.variables)[:height - 3]
            for i, line in enumerate(lines):
                stdscr.addnstr(2 + i, 0, f"  {line}", width - 1)
        else:
            stdscr.addnstr(1, 0, "  VARIABLE = VALUE", width - 1,
                           curses.A_BOLD)
            items = sorted(_flatten(state.variables))[:height - 3]
            for i, (key, value) in enumerate(items):
                stdscr.addnstr(2 + i, 0, f"  {key} = {value}",
                               width - 1)
        actions = state.plugin_actions()
        action_help = "".join(f" · {key.upper()} {label}"
                              for key, (label, _) in actions.items())
        footer = f" ESC back · Q quit{action_help}"
    else:
        stdscr.addnstr(1, 0, "  LOG", width - 1, curses.A_BOLD)
        for i, line in enumerate(state.logs[-(height - 3):]):
            stdscr.addnstr(2 + i, 0, f"  {line}", width - 1)
        footer = " ESC back · Q quit"
    stdscr.addnstr(height - 1, 0, footer.ljust(width - 1), width - 1,
                   curses.A_REVERSE)
    stdscr.refresh()


def _flatten(tree, prefix=""):
    for key, value in tree.items():
        if isinstance(value, dict):
            yield from _flatten(value, f"{prefix}{key}.")
        else:
            yield f"{prefix}{key}", value


def run_dashboard(stdscr, process):
    import curses
    curses.curs_set(0)
    stdscr.nodelay(True)
    state = DashboardState(process)
    while True:
        _render(stdscr, state)
        deadline = time.time() + REFRESH_SECONDS
        while time.time() < deadline:
            key = stdscr.getch()
            if key == -1:
                time.sleep(0.02)
                continue
            if key in (ord("q"), ord("Q")):
                return
            if state.page == "services":
                if key == curses.KEY_UP:
                    state.select(state.selected - 1)
                elif key == curses.KEY_DOWN:
                    state.select(state.selected + 1)
                elif key in (10, 13, curses.KEY_ENTER):
                    state.open_variables()
                elif key in (ord("l"), ord("L")):
                    state.open_log()
                elif key in (ord("k"), ord("K")):
                    state.kill_selected()
                elif key in (ord("d"), ord("D")):
                    state.set_log_level("DEBUG")
                elif key in (ord("i"), ord("I")):
                    state.set_log_level("INFO")
            elif key == 27:   # ESC
                state.close_views()
            elif state.page == "variables" and 0 <= key < 256:
                state.run_plugin_action(chr(key).lower())
            break


@click.command()
@click.option("--headless", is_flag=True,
              help="Print one directory snapshot and exit")
@click.option("--wait", default=3.0, type=float,
              help="Seconds to wait for the directory in headless mode")
@click.option("--plugin", "plugins", multiple=True,
              help="Plugin module to load: dotted path or path/to/file.py "
                   "(registers @dashboard_plugin pages; reference "
                   "dashboard.py:744)")
def main(headless, wait, plugins):
    from ..utils.importer import load_modules
    load_modules(list(plugins))
    process = default_process()
    thread = process.run(in_thread=True)
    if headless:
        cache = services_cache_create_singleton(process)
        deadline = time.time() + wait
        while time.time() < deadline and cache.state != "loaded":
            time.sleep(0.05)
        print(f"directory state: {cache.state}")
        for fields in cache.services:
            print(f"{fields.topic_path:32} {fields.name:24} "
                  f"{fields.protocol or '-'}")
        process.terminate()
        return
    import curses
    try:
        state_process = process
        curses.wrapper(run_dashboard, state_process)
    finally:
        process.terminate()


if __name__ == "__main__":
    main()

"""Checkpoint import: HF-layout safetensors → framework param pytrees.

The reference's examples run *trained* models through external
runtimes — ultralytics YOLO (reference examples/yolo/yolo.py:46-88),
WhisperX (examples/speech/speech_elements.py:109), Ollama llama3.1
(examples/llm/elements_llm.py:191-220).  Here the models are native
JAX, so "trained" means importing public checkpoint weights into the
:mod:`..models.llama` / :mod:`..models.asr` pytrees.

Format: HuggingFace-layout **safetensors** — a directory holding
``config.json`` plus either ``model.safetensors`` or an
``model.safetensors.index.json`` shard map, or a bare ``*.safetensors``
file.  Tensors load lazily one at a time (an 8B checkpoint never needs
2× memory), directly as JAX arrays (bf16-safe).

Layout notes (verified against ``transformers`` modeling code by the
differential tests in ``tests/test_import_weights.py``):

- torch ``nn.Linear`` stores ``(out, in)``; every projection is
  transposed into the framework's ``(in, out)`` matmul layout.
- Llama: HF checkpoints use the rotate-half RoPE layout — exactly
  :func:`..models.llama.apply_rope`'s convention — so q/k need no
  permutation.  GQA needs no head splitting either: ``wk``/``wv`` stay
  ``(d, n_kv_heads*head_dim)``.
- Whisper: biases ride along (q/v/out yes, k none — absent biases stay
  absent rather than zero-filled), attention projections fuse into the
  framework's ``wqkv``/``wkv_cross`` blocks, and the encoder's
  positional table is imported verbatim (Whisper concatenates sin‖cos
  halves; the random-init path interleaves, so the table must come
  from the checkpoint).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp

__all__ = [
    "load_checkpoint_tensors", "llama_config_from_hf",
    "import_llama", "export_llama", "export_llama_checkpoint",
    "import_lora", "export_lora_checkpoint",
    "asr_config_from_hf", "import_whisper",
]


# --------------------------------------------------------------------------- #
# Tensor access

class CheckpointTensors:
    """Lazy name→tensor access over one safetensors file or an HF
    sharded checkpoint directory."""

    def __init__(self, files: Dict[str, str]):
        #: tensor name -> file path
        self._files = files
        self._handles: Dict[str, Any] = {}

    @property
    def names(self):
        return set(self._files)

    def _handle(self, path):
        if path not in self._handles:
            import safetensors
            self._handles[path] = safetensors.safe_open(
                path, framework="flax")
        return self._handles[path]

    def get(self, name: str, dtype=None):
        tensor = self._handle(self._files[name]).get_tensor(name)
        return tensor if dtype is None else tensor.astype(dtype)

    def has(self, name: str) -> bool:
        return name in self._files

    def close(self):
        self._handles.clear()


def load_checkpoint_tensors(path: str) -> Tuple[CheckpointTensors,
                                                Optional[dict]]:
    """Returns (tensors, config-dict-or-None) for a safetensors file or
    an HF checkpoint directory (sharded or single-file)."""
    import safetensors

    config = None
    if os.path.isdir(path):
        config_path = os.path.join(path, "config.json")
        if os.path.exists(config_path):
            with open(config_path, encoding="utf-8") as fh:
                config = json.load(fh)
        index_path = os.path.join(path, "model.safetensors.index.json")
        if os.path.exists(index_path):
            with open(index_path, encoding="utf-8") as fh:
                index = json.load(fh)
            files = {name: os.path.join(path, shard)
                     for name, shard in index["weight_map"].items()}
            return CheckpointTensors(files), config
        candidates = [os.path.join(path, n) for n in sorted(os.listdir(path))
                      if n.endswith(".safetensors")]
        if not candidates:
            raise FileNotFoundError(f"no .safetensors under {path}")
        files = {}
        for file_path in candidates:
            with safetensors.safe_open(file_path,
                                       framework="flax") as handle:
                for name in handle.keys():
                    files[name] = file_path
        return CheckpointTensors(files), config

    files = {}
    with safetensors.safe_open(path, framework="flax") as handle:
        for name in handle.keys():
            files[name] = path
    sibling = os.path.join(os.path.dirname(path), "config.json")
    if os.path.exists(sibling):
        with open(sibling, encoding="utf-8") as fh:
            config = json.load(fh)
    return CheckpointTensors(files), config


def _strip_prefix(tensors: CheckpointTensors, prefix: str):
    """HF checkpoints may carry a top-level module prefix ('model.')."""
    if any(name.startswith(prefix) for name in tensors.names):
        return prefix
    return ""


# --------------------------------------------------------------------------- #
# Llama

def llama_config_from_hf(cfg: dict) -> "LlamaConfig":
    from ..models.llama import LlamaConfig
    rope_scaling = None
    scaling_cfg = cfg.get("rope_scaling")
    if scaling_cfg:
        kind = scaling_cfg.get("rope_type",
                               scaling_cfg.get("type", "default"))
        if kind == "llama3":
            rope_scaling = (
                float(scaling_cfg["factor"]),
                float(scaling_cfg["low_freq_factor"]),
                float(scaling_cfg["high_freq_factor"]),
                int(scaling_cfg["original_max_position_embeddings"]))
        elif kind != "default":
            # linear/dynamic/yarn would silently mis-position every
            # token if dropped — refuse instead.
            raise ValueError(f"unsupported rope_scaling type {kind!r}")
    return LlamaConfig(
        vocab_size=cfg["vocab_size"],
        d_model=cfg["hidden_size"],
        n_layers=cfg["num_hidden_layers"],
        n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg.get("num_key_value_heads",
                           cfg["num_attention_heads"]),
        d_ff=cfg["intermediate_size"],
        rope_theta=cfg.get("rope_theta", 10_000.0),
        norm_eps=cfg.get("rms_norm_eps", 1e-5),
        max_seq_len=cfg.get("max_position_embeddings", 8192),
        sliding_window=cfg.get("sliding_window"),
        rope_scaling=rope_scaling,
    )


def import_llama(path: str, config=None, dtype=jnp.bfloat16,
                 bits: Optional[int] = None):
    """HF-layout Llama/Mistral safetensors → (params, config).

    ``bits`` quantizes on the fly (8 or 4): each layer is quantized as
    soon as it is assembled and its bf16 tensors dropped, so peak
    memory stays ~one checkpoint + one layer, not checkpoint + full
    quantized copy (an 8B import fits a 16 GB host).
    """
    from ..ops.quant import quantize_tree

    tensors, hf_config = load_checkpoint_tensors(path)
    if config is None:
        if hf_config is None:
            raise ValueError(f"no config.json next to {path}; pass "
                             "config= explicitly")
        config = llama_config_from_hf(hf_config)
    prefix = _strip_prefix(tensors, "model.")

    def dense(name):               # torch Linear (out,in) -> (in,out)
        return tensors.get(name, dtype).T

    def vector(name):
        return tensors.get(name, dtype)

    layers = []
    for i in range(config.n_layers):
        base = f"{prefix}layers.{i}."
        layer = {
            "attn_norm": vector(base + "input_layernorm.weight"),
            "wq": dense(base + "self_attn.q_proj.weight"),
            "wk": dense(base + "self_attn.k_proj.weight"),
            "wv": dense(base + "self_attn.v_proj.weight"),
            "wo": dense(base + "self_attn.o_proj.weight"),
            "mlp_norm": vector(base + "post_attention_layernorm.weight"),
            "w_gate": dense(base + "mlp.gate_proj.weight"),
            "w_up": dense(base + "mlp.up_proj.weight"),
            "w_down": dense(base + "mlp.down_proj.weight"),
        }
        if bits is not None:
            layer = quantize_tree(layer, bits=bits)
        layers.append(layer)
    embed = tensors.get(prefix + "embed_tokens.weight", dtype)
    if tensors.has("lm_head.weight"):
        lm_head = dense("lm_head.weight")
    else:                           # tied embeddings (llama-3.2 class)
        lm_head = embed.T
    if bits is not None:
        # Embedding stays int8 even at bits=4 (row-gather path) —
        # matches quantize_params' policy.
        embed = quantize_tree(embed)
        lm_head = quantize_tree(lm_head, bits=bits)
    params = {
        "embed": embed,
        "layers": layers,
        "final_norm": vector(prefix + "norm.weight"),
        "lm_head": lm_head,
    }
    tensors.close()
    return params, config


def export_llama(params: Dict, path: str):
    """Framework pytree → HF-layout safetensors file (float32).

    The inverse of :func:`import_llama`, used by the round-trip test:
    export random-init params, re-import, require bit-exact equality.
    float32 storage represents bf16 values exactly, so the cast chain
    bf16→f32→bf16 is lossless.
    """
    import numpy as np
    from safetensors.numpy import save_file

    out = {}

    def put(name, value, transpose):
        value = np.asarray(jnp.asarray(value, jnp.float32))
        if transpose:
            # ascontiguousarray is load-bearing: safetensors' numpy
            # save_file serializes the BASE buffer of a strided view
            # (shape recorded transposed, bytes not) — silent
            # corruption caught by the round-trip test.
            value = np.ascontiguousarray(value.T)
        out[name] = value

    put("model.embed_tokens.weight", params["embed"], False)
    for i, layer in enumerate(params["layers"]):
        base = f"model.layers.{i}."
        put(base + "input_layernorm.weight", layer["attn_norm"], False)
        put(base + "self_attn.q_proj.weight", layer["wq"], True)
        put(base + "self_attn.k_proj.weight", layer["wk"], True)
        put(base + "self_attn.v_proj.weight", layer["wv"], True)
        put(base + "self_attn.o_proj.weight", layer["wo"], True)
        put(base + "post_attention_layernorm.weight",
            layer["mlp_norm"], False)
        put(base + "mlp.gate_proj.weight", layer["w_gate"], True)
        put(base + "mlp.up_proj.weight", layer["w_up"], True)
        put(base + "mlp.down_proj.weight", layer["w_down"], True)
    put("model.norm.weight", params["final_norm"], False)
    put("lm_head.weight", params["lm_head"], True)
    save_file(out, path)


def export_llama_checkpoint(params: Dict, config, path: str):
    """Write a COMPLETE HF-layout checkpoint directory —
    ``model.safetensors`` + ``config.json`` — loadable by
    :func:`import_llama` (and by ``transformers``).  This is how
    natively-trained models become servable artifacts
    (``PE_LLM(checkpoint=...)``, ``make_llama_infer(checkpoint=...)``)."""
    os.makedirs(path, exist_ok=True)
    export_llama(params, os.path.join(path, "model.safetensors"))
    hf_config = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "vocab_size": config.vocab_size,
        "hidden_size": config.d_model,
        "num_hidden_layers": config.n_layers,
        "num_attention_heads": config.n_heads,
        "num_key_value_heads": config.n_kv_heads,
        "intermediate_size": config.d_ff,
        "rope_theta": config.rope_theta,
        "rms_norm_eps": config.norm_eps,
        "max_position_embeddings": config.max_seq_len,
        "tie_word_embeddings": False,
        "torch_dtype": "float32",
    }
    if config.sliding_window is not None:
        hf_config["sliding_window"] = config.sliding_window
    if config.rope_scaling is not None:
        factor, low, high, original = config.rope_scaling
        hf_config["rope_scaling"] = {
            "rope_type": "llama3", "factor": factor,
            "low_freq_factor": low, "high_freq_factor": high,
            "original_max_position_embeddings": original,
        }
    with open(os.path.join(path, "config.json"), "w",
              encoding="utf-8") as fh:
        json.dump(hf_config, fh, indent=1)


# --------------------------------------------------------------------------- #
# LoRA adapters (PEFT layout)

#: our target name -> the HF module path PEFT keys carry.
_PEFT_TARGETS = {
    "wq": "self_attn.q_proj", "wk": "self_attn.k_proj",
    "wv": "self_attn.v_proj", "wo": "self_attn.o_proj",
    "w_gate": "mlp.gate_proj", "w_up": "mlp.up_proj",
    "w_down": "mlp.down_proj",
}
_PEFT_MODULES = {module.split(".")[-1]: target
                 for target, module in _PEFT_TARGETS.items()}


def import_lora(path: str, config, dtype=jnp.bfloat16):
    """PEFT-layout LoRA adapter (``adapter_model.safetensors`` +
    ``adapter_config.json``) → ``(lora_params, LoRAConfig)`` matching
    :mod:`..models.lora` — rank/alpha/targets from the adapter config,
    factors transposed from torch (out, in) to our (in, r)/(r, out).

    This is how an externally fine-tuned adapter (PEFT/`peft` trainer
    output) becomes servable through the multi-adapter batch
    (``ContinuousBatchingServer(adapters={name: lora_params})``)."""
    from ..models.lora import LoRAConfig, factor_dims

    adapter_config = None
    if os.path.isdir(path):
        cfg_path = os.path.join(path, "adapter_config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path, encoding="utf-8") as fh:
                adapter_config = json.load(fh)
    if adapter_config is None:
        raise FileNotFoundError(
            f"no adapter_config.json under {path} (PEFT layout)")
    # PEFT options that change the EFFECTIVE weights must fail loudly:
    # ignoring them loads without error but serves at the wrong scale
    # (use_rslora: alpha/sqrt(r) vs our alpha/r; use_dora: magnitude-
    # vector recomposition; rank_pattern/alpha_pattern: per-module
    # overrides) or drops weights entirely (modules_to_save:
    # full-weight module copies).
    unsupported = [
        option for option in ("use_rslora", "use_dora", "rank_pattern",
                              "alpha_pattern", "modules_to_save")
        if adapter_config.get(option)]
    if unsupported:
        raise ValueError(
            f"PEFT adapter options {unsupported} are not supported by "
            f"import_lora; the adapter would serve at the wrong scale "
            f"or with missing weights")
    modules = adapter_config.get("target_modules") or []
    try:
        targets = tuple(_PEFT_MODULES[m] for m in modules)
    except KeyError as error:
        raise ValueError(f"unsupported PEFT target module {error}; "
                         f"known: {sorted(_PEFT_MODULES)}")
    lora_config = LoRAConfig(
        rank=int(adapter_config["r"]),
        alpha=float(adapter_config.get("lora_alpha",
                                       adapter_config["r"])),
        targets=targets)

    tensors, _ = load_checkpoint_tensors(path)
    try:
        sample = next(name for name in tensors.names
                      if "model.layers." in name)
        prefix = sample.split("model.layers.")[0] + "model.layers."
        in_dims, out_dims = factor_dims(config)
        layers = []
        for i in range(config.n_layers):
            layer = {}
            for target in targets:
                base = f"{prefix}{i}.{_PEFT_TARGETS[target]}."
                if tensors.has(base + "lora_A.weight"):
                    # torch lora_A (r, in) -> a (in, r);
                    # lora_B (out, r) -> b (r, out).
                    layer[target] = {
                        "a": tensors.get(base + "lora_A.weight",
                                         dtype).T,
                        "b": tensors.get(base + "lora_B.weight",
                                         dtype).T,
                    }
                else:
                    # PEFT ``layers_to_transform`` leaves untouched
                    # layers without factors: an exact identity.
                    layer[target] = {
                        "a": jnp.zeros((in_dims[target],
                                        lora_config.rank), dtype),
                        "b": jnp.zeros((lora_config.rank,
                                        out_dims[target]), dtype),
                    }
            layers.append(layer)
    finally:
        tensors.close()
    return {"layers": layers}, lora_config


def export_lora_checkpoint(lora_params: Dict, lora_config, config,
                           path: str):
    """Framework LoRA tree → a PEFT-layout adapter directory
    (``adapter_model.safetensors`` + ``adapter_config.json``) —
    the inverse of :func:`import_lora` (round-trip tested), and
    loadable by the ``peft`` library against the matching HF base."""
    import numpy as np
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    out = {}
    for i, layer in enumerate(lora_params["layers"]):
        for target, factors in layer.items():
            base = (f"base_model.model.model.layers.{i}."
                    f"{_PEFT_TARGETS[target]}.")
            a = np.asarray(jnp.asarray(factors["a"], jnp.float32))
            b = np.asarray(jnp.asarray(factors["b"], jnp.float32))
            out[base + "lora_A.weight"] = np.ascontiguousarray(a.T)
            out[base + "lora_B.weight"] = np.ascontiguousarray(b.T)
    save_file(out, os.path.join(path, "adapter_model.safetensors"))
    adapter_config = {
        "peft_type": "LORA",
        "r": lora_config.rank,
        "lora_alpha": lora_config.alpha,
        "target_modules": [_PEFT_TARGETS[t].split(".")[-1]
                           for t in lora_config.targets],
        "task_type": "CAUSAL_LM",
    }
    with open(os.path.join(path, "adapter_config.json"), "w",
              encoding="utf-8") as fh:
        json.dump(adapter_config, fh, indent=1)


# --------------------------------------------------------------------------- #
# Whisper

def asr_config_from_hf(cfg: dict, dtype=jnp.bfloat16) -> "ASRConfig":
    from ..models.asr import ASRConfig
    return ASRConfig(
        n_mels=cfg["num_mel_bins"],
        n_audio_ctx=cfg.get("max_source_positions", 1500),
        d_model=cfg["d_model"],
        n_heads=cfg["encoder_attention_heads"],
        n_encoder_layers=cfg["encoder_layers"],
        n_decoder_layers=cfg["decoder_layers"],
        vocab_size=cfg["vocab_size"],
        n_text_ctx=cfg.get("max_target_positions", 448),
        dtype=dtype,
        norm_eps=1e-5,               # torch LayerNorm default
    )


def import_whisper(path: str, config=None, dtype=jnp.bfloat16):
    """HF-layout Whisper safetensors → (params, config) for
    :mod:`..models.asr` (fused-projection blocks with biases)."""
    tensors, hf_config = load_checkpoint_tensors(path)
    if config is None:
        if hf_config is None:
            raise ValueError(f"no config.json next to {path}; pass "
                             "config= explicitly")
        config = asr_config_from_hf(hf_config, dtype=dtype)
    prefix = _strip_prefix(tensors, "model.")

    def dense(name):
        return tensors.get(name, dtype).T

    def vector(name):
        return tensors.get(name, dtype)

    def fused_qkv(base):
        """q/k/v (out,in) -> (d, 3d); k has no bias in Whisper."""
        wq = dense(base + "q_proj.weight")
        wk = dense(base + "k_proj.weight")
        wv = dense(base + "v_proj.weight")
        b_q = vector(base + "q_proj.bias")
        b_v = vector(base + "v_proj.bias")
        b_k = jnp.zeros_like(b_q)
        return (jnp.concatenate([wq, wk, wv], axis=1),
                jnp.concatenate([b_q, b_k, b_v]))

    def block(base, cross: bool):
        wqkv, b_qkv = fused_qkv(base + "self_attn.")
        entry = {
            "norm1": vector(base + "self_attn_layer_norm.weight"),
            "norm1_b": vector(base + "self_attn_layer_norm.bias"),
            "wqkv": wqkv, "b_qkv": b_qkv,
            "wo": dense(base + "self_attn.out_proj.weight"),
            "b_o": vector(base + "self_attn.out_proj.bias"),
            "norm_mlp": vector(base + "final_layer_norm.weight"),
            "norm_mlp_b": vector(base + "final_layer_norm.bias"),
            "w1": dense(base + "fc1.weight"),
            "b1": vector(base + "fc1.bias"),
            "w2": dense(base + "fc2.weight"),
            "b2": vector(base + "fc2.bias"),
        }
        if cross:
            ca = base + "encoder_attn."
            wk = dense(ca + "k_proj.weight")
            wv = dense(ca + "v_proj.weight")
            b_v = vector(ca + "v_proj.bias")
            entry.update({
                "norm_cross": vector(
                    base + "encoder_attn_layer_norm.weight"),
                "norm_cross_b": vector(
                    base + "encoder_attn_layer_norm.bias"),
                "wq_cross": dense(ca + "q_proj.weight"),
                "b_q_cross": vector(ca + "q_proj.bias"),
                "wkv_cross": jnp.concatenate([wk, wv], axis=1),
                "b_kv_cross": jnp.concatenate(
                    [jnp.zeros_like(b_v), b_v]),
                "wo_cross": dense(ca + "out_proj.weight"),
                "b_o_cross": vector(ca + "out_proj.bias"),
            })
        return entry

    # torch Conv1d weight (out, in, k) -> (k, in, out)
    def conv(name):
        return jnp.transpose(tensors.get(name, dtype), (2, 1, 0))

    params = {
        "conv1": conv(prefix + "encoder.conv1.weight"),
        "conv1_b": vector(prefix + "encoder.conv1.bias"),
        "conv2": conv(prefix + "encoder.conv2.weight"),
        "conv2_b": vector(prefix + "encoder.conv2.bias"),
        "enc_pos_embed": vector(
            prefix + "encoder.embed_positions.weight"),
        "encoder_layers": [
            block(f"{prefix}encoder.layers.{i}.", cross=False)
            for i in range(config.n_encoder_layers)],
        "encoder_norm": vector(prefix + "encoder.layer_norm.weight"),
        "encoder_norm_b": vector(prefix + "encoder.layer_norm.bias"),
        "token_embed": tensors.get(
            prefix + "decoder.embed_tokens.weight", dtype),
        "pos_embed": vector(prefix + "decoder.embed_positions.weight"),
        "decoder_layers": [
            block(f"{prefix}decoder.layers.{i}.", cross=True)
            for i in range(config.n_decoder_layers)],
        "decoder_norm": vector(prefix + "decoder.layer_norm.weight"),
        "decoder_norm_b": vector(prefix + "decoder.layer_norm.bias"),
    }
    tensors.close()
    return params, config

from .recorder import Recorder
from .storage import Storage

from .recorder import Recorder
from .storage import Storage
from .profiler import ProfilerActor, ProfilerMixin
from .loadgen import LoadGenerator, LoadReport

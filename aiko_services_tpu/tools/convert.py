"""Standalone media converters.

Reference parity: ``/root/reference/src/aiko_services/elements/media/
images_to_video.py`` (33 LoC) and ``video_to_images.py`` (42 LoC) —
small CLI utilities that shuttle between image-file directories and
video files.  Implemented as library functions plus a single click CLI
(``python -m aiko_services_tpu.tools.convert``).
"""

from __future__ import annotations

import glob
import os

import click
import numpy as np


def images_to_video(image_glob: str, video_path: str,
                    frame_rate: float = 30.0) -> int:
    """Encode every image matching ``image_glob`` (sorted) into
    ``video_path``.  Returns the number of frames written."""
    import cv2
    paths = sorted(glob.glob(image_glob))
    if not paths:
        raise FileNotFoundError(f"no images match {image_glob}")
    first = cv2.imread(paths[0])
    if first is None:
        raise ValueError(f"cannot read image {paths[0]}")
    height, width = first.shape[:2]
    writer = cv2.VideoWriter(
        video_path, cv2.VideoWriter_fourcc(*"mp4v"), float(frame_rate),
        (width, height))
    if not writer.isOpened():
        raise ValueError(f"cannot open video writer for {video_path}")
    count = 0
    try:
        for path in paths:
            image = cv2.imread(path)
            if image is None:
                continue
            if image.shape[:2] != (height, width):
                image = cv2.resize(image, (width, height))
            writer.write(image)
            count += 1
    finally:
        writer.release()
    return count


def video_to_images(video_path: str, image_directory: str,
                    image_format: str = "frame_{:06d}.png") -> int:
    """Decode ``video_path`` into one image file per frame under
    ``image_directory``.  Returns the number of frames written."""
    import cv2
    capture = cv2.VideoCapture(video_path)
    if not capture.isOpened():
        raise FileNotFoundError(f"cannot open video {video_path}")
    os.makedirs(image_directory, exist_ok=True)
    count = 0
    try:
        while True:
            okay, frame = capture.read()
            if not okay:
                break
            cv2.imwrite(os.path.join(image_directory,
                                     image_format.format(count)), frame)
            count += 1
    finally:
        capture.release()
    return count


@click.group()
def main():
    """Media conversion utilities."""


@main.command("images_to_video")
@click.argument("image_glob")
@click.argument("video_path")
@click.option("--frame_rate", default=30.0, type=float)
def _images_to_video(image_glob, video_path, frame_rate):
    count = images_to_video(image_glob, video_path, frame_rate)
    print(f"wrote {count} frames to {video_path}")


@main.command("video_to_images")
@click.argument("video_path")
@click.argument("image_directory")
@click.option("--image_format", default="frame_{:06d}.png")
def _video_to_images(video_path, image_directory, image_format):
    count = video_to_images(video_path, image_directory, image_format)
    print(f"wrote {count} images to {image_directory}")


if __name__ == "__main__":
    main()

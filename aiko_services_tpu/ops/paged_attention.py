"""Ragged paged decode-attention: a Pallas TPU kernel that walks each
row's block table directly in HBM, plus the jnp oracle it must match.

Decode attention is the serving hot path: one query token per row
against that row's whole KV history.  The fallback implementation
(:func:`cached_gqa_attention`, shared with chunked prefill and
speculative verify) masks over the FULL preallocated cache — O(max_seq)
HBM reads per row per step no matter how short the row really is, and
the paged layout must first gather its blocks into a contiguous bucket.
The kernel here reads only the blocks a row actually occupies:

* grid ``(batch-row, kv-head, block)``; the block axis is
  fastest-varying, so one program instance sweeps one row × kv-head
  through its live blocks carrying online-softmax state in VMEM scratch
  (flash-decoding style — running max ``m``, denominator ``l``,
  accumulator ``acc`` in f32).
* the block table and per-row positions ride scalar prefetch
  (``PrefetchScalarGridSpec``), so the K/V BlockSpec index maps resolve
  ``tables[row, j]`` into a pool block id BEFORE the body runs — the
  DMA engine streams exactly the row's own blocks, nothing else.
* dead grid steps (``j`` past the row's last live block, or wholly
  below the sliding window) clamp their index map to a resident block
  and skip compute via ``pl.when`` — no HBM traffic, (almost) no work.
* all ``group = n_heads // n_kv_heads`` query heads of a kv head run in
  ONE program, so the MXU sees a (group, head_dim) × (head_dim,
  block_size) matmul per block instead of ``group`` skinny dot
  products.
* int8 KV dequantizes in-kernel: per-(token, head) scales load as a
  (block_size, 1) column and broadcast-multiply the int8 block right
  after the load — the cache is read at 1 byte/element and no bf16
  copy of it ever exists.

The contiguous ragged cache is the degenerate case: reshape
``(batch, S, kv, hd)`` to ``(batch·S/bs, bs, kv, hd)`` with iota block
tables (a free reshape) and the same kernel serves both layouts.

Layout contract and dispatch rules are documented in docs/KERNELS.md.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .attention import NEG_INF, _PALLAS_TPU

if _PALLAS_TPU:
    from jax.experimental.pallas import tpu as pltpu
else:  # pragma: no cover
    pltpu = None

__all__ = ["paged_decode_attention", "paged_decode_reference",
           "cached_gqa_attention", "decode_kernel_mode",
           "decode_attention_path", "contiguous_block_size"]

#: Maximum pool block size the degenerate contiguous view uses — small
#: enough that short rows skip most of the cache, large enough for the
#: MXU's lane dimension.
CONTIGUOUS_BLOCK_CAP = 128

#: Fallback dequantization span cap (see :func:`_dequant_block`).
DEQUANT_BLOCK_CAP = 512


# ---------------------------------------------------------------------------
# Dispatch policy


def decode_kernel_mode() -> Tuple[bool, bool]:
    """``(use_kernel, interpret)`` for the decode-attention dispatch.

    Controlled by ``AIKO_DECODE_ATTENTION`` (read at TRACE time — set it
    before the first decode call of a given shape, jit caches traces):

    * ``auto`` (default): kernel on TPU, jnp reference elsewhere.
    * ``kernel``: force the kernel; off-TPU it runs in interpret mode
      (slow — testing only).
    * ``interpret``: kernel in interpret mode everywhere.
    * ``reference`` / ``off`` / ``0``: always the jnp reference.
    """
    mode = os.environ.get("AIKO_DECODE_ATTENTION", "auto").lower()
    if mode in ("reference", "fallback", "off", "0"):
        return False, False
    on_tpu = jax.default_backend() == "tpu"
    if mode in ("kernel", "force"):
        return _PALLAS_TPU, not on_tpu
    if mode == "interpret":
        return _PALLAS_TPU, True
    return _PALLAS_TPU and on_tpu, False


def decode_attention_path() -> str:
    """``"kernel"`` or ``"reference"`` — the serving-counter path tag."""
    return "kernel" if decode_kernel_mode()[0] else "reference"


def contiguous_block_size(max_seq: int) -> int:
    """Block size for viewing a contiguous ``(batch, max_seq, kv, hd)``
    cache as a degenerate block pool, or 0 when no usable size exists
    (→ caller falls back to the jnp reference).  Largest power of two
    dividing ``max_seq``, capped at :data:`CONTIGUOUS_BLOCK_CAP`; at
    least 16 so blocks meet the int8 sublane tile."""
    if max_seq <= 0:
        return 0
    bs = min(max_seq & -max_seq, CONTIGUOUS_BLOCK_CAP)
    return bs if bs >= 16 else 0


# ---------------------------------------------------------------------------
# jnp oracle (also the CPU / chunked-prefill / speculative-verify path)


def _dequant_block(seq: int) -> int:
    """Span the quantized fallback dequantizes at a time: the largest
    power-of-two divisor of ``seq`` capped at
    :data:`DEQUANT_BLOCK_CAP`, halved if it would cover the whole
    cache — so a full-cache bf16 copy is never materialized (the kv8
    regression: reading int8 at 1 byte/elem is the POINT of the
    layout; a wholesale ``astype`` turns that into 5 bytes/elem of
    traffic).  Odd ``seq`` degenerates to the single-span path."""
    if seq <= 1 or seq % 2:
        return seq
    block = min(seq & -seq, DEQUANT_BLOCK_CAP)
    if block == seq:
        block = seq // 2
    return block


def _quantized_scores(q, k_cache, ks, hd):
    """q·k scores against an int8 K cache, dequantizing one
    :func:`_dequant_block` span per loop step — numerically identical
    per element to the single-shot einsum (the hd contraction never
    crosses span boundaries), with peak extra memory O(span) instead
    of O(max_seq).  Returns f32 ``(b, kv, group, Q, S)``."""
    seq = k_cache.shape[1]
    span = _dequant_block(seq)
    scale = hd ** -0.5

    def span_scores(k_blk, ks_blk):
        s = jnp.einsum("bqkgd,bskd->bkgqs", q, k_blk.astype(q.dtype),
                       preferred_element_type=jnp.float32) * scale
        return s * ks_blk.transpose(0, 2, 1)[:, :, None, None, :]

    if span == seq:
        return span_scores(k_cache, ks)
    batch, Q, kv, group = (q.shape[0], q.shape[1], q.shape[2],
                           q.shape[3])
    init = jnp.zeros((batch, kv, group, Q, seq), jnp.float32)

    def body(i, buf):
        start = i * span
        k_blk = jax.lax.dynamic_slice_in_dim(k_cache, start, span, 1)
        ks_blk = jax.lax.dynamic_slice_in_dim(ks, start, span, 1)
        return jax.lax.dynamic_update_slice_in_dim(
            buf, span_scores(k_blk, ks_blk), start, axis=4)

    return jax.lax.fori_loop(0, seq // span, body, init)


def _quantized_weighted_sum(weights, v_cache, vs, out_dtype):
    """``softmax-weights @ V`` against an int8 V cache, one span at a
    time with f32 accumulation across spans.  ``weights`` f32
    ``(b, kv, group, Q, S)``; returns ``(b, Q, kv, group, hd)``."""
    seq = v_cache.shape[1]
    span = _dequant_block(seq)

    def span_sum(w_blk, v_blk, vs_blk):
        w = w_blk * vs_blk.transpose(0, 2, 1)[:, :, None, None, :]
        return jnp.einsum("bkgqs,bskd->bqkgd", w.astype(out_dtype),
                          v_blk.astype(out_dtype),
                          preferred_element_type=jnp.float32)

    if span == seq:
        return span_sum(weights, v_cache, vs).astype(out_dtype)
    batch, kv, group, Q = weights.shape[:4]
    hd = v_cache.shape[-1]
    init = jnp.zeros((batch, Q, kv, group, hd), jnp.float32)

    def body(i, acc):
        start = i * span
        v_blk = jax.lax.dynamic_slice_in_dim(v_cache, start, span, 1)
        vs_blk = jax.lax.dynamic_slice_in_dim(vs, start, span, 1)
        w_blk = jax.lax.dynamic_slice_in_dim(weights, start, span, 4)
        return acc + span_sum(w_blk, v_blk, vs_blk)

    acc = jax.lax.fori_loop(0, seq // span, body, init)
    return acc.astype(out_dtype)


def cached_gqa_attention(q, cache_layer, query_positions, hd,
                         window: Optional[int] = None):
    """Masked GQA attention over a KV cache — the jnp oracle shared by
    ragged decode (CPU fallback), chunked prefill, and speculative
    verify.  ``q`` (batch, Q, kv, group, hd); ``query_positions``
    (batch, Q) absolute positions; key row ``s`` is attended iff ``s <=
    position`` of the query (and within ``window`` of it, when
    sliding-window attention is on).

    Int8 KV layout: per-(token, head) scales factor OUT of the q·k
    contraction (over hd), so they multiply the score afterwards; on
    the value side they factor INTO the softmax weights (contraction is
    over tokens), so the weights are scaled per key row before the
    weighted sum — both exact dequantizations.  Dequantization runs one
    :func:`_dequant_block` span at a time so the int8 cache is read at
    1 byte/element and no full-cache bf16 copy is ever materialized
    (asserted by tests/test_paged_attention.py on the decode jaxpr)."""
    k_cache, v_cache = cache_layer["k"], cache_layer["v"]
    quantized = "ks" in cache_layer
    if quantized:
        s = _quantized_scores(q, k_cache, cache_layer["ks"], hd)
    else:
        s = jnp.einsum("bqkgd,bskd->bkgqs", q, k_cache,
                       preferred_element_type=jnp.float32) * hd ** -0.5
    if "pos" in cache_layer:
        # Rolling layout: each row stores its ABSOLUTE position (-1 =
        # never written); visibility is decided from those, so ring
        # wraparound needs no special casing.
        key_pos = cache_layer["pos"][:, None, :]     # (b, 1, S)
        mask = (key_pos >= 0) & (key_pos
                                 <= query_positions[:, :, None])
    else:
        key_pos = jnp.arange(k_cache.shape[1])[None, None, :]
        mask = key_pos <= query_positions[:, :, None]
    if window is not None:
        mask &= key_pos > query_positions[:, :, None] - window
    s = jnp.where(mask[:, None, None, :, :], s, NEG_INF)
    weights = jax.nn.softmax(s, axis=-1)
    if quantized:
        return _quantized_weighted_sum(weights, v_cache,
                                       cache_layer["vs"], q.dtype)
    return jnp.einsum("bkgqs,bskd->bqkgd",
                      weights.astype(v_cache.dtype), v_cache)


def paged_decode_reference(q, k_pool, v_pool, tables, positions,
                           ks=None, vs=None,
                           window: Optional[int] = None):
    """Gather-then-masked-attend oracle for the kernel: pool[tables] →
    per-row contiguous view, then :func:`cached_gqa_attention`.  ``q``
    (batch, kv, group, hd); pools (n_blocks, bs, kv, hd); returns
    (batch, kv, group, hd)."""
    def view(pool):
        gathered = pool[tables]
        batch, n_blocks, bs = gathered.shape[:3]
        return gathered.reshape((batch, n_blocks * bs)
                                + gathered.shape[3:])

    cache_layer = {"k": view(k_pool), "v": view(v_pool)}
    if ks is not None:
        cache_layer["ks"] = view(ks)
        cache_layer["vs"] = view(vs)
    hd = q.shape[-1]
    out = cached_gqa_attention(q[:, None], cache_layer,
                               positions[:, None], hd, window=window)
    return out[:, 0]


# ---------------------------------------------------------------------------
# The kernel


def _paged_decode_kernel(tables_ref, positions_ref,   # scalar prefetch
                         q_ref, k_ref, v_ref, *rest,
                         block_size: int, sm_scale: float,
                         window: Optional[int], quantized: bool):
    """Grid: (batch, kv_heads, blocks); blocks fastest-varying.

    One program = one (row, kv-head) × one pool block.  Scratch carries
    the online-softmax state across the block sweep.  ``tables_ref`` /
    ``positions_ref`` are the scalar-prefetched block table and per-row
    positions (also consumed by the K/V index maps in
    :func:`paged_decode_attention`)."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    row = pl.program_id(0)
    j = pl.program_id(2)
    num_j = pl.num_programs(2)
    pos = positions_ref[row]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Liveness: a block past the row's length contributes nothing, and
    # with a sliding window neither does a block whose LAST key is
    # already out of the window.  Dead steps also clamp their index map
    # (see kv_index) so they trigger no HBM→VMEM copy.  Every live
    # block provably contains ≥1 visible key, so no bogus softmax mass
    # is ever accumulated (NEG_INF stays finite regardless — see
    # ops/attention.py).
    block_live = j * block_size <= pos
    if window is not None:
        block_live &= (j + 1) * block_size - 1 > pos - window

    @pl.when(block_live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)           # (group, hd)
        k = k_ref[0, :, 0].astype(jnp.float32)        # (bs, hd)
        v = v_ref[0, :, 0].astype(jnp.float32)        # (bs, hd)
        if quantized:
            # Per-(token, head) scales load as a (bs, 1) column and
            # broadcast along hd — dequantization never leaves VMEM.
            k = k * ks_ref[0]
            v = v * vs_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (group, bs)

        key_ids = jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1) + j * block_size
        visible = key_ids <= pos
        if window is not None:
            visible &= key_ids > pos - window
        s = jnp.where(visible, s, NEG_INF)

        m_prev = m_scr[:]                              # (group, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                         # (group, bs)
        correction = jnp.exp(m_prev - m_new)
        l_scr[:] = correction * l_scr[:] + jnp.sum(p, axis=-1,
                                                   keepdims=True)
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(j == num_j - 1)
    def _finish():
        denom = jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])
        o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, tables, positions,
                           ks=None, vs=None,
                           window: Optional[int] = None,
                           sm_scale: Optional[float] = None,
                           interpret: bool = False):
    """Ragged paged GQA decode attention.

    Args:
      q: ``(batch, kv_heads, group, head_dim)`` — ONE query token per
        row, all query heads of each kv head together.
      k_pool / v_pool: ``(n_blocks, block_size, kv_heads, head_dim)``
        block pools (bf16/f32, or int8 with ``ks``/``vs``).
      tables: ``(batch, max_blocks)`` int32 — pool block id of each
        row's logical block ``j`` (entries past the row's length are
        never read).
      positions: ``(batch,)`` int32 — the query's absolute position;
        keys ``0..positions[row]`` are visible (the current token's K/V
        must already be written to the pool).
      ks / vs: optional ``(n_blocks, block_size, kv_heads)`` f32
        per-(token, head) scales → int8 in-kernel dequantization.
      window: sliding-window size (Mistral semantics, matches
        :func:`cached_gqa_attention`).
      interpret: run the Pallas kernel in interpret mode (CPU testing).

    Returns ``(batch, kv_heads, group, head_dim)`` in ``q.dtype``.
    Dispatches to :func:`paged_decode_reference` when Pallas TPU is
    unavailable (and not interpreting) or the shape is unsupported.
    """
    batch, kv_heads, group, head_dim = q.shape
    n_blocks, block_size = k_pool.shape[0], k_pool.shape[1]
    max_blocks = tables.shape[1]
    quantized = ks is not None
    if sm_scale is None:
        sm_scale = head_dim ** -0.5

    on_tpu = jax.default_backend() == "tpu"
    if not (_PALLAS_TPU and (on_tpu or interpret)) or head_dim > 128:
        return paged_decode_reference(q, k_pool, v_pool, tables,
                                      positions, ks=ks, vs=vs,
                                      window=window)

    tables = tables.astype(jnp.int32)
    positions = positions.astype(jnp.int32)
    grid = (batch, kv_heads, max_blocks)

    def kv_index(row, head, j, tables_ref, positions_ref):
        # Clamp dead steps into the live band [first_live, last_live]:
        # an unchanged block index means Pallas reuses the resident
        # VMEM tile instead of issuing a fresh HBM copy, so a row's
        # HBM traffic is O(its actual length), not O(max_seq).
        pos = positions_ref[row]
        j_c = jnp.minimum(j, pos // block_size)
        if window is not None:
            first_live = jnp.maximum(pos - window + 1, 0) // block_size
            j_c = jnp.maximum(j_c, first_live)
        return (tables_ref[row, j_c], 0, head, 0)

    def scale_index(row, head, j, tables_ref, positions_ref):
        return kv_index(row, head, j, tables_ref, positions_ref)[:3]

    def q_index(row, head, j, tables_ref, positions_ref):
        return (row, head, 0, 0)

    in_specs = [
        pl.BlockSpec((1, 1, group, head_dim), q_index),
        pl.BlockSpec((1, block_size, 1, head_dim), kv_index),
        pl.BlockSpec((1, block_size, 1, head_dim), kv_index),
    ]
    operands = [q, k_pool, v_pool]
    if quantized:
        in_specs += [pl.BlockSpec((1, block_size, 1), scale_index),
                     pl.BlockSpec((1, block_size, 1), scale_index)]
        operands += [ks, vs]

    kernel = functools.partial(
        _paged_decode_kernel, block_size=block_size,
        sm_scale=sm_scale, window=window, quantized=quantized)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group, head_dim), q_index),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, head_dim), jnp.float32),
        ])
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(tables, positions, *operands)

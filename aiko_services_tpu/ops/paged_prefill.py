"""Ragged paged append-attention: chunked prefill straight against the
block pool, plus the jnp oracle it must match.

Admission is the serving cold path that stalls the hot one: the bucket
admission flow gathers a prompt's cached blocks into a contiguous
bucket (``paged_gather_blocks``), runs contiguous chunked prefill over
it, then scatters the result back into the pool
(``paged_scatter_blocks``) — every prompt KV byte crosses HBM twice
before the first decode step, and a prefix-cache hit still pays the
full gather.  The append kernel here removes both copies:

* a **write kernel** (grid ``(row, kv-head, chunk-block)``) quantizes
  (int8 layout) and lands the chunk's new K/V rows directly in the
  row's pool blocks — the block table rides scalar prefetch, so the
  output BlockSpec index map targets ``tables[row, cached//bs + cb]``
  and the flush IS the pool write.  Blocks past ``chunk_len`` retarget
  the allocator's reserved scratch block 0 (never attendable, the same
  contract inactive decode lanes rely on).
* an **attention kernel** (grid ``(row, kv-head, query-tile,
  kv-block)``, kv fastest) runs flash-style online softmax for the
  chunk's queries over the row's cached prefix blocks plus the
  causally-visible part of the chunk itself, reading K/V straight from
  the pool.  Per-row ``(cached_len, chunk_len)`` metadata rides scalar
  prefetch; dead steps (blocks past the tile's last query, or wholly
  below its sliding window) clamp their index map to a resident block
  and skip compute, so a row's HBM traffic is O(its real history).
* all ``group`` query heads of a kv head stack into the tile's row
  axis (``(q_tile·group, head_dim)``), so masking is per-row by
  absolute ids and every matmul is MXU-shaped 2D.
* unlike single-token decode, a multi-query tile CAN hold rows with no
  visible key in a live block (a later chunk row's first block, or a
  window that has slid past), so masked positions are explicitly
  zeroed in the probability tile — the decode kernel's "every live
  block has a visible key" invariant does not extend here.

``cached_lens`` must be block-aligned (multiples of ``block_size``):
shared prefixes are whole blocks and chunk widths are powers of two,
so every caller satisfies this by construction.  The sequence-parallel
prefill window (``models/llama_tp._tp_sp_prefill_core``) dispatches
through this same path per sp shard — shard ``j`` appends chunk ``j``
with ``cached_lens = start + j·cap`` (cap is the admission cap, a pow2
multiple of ``block_size``, so alignment holds per shard) and the
window's K/V is all-gathered so every sp pool replica lands identical
bytes.  Layout contract and dispatch rules are documented in
docs/KERNELS.md.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .attention import NEG_INF, _PALLAS_TPU
from .paged_attention import cached_gqa_attention

if _PALLAS_TPU:
    from jax.experimental.pallas import tpu as pltpu
else:  # pragma: no cover
    pltpu = None

__all__ = ["paged_prefill_attention", "paged_prefill_reference",
           "paged_verify_attention",
           "prefill_kernel_mode", "prefill_attention_path"]

#: Largest query tile (tokens) one attention program carries; the tile
#: row axis is ``q_tile * group`` so this also bounds scratch size.
Q_TILE_CAP = 128


# ---------------------------------------------------------------------------
# Dispatch policy


def prefill_kernel_mode() -> Tuple[bool, bool]:
    """``(use_kernel, interpret)`` for the append-attention dispatch.

    Controlled by ``AIKO_PREFILL_ATTENTION`` (read at TRACE time — set
    it before the first admission of a given shape, jit caches traces):

    * ``auto`` (default): kernel on TPU, jnp reference elsewhere.
    * ``kernel``: force the kernel; off-TPU it runs in interpret mode
      (slow — testing only).
    * ``interpret``: kernel in interpret mode everywhere.
    * ``reference`` / ``off`` / ``0``: always the jnp reference.
    """
    mode = os.environ.get("AIKO_PREFILL_ATTENTION", "auto").lower()
    if mode in ("reference", "fallback", "off", "0"):
        return False, False
    on_tpu = jax.default_backend() == "tpu"
    if mode in ("kernel", "force"):
        return _PALLAS_TPU, not on_tpu
    if mode == "interpret":
        return _PALLAS_TPU, True
    return _PALLAS_TPU and on_tpu, False


def prefill_attention_path() -> str:
    """``"kernel"`` or ``"reference"`` — the serving-counter path tag."""
    return "kernel" if prefill_kernel_mode()[0] else "reference"


def _q_tile_size(chunk: int) -> int:
    """Default query tile: largest power-of-two divisor of ``chunk``,
    capped at :data:`Q_TILE_CAP`."""
    return min(chunk & -chunk, Q_TILE_CAP)


# ---------------------------------------------------------------------------
# jnp oracle (also the CPU path) — numerics the kernel must match


def _kv_quantize_rows(rows):
    """(…, hd) → (int8 rows, f32 scales (…,)) — symmetric absmax per
    vector, identical numerics to the models-side cache quantizer (one
    scale per token per kv head)."""
    r32 = rows.astype(jnp.float32)
    amax = jnp.max(jnp.abs(r32), axis=-1)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(r32 / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _write_rows_reference(pool, k_new, v_new, tables, positions):
    """Scatter the chunk rows (every padded row — pad keys land past
    every real query's visibility) into the pool at their absolute
    positions; int8 layouts quantize exactly like the cache writer."""
    block_size = pool["k"].shape[1]
    block_ids = jnp.take_along_axis(tables, positions // block_size,
                                    axis=1)
    offsets = positions % block_size
    if "ks" in pool:
        kq, ks = _kv_quantize_rows(k_new)
        vq, vs = _kv_quantize_rows(v_new)
        sources = {"k": kq, "v": vq, "ks": ks, "vs": vs}
    else:
        sources = {"k": k_new, "v": v_new}
    return {key: pool[key].at[block_ids, offsets].set(
                src.astype(pool[key].dtype))
            for key, src in sources.items()}


def paged_prefill_reference(q, k_new, v_new, pool, tables, cached_lens,
                            chunk_lens, window: Optional[int] = None):
    """Write-then-gather-then-attend oracle for the append kernel:
    scatter the chunk's K/V into the pool, view ``pool[tables]`` as
    per-row contiguous caches, and run :func:`cached_gqa_attention`
    with query positions ``cached + [0, T)``.

    ``q`` (batch, T, kv, group, hd); ``k_new``/``v_new`` (batch, T, kv,
    hd); ``pool`` the per-layer dict (``k``/``v`` + optional
    ``ks``/``vs``); returns ``(out (batch, T, kv, group, hd),
    new_pool)``.  Query/output rows at or past ``chunk_lens[row]`` are
    padding — attended against garbage, discarded by callers."""
    batch, T = k_new.shape[:2]
    hd = q.shape[-1]
    positions = (cached_lens.astype(jnp.int32)[:, None]
                 + jnp.arange(T, dtype=jnp.int32)[None, :])
    new_pool = _write_rows_reference(pool, k_new, v_new, tables,
                                     positions)

    def view(buf):
        gathered = buf[tables]
        n_blocks, bs = gathered.shape[1:3]
        return gathered.reshape((batch, n_blocks * bs)
                                + gathered.shape[3:])

    cache_layer = {key: view(buf) for key, buf in new_pool.items()}
    out = cached_gqa_attention(q, cache_layer, positions, hd,
                               window=window)
    return out, new_pool


# ---------------------------------------------------------------------------
# The write kernel: land the chunk's K/V rows in their pool blocks


def _append_kv_kernel(tables_ref, meta_ref,        # scalar prefetch
                      k_new_ref, v_new_ref, k_in_ref, v_in_ref, *rest,
                      quantized: bool):
    """Grid: (batch, kv_heads, chunk_blocks).  One program moves one
    (row, kv-head) chunk block from the activation slab into the pool
    block the index map resolved from the prefetched table — the
    output flush IS the pool write.  Dead steps (block past
    ``chunk_len``) still flush, but the index map retargeted them at
    reserved scratch block 0, which is never attendable."""
    if quantized:
        _ks_in, _vs_in, k_out, v_out, ks_out, vs_out = rest
    else:
        k_out, v_out = rest
    k = k_new_ref[0, :, 0]                      # (bs, hd)
    v = v_new_ref[0, :, 0]
    if quantized:
        for new, out, scale_out in ((k, k_out, ks_out),
                                    (v, v_out, vs_out)):
            r32 = new.astype(jnp.float32)
            amax = jnp.max(jnp.abs(r32), axis=-1, keepdims=True)
            scale = jnp.where(amax == 0, 1.0, amax / 127.0)  # (bs, 1)
            out[0, :, 0] = jnp.clip(jnp.round(r32 / scale),
                                    -127, 127).astype(out.dtype)
            scale_out[0] = scale
    else:
        k_out[0, :, 0] = k.astype(k_out.dtype)
        v_out[0, :, 0] = v.astype(v_out.dtype)


def _append_kv(k_new, v_new, pool, tables, meta, interpret: bool):
    """Write the (batch, T, kv, hd) chunk slabs into the pool blocks
    named by ``tables`` starting at block ``cached // bs`` — in-kernel,
    via aliased pool outputs whose index maps resolve the target block
    from the scalar-prefetched table."""
    batch, T, kv_heads, head_dim = k_new.shape
    block_size = pool["k"].shape[1]
    max_blocks = tables.shape[1]
    quantized = "ks" in pool
    chunk_blocks = T // block_size
    grid = (batch, kv_heads, chunk_blocks)

    def new_index(b, h, cb, tables_ref, meta_ref):
        return (b, cb, h, 0)

    def pool_index(b, h, cb, tables_ref, meta_ref):
        # Blocks past the row's real chunk length flush garbage — but
        # into reserved scratch block 0, exactly like inactive decode
        # lanes.  The live-block table lookup is clamped so dead steps
        # never read past the row's allocated entries.
        live = cb * block_size < meta_ref[b, 1]
        entry = jnp.minimum(meta_ref[b, 0] // block_size + cb,
                            max_blocks - 1)
        return (jnp.where(live, tables_ref[b, entry], 0), 0, h, 0)

    def scale_index(b, h, cb, tables_ref, meta_ref):
        return pool_index(b, h, cb, tables_ref, meta_ref)[:3]

    kv_spec = pl.BlockSpec((1, block_size, 1, head_dim), new_index)
    pool_spec = pl.BlockSpec((1, block_size, 1, head_dim), pool_index)
    scale_spec = pl.BlockSpec((1, block_size, 1), scale_index)

    in_specs = [kv_spec, kv_spec, pool_spec, pool_spec]
    operands = [k_new, v_new, pool["k"], pool["v"]]
    out_specs = [pool_spec, pool_spec]
    out_shape = [jax.ShapeDtypeStruct(pool["k"].shape, pool["k"].dtype),
                 jax.ShapeDtypeStruct(pool["v"].shape, pool["v"].dtype)]
    # Aliased pool operands: positions count scalar-prefetch args, so
    # (tables, meta, k_new, v_new, k, v[, ks, vs]) puts the pools at 4+.
    aliases = {4: 0, 5: 1}
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [pool["ks"], pool["vs"]]
        out_specs += [scale_spec, scale_spec]
        out_shape += [
            jax.ShapeDtypeStruct(pool["ks"].shape, pool["ks"].dtype),
            jax.ShapeDtypeStruct(pool["vs"].shape, pool["vs"].dtype)]
        aliases.update({6: 2, 7: 3})

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2, grid=grid,
        in_specs=in_specs, out_specs=out_specs)
    outs = pl.pallas_call(
        functools.partial(_append_kv_kernel, quantized=quantized),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(tables, meta, *operands)
    new_pool = {"k": outs[0], "v": outs[1]}
    if quantized:
        new_pool["ks"], new_pool["vs"] = outs[2], outs[3]
    return new_pool


# ---------------------------------------------------------------------------
# The attention kernel: chunk queries over cached prefix + chunk


def _prefill_attention_kernel(tables_ref, meta_ref,   # scalar prefetch
                              q_ref, k_ref, v_ref, *rest,
                              block_size: int, q_tile: int, group: int,
                              sm_scale: float, window: Optional[int],
                              quantized: bool):
    """Grid: (batch, kv_heads, q_tiles, kv_blocks); kv fastest.

    One program sweeps one (row, kv-head, query-tile) through the
    row's pool blocks carrying online-softmax state in VMEM scratch.
    The tile's row axis interleaves queries and their group heads
    (``row = token·group + head``), so per-row masking by absolute ids
    covers ragged causality AND the sliding window in one 2D tile."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    qt = pl.program_id(2)
    j = pl.program_id(3)
    num_j = pl.num_programs(3)
    cached = meta_ref[b, 0]
    q_min = cached + qt * q_tile          # tile's first query position
    q_max = q_min + q_tile - 1            # tile's last query position

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Liveness: a block wholly past the tile's LAST query contributes
    # nothing; with a sliding window, neither does a block whose last
    # key is out of even the FIRST query's window.  Dead steps also
    # clamp their index map (see kv_index) so they trigger no HBM→VMEM
    # copy.
    block_live = j * block_size <= q_max
    if window is not None:
        block_live &= (j + 1) * block_size - 1 > q_min - window

    @pl.when(block_live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)       # (q_tile*group, hd)
        k = k_ref[0, :, 0].astype(jnp.float32)    # (bs, hd)
        v = v_ref[0, :, 0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0]
            v = v * vs_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

        q_ids = q_min + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0) // group
        key_ids = jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1) + j * block_size
        visible = key_ids <= q_ids
        if window is not None:
            visible &= key_ids > q_ids - window
        s = jnp.where(visible, s, NEG_INF)

        m_prev = m_scr[:]                         # (q_tile*group, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # A live block can hold rows with NO visible key (later chunk
        # rows, or a window that slid past): their m stays NEG_INF and
        # exp(NEG_INF - NEG_INF) = 1 would be bogus mass — zero masked
        # probabilities explicitly (the single-query decode kernel's
        # every-live-block-has-a-visible-key invariant does not extend
        # to multi-query tiles).
        p = jnp.where(visible, jnp.exp(s - m_new), 0.0)
        correction = jnp.exp(m_prev - m_new)
        l_scr[:] = correction * l_scr[:] + jnp.sum(p, axis=-1,
                                                   keepdims=True)
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(j == num_j - 1)
    def _finish():
        denom = jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])
        o_ref[0, 0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def _chunk_attention(q, pool, tables, meta, window: Optional[int],
                     sm_scale: float, q_tile: int,
                     kv_blocks: int, interpret: bool):
    """Dispatch the attention kernel over the (already appended) pool.
    ``q`` (batch, T, kv, group, hd) → out same shape."""
    batch, T, kv_heads, group, head_dim = q.shape
    block_size = pool["k"].shape[1]
    quantized = "ks" in pool
    # All group heads of a query stack into the tile row axis: 2D tiles
    # everywhere in-kernel, one (q_tile*group, hd) x (hd, bs) matmul
    # per block.
    q_r = q.transpose(0, 2, 1, 3, 4).reshape(batch, kv_heads,
                                             T * group, head_dim)
    grid = (batch, kv_heads, T // q_tile, kv_blocks)
    rows = q_tile * group

    def q_index(b, h, qt, j, tables_ref, meta_ref):
        return (b, h, qt, 0)

    def kv_index(b, h, qt, j, tables_ref, meta_ref):
        # Clamp dead steps into the tile's live band: an unchanged
        # block index makes Pallas reuse the resident VMEM tile
        # instead of issuing a fresh HBM copy.
        cached = meta_ref[b, 0]
        last = (cached + (qt + 1) * q_tile - 1) // block_size
        j_c = jnp.minimum(j, last)
        if window is not None:
            first_live = jnp.maximum(
                cached + qt * q_tile - window + 1, 0) // block_size
            j_c = jnp.maximum(j_c, first_live)
        return (tables_ref[b, j_c], 0, h, 0)

    def scale_index(b, h, qt, j, tables_ref, meta_ref):
        return kv_index(b, h, qt, j, tables_ref, meta_ref)[:3]

    in_specs = [
        pl.BlockSpec((1, 1, rows, head_dim), q_index),
        pl.BlockSpec((1, block_size, 1, head_dim), kv_index),
        pl.BlockSpec((1, block_size, 1, head_dim), kv_index),
    ]
    operands = [q_r, pool["k"], pool["v"]]
    if quantized:
        in_specs += [pl.BlockSpec((1, block_size, 1), scale_index),
                     pl.BlockSpec((1, block_size, 1), scale_index)]
        operands += [pool["ks"], pool["vs"]]

    kernel = functools.partial(
        _prefill_attention_kernel, block_size=block_size,
        q_tile=q_tile, group=group, sm_scale=sm_scale, window=window,
        quantized=quantized)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rows, head_dim), q_index),
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, head_dim), jnp.float32),
        ])
    out_r = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q_r.shape, q.dtype),
        interpret=interpret,
    )(tables, meta, *operands)
    return out_r.reshape(batch, kv_heads, T, group,
                         head_dim).transpose(0, 2, 1, 3, 4)


def paged_prefill_attention(q, k_new, v_new, pool, tables, cached_lens,
                            chunk_lens, window: Optional[int] = None,
                            sm_scale: Optional[float] = None,
                            interpret: bool = False,
                            q_tile: Optional[int] = None,
                            kv_limit: Optional[int] = None):
    """Ragged paged append attention: write the chunk's K/V into the
    pool in-kernel, then attend the chunk's queries over cached prefix
    blocks + the causally-visible chunk itself.

    Args:
      q: ``(batch, T, kv_heads, group, head_dim)`` chunk queries (rope
        applied), all query heads of each kv head together.
      k_new / v_new: ``(batch, T, kv_heads, head_dim)`` the chunk's new
        K/V rows (rope applied to K) — written to the pool at absolute
        positions ``cached_lens[row] + [0, T)``.
      pool: per-layer dict ``{"k", "v"[, "ks", "vs"]}`` of
        ``(n_blocks, block_size, kv_heads, head_dim)`` block pools
        (int8 layouts quantize in-kernel, absmax per (token, head)).
      tables: ``(batch, max_blocks)`` int32 block table; entries
        covering ``[0, cached + T)`` must be allocated.
      cached_lens: ``(batch,)`` int32 — tokens already in the pool for
        the row; MUST be a multiple of ``block_size`` (true by
        construction: shared prefixes are whole blocks, chunk widths
        are powers of two ≥ the block size).
      chunk_lens: ``(batch,)`` int32 — real new tokens (≤ T).  Rows at
        or past a row's chunk length are padding: their K/V lands in
        scratch-block garbage territory past every real query's
        visibility, and their output rows are garbage the caller
        discards.
      window: sliding-window size (Mistral semantics).
      sm_scale: score scale (default ``head_dim ** -0.5``).
      interpret: run the Pallas kernels in interpret mode (CPU tests).
      q_tile: queries per attention program (default: largest pow2
        divisor of T, capped at :data:`Q_TILE_CAP`).
      kv_limit: static bound on the kv-block sweep (e.g. the padded
        bucket's block count) — trims dead grid steps when the table
        is much longer than the row can be.

    Returns ``(out (batch, T, kv_heads, group, head_dim) in q.dtype,
    new_pool)``.  Falls back to :func:`paged_prefill_reference` when
    Pallas TPU is unavailable (and not interpreting) or the shape is
    unsupported (``head_dim > 128``, ``T`` not block-aligned).
    """
    batch, T, kv_heads, group, head_dim = q.shape
    block_size = pool["k"].shape[1]
    max_blocks = tables.shape[1]
    if sm_scale is None:
        sm_scale = head_dim ** -0.5

    on_tpu = jax.default_backend() == "tpu"
    if (not (_PALLAS_TPU and (on_tpu or interpret))
            or head_dim > 128 or T % block_size != 0):
        return paged_prefill_reference(q, k_new, v_new, pool, tables,
                                       cached_lens, chunk_lens,
                                       window=window)

    tables = tables.astype(jnp.int32)
    meta = jnp.stack([cached_lens.astype(jnp.int32),
                      chunk_lens.astype(jnp.int32)], axis=1)
    if q_tile is None:
        q_tile = _q_tile_size(T)
    if T % q_tile:
        raise ValueError(f"q_tile {q_tile} must divide chunk width {T}")
    kv_blocks = max_blocks if kv_limit is None else min(kv_limit,
                                                        max_blocks)

    new_pool = _append_kv(k_new, v_new, pool, tables, meta, interpret)
    out = _chunk_attention(q, new_pool, tables, meta, window=window,
                           sm_scale=sm_scale, q_tile=q_tile,
                           kv_blocks=kv_blocks, interpret=interpret)
    return out, new_pool


# ---------------------------------------------------------------------------
# Ragged verify: short append chunks at UNALIGNED per-row positions
# (speculative decoding on the paged path — each slot's verify window
# starts mid-block at its own decode position)


def _append_kv_ragged_kernel(tables_ref, meta_ref,     # scalar prefetch
                             k_new_ref, v_new_ref, k_in_ref, v_in_ref,
                             *rest, block_size: int, span: int,
                             quantized: bool):
    """Grid: (batch, kv_heads, span_blocks).  One program MERGES the
    row's verify slab into one pool block: unlike the aligned chunk
    writer (whole-block overwrite), a verify window starts mid-block,
    so the program reads the resident block, replaces only the rows in
    ``[cached, cached + chunk_len)``, and flushes the merge back.

    Row selection is an unrolled ``jnp.where`` sweep over the slab (2D
    tiles only, no gather): exact value passthrough, so the int8 quant
    below is bit-identical to the aligned writer's per-row absmax."""
    if quantized:
        ks_in, vs_in, k_out, v_out, ks_out, vs_out = rest
    else:
        k_out, v_out = rest
    b = pl.program_id(0)
    sb = pl.program_id(2)
    cached = meta_ref[b, 0]
    chunk_len = meta_ref[b, 1]
    # Token index held by this block's row 0 (negative in the first
    # block of an unaligned span: rows before ``cached`` keep their
    # committed values).
    entry = cached // block_size + sb
    base = entry * block_size - cached
    t = base + jax.lax.broadcasted_iota(jnp.int32, (block_size, 1), 0)
    row_new = (t >= 0) & (t < chunk_len)

    def select(slab_ref):
        slab = slab_ref[0, :, 0].astype(jnp.float32)      # (span, hd)
        acc = jnp.zeros((block_size, slab.shape[-1]), jnp.float32)
        for tt in range(span):
            acc = jnp.where(t == tt, slab[tt:tt + 1, :], acc)
        return acc

    if quantized:
        for slab_ref, in_ref, s_in, out, s_out in (
                (k_new_ref, k_in_ref, ks_in, k_out, ks_out),
                (v_new_ref, v_in_ref, vs_in, v_out, vs_out)):
            r32 = select(slab_ref)
            amax = jnp.max(jnp.abs(r32), axis=-1, keepdims=True)
            scale = jnp.where(amax == 0, 1.0, amax / 127.0)  # (bs, 1)
            rows_q = jnp.clip(jnp.round(r32 / scale),
                              -127, 127).astype(out.dtype)
            out[0, :, 0] = jnp.where(row_new, rows_q, in_ref[0, :, 0])
            s_out[0] = jnp.where(row_new, scale, s_in[0])
    else:
        for slab_ref, in_ref, out in ((k_new_ref, k_in_ref, k_out),
                                      (v_new_ref, v_in_ref, v_out)):
            rows = select(slab_ref).astype(out.dtype)
            out[0, :, 0] = jnp.where(row_new, rows, in_ref[0, :, 0])


def _append_kv_ragged(k_new, v_new, pool, tables, meta,
                      interpret: bool):
    """Merge (batch, T, kv, hd) verify slabs into pool blocks at
    arbitrary (unaligned) per-row start positions ``meta[:, 0]``.
    Blocks outside a row's live span — and every block of a row with
    ``chunk_len == 0`` — retarget reserved scratch block 0 and write
    back what they read (identity flush)."""
    batch, T, kv_heads, head_dim = k_new.shape
    block_size = pool["k"].shape[1]
    max_blocks = tables.shape[1]
    quantized = "ks" in pool
    # An unaligned span of T rows straddles at most ceil(T/bs)+1 blocks.
    span_blocks = -(-T // block_size) + 1
    grid = (batch, kv_heads, span_blocks)

    def new_index(b, h, sb, tables_ref, meta_ref):
        return (b, 0, h, 0)

    def pool_index(b, h, sb, tables_ref, meta_ref):
        cached = meta_ref[b, 0]
        entry = cached // block_size + sb
        live = (entry * block_size < cached + meta_ref[b, 1]) \
            & (meta_ref[b, 1] > 0)
        entry = jnp.minimum(entry, max_blocks - 1)
        return (jnp.where(live, tables_ref[b, entry], 0), 0, h, 0)

    def scale_index(b, h, sb, tables_ref, meta_ref):
        return pool_index(b, h, sb, tables_ref, meta_ref)[:3]

    kv_spec = pl.BlockSpec((1, T, 1, head_dim), new_index)
    pool_spec = pl.BlockSpec((1, block_size, 1, head_dim), pool_index)
    scale_spec = pl.BlockSpec((1, block_size, 1), scale_index)

    in_specs = [kv_spec, kv_spec, pool_spec, pool_spec]
    operands = [k_new, v_new, pool["k"], pool["v"]]
    out_specs = [pool_spec, pool_spec]
    out_shape = [jax.ShapeDtypeStruct(pool["k"].shape, pool["k"].dtype),
                 jax.ShapeDtypeStruct(pool["v"].shape, pool["v"].dtype)]
    aliases = {4: 0, 5: 1}
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [pool["ks"], pool["vs"]]
        out_specs += [scale_spec, scale_spec]
        out_shape += [
            jax.ShapeDtypeStruct(pool["ks"].shape, pool["ks"].dtype),
            jax.ShapeDtypeStruct(pool["vs"].shape, pool["vs"].dtype)]
        aliases.update({6: 2, 7: 3})

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2, grid=grid,
        in_specs=in_specs, out_specs=out_specs)
    outs = pl.pallas_call(
        functools.partial(_append_kv_ragged_kernel,
                          block_size=block_size, span=T,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
    )(tables, meta, *operands)
    new_pool = {"k": outs[0], "v": outs[1]}
    if quantized:
        new_pool["ks"], new_pool["vs"] = outs[2], outs[3]
    return new_pool


def paged_verify_attention(q, k_new, v_new, pool, tables, cached_lens,
                           chunk_lens, window: Optional[int] = None,
                           sm_scale: Optional[float] = None,
                           interpret: bool = False,
                           kv_limit: Optional[int] = None):
    """Ragged paged VERIFY attention: the speculative twin of
    :func:`paged_prefill_attention` for short windows at arbitrary
    (mid-block) per-row start positions.

    Two contract differences from the prefill entry:

    * ``cached_lens`` need NOT be block-aligned — each slot verifies at
      its own decode position, so the write kernel merges into the
      partial first block instead of overwriting whole blocks.
    * ``chunk_lens`` may vary per row (ragged k across the batch); rows
      with ``chunk_lens[row] == 0`` (inactive slots) write nothing at
      all — their programs identity-flush scratch block 0.

    ``T`` (the slab width) is padded internally to a power of two ≥ 16
    so the attention tile satisfies the TPU sublane floor; pad rows are
    never written and their output rows are sliced off.  The attention
    sweep is the SAME online-softmax kernel chunked prefill uses
    (absolute-id masking already handles unaligned ``cached``), so a
    verify pass reads each row's real history once — no pool gather.

    Returns ``(out (batch, T, kv_heads, group, head_dim), new_pool)``.
    Falls back to :func:`paged_prefill_reference` (which supports
    arbitrary per-row positions natively) off-TPU or for
    ``head_dim > 128`` / ``T > Q_TILE_CAP``.
    """
    batch, T, kv_heads, group, head_dim = q.shape
    max_blocks = tables.shape[1]
    if sm_scale is None:
        sm_scale = head_dim ** -0.5

    on_tpu = jax.default_backend() == "tpu"
    if (not (_PALLAS_TPU and (on_tpu or interpret))
            or head_dim > 128 or T > Q_TILE_CAP):
        return paged_prefill_reference(q, k_new, v_new, pool, tables,
                                       cached_lens, chunk_lens,
                                       window=window)

    Tp = max(16, 1 << (T - 1).bit_length())
    if Tp != T:
        pad = ((0, 0), (0, Tp - T)) + ((0, 0),) * (q.ndim - 2)
        q = jnp.pad(q, pad)
        k_new = jnp.pad(k_new, pad[:k_new.ndim])
        v_new = jnp.pad(v_new, pad[:v_new.ndim])

    tables = tables.astype(jnp.int32)
    meta = jnp.stack([cached_lens.astype(jnp.int32),
                      chunk_lens.astype(jnp.int32)], axis=1)
    kv_blocks = max_blocks if kv_limit is None else min(kv_limit,
                                                        max_blocks)
    new_pool = _append_kv_ragged(k_new, v_new, pool, tables, meta,
                                 interpret)
    out = _chunk_attention(q, new_pool, tables, meta, window=window,
                           sm_scale=sm_scale, q_tile=Tp,
                           kv_blocks=kv_blocks, interpret=interpret)
    return out[:, :T], new_pool

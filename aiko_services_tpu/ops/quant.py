"""Int8 weight-only quantization with a fused dequant-matmul Pallas kernel.

Autoregressive decode on TPU is HBM-bandwidth-bound: every step streams
every weight matrix once.  Storing weights as int8 with per-output-channel
f32 scales halves the bytes per step vs bfloat16 (≈2× decode throughput
ceiling) and lets an 8B-parameter model fit in a single v5e chip's 16 GB
HBM.  The reference framework has no tensor abstraction at all (SURVEY.md
§2.6) — this op exists for the framework's own native model families.

Two execution paths with identical numerics:
- Pallas TPU kernel: grid over output-column blocks; each program loads an
  int8 weight tile into VMEM, converts in-register, feeds the MXU with
  ``preferred_element_type=f32``, and applies the column scales before the
  single store — the f32 dequantized weights never exist in HBM.
- XLA fallback (CPU/tests, odd shapes): ``(x @ q.astype(dt)) * s``.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _PALLAS_TPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _PALLAS_TPU = False

__all__ = ["quantize_int8", "dequantize", "int8_matmul",
           "quantize_tree", "is_quantized"]

#: int8 symmetric range (−127…127; −128 unused to keep scales symmetric).
_QMAX = 127.0


def quantize_int8(w) -> Dict:
    """Per-output-channel symmetric int8 quantization of a 2-D weight
    ``(in, out)`` → ``{"q": int8 (in, out), "s": f32 (1, out)}``."""
    w32 = jnp.asarray(w, jnp.float32)
    scale = jnp.max(jnp.abs(w32), axis=0, keepdims=True) / _QMAX
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(w32 / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return {"q": q, "s": scale}


def dequantize(qw: Dict, dtype=jnp.bfloat16):
    return (qw["q"].astype(jnp.float32) * qw["s"]).astype(dtype)


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def _kernel(x_ref, q_ref, s_ref, o_ref):
    acc = jnp.dot(x_ref[:], q_ref[:].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    o_ref[:] = (acc * s_ref[:]).astype(o_ref.dtype)


#: VMEM budget per program (v5e has 16 MB more-or-less shared with XLA's
#: own scoped allocations; stay well under).
_VMEM_BUDGET = 6 * 1024 * 1024


def _pick_block(m: int, k: int, n: int) -> int:
    """Largest output-column block whose working set (x bf16 + int8 weight
    tile + f32 out/scales) fits the VMEM budget; 0 = no fit."""
    for block in (1024, 512, 256, 128):
        if n % block:
            continue
        working_set = 2 * m * k + k * block + 4 * m * block + 4 * block
        if working_set <= _VMEM_BUDGET:
            return block
    return 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_matmul(x, q, s, interpret: bool = False):
    """``x (…, K) @ dequant(q (K, N), s (1, N)) → (…, N)`` in x.dtype.

    Uses the fused Pallas kernel on TPU when shapes tile cleanly (K a
    multiple of the int8 sublane tile 32, N of 128); otherwise the XLA
    fallback, which still stores int8 in HBM and fuses the convert into
    the matmul."""
    lead = x.shape[:-1]
    k, n = q.shape
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    block_n = _pick_block(m, k, n)
    on_tpu = jax.default_backend() == "tpu"
    # The kernel targets bandwidth-bound small-m (decode) matmuls; large-m
    # (prefill/training) shapes are compute-bound and XLA's own int8
    # convert+dot fusion handles them without VMEM pressure.
    if not (_PALLAS_TPU and (on_tpu or interpret)) or block_n == 0 \
            or k % 32 or m > 64:
        out = jnp.dot(x2, q.astype(x.dtype),
                      preferred_element_type=jnp.float32) * s
        return out.astype(x.dtype).reshape(*lead, n)
    out = pl.pallas_call(
        _kernel,
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((m, k), lambda j: (0, 0)),
            pl.BlockSpec((k, block_n), lambda j: (0, j)),
            pl.BlockSpec((1, block_n), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((m, block_n), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=interpret,
    )(x2, q, s)
    return out.reshape(*lead, n)


def quantize_tree(tree):
    """Quantize every 2-D float leaf of a parameter pytree (norm vectors
    and anything 1-D stay as-is)."""
    def visit(leaf):
        if isinstance(leaf, jnp.ndarray) and leaf.ndim == 2 and \
                jnp.issubdtype(leaf.dtype, jnp.floating):
            return quantize_int8(leaf)
        return leaf
    return jax.tree_util.tree_map(
        visit, tree, is_leaf=lambda x: isinstance(x, jnp.ndarray))

"""Int8 weight-only quantization with a fused dequant-matmul Pallas kernel.

Autoregressive decode on TPU is HBM-bandwidth-bound: every step streams
every weight matrix once.  Storing weights as int8 with per-output-channel
f32 scales halves the bytes per step vs bfloat16 (≈2× decode throughput
ceiling) and lets an 8B-parameter model fit in a single v5e chip's 16 GB
HBM.  The reference framework has no tensor abstraction at all (SURVEY.md
§2.6) — this op exists for the framework's own native model families.

Two execution paths with identical numerics:
- Pallas TPU kernel: grid over output-column blocks; each program loads an
  int8 weight tile into VMEM, converts in-register, feeds the MXU with
  ``preferred_element_type=f32``, and applies the column scales before the
  single store — the f32 dequantized weights never exist in HBM.
- XLA fallback (CPU/tests, odd shapes): ``(x @ q.astype(dt)) * s``.
"""

from __future__ import annotations

import functools
import os
from typing import Dict

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _PALLAS_TPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _PALLAS_TPU = False

__all__ = ["quantize_int8", "dequantize", "int8_matmul",
           "quantize_int4", "dequantize_int4", "int4_matmul",
           "quantize_tree", "is_quantized", "is_quantized_int4"]

#: AIKO_INT4_XLA=1 (read at import): route int4_matmul through the XLA
#: grouped-einsum path even on TPU, bypassing the Pallas kernel.  XLA
#: fuses the nibble unpack + scale into the contraction itself; this
#: switch exists so benchmarks can compare the two int4 lowerings
#: head-to-head on hardware without any new Pallas compile (a failed
#: Pallas compile can wedge the dev relay).
_INT4_FORCE_XLA = os.environ.get("AIKO_INT4_XLA", "") not in ("", "0")

#: AIKO_INT8_XLA=1 (read at import): route int8_matmul through XLA's
#: fused convert+dot even at kernel-eligible decode shapes (m <= 64).
#: Same rationale as the int4 switch: lets the bench capture both int8
#: lowerings head-to-head with zero new Pallas compiles.
_INT8_FORCE_XLA = os.environ.get("AIKO_INT8_XLA", "") not in ("", "0")

#: int8 symmetric range (−127…127; −128 unused to keep scales symmetric).
_QMAX = 127.0
#: int4 symmetric range (−7…7; −8 unused to keep scales symmetric).
_QMAX4 = 7.0


def quantize_int8(w) -> Dict:
    """Per-output-channel symmetric int8 quantization of a 2-D weight
    ``(in, out)`` → ``{"q": int8 (in, out), "s": f32 (1, out)}``."""
    w32 = jnp.asarray(w, jnp.float32)
    scale = jnp.max(jnp.abs(w32), axis=0, keepdims=True) / _QMAX
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(w32 / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return {"q": q, "s": scale}


def dequantize(qw: Dict, dtype=jnp.bfloat16):
    return (qw["q"].astype(jnp.float32) * qw["s"]).astype(dtype)


def is_quantized(w) -> bool:
    return isinstance(w, dict) and ("q" in w or "q4" in w) and "s" in w


def is_quantized_int4(w) -> bool:
    return isinstance(w, dict) and "q4" in w and "s" in w


# --------------------------------------------------------------------------- #
# Int4 (nibble-packed, per-group scales)
#
# Packing layout: adjacent input rows share a byte — packed[k, n] holds
# w[2k, n] in its low nibble and w[2k+1, n] in its high nibble.  A
# contiguous slice of packed rows [a, b) therefore covers the contiguous
# original rows [2a, 2b), so megatron row-parallel sharding of the packed
# matrix along axis 0 stays correct (each TP shard's packed rows line up
# with its activation slice), and per-group scales shard the same way.


def quantize_int4(w, group_size: int = 128) -> Dict:
    """Per-(input-group, output-channel) symmetric int4 quantization of a
    2-D weight ``(in, out)`` → ``{"q4": int8 (in/2, out) nibble-packed,
    "s": f32 (in/group, out)}``.  Grouped scales (default 128) bound the
    quantization error per small row-block — the standard accuracy fix
    for 4-bit weights."""
    w32 = jnp.asarray(w, jnp.float32)
    k, n = w32.shape
    if k % 2:
        raise ValueError(f"int4 packing needs an even input dim, got {k}")
    if group_size % 2 or k % group_size:
        group_size = k  # degenerate: one group per column
    g = k // group_size
    grouped = w32.reshape(g, group_size, n)
    scale = jnp.max(jnp.abs(grouped), axis=1, keepdims=True) / _QMAX4
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(grouped / scale), -_QMAX4, _QMAX4)
    q = q.reshape(k, n).astype(jnp.int32)
    packed = (q[0::2] & 0xF) | ((q[1::2] & 0xF) << 4)
    packed = jnp.where(packed >= 128, packed - 256, packed).astype(jnp.int8)
    return {"q4": packed, "s": scale.reshape(g, n)}


def _unpack_int4(packed):
    """int8 (K/2, N) → (low, high) int32 nibbles, sign-extended; low[k]
    is original row 2k, high[k] row 2k+1."""
    p = packed.astype(jnp.int32)
    low = (p << 28) >> 28
    high = p >> 4
    return low, high


def dequantize_int4(qw: Dict, dtype=jnp.bfloat16):
    packed, scale = qw["q4"], qw["s"]
    khalf, n = packed.shape
    k = 2 * khalf
    g = scale.shape[0]
    low, high = _unpack_int4(packed)
    q = jnp.stack([low, high], axis=1).reshape(k, n).astype(jnp.float32)
    w = q.reshape(g, k // g, n) * scale[:, None, :]
    return w.reshape(k, n).astype(dtype)


def _kernel(x_ref, q_ref, s_ref, o_ref):
    acc = jnp.dot(x_ref[:], q_ref[:].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    o_ref[:] = (acc * s_ref[:]).astype(o_ref.dtype)


#: VMEM budget per program (v5e has 16 MB more-or-less shared with XLA's
#: own scoped allocations; stay well under).
_VMEM_BUDGET = 6 * 1024 * 1024


def _pick_block(m: int, k: int, n: int) -> int:
    """Largest output-column block whose working set (x bf16 + int8 weight
    tile + f32 out/scales) fits the VMEM budget; 0 = no fit."""
    for block in (1024, 512, 256, 128):
        if n % block:
            continue
        working_set = 2 * m * k + k * block + 4 * m * block + 4 * block
        if working_set <= _VMEM_BUDGET:
            return block
    return 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_matmul(x, q, s, interpret: bool = False):
    """``x (…, K) @ dequant(q (K, N), s (1, N)) → (…, N)`` in x.dtype.

    Uses the fused Pallas kernel on TPU when shapes tile cleanly (K a
    multiple of the int8 sublane tile 32, N of 128); otherwise the XLA
    fallback, which still stores int8 in HBM and fuses the convert into
    the matmul."""
    lead = x.shape[:-1]
    k, n = q.shape
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    block_n = _pick_block(m, k, n)
    on_tpu = jax.default_backend() == "tpu"
    # The kernel targets bandwidth-bound small-m (decode) matmuls; large-m
    # (prefill/training) shapes are compute-bound and XLA's own int8
    # convert+dot fusion handles them without VMEM pressure.
    if not (_PALLAS_TPU and (on_tpu or interpret)) or block_n == 0 \
            or k % 32 or m > 64 or (_INT8_FORCE_XLA and not interpret):
        out = jnp.dot(x2, q.astype(x.dtype),
                      preferred_element_type=jnp.float32) * s
        return out.astype(x.dtype).reshape(*lead, n)
    out = pl.pallas_call(
        _kernel,
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((m, k), lambda j: (0, 0)),
            pl.BlockSpec((k, block_n), lambda j: (0, j)),
            pl.BlockSpec((1, block_n), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((m, block_n), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=interpret,
    )(x2, q, s)
    return out.reshape(*lead, n)


def _int4_kernel_repeat(xe_ref, xo_ref, p_ref, s_ref, o_ref,
                        *, gs_half: int, compute_dtype):
    """Whole-tile fused int4 dequant-matmul: unpack the packed nibble
    tile in-register, expand the group scales along rows, scale to
    bf16, and run TWO full-K/2 MXU dots (even/odd original rows).
    Mosaic fuses the unpack/scale chain into the dot's operand stream,
    so neither the dequantized weights nor the f32 intermediates
    materialize in HBM — measured 2.6x faster than the grouped-unroll
    kernel at K=4096 decode shapes on v5e (scripts/int4_kernel_lab.py)
    and equal at K=14336."""
    low, high = _unpack_int4(p_ref[:])
    se = jnp.repeat(s_ref[:], gs_half, axis=0)
    # bf16 weights feed the MXU at full rate on TPU; interpret mode
    # (CPU tests) computes in f32 because the CPU dot thunk has no
    # bf16 x bf16 path.
    wl = (low.astype(jnp.float32) * se).astype(compute_dtype)
    wh = (high.astype(jnp.float32) * se).astype(compute_dtype)
    xe = xe_ref[:].astype(compute_dtype)
    xo = xo_ref[:].astype(compute_dtype)
    acc = (jnp.dot(xe, wl, preferred_element_type=jnp.float32)
           + jnp.dot(xo, wh, preferred_element_type=jnp.float32))
    o_ref[:] = acc.astype(o_ref.dtype)


#: khalf -> output-column blocks (preferred first), drawn from the tile
#: classes compiled and run on the v5e (scripts/int4_kernel_lab.py):
#: K=4096 (khalf 2048) ran at bn 128/256/512 — 256 measured fastest,
#: 512 validated but never preferred (any n divisible by 512 picks 256
#: first anyway) — and K=14336 (khalf 7168) at bn=128.  A bn=512 tile
#: at K=14336 failed server-side and wedged the relay; no other khalf
#: class has ever been compiled, so no other is dispatched on hardware.
_REPEAT_VALIDATED = {2048: (256, 128), 7168: (128,)}


def _pick_block_repeat(khalf: int, n: int, interpret: bool) -> int:
    """Output-column block for the repeat kernel.  On hardware the
    dispatch is restricted to the validated classes above (a failed
    Pallas compile wedges the axon relay); interpret mode runs no
    Mosaic compile, so tests may exercise any tileable shape."""
    if interpret:
        blocks = (256, 128) if khalf <= 2048 else (128,)
    else:
        blocks = _REPEAT_VALIDATED.get(khalf, ())
    for block in blocks:
        if n % block == 0:
            return block
    return 0


def _int4_kernel(xe_ref, xo_ref, p_ref, s_ref, o_ref, *, gs_half: int,
                 groups: int):
    """Grouped fused int4 dequant-matmul (fallback for shapes outside
    the repeat kernel's validated envelope): per scale group, unpack
    the packed nibble tile in-register, run two MXU dots (even/odd
    original rows), and apply the group's column scales into the f32
    accumulator.  The dequantized weights never exist in HBM."""
    m = xe_ref.shape[0]
    acc = jnp.zeros((m, o_ref.shape[1]), jnp.float32)
    # Static (unrolled) group loop: Mosaic has no dynamic_slice on
    # values, and `groups` is a trace-time constant anyway (≤ ~112).
    for g in range(groups):
        rows = slice(g * gs_half, (g + 1) * gs_half)
        low, high = _unpack_int4(p_ref[rows, :])
        xe_g = xe_ref[:, rows].astype(jnp.float32)
        xo_g = xo_ref[:, rows].astype(jnp.float32)
        part = (jnp.dot(xe_g, low.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
                + jnp.dot(xo_g, high.astype(jnp.float32),
                          preferred_element_type=jnp.float32))
        acc = acc + part * s_ref[g:g + 1, :]
    o_ref[:] = acc.astype(o_ref.dtype)


def _pick_block_int4(m: int, khalf: int, n: int, groups: int) -> int:
    """Largest output-column block fitting the VMEM budget: x halves
    (bf16, whole K), packed int8 tile, f32 scales, f32 accumulator plus
    per-group unpack temporaries (~3 int32/f32 copies of one group)."""
    for block in (1024, 512, 256, 128):
        if n % block:
            continue
        gs_half = khalf // groups
        working_set = (2 * 2 * m * khalf          # xe + xo bf16
                       + khalf * block            # packed int8 tile
                       + 4 * groups * block       # scales f32
                       + 4 * m * block            # accumulator
                       + 12 * gs_half * block)    # unpack temporaries
        if working_set <= _VMEM_BUDGET:
            return block
    return 0


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_matmul(x, q4, s, interpret: bool = False):
    """``x (…, K) @ dequant(q4 (K/2, N) packed, s (G, N)) → (…, N)``.

    Decode shapes (m ≤ 64) on TPU use the fused Pallas kernel — int4
    halves the HBM bytes per step vs int8, so the weight-streaming
    decode ceiling roughly doubles.  Other shapes take an XLA grouped
    einsum that never materializes the full dequantized matrix at rest
    (XLA fuses the unpack/scale into the contraction)."""
    lead = x.shape[:-1]
    khalf, n = q4.shape
    k = 2 * khalf
    groups = s.shape[0]
    gs_half = khalf // groups
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    on_tpu = jax.default_backend() == "tpu"
    pallas_ok = (_PALLAS_TPU and (on_tpu or interpret) and m <= 64
                 and not _INT4_FORCE_XLA)
    repeat_block = _pick_block_repeat(khalf, n, interpret) \
        if pallas_ok else 0
    unroll_block = _pick_block_int4(m, khalf, n, groups) \
        if pallas_ok else 0
    # gs_half alignment: validation used group_size=128 (gs_half 64);
    # 32-multiples share its int8 sublane tiling.
    if repeat_block and gs_half >= 32 and gs_half % 32 == 0:
        kernel = functools.partial(
            _int4_kernel_repeat, gs_half=gs_half,
            compute_dtype=jnp.float32 if interpret else jnp.bfloat16)
        block_n = repeat_block
    elif unroll_block and gs_half >= 32 and gs_half % 32 == 0:
        kernel = functools.partial(_int4_kernel, gs_half=gs_half,
                                   groups=groups)
        block_n = unroll_block
    else:
        low, high = _unpack_int4(q4)
        q = jnp.stack([low, high], axis=1).reshape(k, n)
        x3 = x2.astype(jnp.float32).reshape(m, groups, k // groups)
        w3 = q.reshape(groups, k // groups, n).astype(jnp.float32)
        out = jnp.einsum("mgk,gkn,gn->mn", x3, w3, s,
                         preferred_element_type=jnp.float32)
        return out.astype(x.dtype).reshape(*lead, n)
    xe = x2[:, 0::2]
    xo = x2[:, 1::2]
    out = pl.pallas_call(
        kernel,
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((m, khalf), lambda j: (0, 0)),
            pl.BlockSpec((m, khalf), lambda j: (0, 0)),
            pl.BlockSpec((khalf, block_n), lambda j: (0, j)),
            pl.BlockSpec((groups, block_n), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((m, block_n), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=interpret,
    )(xe, xo, q4, s)
    return out.reshape(*lead, n)


def quantize_tree(tree, bits: int = 8, group_size: int = 128):
    """Quantize every 2-D float leaf of a parameter pytree (norm vectors
    and anything 1-D stay as-is).  ``bits`` ∈ {8, 4}; int4 uses
    nibble-packed storage with per-group scales."""
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")

    def visit(leaf):
        if isinstance(leaf, jnp.ndarray) and leaf.ndim == 2 and \
                jnp.issubdtype(leaf.dtype, jnp.floating):
            if bits == 4:
                return quantize_int4(leaf, group_size)
            return quantize_int8(leaf)
        return leaf
    return jax.tree_util.tree_map(
        visit, tree, is_leaf=lambda x: isinstance(x, jnp.ndarray))

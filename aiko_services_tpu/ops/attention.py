"""Attention ops: Pallas flash-attention kernel for TPU with a reference
jnp fallback.

The reference framework has no attention code at all (SURVEY.md §5.7) —
its LLM examples call Ollama over HTTP.  Here attention is a first-class
op: the kernel implements online-softmax flash attention (one pass over
K/V blocks, f32 running max/denominator in VMEM scratch, bf16-friendly
inputs) tiled for the MXU; the fallback is a numerically-identical jnp
implementation used on CPU and for testing (the kernel itself is also
testable on CPU via ``interpret=True``).

Layout: ``(batch, heads, seq, head_dim)``; ``head_dim`` ≤ 128 rides the
lane dimension, query blocks ride sublanes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend only exists on TPU-enabled jaxlibs
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_TPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _PALLAS_TPU = False

__all__ = ["flash_attention", "attention_reference", "NEG_INF"]

NEG_INF = -1e30
# NEG_INF must stay FINITE (never -inf): with sliding-window masking a
# q-row can be fully masked inside the first LIVE k-block, making every
# score NEG_INF → m_new == NEG_INF and p == exp(0) == 1 of bogus mass.
# That mass is cancelled later only because the row's diagonal block is
# guaranteed live and its rescale correction exp(NEG_INF - m_real)
# underflows to exactly 0.0.  With -inf the same update computes
# exp(-inf - (-inf)) = NaN.  (See the online-softmax update in
# _flash_kernel.)
assert NEG_INF < 0 and NEG_INF > float("-inf")

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def attention_reference(q, k, v, causal: bool = True,
                        sm_scale: Optional[float] = None,
                        window: Optional[int] = None):
    """Plain jnp attention (the numerics oracle and CPU path).

    ``window`` (requires ``causal``): each query attends to at most the
    ``window`` most recent positions including itself (Mistral-style
    sliding-window attention)."""
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        q_ids = jnp.arange(q_len)[:, None] + (k_len - q_len)
        k_ids = jnp.arange(k_len)[None, :]
        visible = k_ids <= q_ids
        if window is not None:
            visible &= k_ids > q_ids - window
        logits = jnp.where(visible, logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd",
                      weights.astype(v.dtype), v).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref,
                  m_scratch, l_scratch, acc_scratch,
                  *, sm_scale: float, causal: bool,
                  block_q: int, block_k: int, k_len: int, q_len: int,
                  window: Optional[int]):
    """Grid: (batch*heads, q_blocks, k_blocks); k fastest-varying.

    Scratch carries the online-softmax state (running max ``m``, sum
    ``l``, accumulator ``acc``) across the k-block sweep for one q block.
    """
    k_idx = pl.program_id(2)
    num_k = pl.num_programs(2)
    # program_id must be read at kernel top level (not inside pl.when's
    # traced cond body).
    q_block_start = pl.program_id(1) * block_q

    @pl.when(k_idx == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    if causal:
        # Causal block skipping: a k block strictly above the diagonal
        # (its first key id > this q block's last query id) contributes
        # nothing — skip its MXU work entirely.  Paired with the clamped
        # K/V index maps in flash_attention, the skipped steps also
        # trigger no new HBM->VMEM copies, so causal prefill does ~half
        # the work of the full grid sweep.
        q_last = q_block_start + block_q - 1 + (k_len - q_len)
        block_live = k_idx * block_k <= q_last
        if window is not None:
            # Sliding window: a k block entirely BELOW the window of
            # this q block's first query contributes nothing either —
            # long-context prefill cost becomes O(seq * window).
            q_first = q_block_start + (k_len - q_len)
            block_live &= (k_idx + 1) * block_k - 1 > q_first - window
    else:
        block_live = True

    @pl.when(block_live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # (block_q, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)          # (block_k, d)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # (bq, bk)

        if causal:
            q_ids = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) \
                + q_block_start + (k_len - q_len)
            k_ids = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + k_idx * block_k
            visible = k_ids <= q_ids
            if window is not None:
                visible &= k_ids > q_ids - window
            s = jnp.where(visible, s, NEG_INF)

        m_prev = m_scratch[:]                      # (bq, 1)
        # Fully-masked rows rely on NEG_INF being finite: s == NEG_INF
        # everywhere gives p == 1 (bogus mass), later cancelled by the
        # diagonal block's correction underflowing to exactly 0 — see
        # the NEG_INF module comment before "simplifying" to -inf.
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                     # (bq, bk)
        correction = jnp.exp(m_prev - m_new)       # (bq, 1)
        l_new = correction * l_scratch[:] + \
            jnp.sum(p, axis=-1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scratch[:] = m_new
        l_scratch[:] = l_new

    @pl.when(k_idx == num_k - 1)
    def _finish():
        denom = jnp.where(l_scratch[:] == 0.0, 1.0, l_scratch[:])
        o_ref[0] = (acc_scratch[:] / denom).astype(o_ref.dtype)


def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False,
                    window: Optional[int] = None):
    """Flash attention; dispatches to the Pallas kernel on TPU (or in
    interpret mode), else the jnp reference.

    Grouped-query attention is native: ``k``/``v`` may carry fewer heads
    than ``q`` (``heads % kv_heads == 0``) — query-head grid steps index
    the shared K/V head via the BlockSpec index map, so the repeated K/V
    never exists in memory (repeating would multiply HBM traffic by the
    group size)."""
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5

    batch, heads, q_len, head_dim = q.shape
    kv_heads, k_len = k.shape[1], k.shape[2]
    assert heads % kv_heads == 0, (heads, kv_heads)
    group = heads // kv_heads

    def fallback():
        k_full = jnp.repeat(k, group, axis=1) if group > 1 else k
        v_full = jnp.repeat(v, group, axis=1) if group > 1 else v
        return attention_reference(q, k_full, v_full, causal=causal,
                                   sm_scale=sm_scale, window=window)

    on_tpu = jax.default_backend() == "tpu"
    if not (_PALLAS_TPU and (on_tpu or interpret)):
        return fallback()
    block_q = min(block_q, q_len)
    block_k = min(block_k, k_len)
    if q_len % block_q or k_len % block_k:
        return fallback()
    if causal and q_len > k_len:
        # Rows with no visible keys make the block-skip index map go
        # negative; the jnp reference defines the semantics here.
        return fallback()

    bh = batch * heads
    q3 = q.reshape(bh, q_len, head_dim)
    k3 = k.reshape(batch * kv_heads, k_len, head_dim)
    v3 = v.reshape(batch * kv_heads, k_len, head_dim)

    grid = (bh, q_len // block_q, k_len // block_k)
    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, k_len=k_len, q_len=q_len,
        window=window)

    if causal:
        # Clamp the k index for blocks outside the live band: the
        # kernel skips their compute (pl.when), and an unchanged block
        # index means Pallas re-uses the already-resident VMEM tile
        # instead of issuing a fresh HBM copy.  With a sliding window
        # the band is two-sided (diagonal above, window edge below).
        def kv_index(b, i, j):
            q_first = i * block_q + (k_len - q_len)
            last_live = (q_first + block_q - 1) // block_k
            j_clamped = jnp.minimum(j, last_live)
            if window is not None:
                first_live = jnp.maximum(
                    q_first - window + 1, 0) // block_k
                j_clamped = jnp.maximum(j_clamped, first_live)
            return (b // group, j_clamped, 0)
    else:
        def kv_index(b, i, j):
            return (b // group, j, 0)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim),
                         lambda b, i, j: (b, i, 0)),
            # Query-head b uses shared K/V head b // group.
            pl.BlockSpec((1, block_k, head_dim), kv_index),
            pl.BlockSpec((1, block_k, head_dim), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim),
                               lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, q_len, head_dim), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(batch, heads, q_len, head_dim)

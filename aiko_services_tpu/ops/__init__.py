from .attention import flash_attention, attention_reference
from .paged_attention import (paged_decode_attention,
                              paged_decode_reference,
                              cached_gqa_attention,
                              decode_attention_path,
                              decode_kernel_mode,
                              contiguous_block_size)

from .attention import flash_attention, attention_reference
from .paged_attention import (paged_decode_attention,
                              paged_decode_reference,
                              cached_gqa_attention,
                              decode_attention_path,
                              decode_kernel_mode,
                              contiguous_block_size)
from .paged_prefill import (paged_prefill_attention,
                            paged_prefill_reference,
                            prefill_attention_path,
                            prefill_kernel_mode)

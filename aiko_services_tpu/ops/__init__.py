from .attention import flash_attention, attention_reference

from .mesh import (MeshSpec, make_mesh, named_sharding,
                   logical_axis_rules, filter_specs_for_mesh)
from .ring_attention import ring_attention, ring_attention_sharded
from .ulysses import ulysses_attention, ulysses_attention_sharded
from .collective_matmul import (
    allgather_matmul, matmul_reducescatter,
    allgather_matmul_sharded, matmul_reducescatter_sharded,
)
from .pipeline_parallel import (
    pipeline_apply, pipeline_apply_sharded, stack_stages,
)
from .checkpoint import (TrainCheckpointer, StreamCheckpoint,
                         save_stream_checkpoint, load_stream_checkpoint)
from .elastic import ElasticTrainer
from .distributed import (MultiHostConfig, initialize_multihost,
                          hybrid_mesh, CoordinatorAnnouncer,
                          discover_coordinator, worker_env)

from .mesh import MeshSpec, make_mesh, named_sharding, logical_axis_rules
from .ring_attention import ring_attention, ring_attention_sharded

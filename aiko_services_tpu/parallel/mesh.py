"""Device meshes and sharding policy.

The TPU build's answer to the reference's process-fleet scaling
(SURVEY.md §2.6): instead of NCCL/MPI-style point-to-point plumbing, a
``jax.sharding.Mesh`` over the chip topology with named axes, and
``NamedSharding`` annotations that let XLA insert ICI collectives.

Axis conventions (the "How to Scale Your Model" recipe):

* ``dp``    — data parallel (batch dimension)
* ``tp``    — tensor parallel (hidden / heads dimension)
* ``sp``    — sequence/context parallel (ring attention over this axis)
* ``pp``    — pipeline-parallel stage axis (inter-stage hand-off)

``make_mesh`` builds a mesh from whatever devices exist (real TPU chips,
or the 8 virtual CPU devices used in tests via
``--xla_force_host_platform_device_count=8``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "MeshSpec", "make_mesh", "named_sharding", "shard_batch_spec",
    "logical_axis_rules", "filter_specs_for_mesh", "DEFAULT_AXES",
    "ReplicaMesh",
]

DEFAULT_AXES = ("dp", "tp")

P = PartitionSpec


class MeshSpec:
    """Declarative mesh shape: ``MeshSpec(dp=2, tp=4)``.

    ``-1`` for one axis means "all remaining devices".
    """

    def __init__(self, **axes: int):
        if not axes:
            axes = {"dp": -1}
        self.axes: Dict[str, int] = dict(axes)

    def resolve(self, device_count: int) -> Dict[str, int]:
        sizes = dict(self.axes)
        wildcard = [k for k, v in sizes.items() if v == -1]
        if len(wildcard) > 1:
            raise ValueError("Only one mesh axis may be -1")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wildcard:
            if device_count % fixed:
                raise ValueError(
                    f"{device_count} devices not divisible by {fixed}")
            sizes[wildcard[0]] = device_count // fixed
        elif fixed != device_count:
            raise ValueError(
                f"Mesh {sizes} needs {fixed} devices, have {device_count}")
        return sizes

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        sizes = self.resolve(len(devices))
        shape = tuple(sizes.values())
        array = np.asarray(devices).reshape(shape)
        return Mesh(array, tuple(sizes.keys()))


def make_mesh(devices: Optional[Sequence] = None, **axes: int) -> Mesh:
    return MeshSpec(**axes).build(devices)


@dataclasses.dataclass(frozen=True)
class ReplicaMesh:
    """One serving replica's device mesh: ``tp`` chips on the tensor
    axis, optionally × a SECOND axis (``sp`` sequence-parallel OR
    ``ep`` expert-parallel).  The serving tier's unit of capacity
    changes from "one chip" to "one mesh" — the paged KV pool shards
    along the kv-head dimension over ``axis`` (and REPLICATES over the
    second axis), model weights shard on their output feature axis,
    and the per-slot decode state stays replicated so the host-side
    admission/commit protocol is mesh-agnostic.

    Second-axis roles:

    * ``sp`` — sequence-parallel chunked prefill: one admission
      dispatch carries ``sp`` prompt chunks, each shard prefills its
      own chunk and all-gathers the window's K/V so every pool copy
      stays identical.  Decode runs replicated over ``sp`` (prefill
      TTFT is what the axis buys).
    * ``ep`` — expert-parallel MoE: the 3-D expert weights shard
      ``P(ep, None, tp)`` and every collective stays an all-gather, so
      MoE serving is exact (the old blanket MoE rejection is gone).

    A speculative DRAFT model rides the same mesh fully REPLICATED
    (params + its contiguous cache): draft passes run collective-free
    on every chip, identical by construction, and only the target's
    verify/decode programs shard — so TP spec serving stays bitwise
    equal to single-chip (ARCHITECTURE invariants 9 + 11 + 19).

    ``tp=1`` (and ``sp=ep=1``) degenerates to the single-chip layout.
    ``overlap=True`` opts the MLP down-projection into the
    :mod:`..parallel.collective_matmul` reduce-scatter layout — a
    LOSSY-layout bandwidth trade (partial-sum float order differs from
    single-chip), bench-only, off by default.
    """

    tp: int = 1
    axis: str = "tp"
    sp: int = 1
    ep: int = 1
    sp_axis: str = "sp"
    ep_axis: str = "ep"
    overlap: bool = False

    @property
    def size(self) -> int:
        return self.tp * self.sp * self.ep

    @property
    def second_axis(self) -> Optional[str]:
        """Name of the active second axis, or None for a 1-D mesh."""
        if self.sp > 1:
            return self.sp_axis
        if self.ep > 1:
            return self.ep_axis
        return None

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None
                       else jax.devices())
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if self.sp < 1 or self.ep < 1:
            raise ValueError(
                f"sp/ep must be >= 1, got sp={self.sp} ep={self.ep}")
        if self.sp > 1 and self.ep > 1:
            raise ValueError(
                "ReplicaMesh is at most 2-D: pick ONE second axis "
                f"(got sp={self.sp} AND ep={self.ep})")
        need = self.size
        if len(devices) < need:
            raise ValueError(
                f"ReplicaMesh(tp={self.tp}, sp={self.sp}, "
                f"ep={self.ep}) needs {need} devices, "
                f"have {len(devices)} (tests: set XLA_FLAGS="
                "--xla_force_host_platform_device_count=8)")
        second = self.second_axis
        if second is None:
            return Mesh(np.asarray(devices[: self.tp]), (self.axis,))
        n2 = self.sp if self.sp > 1 else self.ep
        array = np.asarray(devices[:need]).reshape(self.tp, n2)
        return Mesh(array, (self.axis, second))

    def validate(self, config) -> None:
        """Fail fast on layouts the TP engine cannot shard exactly.

        Every tensor-sharded dimension must divide by ``tp``: kv heads
        (the paged pool + attention grid), query heads (contiguous
        q-head ranges must cover whole kv-head groups), d_model / d_ff
        / vocab (output-axis weight sharding).  MoE configs shard
        their expert weights over the second (``ep``) axis — so
        ``n_experts`` must divide by ``ep`` — and their per-expert
        feature dims fall under the same ``tp`` rule."""
        if self.sp > 1 and self.ep > 1:
            raise ValueError(
                "ReplicaMesh is at most 2-D: pick ONE second axis "
                f"(got sp={self.sp} AND ep={self.ep})")
        n_experts = getattr(config, "n_experts", 0)
        if n_experts and n_experts % self.ep:
            raise ValueError(
                f"ReplicaMesh(ep={self.ep}): config.n_experts="
                f"{n_experts} is not divisible by the 'ep' axis size "
                f"{self.ep} (MoE expert weights shard over the "
                "second, expert-parallel mesh axis)")
        if self.ep > 1 and not n_experts:
            raise ValueError(
                f"ReplicaMesh(ep={self.ep}): the 'ep' axis shards MoE "
                "expert weights, but config.n_experts=0 (dense "
                "config) — use sp for a dense second axis")
        for name in ("n_kv_heads", "n_heads", "d_model", "d_ff",
                     "vocab_size"):
            value = getattr(config, name)
            if value % self.tp:
                raise ValueError(
                    f"ReplicaMesh(tp={self.tp}): config.{name}="
                    f"{value} is not divisible by the 'tp' axis size "
                    f"{self.tp}")


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def shard_batch_spec(mesh: Mesh) -> PartitionSpec:
    """Batch sharded over dp (and sp if present merges into batch rows)."""
    return P("dp") if "dp" in mesh.axis_names else P()


#: Logical-axis → mesh-axis rules for model parameter shardings
#: (flax-linen style but framework-agnostic).
def logical_axis_rules(mesh: Mesh) -> Dict[str, Optional[str]]:
    names = mesh.axis_names
    return {
        "batch": "dp" if "dp" in names else None,
        "seq": "sp" if "sp" in names else None,
        "heads": "tp" if "tp" in names else None,
        "kv_heads": "tp" if "tp" in names else None,
        "embed": None,
        "mlp": "tp" if "tp" in names else None,
        "vocab": "tp" if "tp" in names else None,
        "stage": "pp" if "pp" in names else None,
    }


def filter_specs_for_mesh(specs, mesh: Mesh):
    """Drop spec axes the mesh does not have (e.g. megatron "tp" specs
    on a dp-only mesh become replicated on that dim) — the same param
    layout tree then serves every topology."""
    names = set(mesh.axis_names)

    def fix(spec):
        if not isinstance(spec, PartitionSpec):
            return spec
        return PartitionSpec(*(axis if axis in names else None
                               for axis in spec))

    return jax.tree.map(
        fix, specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec))


def mark_varying(x, axis_name):
    """shard_map varying-axis tracking: loop carries that pass through
    ``ppermute`` become axis-varying, so zero-inits must be marked
    varying too.  Single home for the jax version dispatch."""
    import jax
    if hasattr(jax.lax, "pcast"):          # jax >= 0.8
        return jax.lax.pcast(x, axis_name, to="varying")
    if hasattr(jax.lax, "pvary"):          # deprecated predecessor
        return jax.lax.pvary(x, axis_name)
    return x

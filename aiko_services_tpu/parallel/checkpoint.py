"""Checkpoint / resume for model and training state.

The reference framework has **no checkpointing** (SURVEY.md §5.4: the
closest artifacts are the sqlite ``Storage`` actor skeleton at
``main/storage.py:49-63`` and the ``Frame`` continuation at
``main/stream.py:66-71``).  A TPU training/serving framework needs real
checkpointing, so this subsystem is designed fresh:

* orbax-backed, async-capable saves of arbitrary pytrees (params,
  optimizer state, step counters, RNG keys);
* **sharding-aware restore**: state saved from one mesh topology can be
  restored onto a *different* mesh (e.g. save on dp=2×tp=4, resume on
  dp=4×tp=2) — orbax reads each array's saved global shape and lays it
  out according to the target ``NamedSharding``, so resume after an
  elastic topology change is a first-class operation;
* retention policy (``max_to_keep``) and step bookkeeping via
  ``orbax.CheckpointManager``;
* a host-side ``StreamCheckpoint`` record for the pipeline engine: the
  reference's ``Frame`` is already "an explicit continuation able to
  resume mid-graph" — we make that durable by snapshotting stream
  parameters + swag (non-array entries) alongside the device state.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Mapping, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

__all__ = [
    "TrainCheckpointer",
    "StreamCheckpoint",
    "save_stream_checkpoint",
    "load_stream_checkpoint",
]


def _abstract_like(tree, mesh: Optional[Mesh], specs):
    """Build a pytree of ShapeDtypeStructs carrying target shardings."""

    def leaf(x, spec):
        sharding = None
        if mesh is not None and spec is not None:
            sharding = NamedSharding(mesh, spec)
        shape = getattr(x, "shape", ())
        dtype = getattr(x, "dtype", None) or np.asarray(x).dtype
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    if specs is None:
        return jax.tree.map(lambda x: leaf(x, None), tree)
    # PartitionSpec is a pytree leaf, so a specs tree mirroring ``tree``'s
    # structure (dicts, lists, optax NamedTuples alike) maps one-to-one.
    return jax.tree.map(leaf, tree, specs)


class TrainCheckpointer:
    """Save/restore training state with step management.

    Wraps ``orbax.checkpoint.CheckpointManager``.  State is a dict of
    named pytrees, e.g. ``{"params": ..., "opt_state": ...}``; metadata
    (pure-Python scalars) rides along as JSON.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = False):
        """``async_save=True`` overlaps checkpoint serialization with
        the training step that follows: ``save()`` snapshots device
        arrays then returns while orbax writes in a background thread
        (the standard TPU pattern — the next step's compute hides the
        host IO).  Call :meth:`wait` (or ``save``/``close``, which
        barrier implicitly) before reading the files."""
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._async = bool(async_save)
        self._directory = os.path.abspath(directory)
        os.makedirs(self._directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=self._async)
        self._manager = ocp.CheckpointManager(self._directory, options=options)

    # -- save ---------------------------------------------------------

    _RESERVED = frozenset({"metadata", "step"})

    def save(self, step: int, state: Mapping[str, Any],
             metadata: Optional[Mapping[str, Any]] = None) -> bool:
        ocp = self._ocp
        bad = self._RESERVED & set(state)
        if bad:
            raise ValueError(f"state names {sorted(bad)} are reserved")
        items = {name: ocp.args.StandardSave(tree)
                 for name, tree in state.items()}
        if metadata is not None:
            items["metadata"] = ocp.args.JsonSave(dict(metadata))
        saved = self._manager.save(step, args=ocp.args.Composite(**items))
        if not self._async:
            self._manager.wait_until_finished()
        return saved

    def wait(self):
        """Barrier for async saves: returns when every pending
        checkpoint write has committed."""
        self._manager.wait_until_finished()

    # -- restore ------------------------------------------------------

    def restore(self, templates: Mapping[str, Any], *,
                step: Optional[int] = None,
                mesh: Optional[Mesh] = None,
                specs: Optional[Mapping[str, Any]] = None):
        """Restore state at ``step`` (default: latest).

        ``templates`` gives a pytree per state name matching the saved
        structure (shapes/dtypes; values are ignored).  When ``mesh``
        and per-name partition ``specs`` are given, arrays are restored
        directly into that sharding — this is how a checkpoint saved on
        one topology resumes on another.
        """
        ocp = self._ocp
        bad = self._RESERVED & set(templates)
        if bad:
            raise ValueError(f"state names {sorted(bad)} are reserved")
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self._directory}")
        items = {}
        for name, tree in templates.items():
            spec_tree = None if specs is None else specs.get(name)
            abstract = _abstract_like(tree, mesh, spec_tree)
            items[name] = ocp.args.StandardRestore(abstract)
        items["metadata"] = ocp.args.JsonRestore()
        try:
            restored = self._manager.restore(
                step, args=ocp.args.Composite(**items))
        except (FileNotFoundError, KeyError):
            items.pop("metadata")
            restored = self._manager.restore(
                step, args=ocp.args.Composite(**items))
        out = {name: restored[name] for name in templates}
        out["metadata"] = restored.get("metadata") if hasattr(
            restored, "get") else None
        out["step"] = step
        return out

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def all_steps(self):
        return sorted(self._manager.all_steps())

    def close(self):
        self._manager.close()


# ---------------------------------------------------------------------------
# Host-side pipeline stream checkpoints
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class StreamCheckpoint:
    """Durable snapshot of a pipeline stream's host-side continuation.

    Mirrors the reference's ``Stream``/``Frame`` continuation fields
    (``main/stream.py:65-109``): enough to re-create the stream and
    resume frame numbering after a process restart.
    """
    stream_id: str
    frame_id: int
    graph_path: Optional[str]
    parameters: dict
    variables: dict
    swag: dict  # JSON-serializable swag entries only

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, text: str) -> "StreamCheckpoint":
        return cls(**json.loads(text))


def _json_safe(mapping: Mapping[str, Any]) -> dict:
    out = {}
    for key, value in mapping.items():
        try:
            json.dumps(value)
        except (TypeError, ValueError):
            continue
        out[key] = value
    return out


def save_stream_checkpoint(directory: str, stream,
                           swag: Optional[Mapping[str, Any]] = None) -> str:
    """Snapshot ``stream`` (a pipeline ``Stream``) to ``directory``.

    Array-valued swag entries belong in the model checkpoint (they are
    device state); only JSON-representable entries are kept here.
    """
    os.makedirs(directory, exist_ok=True)
    record = StreamCheckpoint(
        stream_id=str(stream.stream_id),
        frame_id=int(stream.frame_id),
        graph_path=getattr(stream, "graph_path", None),
        parameters=_json_safe(getattr(stream, "parameters", {}) or {}),
        variables=_json_safe(getattr(stream, "variables", {}) or {}),
        swag=_json_safe(swag or {}))
    path = os.path.join(directory, f"stream_{record.stream_id}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(record.to_json())
    os.replace(tmp, path)
    return path


def load_stream_checkpoint(directory: str,
                           stream_id: str) -> StreamCheckpoint:
    path = os.path.join(directory, f"stream_{stream_id}.json")
    with open(path) as fh:
        return StreamCheckpoint.from_json(fh.read())

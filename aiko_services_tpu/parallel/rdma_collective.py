"""Raw-RDMA ring collective matmuls (Pallas kernels).

The shard_map+ppermute collective matmuls
(:mod:`.collective_matmul`) let XLA schedule the overlap; these
kernels own it explicitly with inter-chip RDMA
(``pltpu.make_async_remote_copy``): per ring step, the MXU multiplies
the activation block a device already holds while the DMA engines move
the next block to its right neighbor — transfer strictly behind
compute, no collective op in the XLA graph at all (SURVEY.md §7.2
step 8's "raw RDMA" north star).

Correctness protocol (the part ppermute gave us for free):

- **Double-buffered comm slots** ``(2, m_local, k)``: step ``s``
  computes from ``slot = s % 2`` while the RDMA receives the next
  block into ``1 - slot``.
- **Capacity handshake** (REGULAR semaphore): a sender may overwrite a
  receiver slot only after the receiver signalled it free — without
  it, a fast left neighbor racing one step ahead corrupts the block a
  slow device is still multiplying (a real hazard of raw RDMA; the
  kernel would be wrong on hardware even though interpret mode's
  sequential execution can't exhibit it).
- **Start barrier** (``pltpu.get_barrier_semaphore``): ring neighbors
  must not start signalling before everyone entered the kernel.

Validation: interpret mode on the virtual CPU mesh, exact against the
dense oracle and the ppermute twins (``tests/test_rdma_collective.py``).
Hardware dispatch stays GATED (``interpret=False`` requires a real
multi-chip TPU backend) — single-chip axon cannot exercise inter-chip
DMA, and an unvalidated Mosaic compile wedges the relay.
"""

from __future__ import annotations

import functools
import inspect

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

if "check_vma" not in inspect.signature(shard_map).parameters:
    # Older jax spells the replication check ``check_rep``.  Accept the
    # new-style kwarg everywhere in this module and translate.
    _shard_map_new = shard_map

    def shard_map(*args, check_vma=None, **kwargs):  # noqa: F811
        if check_vma is not None and "check_rep" in inspect.signature(
                _shard_map_new).parameters:
            kwargs.setdefault("check_rep", check_vma)
        return _shard_map_new(*args, **kwargs)

from .collective_matmul import _axis_size

# Renamed TPUCompilerParams -> CompilerParams in newer pallas.
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

__all__ = ["rdma_allgather_matmul", "rdma_matmul_reducescatter",
           "rdma_allgather_matmul_sharded",
           "rdma_matmul_reducescatter_sharded"]

#: Distinct collective_ids per kernel family (barrier semaphores are
#: keyed by these; sharing one id across different kernels deadlocks).
_AG_COLLECTIVE_ID = 11
_RS_COLLECTIVE_ID = 12


def _neighbors(axis_name):
    my_id = jax.lax.axis_index(axis_name)
    num = _axis_size(axis_name)
    right = jax.lax.rem(my_id + 1, num)
    left = jax.lax.rem(my_id + num - 1, num)
    return my_id, num, right, left


def _ring_barrier(left, right):
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)


def _ag_kernel(x_ref, w_ref, out_ref, comm_ref, local_sem, send_sem,
               recv_sem, capacity_sem, *, axis_name, m_local,
               interpret):
    # `interpret` gates the REMOTE-SEMAPHORE protocol (start barrier +
    # capacity handshake) at trace time: interpret mode implements
    # remote DMA but not remote signals (NotImplementedError), and its
    # sequential execution cannot exhibit the overwrite race the
    # handshake prevents.  Hardware dispatch traces the full protocol.
    my_id, num, right, left = _neighbors(axis_name)
    if not interpret:
        _ring_barrier(left, right)

    # Stage the local shard into comm slot 0 (plain local DMA).
    staged = pltpu.make_async_copy(x_ref, comm_ref.at[0], local_sem)
    staged.start()
    staged.wait()
    if not interpret:
        # Slot 1 is free for the left neighbor's first incoming block.
        pltpu.semaphore_signal(
            capacity_sem, inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL)

    def body(step, _):
        slot = jax.lax.rem(step, 2)
        next_slot = jax.lax.rem(step + 1, 2)
        # The block held at `step` originated on (my_id - step) mod
        # num: its product lands at that row offset.
        src = jax.lax.rem(my_id - step + num, num)

        @pl.when(step < num - 1)
        def _send():
            # Right neighbor must have freed the slot we are about to
            # overwrite (capacity handshake).
            if not interpret:
                pltpu.semaphore_wait(capacity_sem, 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_ref.at[slot],
                dst_ref=comm_ref.at[next_slot],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[next_slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()

        # MXU work overlaps the in-flight DMAs.
        product = jnp.dot(comm_ref[slot], w_ref[:],
                          preferred_element_type=jnp.float32)
        out_ref[pl.ds(src * m_local, m_local), :] = \
            product.astype(out_ref.dtype)

        @pl.when(step < num - 1)
        def _settle():
            # Send done (slot reusable) + our own receive arrived.
            pltpu.make_async_copy(comm_ref.at[slot], comm_ref.at[slot],
                                  send_sem.at[slot]).wait()
            pltpu.make_async_copy(comm_ref.at[next_slot],
                                  comm_ref.at[next_slot],
                                  recv_sem.at[next_slot]).wait()
            if not interpret:
                # The slot we just computed from is now free for the
                # left neighbor's NEXT write.
                pltpu.semaphore_signal(
                    capacity_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    jax.lax.fori_loop(0, num, body, 0)
    if not interpret:
        # Drain the one unconsumed credit (num signals received,
        # num-1 waited): a REGULAR semaphore must leave the kernel at
        # zero or the next invocation starts with a stale +1 — which
        # would let a fast left neighbor skip one handshake.
        pltpu.semaphore_wait(capacity_sem, 1)


def rdma_allgather_matmul(x_shard, w_shard, axis_name: str,
                          interpret: bool = True):
    """``allgather(x, axis) @ w_shard`` — shard_map-body twin of
    ``collective_matmul.allgather_matmul``, transfer via raw RDMA.
    x_shard ``(m_local, k)``, w_shard ``(k, n_local)`` →
    ``(m_local * axis_size, n_local)``."""
    m_local, k = x_shard.shape
    n_local = w_shard.shape[1]
    size = _axis_size(axis_name)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, m_local, k), x_shard.dtype),  # comm slots
            pltpu.SemaphoreType.DMA(()),                 # local stage
            pltpu.SemaphoreType.DMA((2,)),               # send per slot
            pltpu.SemaphoreType.DMA((2,)),               # recv per slot
            pltpu.SemaphoreType.REGULAR,                 # capacity
        ],
    )
    return pl.pallas_call(
        functools.partial(_ag_kernel, axis_name=axis_name,
                          m_local=m_local, interpret=interpret),
        out_shape=jax.ShapeDtypeStruct((m_local * size, n_local),
                                       x_shard.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=_CompilerParams(
            collective_id=_AG_COLLECTIVE_ID),
    )(x_shard, w_shard)


def _rs_kernel(x_ref, w_ref, out_ref, acc_ref, partial_ref, local_sem,
               send_sem, recv_sem, capacity_sem, *, axis_name, n_local,
               interpret):
    my_id, num, right, left = _neighbors(axis_name)
    if not interpret:          # see _ag_kernel on the interpret gate
        _ring_barrier(left, right)
        pltpu.semaphore_signal(
            capacity_sem, inc=1, device_id=left,
            device_id_type=pltpu.DeviceIdType.LOGICAL)

    def partial_for(owner):
        w_slice = w_ref[:, pl.dslice(owner * n_local, n_local)]
        return jnp.dot(x_ref[:], w_slice,
                       preferred_element_type=jnp.float32)

    def body(step, _):
        slot = jax.lax.rem(step, 2)
        next_slot = jax.lax.rem(step + 1, 2)
        # The accumulator we hold is travelling to device
        # (my_id + num-1-step) mod num — add our partial for it.
        owner = jax.lax.rem(my_id + num - 1 - step, num)

        @pl.when(step == 0)
        def _init():
            acc_ref[0] = partial_for(owner).astype(acc_ref.dtype)

        @pl.when(step > 0)
        def _accumulate():
            # The matmul for this step was precomputed while the
            # accumulator was in flight — only a cheap add here.
            acc_ref[slot] = acc_ref[slot] + partial_ref[:]

        @pl.when(step < num - 1)
        def _forward():
            if not interpret:
                pltpu.semaphore_wait(capacity_sem, 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=acc_ref.at[slot],
                dst_ref=acc_ref.at[next_slot],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[next_slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL)
            rdma.start()
            # Overlap: while the accumulator flies, compute the
            # partial the INCOMING accumulator will need (next step's
            # owner = this owner - 1 mod num).  partial_ref and the
            # in-flight acc slots are distinct buffers, so this is
            # race-free.
            next_owner = jax.lax.rem(owner + num - 1, num)
            partial_ref[:] = partial_for(next_owner)
            rdma.wait()
            if not interpret:
                pltpu.semaphore_signal(
                    capacity_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    jax.lax.fori_loop(0, num, body, 0)
    if not interpret:
        pltpu.semaphore_wait(capacity_sem, 1)   # drain (see _ag_kernel)
    final_slot = jax.lax.rem(num - 1, 2)
    out_ref[:] = acc_ref[final_slot].astype(out_ref.dtype)


def rdma_matmul_reducescatter(x_shard, w_shard, axis_name: str,
                              interpret: bool = True):
    """``reduce_scatter(x_shard @ w_shard, axis)`` — twin of
    ``collective_matmul.matmul_reducescatter`` over raw RDMA.
    x_shard ``(m, k_local)``, w_shard ``(k_local, n)`` →
    ``(m, n // axis_size)``."""
    m = x_shard.shape[0]
    n = w_shard.shape[1]
    size = _axis_size(axis_name)
    n_local = n // size
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, m, n_local), jnp.float32),    # acc slots
            pltpu.VMEM((m, n_local), jnp.float32),       # next partial
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
        ],
    )
    return pl.pallas_call(
        functools.partial(_rs_kernel, axis_name=axis_name,
                          n_local=n_local, interpret=interpret),
        out_shape=jax.ShapeDtypeStruct((m, n_local), x_shard.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=_CompilerParams(
            collective_id=_RS_COLLECTIVE_ID),
    )(x_shard, w_shard)


def _require_multichip_tpu():
    if jax.default_backend() not in ("tpu",) or len(jax.devices()) < 2:
        raise RuntimeError(
            "interpret=False needs a real multi-chip TPU backend; "
            "inter-chip RDMA cannot run on a single chip or CPU "
            "(keep interpret=True there)")


@functools.partial(jax.jit,
                   static_argnames=("mesh", "axis", "interpret"))
def rdma_allgather_matmul_sharded(x, w, mesh: Mesh, axis: str = "tp",
                                  interpret: bool = True):
    """Host-level wrapper matching
    ``collective_matmul.allgather_matmul_sharded``."""
    if not interpret:
        _require_multichip_tpu()
    return shard_map(
        functools.partial(rdma_allgather_matmul, axis_name=axis,
                          interpret=interpret),
        mesh=mesh,
        in_specs=(P(axis, None), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
    )(x, w)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "axis", "interpret"))
def rdma_matmul_reducescatter_sharded(x, w, mesh: Mesh,
                                      axis: str = "tp",
                                      interpret: bool = True):
    """Host-level wrapper matching
    ``collective_matmul.matmul_reducescatter_sharded``."""
    if not interpret:
        _require_multichip_tpu()
    return shard_map(
        functools.partial(rdma_matmul_reducescatter, axis_name=axis,
                          interpret=interpret),
        mesh=mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(None, axis),
        check_vma=False,
    )(x, w)

"""Ring attention: exact attention over sequences sharded across a mesh
axis (context parallelism).

The reference has no long-context machinery (SURVEY.md §5.7); this is the
TPU-native design: Q/K/V are sharded over the ``sp`` mesh axis on their
sequence dimension; each device computes blockwise attention against the
K/V shard it currently holds while rotating K/V shards around the ring
with ``ppermute`` (ICI neighbor exchange), merging partial results with
the online-softmax recurrence — so memory per device stays O(seq/n) and
the full-sequence result is exact (Liu et al. ring attention, via
blockwise attention numerics).

Causality is handled with *global* position ids so the mask is correct
regardless of which ring step a K/V block arrives on.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..ops.attention import NEG_INF

__all__ = ["ring_attention", "ring_attention_sharded"]


def _block_attend(q, k, v, q_offset, k_offset, sm_scale, causal,
                  m, l, acc, window=None):
    """One blockwise-attention accumulation step (f32 state).

    GQA-native: ``q`` is (batch, kv_heads, group, q_len, head_dim) and
    ``k``/``v`` are (batch, kv_heads, k_len, head_dim) — the rotated
    K/V never materialize the repeated query heads.  ``window``:
    sliding-window masking by GLOBAL position (requires causal)."""
    s = jnp.einsum("bkgqd,bksd->bkgqs", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        q_len, k_len = q.shape[3], k.shape[2]
        q_ids = jnp.arange(q_len)[:, None] + q_offset
        k_ids = jnp.arange(k_len)[None, :] + k_offset
        visible = k_ids <= q_ids
        if window is not None:
            visible &= k_ids > q_ids - window
        s = jnp.where(visible[None, None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    correction = jnp.exp(m - m_new)
    l_new = correction * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * correction + jnp.einsum(
        "bkgqs,bksd->bkgqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   window: Optional[int] = None):
    """Inside-shard_map body: local q (batch, heads, seq_local, hd) and
    k/v (batch, kv_heads, seq_local, hd) shards — ``kv_heads`` may be
    smaller (GQA; only the kv heads rotate around the ring).  Returns
    the local output shard.  K/V rotate ``axis_size`` steps.

    ``window``: sliding-window (Mistral-class) masking by global
    position — requires ``causal``.  Shards entirely below a device's
    window are skipped like future shards, so long-context windowed
    prefill does O(window/shard + 1) live steps per device instead of
    O(axis_index)."""
    if window is not None and not causal:
        raise ValueError("sliding window requires causal attention")
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    axis_size = jax.lax.psum(1, axis_name)
    axis_index = jax.lax.axis_index(axis_name)
    seq_local = q.shape[2]
    q_offset = axis_index * seq_local

    batch, heads, _, head_dim = q.shape
    kv_heads = k.shape[1]
    group = heads // kv_heads
    q = q.reshape(batch, kv_heads, group, seq_local, head_dim)
    state_shape = (batch, kv_heads, group, seq_local, 1)
    m = jnp.full(state_shape, NEG_INF, jnp.float32)
    l = jnp.zeros(state_shape, jnp.float32)
    acc = jnp.zeros((batch, kv_heads, group, seq_local, head_dim),
                    jnp.float32)
    # shard_map's varying-axis tracking: the carry becomes 'sp'-varying
    # after the first step, so the init must be marked varying too.
    from .mesh import mark_varying
    m, l, acc = (mark_varying(x, axis_name) for x in (m, l, acc))

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(i, carry):
        k_cur, v_cur, m, l, acc = carry
        # The block currently held arrived from device (index - i).
        src = (axis_index - i) % axis_size
        k_offset = src * seq_local
        if causal:
            # Causal step skipping: a K/V shard whose keys all come
            # after this device's queries (src > axis_index) is fully
            # masked — skip its attention math (the rotation still
            # happens; later devices need the shard).  Halves causal
            # ring FLOPs on average.  With a sliding window, a shard
            # entirely BELOW the window of this device's first query
            # (max key id <= min query id - window) is fully masked
            # too — windowed long-context prefill then runs
            # O(window/shard + 1) live steps per device.
            live = src <= axis_index
            if window is not None:
                live &= (src + 1) * seq_local - 1 > q_offset - window
            m, l, acc = jax.lax.cond(
                live,
                lambda state: _block_attend(
                    q, k_cur, v_cur, q_offset, k_offset, sm_scale,
                    True, *state, window=window),
                lambda state: state,
                (m, l, acc))
        else:
            m, l, acc = _block_attend(q, k_cur, v_cur, q_offset,
                                      k_offset, sm_scale, False,
                                      m, l, acc)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_next, v_next, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(
        0, axis_size, step, (k, v, m, l, acc))
    denom = jnp.where(l == 0.0, 1.0, l)
    out = (acc / denom).astype(q.dtype)
    return out.reshape(batch, heads, seq_local, head_dim)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis: str = "sp",
                           causal: bool = True,
                           sm_scale: Optional[float] = None,
                           window: Optional[int] = None):
    """Global entry: q/k/v are full arrays (batch, heads, seq, head_dim);
    shard_map shards the sequence dimension over ``axis`` and runs the
    ring.  Heads are additionally sharded over ``tp`` when present.
    ``window``: sliding-window masking by global position (causal)."""
    head_axis = "tp" if "tp" in mesh.axis_names else None
    spec = P(None, head_axis, axis, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis, causal=causal,
                          sm_scale=sm_scale, window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)

"""Multi-host bootstrap: the DCN story.

The reference scales by spawning OS processes glued with MQTT
(``main/process_manager.py:48-110``, ``main/lifecycle.py:98-288``); its
"comms backend" is the broker.  The TPU equivalent splits the two
planes: the *control* plane stays on the framework's broker transports,
while the *data* plane is ``jax.distributed`` — one JAX process per
host, a global device set, and XLA collectives riding ICI within a
slice and DCN across slices.

Three pieces:

* :func:`initialize_multihost` — guarded, idempotent wrapper around
  ``jax.distributed.initialize``; reads standard env vars, supports
  UDP coordinator discovery (same idiom as the reference's ``boot?``
  broadcast, ``utilities/configuration.py:160-187``), and picks the
  gloo CPU collectives automatically so the SAME code path runs real
  multi-process tests on CPU hosts.
* :class:`CoordinatorAnnouncer` / :func:`discover_coordinator` — the
  process hosting the coordinator answers ``coord?`` broadcasts with
  ``coord {address} {num_processes}`` so workers need no static config.
* :func:`hybrid_mesh` — a ``Mesh`` whose leading axes span slices (DCN)
  and trailing axes span chips within a slice (ICI), grouped by the
  devices' slice/process attributes.  Shardings then place the
  bandwidth-hungry collectives (tp/sp) on ICI and the amortized ones
  (dp gradient reduction) on DCN.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Sequence

from ..utils.config import UdpResponder, udp_request

__all__ = [
    "MultiHostConfig", "initialize_multihost", "hybrid_mesh",
    "CoordinatorAnnouncer", "discover_coordinator", "worker_env",
    "COORDINATOR_DISCOVERY_PORT",
]

#: One above the reference's broker-bootstrap port (4149): same idiom,
#: different plane.
COORDINATOR_DISCOVERY_PORT = 4150
_DISCOVERY_REQUEST = b"coord?"


@dataclasses.dataclass(frozen=True)
class MultiHostConfig:
    coordinator_address: str
    num_processes: int
    process_id: int

    @classmethod
    def from_env(cls) -> Optional["MultiHostConfig"]:
        """Standard jax.distributed env triplet; None when absent (the
        single-host case — callers then skip initialization)."""
        address = os.environ.get("JAX_COORDINATOR_ADDRESS")
        num = os.environ.get("JAX_NUM_PROCESSES")
        pid = os.environ.get("JAX_PROCESS_ID")
        if not (address and num and pid):
            return None
        return cls(address, int(num), int(pid))


def worker_env(process_id: int, num_processes: int,
               coordinator_address: str,
               local_device_count: Optional[int] = None) -> Dict[str, str]:
    """Environment for a ProcessManager-spawned multi-host worker: the
    orchestration layer (reference semantics: LifeCycleManager fleets)
    starts one OS process per host with exactly this env and the child
    calls :func:`initialize_multihost()` with no arguments."""
    env = {
        "JAX_COORDINATOR_ADDRESS": coordinator_address,
        "JAX_NUM_PROCESSES": str(num_processes),
        "JAX_PROCESS_ID": str(process_id),
    }
    if local_device_count is not None:
        # Append to (not clobber) any operator-supplied tuning flags.
        existing = os.environ.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{existing} --xla_force_host_platform_device_count="
            f"{local_device_count}").strip()
    return env


def initialize_multihost(config: Optional[MultiHostConfig] = None,
                         discover: bool = False,
                         discovery_port: int = COORDINATOR_DISCOVERY_PORT,
                         discovery_timeout: float = 5.0,
                         discovery_address: str = "255.255.255.255",
                         process_id: Optional[int] = None,
                         _initialize=None) -> Dict:
    """Bring this process into the global JAX world.

    Resolution order: explicit ``config`` → env triplet
    (:meth:`MultiHostConfig.from_env`) → UDP discovery (when
    ``discover=True``; the coordinator host runs a
    :class:`CoordinatorAnnouncer` and workers learn the address +
    world size, supplying only their ``process_id``).  Idempotent: a
    second call returns the current world without re-initializing.

    Returns ``{"initialized", "process_id", "num_processes",
    "coordinator_address"}``.  ``_initialize`` is injectable for tests.
    """
    import jax

    try:  # private API, guarded: absence just disables the fast no-op
        state = jax._src.distributed.global_state
        already = getattr(state, "client", None) is not None
    except Exception:  # noqa: BLE001
        state, already = None, False
    if already:
        return {"initialized": False,
                "process_id": jax.process_index(),
                "num_processes": jax.process_count(),
                "coordinator_address": getattr(
                    state, "coordinator_address", None)}

    if config is None:
        config = MultiHostConfig.from_env()
    if config is None and discover:
        found = discover_coordinator(port=discovery_port,
                                     timeout=discovery_timeout,
                                     address=discovery_address)
        if found is None:
            raise RuntimeError(
                "coordinator discovery timed out: no CoordinatorAnnouncer "
                f"answered on UDP port {discovery_port}")
        address, num_processes = found
        if process_id is None:
            raise ValueError(
                "discovery provides the coordinator, not your rank: pass "
                "process_id=")
        config = MultiHostConfig(address, num_processes, process_id)
    if config is None:
        raise RuntimeError(
            "no multi-host config: pass MultiHostConfig, set "
            "JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID, "
            "or use discover=True")

    # CPU fleets/tests need gloo collectives to cross process
    # boundaries the way ICI/DCN do on pods.  Inspect the CONFIG, not
    # jax.default_backend(): touching the backend before
    # jax.distributed.initialize would pin a single-process world.
    platforms = (jax.config.jax_platforms or
                 os.environ.get("JAX_PLATFORMS", ""))
    if "cpu" in (platforms or ""):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 - older jaxlib: single impl
            pass

    initialize = _initialize or jax.distributed.initialize
    try:
        initialize(coordinator_address=config.coordinator_address,
                   num_processes=config.num_processes,
                   process_id=config.process_id)
    except RuntimeError as error:
        # Idempotence backstop should the private-state probe above
        # ever stop working across a jax upgrade.
        if "already" in str(error).lower():
            return {"initialized": False,
                    "process_id": jax.process_index(),
                    "num_processes": jax.process_count(),
                    "coordinator_address": config.coordinator_address}
        raise
    return {"initialized": True,
            "process_id": config.process_id,
            "num_processes": config.num_processes,
            "coordinator_address": config.coordinator_address}


# --------------------------------------------------------------------------- #
# Coordinator discovery (UDP broadcast, reference boot? idiom)

class CoordinatorAnnouncer(UdpResponder):
    """Answer ``coord?`` broadcasts with ``coord {address} {n}`` — the
    reference's broker-bootstrap idiom applied to the data plane.  Run
    on the host that will be process 0; ``port=0`` binds an ephemeral
    port (tests)."""

    def __init__(self, coordinator_address: str, num_processes: int,
                 port: int = COORDINATOR_DISCOVERY_PORT,
                 bind_address: str = ""):
        super().__init__(
            _DISCOVERY_REQUEST,
            f"coord {coordinator_address} {num_processes}".encode(),
            port, bind_address, thread_name="coordinator_announcer")


def discover_coordinator(port: int = COORDINATOR_DISCOVERY_PORT,
                         timeout: float = 5.0,
                         address: str = "255.255.255.255"):
    """Broadcast ``coord?``; returns (coordinator_address, num_processes)
    or None on timeout."""
    def parse(fields):
        if len(fields) == 3 and fields[0] == "coord":
            return fields[1], int(fields[2])
        return None
    return udp_request(_DISCOVERY_REQUEST, parse, port, timeout, address)


# --------------------------------------------------------------------------- #
# Hybrid DCN x ICI meshes

def _group_keys(devices):
    """Slice keys for DCN grouping.  TPU multislice: ``slice_index``
    differs per slice.  When every device reports the same slice (CPU
    fleets, single-slice pods driven as a process fleet), the owning
    process stands in — the process boundary IS the DCN there."""
    slice_keys = [getattr(d, "slice_index", None) for d in devices]
    if None not in slice_keys and len(set(slice_keys)) > 1:
        return [int(k) for k in slice_keys]
    return [int(getattr(d, "process_index", 0)) for d in devices]


def hybrid_mesh(dcn: Dict[str, int], ici: Dict[str, int],
                devices: Optional[Sequence] = None):
    """Mesh with leading DCN axes (across slices) and trailing ICI axes
    (within a slice): ``hybrid_mesh({"dp": 2}, {"tp": 4})`` on 2 slices
    x 4 chips.  Data-parallel gradient reductions then cross DCN once
    per step while tensor/sequence-parallel collectives stay on ICI —
    the standard placement, because tp/sp traffic is per-layer and
    bandwidth-hungry.

    Device order within each group follows ``id`` (jax's enumeration
    order, which matches the physical ICI order for TPU backends).
    ``-1`` works as in :class:`MeshSpec` within each of dcn/ici.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from .mesh import MeshSpec

    devices = list(devices if devices is not None else jax.devices())
    groups: Dict[int, list] = {}
    for device, key in zip(devices, _group_keys(devices)):
        groups.setdefault(key, []).append(device)
    n_slices = len(groups)
    per_slice = {key: len(group) for key, group in groups.items()}
    if len(set(per_slice.values())) != 1:
        raise ValueError(f"uneven slices: {per_slice}")
    slice_size = next(iter(per_slice.values()))

    dcn_sizes = MeshSpec(**dcn).resolve(n_slices)
    ici_sizes = MeshSpec(**ici).resolve(slice_size)
    overlap = set(dcn_sizes) & set(ici_sizes)
    if overlap:
        raise ValueError(f"axis named in both dcn and ici: {overlap}")

    ordered = []
    for key in sorted(groups):
        ordered.extend(sorted(groups[key], key=lambda d: d.id))
    shape = tuple(dcn_sizes.values()) + tuple(ici_sizes.values())
    array = np.asarray(ordered, dtype=object).reshape(shape)
    return Mesh(array, tuple(dcn_sizes.keys()) + tuple(ici_sizes.keys()))

"""Elastic training: survive topology changes via checkpoint + re-shard.

The reference's elasticity is service-level — things may appear or
disappear at any time, LWT + leases detect it, proxies swap live
(SURVEY.md §5.3).  For a TPU *training job*, elasticity means the mesh
itself changes: chips are lost (preemption, failure) or gained, and the
job must resume from the latest checkpoint on the NEW topology with
identical numbers.  The mechanism is the sharding-aware cross-topology
restore in :mod:`.checkpoint` (orbax re-lays every array out for the
target ``NamedSharding``); this module packages it as a driver:

    trainer = ElasticTrainer(config, optimizer, directory, mesh_a)
    trainer.run(batches_a)                  # checkpoints every N steps
    # ... topology change: rebuild on a different mesh ...
    trainer = ElasticTrainer(config, optimizer, directory, mesh_b)
    trainer.run(batches_b)                  # resumes from latest step

Resume is exact: optimizer moments and the step counter restore with
the params, so loss curves continue as if the change never happened
(tested: dp=8 -> dp=4xtp=2 mid-run equals an uninterrupted run).
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama
from .checkpoint import TrainCheckpointer
from .train import (init_train_state, make_train_step,
                    shard_train_state, train_state_specs)

__all__ = ["ElasticTrainer"]


class ElasticTrainer:
    """Checkpoint-backed training driver bound to ONE mesh topology;
    rebuilding it on a different mesh resumes from the latest step."""

    def __init__(self, config: llama.LlamaConfig, optimizer,
                 directory: str, mesh: Mesh, save_every: int = 10,
                 accum_steps: int = 1, remat: bool = False,
                 seed: int = 0, async_save: bool = False):
        self.config = config
        self.optimizer = optimizer
        self.mesh = mesh
        self.save_every = save_every
        self.checkpointer = TrainCheckpointer(directory,
                                              async_save=async_save)
        self._step_fn = jax.jit(
            make_train_step(config, optimizer, accum_steps=accum_steps,
                            remat=remat),
            donate_argnums=(0, 1))

        latest = self.checkpointer.latest_step()
        if latest is not None:
            # Restore path needs shape/dtype TEMPLATES only — eval_shape
            # avoids materializing a full random init just to discard it
            # (matters at 70B scale).
            templates = jax.eval_shape(
                lambda: init_train_state(config, jax.random.PRNGKey(0),
                                         optimizer))
            t_params, t_opt = templates
            specs = train_state_specs(config, t_opt, mesh)
            restored = self.checkpointer.restore(
                {"params": t_params, "opt_state": t_opt},
                mesh=mesh,
                specs={"params": specs[0], "opt_state": specs[1]})
            self.params = restored["params"]
            self.opt_state = _retuple(t_opt, restored["opt_state"])
            self.step = restored["step"]
        else:
            self.step = 0
            params, opt_state = init_train_state(
                config, jax.random.PRNGKey(seed), optimizer)
            self.params, self.opt_state = shard_train_state(
                params, opt_state, mesh, config)

    @property
    def batch_sharding(self) -> NamedSharding:
        spec = P("dp" if "dp" in self.mesh.axis_names else None)
        return NamedSharding(self.mesh, spec)

    def run(self, batches: Iterable, max_steps: Optional[int] = None):
        """Consume ``batches`` (host or device arrays of token ids),
        checkpointing every ``save_every`` steps.  Returns the list of
        losses."""
        losses = []
        for batch in batches:
            batch = jax.device_put(np.asarray(batch),
                                   self.batch_sharding)
            self.params, self.opt_state, loss = self._step_fn(
                self.params, self.opt_state, batch)
            self.step += 1
            losses.append(float(loss))
            if self.save_every and self.step % self.save_every == 0:
                self.save()
            if max_steps and len(losses) >= max_steps:
                break
        return losses

    def save(self):
        self.checkpointer.save(
            self.step,
            {"params": self.params, "opt_state": self.opt_state},
            metadata={"mesh_axes": dict(
                zip(self.mesh.axis_names,
                    (int(n) for n in self.mesh.devices.shape)))})

    def close(self):
        self.checkpointer.close()


def _retuple(template, restored):
    """Orbax returns plain containers; rebuild the optax NamedTuples
    from the template's structure."""
    flat = jax.tree.leaves(restored)
    treedef = jax.tree.structure(template)
    return jax.tree.unflatten(treedef, flat)

"""Pipeline parallelism (pp): GPipe-style microbatching over a mesh axis.

The reference expresses pipeline stages as remote PipelineElements in
different OS processes with MQTT frame hops (SURVEY.md §2.6 maps that to
PP).  On TPU the same idea lives *inside* one jitted program: layers are
split into ``pp`` stages (one per device along the ``pp`` mesh axis),
microbatches stream through the stages, and activations hop stage→stage
with ``ppermute`` over ICI.  The schedule is the classic GPipe fill/
drain: ``n_micro + pp − 1`` rounds, stage ``s`` working on microbatch
``t − s`` in round ``t``; bubbles compute garbage that is masked out of
the result (branch-free — XLA/SPMD want a uniform program).

``stage_params`` must be a pytree whose leaves are stacked on a leading
stage axis, sharded ``P("pp", …)`` — inside ``shard_map`` every device
then holds exactly its stage's slice.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["pipeline_apply", "pipeline_apply_sharded", "stack_stages"]


def stack_stages(per_stage_params):
    """Stack a list of per-stage pytrees on a new leading stage axis
    (what ``P("pp", …)`` shards)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves),
                        *per_stage_params)


from .mesh import mark_varying as _mark_varying


def pipeline_apply(stage_fn: Callable, stage_params, microbatches,
                   axis_name: str):
    """Inside-shard_map body.

    ``stage_params``: this device's stage slice (leading stage axis of
    size 1, squeezed here).  ``microbatches``: ``(n_micro, mb, …)`` —
    replicated; only stage 0 reads it.  Returns ``(n_micro, mb, …)``
    outputs, valid on the LAST stage (zeros elsewhere; the host wrapper
    psum-selects them).
    """
    pp = jax.lax.axis_size(axis_name)
    index = jax.lax.axis_index(axis_name)
    my_params = jax.tree.map(lambda leaf: leaf[0], stage_params)
    n_micro = microbatches.shape[0]
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    recv = _mark_varying(jnp.zeros_like(microbatches[0]), axis_name)
    outputs = _mark_varying(
        jnp.zeros((n_micro,) + microbatches.shape[1:],
                  microbatches.dtype), axis_name)

    def round_body(carry, t):
        recv, outputs = carry
        # Stage 0 feeds from the microbatch queue; others from the ring.
        feed_index = jnp.clip(t, 0, n_micro - 1)
        feed = jax.lax.dynamic_index_in_dim(microbatches, feed_index,
                                            keepdims=False)
        inp = jnp.where(index == 0, feed, recv)
        out = stage_fn(my_params, inp)
        # Microbatch id this stage just produced; valid in [0, n_micro).
        micro = t - index
        valid = jnp.logical_and(micro >= 0, micro < n_micro)
        is_last = index == pp - 1
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(jnp.logical_and(valid, is_last), out,
                      jax.lax.dynamic_index_in_dim(
                          outputs, jnp.clip(micro, 0, n_micro - 1),
                          keepdims=False)),
            jnp.clip(micro, 0, n_micro - 1), axis=0)
        # Hand this round's activation to the next stage (the wrap-around
        # last→0 edge carries garbage; stage 0 never reads recv).
        recv = jax.lax.ppermute(out, axis_name, perm)
        return (recv, outputs), None

    # scan (not fori_loop) so reverse-mode AD works: this makes the
    # whole schedule differentiable and enables pipeline-parallel
    # TRAINING (grad of ppermute = ppermute with the inverse ring).
    (_, outputs), _ = jax.lax.scan(
        round_body, (recv, outputs), jnp.arange(n_micro + pp - 1))
    # Only the last stage holds real outputs; make them uniform so the
    # host wrapper can return replicated results.
    return jax.lax.psum(
        jnp.where(index == pp - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)


@functools.partial(jax.jit,
                   static_argnames=("stage_fn", "mesh", "axis",
                                    "n_microbatches"))
def pipeline_apply_sharded(stage_fn: Callable, stage_params, x,
                           mesh: Mesh, axis: str = "pp",
                           n_microbatches: int = 4):
    """Host-level wrapper: ``x (batch, …)`` is split into
    ``n_microbatches`` along batch, streamed through the stages, and
    reassembled.  ``stage_params`` leaves are stacked ``(pp, …)`` and
    get sharded over ``axis``."""
    batch = x.shape[0]
    assert batch % n_microbatches == 0, (batch, n_microbatches)
    micro = x.reshape((n_microbatches, batch // n_microbatches)
                      + x.shape[1:])
    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    out = shard_map(
        functools.partial(pipeline_apply, stage_fn,
                          axis_name=axis),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
    )(stage_params, micro)
    return out.reshape((batch,) + out.shape[2:])

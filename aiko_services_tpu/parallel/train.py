"""Sharded training step for the flagship model.

Used by ``__graft_entry__.dryrun_multichip`` and as the template for
full training runs: next-token cross-entropy over a dp×tp mesh, optax
optimizer, parameters/optimizer state sharded by the model's
``param_specs`` so XLA inserts the psum/all-gather collectives over ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama

__all__ = ["make_train_step", "init_train_state", "shard_train_state",
           "train_state_specs", "make_pp_train_step", "to_pp_params"]


def cross_entropy(logits, targets, mask=None):
    """Mean token NLL; ``mask`` (same shape as targets, 0/1) restricts
    the mean to selected positions — supervised-completion training
    (loss on the answer, not the prompt)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logp, targets[..., None],
                                 axis=-1)[..., 0]
    if mask is None:
        return -jnp.mean(picked)
    mask = mask.astype(jnp.float32)
    return -(picked * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def make_train_step(config: llama.LlamaConfig, optimizer,
                    accum_steps: int = 1, remat: bool = False):
    """Build the jittable training step.

    ``accum_steps > 1``: gradient accumulation — the batch is split into
    ``accum_steps`` microbatches scanned sequentially, grads averaged
    before ONE optimizer update (exactly the full-batch mean-loss grads,
    tested); peak activation memory drops by ~accum_steps at the same
    effective batch.  ``remat=True``: rematerialize the forward under
    autodiff (``jax.checkpoint``) — activations are recomputed in the
    backward instead of stored, trading ~33% more FLOPs for O(layers)
    less live memory (the standard large-model training trade on HBM).
    """
    def loss_fn(params, tokens, loss_mask=None):
        forward = llama.forward
        if remat:
            forward = jax.checkpoint(
                forward, static_argnums=(2, 3))
        logits = forward(params, tokens[:, :-1], config, False)
        mask = None if loss_mask is None else loss_mask[:, 1:]
        return cross_entropy(logits, tokens[:, 1:], mask)

    def train_step(params, opt_state, tokens, loss_mask=None):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens,
                                                      loss_mask)
        else:
            assert loss_mask is None, \
                "loss_mask requires accum_steps == 1"
            batch = tokens.shape[0]
            assert batch % accum_steps == 0, (batch, accum_steps)
            micro = tokens.reshape(accum_steps, batch // accum_steps,
                                   tokens.shape[1])

            def accumulate(carry, micro_tokens):
                loss_sum, grad_sum = carry
                loss, grads = jax.value_and_grad(loss_fn)(params,
                                                          micro_tokens)
                grad_sum = jax.tree.map(jnp.add, grad_sum, grads)
                return (loss_sum + loss, grad_sum), None

            zeros = jax.tree.map(
                lambda leaf: jnp.zeros(leaf.shape, jnp.float32), params)
            (loss_sum, grad_sum), _ = jax.lax.scan(
                accumulate, (jnp.float32(0.0), zeros), micro)
            loss = loss_sum / accum_steps
            grads = jax.tree.map(
                lambda g, p: (g / accum_steps).astype(p.dtype),
                grad_sum, params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def init_train_state(config: llama.LlamaConfig, key, optimizer):
    params = llama.init_params(config, key)
    opt_state = optimizer.init(params)
    return params, opt_state


def train_state_specs(config: llama.LlamaConfig, opt_state,
                      mesh: Mesh):
    """(param_specs, opt_specs) for this mesh: the model's TP layout
    filtered to the mesh's axes; adam moments mirror the param layout;
    every other optimizer leaf (step counts etc.) replicates."""
    from .mesh import filter_specs_for_mesh
    param_specs = filter_specs_for_mesh(llama.param_specs(config), mesh)

    def item_specs(item):
        if hasattr(item, "_fields"):        # optax NamedTuple state
            replaced = {}
            for field in item._fields:
                if field in ("mu", "nu"):
                    replaced[field] = param_specs
                else:
                    replaced[field] = jax.tree.map(
                        lambda _: P(), getattr(item, field))
            return item._replace(**replaced)
        return jax.tree.map(lambda _: P(), item)

    return param_specs, tuple(item_specs(item) for item in opt_state)


def shard_train_state(params, opt_state, mesh: Mesh,
                      config: llama.LlamaConfig, specs=None):
    """Place params + optimizer state with the model's partition specs
    (``specs`` = precomputed ``train_state_specs`` result, else derived
    here).  The single placement implementation — ElasticTrainer and
    the dryrun both go through it."""
    if specs is None:
        specs = train_state_specs(config, opt_state, mesh)
    param_specs, opt_specs = specs

    def place_leaf(leaf, spec):
        if hasattr(leaf, "shape"):
            return jax.device_put(leaf, NamedSharding(mesh, spec))
        return leaf

    def place(tree, tree_specs):
        return jax.tree.map(
            place_leaf, tree, tree_specs,
            is_leaf=lambda x: isinstance(x, P))

    params = place(params, param_specs)
    new_opt_state = tuple(place(item, item_spec)
                          for item, item_spec in zip(opt_state,
                                                     opt_specs))
    return params, new_opt_state


def make_pp_train_step(config: llama.LlamaConfig, optimizer, mesh: Mesh,
                       n_microbatches: int = 4, pp_axis: str = "pp"):
    """Pipeline-parallel training step (GPipe schedule, exact grads).

    Parameters live in "pp form": ``{"embed", "stages", "final_norm",
    "lm_head"}`` where ``stages`` is the stacked per-stage layer pytree
    (:func:`~..models.llama.stack_pipeline_params`) sharded ``P("pp",
    …)``.  The forward streams microbatches through the stage devices
    (``parallel/pipeline_parallel.py`` — a ``lax.scan`` schedule, so
    reverse-mode AD runs the backward sweep through the same ring);
    embed / final norm / LM head stay replicated.  Composes with dp on
    the batch axis of ``tokens``.
    """
    def loss_fn(params, tokens):
        logits = llama.pipeline_forward(
            {"embed": params["embed"], "final_norm": params["final_norm"],
             "lm_head": params["lm_head"], "layers": []},
            tokens[:, :-1], config, mesh,
            n_microbatches=n_microbatches, pp_axis=pp_axis,
            stages=params["stages"])
        return cross_entropy(logits, tokens[:, 1:])

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def to_pp_params(params, config: llama.LlamaConfig, pp: int):
    """Convert standard llama params to the "pp form" used by
    :func:`make_pp_train_step` (stages stacked on a leading pp axis)."""
    stages = llama.stack_pipeline_params(params, config, pp)
    return {"embed": params["embed"], "stages": stages,
            "final_norm": params["final_norm"],
            "lm_head": params["lm_head"]}

"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The reference has no long-context machinery (SURVEY.md §5.7).  Ring
attention (``parallel/ring_attention.py``) is one TPU-native answer;
this module is the other standard design (DeepSpeed-Ulysses): instead of
rotating K/V shards around a ring, two ``all_to_all`` collectives swap
the sharded dimension around the attention op —

* inputs arrive sharded on **sequence** over the ``sp`` axis
  (``batch, heads, seq/n, head_dim``);
* an all-to-all re-shards to **heads** (``batch, heads/n, seq,
  head_dim``), so every device holds the *full* sequence for a subset
  of heads and runs ordinary (flash) attention locally — no online
  merge needed;
* a second all-to-all restores sequence sharding for the rest of the
  network (MLP etc. stay sequence-sharded).

Trade-off vs ring: Ulysses does O(2) collectives of the whole activation
per attention instead of ``n`` neighbor exchanges of K/V, and it needs
``heads % n == 0`` — but the local attention is a single dense block
(better MXU utilisation) and composes directly with the Pallas flash
kernel.  Both are exact.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..ops.attention import attention_reference

__all__ = ["ulysses_attention", "ulysses_attention_sharded"]


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      sm_scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None,
                      window: Optional[int] = None):
    """Inside-shard_map body.  ``q/k/v`` are local sequence shards of
    shape ``(batch, heads, seq_local, head_dim)`` with the FULL head
    count; returns the local output shard, same shape.

    ``attn_fn(q, k, v, causal=, sm_scale=, window=)`` runs the
    per-device dense attention; defaults to the jnp reference (swap in
    ``ops.attention.flash_attention`` on real TPU).  ``window``:
    sliding-window masking — after the head-scatter each device holds
    the FULL sequence for its head group, so plain local windowed
    masking is globally correct (no offset bookkeeping).
    """
    if window is not None and not causal:
        raise ValueError("sliding window requires causal attention")
    if attn_fn is None:
        attn_fn = attention_reference
    n = jax.lax.psum(1, axis_name)
    heads = q.shape[1]
    kv_heads = k.shape[1]
    if heads % n or kv_heads % n:
        raise ValueError(
            f"Ulysses needs q heads ({heads}) and kv heads "
            f"({kv_heads}) divisible by axis size ({n}); repeat kv "
            "heads first when they do not divide")

    # seq-sharded -> head-sharded: split the head dim across devices,
    # concatenate the sequence shards.  all_to_all is the single XLA
    # collective purpose-built for this swap (rides ICI all-to-all
    # links; no host involvement).
    def scatter_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def scatter_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    q_h, k_h, v_h = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    # GQA: the all-to-all moved only the kv heads (group-x fewer bytes
    # over ICI); device i's q-head slice [i*h/n, (i+1)*h/n) maps
    # exactly onto its kv-head slice [i*kv/n, (i+1)*kv/n), so a LOCAL
    # repeat aligns them for the dense attention.
    if q_h.shape[1] != k_h.shape[1]:
        group = q_h.shape[1] // k_h.shape[1]
        k_h = jnp.repeat(k_h, group, axis=1)
        v_h = jnp.repeat(v_h, group, axis=1)
    # Full sequence is now local: plain causal masking is correct with
    # no global-offset bookkeeping (unlike the ring).
    o_h = attn_fn(q_h, k_h, v_h, causal=causal, sm_scale=sm_scale,
                  window=window)
    return scatter_seq(o_h)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, axis: str = "sp",
                              causal: bool = True,
                              sm_scale: Optional[float] = None,
                              attn_fn: Optional[Callable] = None,
                              window: Optional[int] = None):
    """Global entry: q/k/v are full arrays ``(batch, heads, seq,
    head_dim)``; shard_map shards the sequence dim over ``axis`` and
    runs the all-to-all swap around dense local attention.
    ``window``: sliding-window masking (causal)."""
    spec = P(None, None, axis, None)
    fn = shard_map(
        functools.partial(ulysses_attention, axis_name=axis,
                          causal=causal, sm_scale=sm_scale,
                          attn_fn=attn_fn, window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)

"""Latency-hiding collective matmuls (ICI overlap).

Tensor-parallel layers alternate between an all-gather (activations) and
a matmul against a weight shard; done naively the ICI transfer and the
MXU work serialize.  These "collective matmul" kernels interleave them:
at every ring step the device multiplies the activation shard it already
holds while ``ppermute`` moves the next shard to its neighbor, so the
transfer hides behind the MXU (the classic TPU decomposition from the
scaling playbook; the reference framework has no tensor math at all —
SURVEY.md §2.6).

Two primitives, both written for use inside ``shard_map`` bodies:

- ``allgather_matmul(x_shard, w_shard, axis)``:
  computes ``allgather(x) @ w_shard`` without ever materializing the
  full gathered ``x``.  (Column-parallel layer: x sharded on batch/seq,
  w sharded on columns.)
- ``matmul_reducescatter(x_shard, w_shard, axis)``:
  computes ``reduce_scatter(x_shard @ w_shard)`` accumulating the ring
  partial sums while shards rotate.  (Row-parallel layer.)

Numerics are exact (pure reordering of the same dot products).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

__all__ = ["allgather_matmul", "matmul_reducescatter",
           "allgather_matmul_sharded", "matmul_reducescatter_sharded"]


def _axis_size(axis_name) -> int:
    """Static mesh-axis size inside a shard_map body.
    ``jax.lax.axis_size`` only exists on newer jax; ``psum`` of a
    literal 1 is constant-folded to a python int on every version."""
    if hasattr(jax.lax, "axis_size"):      # jax >= 0.6
        return int(jax.lax.axis_size(axis_name))
    return int(jax.lax.psum(1, axis_name))


def _ring_perm(axis_name):
    size = _axis_size(axis_name)
    return [(i, (i + 1) % size) for i in range(size)]


from .mesh import mark_varying as _mark_varying


def allgather_matmul(x_shard, w_shard, axis_name: str):
    """``allgather(x, axis) @ w_shard`` with the gather hidden behind the
    matmuls.  x_shard ``(m_local, k)``, w_shard ``(k, n_local)`` →
    ``(m_local * axis_size, n_local)``.

    Each step: start moving our current x block to the next neighbor,
    multiply the block we hold, place the product at the owning row
    offset.  After ``axis_size`` steps every device has computed the
    full gathered product against its own weight shard.
    """
    size = _axis_size(axis_name)
    index = jax.lax.axis_index(axis_name)
    perm = _ring_perm(axis_name)
    m_local = x_shard.shape[0]
    n_local = w_shard.shape[1]
    out = _mark_varying(jnp.zeros((m_local * size, n_local),
                                  x_shard.dtype), axis_name)

    def body(step, carry):
        block, out = carry
        # The block we hold at `step` originated on device
        # (index - step) mod size: its rows live at that offset.
        src = (index - step) % size
        prod = jnp.dot(block, w_shard,
                       preferred_element_type=jnp.float32)
        out = jax.lax.dynamic_update_slice(
            out, prod.astype(out.dtype), (src * m_local, 0))
        # Rotate while the NEXT multiply runs (XLA schedules the
        # ppermute concurrently with the dot — that's the overlap).
        block = jax.lax.ppermute(block, axis_name, perm)
        return block, out

    _, out = jax.lax.fori_loop(0, size, body, (x_shard, out),
                               unroll=True)
    return out


def matmul_reducescatter(x_shard, w_shard, axis_name: str):
    """``reduce_scatter(x_shard @ w_shard, axis)`` with the scatter
    hidden behind the matmuls.  x_shard ``(m, k_local)``, w_shard
    ``(k_local, n)`` → ``(m, n / axis_size)``-worth: every device ends
    with the fully-summed slice of columns it owns.

    Walks the ring accumulating: at each step a device multiplies its
    x/w shard against the column slice owned by the device the
    accumulator is travelling toward, adds, and forwards.
    """
    size = _axis_size(axis_name)
    index = jax.lax.axis_index(axis_name)
    perm = _ring_perm(axis_name)
    m = x_shard.shape[0]
    n = w_shard.shape[1]
    assert n % size == 0, "output columns must divide the axis"
    n_local = n // size
    acc = _mark_varying(jnp.zeros((m, n_local), jnp.float32), axis_name)

    def slice_for(owner):
        return jax.lax.dynamic_slice(w_shard, (0, owner * n_local),
                                     (w_shard.shape[0], n_local))

    def body(step, acc):
        # After `step` hops the accumulator we hold is destined for
        # device (index + (size - 1 - step)) mod size.
        owner = (index + (size - 1 - step)) % size
        partial = jnp.dot(x_shard, slice_for(owner),
                          preferred_element_type=jnp.float32)
        acc = acc + partial
        # Forward every step except the last (it has arrived home).
        return jax.lax.cond(
            step < size - 1,
            lambda a: jax.lax.ppermute(a, axis_name, perm),
            lambda a: a, acc)

    acc = jax.lax.fori_loop(0, size, body, acc, unroll=True)
    return acc.astype(x_shard.dtype)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def allgather_matmul_sharded(x, w, mesh: Mesh, axis: str = "tp"):
    """Host-level wrapper: x sharded ``P(axis, None)`` on rows, w sharded
    ``P(None, axis)`` on columns → fully-gathered-x @ w, sharded on
    columns (standard column-parallel layer)."""
    return shard_map(
        functools.partial(allgather_matmul, axis_name=axis),
        mesh=mesh,
        in_specs=(P(axis, None), P(None, axis)),
        out_specs=P(None, axis),
    )(x, w)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def matmul_reducescatter_sharded(x, w, mesh: Mesh, axis: str = "tp"):
    """Host-level wrapper: x sharded ``P(None, axis)`` on contraction, w
    sharded ``P(axis, None)`` → x @ w summed over shards, scattered on
    columns (standard row-parallel layer)."""
    return shard_map(
        functools.partial(matmul_reducescatter, axis_name=axis),
        mesh=mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(None, axis),
    )(x, w)

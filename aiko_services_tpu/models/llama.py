"""Llama-3-architecture decoder-only transformer, TPU-first.

The flagship model family for the llm_chat workload (the reference calls
an external Ollama llama3.1 over HTTP, ``examples/llm/elements_llm.py:
191-220``; here the model *is* the framework's).  Pure functional JAX:
parameters are a pytree dict, the forward is jit/pjit-friendly, and every
parameter carries a logical sharding spec so the same code runs single-
chip or TP/DP-sharded over a mesh.

Architecture (Llama 3): RMSNorm pre-norm, rotary position embeddings,
grouped-query attention, SwiGLU MLP, untied LM head, bfloat16 params with
f32 layernorm/softmax accumulation.  Prefill uses the Pallas flash
attention kernel; single-token decode attends over a preallocated KV
cache (dense dot — one query row doesn't need flash).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention_reference, flash_attention
from ..ops.paged_attention import (cached_gqa_attention,
                                   contiguous_block_size,
                                   decode_kernel_mode,
                                   paged_decode_attention)
from ..ops.paged_prefill import (paged_prefill_attention,
                                 paged_verify_attention,
                                 prefill_kernel_mode)
from ..ops.quant import (_unpack_int4, int4_matmul, int8_matmul,
                         is_quantized, is_quantized_int4, quantize_tree)

__all__ = ["LlamaConfig", "init_params", "forward",
           "forward_sequence_parallel", "init_cache",
           "decode_step", "generate_tokens", "prefill", "param_specs",
           "quantize_params", "random_quantized_params",
           "quantized_param_specs", "prefill_sequence_parallel",
           "pipeline_forward", "stack_pipeline_params",
           "decode_chunk_ragged", "prefill_chunk", "sample_logits",
           "init_paged_cache", "decode_chunk_paged",
           "serve_chunk_ragged", "serve_chunk_paged",
           "serve_chunk_mixed", "prefill_append_paged",
           "verify_chunk_paged",
           "paged_insert_prefix", "paged_scatter_blocks",
           "paged_gather_blocks", "complete", "CONFIGS"]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1376
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    #: > 0 switches the MLP to a mixture-of-experts (Mixtral-class);
    #: experts shard over the "ep" mesh axis.
    n_experts: int = 0
    moe_top_k: int = 2
    #: Capacity-based token dropping makes routing batch-dependent (a
    #: dropped token depends on its neighbors — standard GShard
    #: semantics).  cf >= n_experts/top_k guarantees no drops, which
    #: keeps decode exactly consistent with full-sequence forward.
    moe_capacity_factor: float = 2.0
    #: Mistral-style sliding-window attention: each position attends to
    #: at most this many most-recent positions (None = full causal).
    #: Long-context prefill cost becomes O(seq·window) via two-sided
    #: block skipping in the flash kernel.
    sliding_window: Optional[int] = None
    #: Llama-3.1-style RoPE frequency rescaling as (factor,
    #: low_freq_factor, high_freq_factor, original_max_position
    #: embeddings) — a tuple so the config stays hashable for jit.
    #: None = plain theta^-2k/d frequencies (Llama-3-8B and earlier).
    rope_scaling: Optional[Tuple[float, float, float, int]] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def moe_config(self):
        from .moe import MoEConfig
        return MoEConfig(d_model=self.d_model, d_ff=self.d_ff,
                         n_experts=self.n_experts, top_k=self.moe_top_k,
                         capacity_factor=self.moe_capacity_factor,
                         dtype=self.dtype)


#: Named configs: tiny/small for tests+bench on one chip, the real ones
#: for parity with BASELINE.json targets.
CONFIGS: Dict[str, LlamaConfig] = {
    "tiny": LlamaConfig(vocab_size=1024, d_model=128, n_layers=2,
                        n_heads=4, n_kv_heads=2, d_ff=352,
                        max_seq_len=512),
    # TP-shardable test config: every sharded dim (kv heads, q heads,
    # d_model, d_ff, vocab) divides by 8, so one config exercises
    # TP=1/2/4/8 on the virtual CPU mesh; GQA group of 2 keeps the
    # grouped-head slicing honest.
    "tiny_tp": LlamaConfig(vocab_size=1024, d_model=128, n_layers=2,
                           n_heads=16, n_kv_heads=8, d_ff=352,
                           max_seq_len=512),
    "small": LlamaConfig(vocab_size=32_000, d_model=1024, n_layers=8,
                         n_heads=16, n_kv_heads=8, d_ff=2816,
                         max_seq_len=2048),
    "1b": LlamaConfig(vocab_size=128_256, d_model=2048, n_layers=16,
                      n_heads=32, n_kv_heads=8, d_ff=8192,
                      max_seq_len=8192),
    "llama3_8b": LlamaConfig(vocab_size=128_256, d_model=4096,
                             n_layers=32, n_heads=32, n_kv_heads=8,
                             d_ff=14_336, max_seq_len=8192),
    "llama3_70b": LlamaConfig(vocab_size=128_256, d_model=8192,
                              n_layers=80, n_heads=64, n_kv_heads=8,
                              d_ff=28_672, max_seq_len=8192),
    "moe_tiny": LlamaConfig(vocab_size=1024, d_model=128, n_layers=2,
                            n_heads=4, n_kv_heads=2, d_ff=352,
                            max_seq_len=512, n_experts=4),
    # 8-expert test config: exercises every tp × ep ReplicaMesh on the
    # virtual 8-device mesh (ep up to 8); cf=4.0 = E/k, drop-free.
    "moe_tiny8": LlamaConfig(vocab_size=1024, d_model=128, n_layers=2,
                             n_heads=4, n_kv_heads=2, d_ff=352,
                             max_seq_len=512, n_experts=8,
                             moe_capacity_factor=4.0),
    # Single-chip MoE bench config (~0.6 B params, int8 ≈ 0.6 GB);
    # cf=4.0 = E/k keeps decode drop-free (see moe_capacity_factor).
    "moe_small": LlamaConfig(vocab_size=32_000, d_model=1024,
                             n_layers=8, n_heads=16, n_kv_heads=8,
                             d_ff=2816, max_seq_len=2048, n_experts=8,
                             moe_capacity_factor=4.0),
    # cf=4.0 = n_experts/top_k: the no-drop bound, so cached decode stays
    # exactly consistent with full-sequence forward (see moe_capacity_factor).
    "mixtral_8x7b": LlamaConfig(vocab_size=32_000, d_model=4096,
                                n_layers=32, n_heads=32, n_kv_heads=8,
                                d_ff=14_336, max_seq_len=32_768,
                                rope_theta=1e6, n_experts=8,
                                moe_capacity_factor=4.0),
    # Mistral-7B-v0.1-class: sliding-window attention (4096).  PREFILL
    # cost is O(seq*window) via the flash kernel's two-sided block
    # skipping; decode masks out-of-window keys but keeps the full
    # cache resident (no rolling KV buffer yet), so decode memory stays
    # O(max_seq_len).
    "mistral_7b": LlamaConfig(vocab_size=32_000, d_model=4096,
                              n_layers=32, n_heads=32, n_kv_heads=8,
                              d_ff=14_336, max_seq_len=32_768,
                              rope_theta=10_000.0, sliding_window=4096),
    "mistral_tiny": LlamaConfig(vocab_size=1024, d_model=128,
                                n_layers=2, n_heads=4, n_kv_heads=2,
                                d_ff=352, max_seq_len=512,
                                sliding_window=16),
}


# --------------------------------------------------------------------------- #
# Parameters

def _dense_init(key, shape, dtype, scale=None):
    fan_in = shape[0]
    scale = scale if scale is not None else fan_in ** -0.5
    return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)


def init_params(config: LlamaConfig, key) -> Dict:
    keys = jax.random.split(key, config.n_layers + 3)
    dt = config.dtype
    d, h, kv, hd, f = (config.d_model, config.n_heads, config.n_kv_heads,
                       config.head_dim, config.d_ff)
    layers = []
    for i in range(config.n_layers):
        lk = jax.random.split(keys[i], 8)
        layer = {
            "attn_norm": jnp.ones((d,), dt),
            "wq": _dense_init(lk[0], (d, h * hd), dt),
            "wk": _dense_init(lk[1], (d, kv * hd), dt),
            "wv": _dense_init(lk[2], (d, kv * hd), dt),
            "wo": _dense_init(lk[3], (h * hd, d), dt),
            "mlp_norm": jnp.ones((d,), dt),
        }
        if config.n_experts:
            from .moe import init_moe_params
            layer["moe"] = init_moe_params(config.moe_config, lk[7])
        else:
            layer.update({
                "w_gate": _dense_init(lk[4], (d, f), dt),
                "w_up": _dense_init(lk[5], (d, f), dt),
                "w_down": _dense_init(lk[6], (f, d), dt),
            })
        layers.append(layer)
    return {
        "embed": _dense_init(keys[-3], (config.vocab_size, d), dt, 1.0),
        "layers": layers,
        "final_norm": jnp.ones((d,), dt),
        "lm_head": _dense_init(keys[-2], (d, config.vocab_size), dt),
    }


def param_specs(config: LlamaConfig) -> Dict:
    """PartitionSpecs for tensor parallelism over the "tp" mesh axis
    (megatron-style: column-parallel qkv/gate/up, row-parallel o/down;
    vocab-sharded embedding + head)."""
    layer = {
        "attn_norm": P(),
        "wq": P(None, "tp"), "wk": P(None, "tp"), "wv": P(None, "tp"),
        "wo": P("tp", None),
        "mlp_norm": P(),
    }
    if config.n_experts:
        from .moe import moe_param_specs
        layer["moe"] = moe_param_specs()
    else:
        layer.update({
            "w_gate": P(None, "tp"), "w_up": P(None, "tp"),
            "w_down": P("tp", None),
        })
    return {
        "embed": P("tp", None),
        "layers": [dict(layer) for _ in range(config.n_layers)],
        "final_norm": P(),
        "lm_head": P(None, "tp"),
    }


def quantize_params(params, bits: int = 8) -> Dict:
    """Weight-only quantization of the whole parameter tree (norm
    vectors stay bf16).  ``bits=8``: per-output-channel int8 — halves
    HBM bytes per decode step and fits 8B-class params in one v5e
    chip's 16 GB.  ``bits=4``: nibble-packed int4 with per-128-group
    scales — halves them again (~2× the int8 decode ceiling); the
    embedding stays int8 because its read path is a row gather, and
    gathering packed nibble rows would split bytes."""
    if bits == 4:
        quantized = quantize_tree(params, bits=4)
        quantized["embed"] = quantize_tree(params["embed"])
        return quantized
    return quantize_tree(params)


def quantized_param_specs(config: LlamaConfig, bits: int = 8) -> Dict:
    """PartitionSpecs matching :func:`quantize_params` output.  int8:
    the matrix keeps its dense spec, the (1, out) scales shard with the
    output axis.  int4: packed rows cover contiguous original rows (two
    per byte), so the packed matrix keeps the dense spec; the (G, out)
    group scales shard only on the output axis (G can be smaller than a
    row-parallel mesh axis, and replicated scales cost ~nothing)."""
    def visit(spec):
        if isinstance(spec, P) and len(spec) == 2:
            return {"q4" if bits == 4 else "q": spec,
                    "s": P(None, spec[1])}
        return spec
    specs = jax.tree_util.tree_map(
        visit, param_specs(config),
        is_leaf=lambda x: isinstance(x, P))
    if bits == 4:
        embed = param_specs(config)["embed"]
        specs["embed"] = {"q": embed, "s": P(None, embed[1])}
    if config.n_experts:
        # The 2-D MoE router also quantizes, but its spec is a bare P()
        # (len 0) which the length-2 rule above misses; 3-D expert
        # weights stay dense (quantize_tree only touches ndim==2).
        for layer in specs["layers"]:
            layer["moe"]["router"] = (
                {"q4": P(), "s": P()} if bits == 4 else
                {"q": P(), "s": P()})
    return specs


def random_quantized_params(config: LlamaConfig, key, bits: int = 8) -> Dict:
    """Random quantized params built DIRECTLY in quantized form — a bf16
    llama3_8b (~16 GB) would not fit next to itself in one chip's HBM,
    so the bf16 tree is never materialized.  Structure matches
    ``quantize_params(init_params(config, key), bits)`` exactly:
    int8 → ``{"q": int8 (in, out), "s": f32 (1, out)}``; int4 →
    ``{"q4": int8 (in/2, out) nibble-packed, "s": f32 (in/128, out)}``
    with the embedding kept int8 (row-gather path).  1-D norm vectors
    stay in the model dtype.  Scales are sized so dequantized weights
    look like fan-in-scaled gaussians — activations stay finite through
    all layers.  Used for benchmarking/capacity checks where real
    checkpoint weights are unavailable."""
    if config.n_experts:
        raise NotImplementedError(
            "random_quantized_params covers dense configs; MoE expert "
            "weights are 3-D and stay bf16 under quantize_params")
    c = config
    d, h, kv, hd, f = (c.d_model, c.n_heads, c.n_kv_heads, c.head_dim,
                       c.d_ff)
    counter = iter(range(10_000))

    # On an accelerator, generate ON DEVICE (threefry): host-side
    # numpy would push the whole weight stream through the transfer
    # path (minutes via the axon relay tunnel).  On the CPU backend
    # the device IS the host, and numpy's generator is ~30x faster
    # than threefry on one core — this is what keeps the 70B-geometry
    # dryrun section fast enough for the driver.
    use_numpy = jax.default_backend() == "cpu"
    if use_numpy:
        import numpy as np
        seed_base = int(jax.random.randint(key, (), 0, 2**31 - 1))

    def _randint8(shape, low, high):
        if use_numpy:
            import numpy as np
            rng = np.random.default_rng(seed_base + next(counter))
            return jnp.asarray(
                rng.integers(low, high, shape, np.int8))
        k = jax.random.fold_in(key, next(counter))
        return jax.random.randint(k, shape, low, high, jnp.int8)

    def q8weight(shape):
        q = _randint8(shape, -127, 128)
        s = jnp.full((1, shape[1]), shape[0] ** -0.5 / 127.0, jnp.float32)
        return {"q": q, "s": s}

    def q4weight(shape):
        kin, n = shape
        packed = _randint8((kin // 2, n), -128, 128)
        groups = max(1, kin // 128)
        s = jnp.full((groups, n), kin ** -0.5 / 7.0, jnp.float32)
        return {"q4": packed, "s": s}

    qweight = q4weight if bits == 4 else q8weight
    layers = []
    for _ in range(c.n_layers):
        layers.append({
            "attn_norm": jnp.ones((d,), c.dtype),
            "wq": qweight((d, h * hd)),
            "wk": qweight((d, kv * hd)),
            "wv": qweight((d, kv * hd)),
            "wo": qweight((h * hd, d)),
            "mlp_norm": jnp.ones((d,), c.dtype),
            "w_gate": qweight((d, f)),
            "w_up": qweight((d, f)),
            "w_down": qweight((f, d)),
        })
    return {
        # The embedding read path is a row gather, so it stays int8
        # even at bits=4 (matches quantize_params).
        "embed": q8weight((c.vocab_size, d)),
        "layers": layers,
        "final_norm": jnp.ones((d,), c.dtype),
        "lm_head": qweight((d, c.vocab_size)),
    }


def _matmul(x, w):
    """Dense or int8/int4-quantized matmul, transparently."""
    if is_quantized_int4(w):
        return int4_matmul(x, w["q4"], w["s"])
    if is_quantized(w):
        return int8_matmul(x, w["q"], w["s"])
    return x @ w


# --------------------------------------------------------------------------- #
# Batched multi-adapter LoRA (SLoRA/punica-style serving)
#
# A serving batch where every row may run a DIFFERENT fine-tuned
# adapter: per-layer factors are stacked over a leading adapter axis
# (n_adapters, d_in, r) / (n_adapters, r, d_out) with index 0 reserved
# as the all-zero identity (a base-model row), and each batch row
# gathers its own pair.  The base weight stream — the decode
# bottleneck — is paid ONCE for the whole mixed batch; the rank-r
# delta adds O(r·(d_in+d_out)) per row.  The reference serves exactly
# one model binary per process (its LLM element shells out to one
# Ollama model, examples/llm/elements_llm.py:185-191).

def _lora_delta(x, factors, ids, scale):
    """Per-row low-rank delta: ``x`` (batch, q, d_in) through row
    ``i``'s own (A, B) = (factors["a"][ids[i]], factors["b"][ids[i]]).
    Computed in f32 (rank-r intermediates are tiny) and cast back.

    Two PINNED einsums, never one 3-operand contraction: the rank-r
    hidden ``x@A`` depends only on the replicated inputs, and each
    output column of ``hidden@B`` is an independent dot over r — so a
    TP shard holding a column slice of B computes exactly its slice of
    this delta, bitwise (llama_tp threads the same two einsums with B
    column-sharded; the all-gather is then pure data movement)."""
    a = factors["a"][ids].astype(jnp.float32)     # (batch, d_in, r)
    b = factors["b"][ids].astype(jnp.float32)     # (batch, r, d_out)
    hidden = jnp.einsum("bqd,bdr->bqr", x.astype(jnp.float32), a)
    delta = jnp.einsum("bqr,bro->bqo", hidden, b)
    return (scale * delta).astype(x.dtype)


def _lora_matmul(x, w, lora_layer, target, lora):
    """Base matmul plus the row-gathered adapter delta when ``target``
    is adapted; exactly ``_matmul`` otherwise (and for lora=None the
    call sites skip this entirely — the compiled program is
    unchanged)."""
    out = _matmul(x, w)
    factors = lora_layer.get(target) if lora_layer else None
    if factors is not None:
        out = out + _lora_delta(x, factors, lora["ids"], lora["scale"])
    return out


def _embed_lookup(params, tokens, dtype):
    embed = params["embed"]
    if is_quantized_int4(embed):
        # Packed rows hold vocab rows (2k, 2k+1) in (low, high) nibbles;
        # gather the byte row, then select the token's nibble.
        low, high = _unpack_int4(embed["q4"][tokens // 2])
        q = jnp.where((tokens % 2 == 0)[..., None], low, high)
        group = 2 * embed["q4"].shape[0] // embed["s"].shape[0]
        scale = embed["s"][tokens // group]
        return (q.astype(jnp.float32) * scale).astype(dtype)
    if is_quantized(embed):
        # Gather int8 rows, dequantize with the per-feature scales.
        return (embed["q"][tokens].astype(jnp.float32)
                * embed["s"]).astype(dtype)
    return embed[tokens]


# --------------------------------------------------------------------------- #
# Building blocks

def rms_norm(x, weight, eps):
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * weight


def _rope_freqs(config: LlamaConfig, positions):
    """positions: (batch, seq) int32 → cos/sin (batch, seq, head_dim/2)."""
    dim = config.head_dim
    inv_freq = 1.0 / (config.rope_theta **
                      (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    if config.rope_scaling is not None:
        # Llama-3.1 frequency rescaling: wavelengths beyond the
        # original context are slowed by ``factor``, in-band ones kept,
        # with a smooth ramp between (checkpoints are TRAINED with
        # these frequencies — skipping this garbles long-range heads).
        factor, low_fac, high_fac, original_max = config.rope_scaling
        wavelen = 2.0 * jnp.pi / inv_freq
        low_wavelen = original_max / low_fac
        high_wavelen = original_max / high_fac
        smooth = (original_max / wavelen - low_fac) / (high_fac - low_fac)
        smoothed = ((1.0 - smooth) * inv_freq / factor
                    + smooth * inv_freq)
        inv_freq = jnp.where(
            wavelen > low_wavelen, inv_freq / factor,
            jnp.where(wavelen < high_wavelen, inv_freq, smoothed))
    angles = positions[..., None].astype(jnp.float32) * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin):
    """x: (batch, seq, heads, head_dim); rotate-half convention."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


def _attention_block(layer, config, x, cos, sin, use_flash=True,
                     attention_fn=None):
    """Full-sequence (no-cache) attention block; returns
    (output, (k, v)) with k/v post-rope in (batch, seq, kv, hd) layout
    — callers that don't need them (plain forward) drop the tuple and
    XLA dead-code-eliminates it; the SP-prefill handoff writes them
    into a decode cache.  The cached-decode path lives in
    :func:`_attention_decode_ragged` (single implementation for both
    shared-position and per-row-position decode).  ``attention_fn``
    overrides the attention itself (e.g. ring attention over an sp
    mesh axis); it receives (q, k, v) in (batch, heads, seq, hd)
    layout and must handle GQA."""
    batch, seq, _ = x.shape
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    normed = rms_norm(x, layer["attn_norm"], config.norm_eps)
    q = _matmul(normed, layer["wq"]).reshape(batch, seq, h, hd)
    k = _matmul(normed, layer["wk"]).reshape(batch, seq, kv, hd)
    v = _matmul(normed, layer["wv"]).reshape(batch, seq, kv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    q_t = q.transpose(0, 2, 1, 3)
    k_t = k.transpose(0, 2, 1, 3)
    v_t = v.transpose(0, 2, 1, 3)
    if attention_fn is not None:
        out = attention_fn(q_t, k_t, v_t)
    elif use_flash:
        # flash_attention is GQA-native (no repeated K/V in memory).
        out = flash_attention(q_t, k_t, v_t, causal=True,
                              window=config.sliding_window)
    else:
        group = h // kv
        out = attention_reference(
            q_t, jnp.repeat(k_t, group, axis=1),
            jnp.repeat(v_t, group, axis=1), causal=True,
            window=config.sliding_window)
    out = out.transpose(0, 2, 1, 3)

    out = _matmul(out.reshape(batch, seq, h * hd), layer["wo"])
    return x + out.astype(x.dtype), (k, v)


def _mlp_block(layer, config, x):
    normed = rms_norm(x, layer["mlp_norm"], config.norm_eps)
    if "moe" in layer:
        from .moe import moe_ffn
        return x + moe_ffn(layer["moe"], normed,
                           config.moe_config).astype(x.dtype)
    gate = jax.nn.silu(_matmul(normed, layer["w_gate"]).astype(jnp.float32))
    up = _matmul(normed, layer["w_up"]).astype(jnp.float32)
    return x + _matmul((gate * up).astype(x.dtype), layer["w_down"])


# --------------------------------------------------------------------------- #
# Entry points

@functools.partial(jax.jit, static_argnames=("config", "use_flash"))
def forward(params, tokens, config: LlamaConfig, use_flash: bool = True):
    """Full-sequence forward (training / prefill-style): tokens
    (batch, seq) int32 → logits (batch, seq, vocab) f32."""
    batch, seq = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(seq), (batch, seq))
    cos, sin = _rope_freqs(config, positions)
    x = _embed_lookup(params, tokens, config.dtype)
    for layer in params["layers"]:
        x, _ = _attention_block(layer, config, x, cos, sin,
                                use_flash=use_flash)
        x = _mlp_block(layer, config, x)
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    return _matmul(x, params["lm_head"]).astype(jnp.float32)


@functools.partial(jax.jit,
                   static_argnames=("config", "mesh", "attention"))
def forward_sequence_parallel(params, tokens, config: LlamaConfig,
                              mesh, attention: str = "ring"):
    """Full-sequence forward with attention sharded over the ``sp``
    mesh axis — the long-context path, exact vs :func:`forward`.
    Sequence length must divide by the sp size.  Everything OUTSIDE
    attention (projections, MLP, norms) is local to each sequence
    shard, so XLA keeps those fully parallel with no collectives.

    ``attention="ring"``: K/V shards rotate around the ICI ring
    (GQA-native — only kv heads move); per-device attention memory
    O(seq/sp).  ``attention="ulysses"``: one all-to-all swaps the
    shard dimension from sequence to heads and back — fewer, larger
    collectives (MXU-friendly dense local attention) but needs
    ``n_heads % sp == 0`` and materializes the full sequence per head
    group (K/V repeated to the full head count first).

    Sliding-window (Mistral-class) configs compose with both:  the ring
    masks by global position and skips shards entirely below the
    window (windowed long-context prefill cost O(seq·window/sp));
    Ulysses holds the full sequence locally after the head scatter, so
    plain windowed masking is globally correct."""
    attention_fn = _sp_attention_fn(config, mesh, attention,
                                    tokens.shape[1])
    batch, seq = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(seq), (batch, seq))
    cos, sin = _rope_freqs(config, positions)
    x = _embed_lookup(params, tokens, config.dtype)
    for layer in params["layers"]:
        x, _ = _attention_block(layer, config, x, cos, sin,
                                attention_fn=attention_fn)
        x = _mlp_block(layer, config, x)
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    return _matmul(x, params["lm_head"]).astype(jnp.float32)


def _sp_attention_fn(config: LlamaConfig, mesh, attention: str,
                     seq_len: int):
    """Validate the sp mesh/config combination and build the
    sequence-parallel attention closure shared by
    :func:`forward_sequence_parallel` and
    :func:`prefill_sequence_parallel`."""
    if "sp" not in mesh.axis_names:
        raise ValueError(
            f"mesh has no 'sp' axis (axes: {mesh.axis_names}) — build "
            "it with make_mesh(sp=...)")
    sp = mesh.shape["sp"]
    if seq_len % sp:
        raise ValueError(
            f"sequence length {seq_len} must divide by the sp "
            f"mesh size {sp}")
    from ..parallel.ring_attention import ring_attention_sharded

    if attention == "ring":
        def ring(q_t, k_t, v_t):
            # ring_attention is GQA-native: only the kv heads rotate.
            return ring_attention_sharded(q_t, k_t, v_t, mesh,
                                          causal=True,
                                          window=config.sliding_window)
        attention_fn = ring
    elif attention == "ulysses":
        from ..parallel.ulysses import ulysses_attention_sharded
        if config.n_heads % sp:
            raise ValueError(
                f"ulysses needs n_heads ({config.n_heads}) divisible "
                f"by the sp mesh size ({sp})")
        group = config.n_heads // config.n_kv_heads
        kv_divides = config.n_kv_heads % sp == 0
        if group > 1 and not kv_divides:
            # Trace-time, so it fires once per compile, not per step.
            import warnings
            warnings.warn(
                f"Ulysses GQA fallback: n_kv_heads "
                f"({config.n_kv_heads}) % sp ({sp}) != 0, so K/V are "
                f"repeated x{group} BEFORE the all-to-all — K/V "
                f"collective bytes multiply by {group}.  Prefer "
                f"sp <= n_kv_heads (or ring attention) for this "
                "config.", stacklevel=2)

        def ulysses(q_t, k_t, v_t):
            if group > 1 and not kv_divides:
                # Head-scatter needs a divisible head count; repeating
                # BEFORE the all-to-all multiplies K/V collective
                # bytes by `group` — only the fallback when the kv
                # heads cannot be scattered directly.
                k_t = jnp.repeat(k_t, group, axis=1)
                v_t = jnp.repeat(v_t, group, axis=1)
            return ulysses_attention_sharded(
                q_t, k_t, v_t, mesh, window=config.sliding_window)
        attention_fn = ulysses
    else:
        raise ValueError(f"unknown attention {attention!r} "
                         "(ring | ulysses)")
    return attention_fn


@functools.partial(jax.jit,
                   static_argnames=("config", "mesh", "attention"),
                   donate_argnames=("cache",))
def prefill_sequence_parallel(params, tokens, cache,
                              config: LlamaConfig, mesh,
                              attention: str = "ring"):
    """SP-prefill → decode handoff: prefill a long prompt with
    attention sharded over the ``sp`` mesh axis (ring or Ulysses, as
    :func:`forward_sequence_parallel`), writing each layer's K/V into a
    standard decode cache.  The cache keeps whatever sharding it was
    created with (typically replicated / single-chip), so XLA inserts
    the sequence all-gather at the slab write — after this returns,
    :func:`generate_tokens` / :func:`decode_step` continue decoding
    from ``start_index = seq`` on a single chip (or any decode
    topology), which is how long-context serving actually runs: SP for
    the O(seq²) prefill, plain cached decode for the O(seq) tail.

    Rolling caches compose: the slab write keeps the last ``window``
    rows.  Returns (last-position logits (batch, vocab), cache)."""
    attention_fn = _sp_attention_fn(config, mesh, attention,
                                    tokens.shape[1])
    batch, seq = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(seq), (batch, seq))
    cos, sin = _rope_freqs(config, positions)
    x = _embed_lookup(params, tokens, config.dtype)
    new_cache = []
    for layer, cache_layer in zip(params["layers"], cache):
        x, (k, v) = _attention_block(layer, config, x, cos, sin,
                                     attention_fn=attention_fn)
        new_cache.append(_cache_write_slab(cache_layer, k, v, 0))
        x = _mlp_block(layer, config, x)
    x = rms_norm(x[:, -1], params["final_norm"], config.norm_eps)
    logits = _matmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


def init_cache(config: LlamaConfig, batch: int,
               max_seq: Optional[int] = None,
               quantize_kv: bool = False,
               rolling: bool = False) -> list:
    """KV cache: list (one per layer) of dicts.  ``quantize_kv`` stores
    K/V as int8 with per-(token, kv-head) f32 scales — halves KV bytes
    per decode step AND cache HBM footprint, which is what bounds batch
    (and therefore throughput) at long context.  ``rolling`` (requires
    ``config.sliding_window``) keeps only the last ``window`` rows in a
    ring buffer — row ``pos % window`` — with each row's ABSOLUTE
    position stored for masking, so sliding-window decode memory is
    O(window) instead of O(max_seq).  Single-token decode paths
    (decode_step, generate_tokens) handle any layout;
    :func:`prefill_chunk` rejects rolling caches for chunk length > 1
    (pre-attention slab writes can evict ring rows still inside earlier
    chunk queries' windows), and :func:`decode_chunk_ragged`'s
    slot-scratch trick is incompatible with rolling and rejects it."""
    if rolling:
        if not config.sliding_window:
            raise ValueError("rolling cache requires sliding_window")
        rows = config.sliding_window
    else:
        rows = max_seq or config.max_seq_len
    cache = _kv_layer_buffers(
        config, (batch, rows, config.n_kv_heads, config.head_dim),
        quantize_kv)
    if rolling:
        for layer in cache:
            # -1 = "row never written": masked out by the position test.
            layer["pos"] = jnp.full((batch, rows), -1, jnp.int32)
    return cache


def _kv_layer_buffers(config: LlamaConfig, shape, quantize_kv: bool):
    """Per-layer KV buffer dicts — the ONE place the cache layout
    (dtypes, scale keys) is defined; the contiguous cache and the
    paged pool differ only in the shape they pass."""
    if quantize_kv:
        sshape = shape[:-1]
        return [{"k": jnp.zeros(shape, jnp.int8),
                 "v": jnp.zeros(shape, jnp.int8),
                 "ks": jnp.ones(sshape, jnp.float32),
                 "vs": jnp.ones(sshape, jnp.float32)}
                for _ in range(config.n_layers)]
    return [{"k": jnp.zeros(shape, config.dtype),
             "v": jnp.zeros(shape, config.dtype)}
            for _ in range(config.n_layers)]


def _kv_quantize(rows):
    """(…, hd) bf16 → (int8 rows, f32 scales (…,)) — symmetric absmax
    per vector (one scale per cached token per kv head)."""
    r32 = rows.astype(jnp.float32)
    amax = jnp.max(jnp.abs(r32), axis=-1)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(r32 / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _quantize_pairs(cache_layer, k, v):
    """(key → source) map for a write: k/v plus int8 scales when the
    layer is quantized."""
    if "ks" in cache_layer:
        kq, ks = _kv_quantize(k)
        vq, vs = _kv_quantize(v)
        return {"k": kq, "v": vq, "ks": ks, "vs": vs}
    return {"k": k, "v": v}


def _cache_write_slab(cache_layer, k, v, start_index):
    """Write a contiguous (batch, K, kv, hd) slab at ``start_index``
    (prefill / chunked-prefill path), any layout.  Rolling layout: only
    the last ``window`` slab rows can survive, so just those are
    scattered at ``pos % window`` (unique targets) and their absolute
    positions recorded."""
    if "pos" in cache_layer:
        window = cache_layer["pos"].shape[1]
        seq = k.shape[1]
        effective = min(seq, window)
        positions = start_index + jnp.arange(seq)[-effective:]
        rows = positions % window
        updated = {}
        for key, src in _quantize_pairs(cache_layer, k[:, -effective:],
                                        v[:, -effective:]).items():
            buf = cache_layer[key]
            updated[key] = buf.at[:, rows].set(src.astype(buf.dtype))
        updated["pos"] = cache_layer["pos"].at[:, rows].set(positions)
        return updated

    def dus(dst, src, start):
        zeros = (0,) * (dst.ndim - 2)
        return jax.lax.dynamic_update_slice(
            dst, src.astype(dst.dtype), (0, start) + zeros)
    return {key: dus(cache_layer[key], src, start_index)
            for key, src in _quantize_pairs(cache_layer, k, v).items()}


def _cache_write_rows(cache_layer, k, v, positions):
    """Write one (batch, 1, kv, hd) row per batch element at per-row
    ``positions`` (ragged decode path), any layout.  vmapped
    dynamic_update_slice lowers to an in-place scatter under
    donation."""
    window = cache_layer["pos"].shape[1] if "pos" in cache_layer else None
    rows = positions % window if window else positions

    def write_row(buf_rows, new, row):
        zeros = (0,) * (buf_rows.ndim - 1)
        return jax.lax.dynamic_update_slice(
            buf_rows, new.astype(buf_rows.dtype), (row,) + zeros)
    write = jax.vmap(write_row)
    updated = {key: write(cache_layer[key], src, rows)
               for key, src in _quantize_pairs(cache_layer, k, v).items()}
    if window:
        updated["pos"] = write(cache_layer["pos"],
                               positions[:, None].astype(jnp.int32),
                               rows)
    return updated


@functools.partial(jax.jit, static_argnames=("config",),
                   donate_argnames=("cache",))
def prefill(params, tokens, cache, config: LlamaConfig, lora=None):
    """Run the prompt through the model filling the KV cache; returns
    (logits_last, cache).  The input cache is DONATED (every caller
    rebinds it): without aliasing, the empty input cache and the
    filled output cache are simultaneously resident, doubling KV
    footprint exactly when prefill peaks — hardware-observed
    RESOURCE_EXHAUSTED for 8B int8 + int8-KV at batch 256 (r04),
    which fits comfortably once donated.  ``lora``: optional batched
    per-row adapters (see :func:`_decode_core_ragged`) — admission
    prefill must apply the SAME adapter the decode chunks will, or
    the prompt KV would be base-model state."""
    batch, seq = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(seq), (batch, seq))
    cos, sin = _rope_freqs(config, positions)
    x = _embed_lookup(params, tokens, config.dtype)
    new_cache = []
    lora_layers = lora["layers"] if lora else [None] * len(cache)
    for layer, cache_layer, lora_layer in zip(params["layers"], cache,
                                              lora_layers):
        normed = rms_norm(x, layer["attn_norm"], config.norm_eps)
        h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
        q = _lora_matmul(normed, layer["wq"], lora_layer, "wq",
                         lora).reshape(batch, seq, h, hd)
        k = _lora_matmul(normed, layer["wk"], lora_layer, "wk",
                         lora).reshape(batch, seq, kv, hd)
        v = _lora_matmul(normed, layer["wv"], lora_layer, "wv",
                         lora).reshape(batch, seq, kv, hd)
        q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
        new_cache.append(_cache_write_slab(cache_layer, k, v, 0))
        q_t = q.transpose(0, 2, 1, 3)
        k_t = k.transpose(0, 2, 1, 3)
        v_t = v.transpose(0, 2, 1, 3)
        out = flash_attention(q_t, k_t, v_t, causal=True,
                              window=config.sliding_window)
        out = out.transpose(0, 2, 1, 3).reshape(batch, seq, h * hd)
        x = x + _lora_matmul(out, layer["wo"], lora_layer, "wo",
                             lora).astype(x.dtype)
        x = _mlp_block(layer, config, x)
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    logits = _matmul(x[:, -1:], params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


# --------------------------------------------------------------------------- #
# Paged KV cache (vLLM-style block pool)
#
# The contiguous cache reserves ``slots x max_seq`` rows up front; a
# paged pool sizes HBM to the tokens actually LIVE (requests rarely all
# run at max length), so a serving replica admits more concurrent
# requests per GB.  Layout per layer: pool (n_blocks, block_size, kv,
# hd); each slot owns a block table (max_blocks,) of pool indices.
# Block 0 is reserved scratch: unallocated table entries and inactive
# slots point there, and it is never attendable (masking is by absolute
# position, and live positions always map to allocated blocks).

def init_paged_cache(config: LlamaConfig, n_blocks: int,
                     block_size: int = 16,
                     quantize_kv: bool = False) -> list:
    """Block pool, one dict per layer.  ``n_blocks`` INCLUDES the
    reserved scratch block 0."""
    return _kv_layer_buffers(
        config,
        (n_blocks, block_size, config.n_kv_heads, config.head_dim),
        quantize_kv)


def _paged_write_rows(pool_layer, k, v, tables, positions):
    """Scatter one (batch, 1, kv, hd) row per slot into the pool at
    (tables[s, pos // bs], pos % bs) — a single batched scatter."""
    block_size = pool_layer["k"].shape[1]
    block_ids = jnp.take_along_axis(
        tables, (positions // block_size)[:, None], axis=1)[:, 0]
    offsets = positions % block_size

    def scatter(pool, rows):
        return pool.at[block_ids, offsets].set(rows.astype(pool.dtype))

    return {key: scatter(pool_layer[key], src)
            for key, src in _quantize_pairs(pool_layer, k[:, 0],
                                            v[:, 0]).items()}


def _paged_gather(pool_layer, tables):
    """Per-slot cache view: pool[tables] → (slots, max_blocks*bs, …) —
    the same layout :func:`_cached_gqa_attention` reads, so paged and
    contiguous attention share ONE implementation.  XLA keeps the pool
    itself compact; the gathered view is a transient."""
    def view(pool):
        gathered = pool[tables]          # (slots, max_blocks, bs, ...)
        slots, max_blocks, block_size = gathered.shape[:3]
        return gathered.reshape((slots, max_blocks * block_size)
                                + gathered.shape[3:])
    return {key: view(buf) for key, buf in pool_layer.items()}


def _paged_write_slab(pool_layer, k, v, tables, positions_b):
    """Scatter a (batch, K, kv, hd) chunk slab into the pool at per-row
    absolute positions — the append-admission reference path: the chunk
    lands straight in its blocks, no bucket cache ever exists."""
    block_size = pool_layer["k"].shape[1]
    block_ids = jnp.take_along_axis(tables, positions_b // block_size,
                                    axis=1)
    offsets = positions_b % block_size

    def scatter(pool, rows):
        return pool.at[block_ids, offsets].set(rows.astype(pool.dtype))

    return {key: scatter(pool_layer[key], src)
            for key, src in _quantize_pairs(pool_layer, k, v).items()}


def _attention_decode_paged(layer, config, x, cos, sin, pool_layer,
                            tables, positions, lora=None,
                            lora_layer=None):
    """Single-token decode against the block pool (per-row positions,
    continuous batching)."""
    batch, seq, _ = x.shape
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    normed = rms_norm(x, layer["attn_norm"], config.norm_eps)
    q = _lora_matmul(normed, layer["wq"], lora_layer, "wq",
                     lora).reshape(batch, seq, h, hd)
    k = _lora_matmul(normed, layer["wk"], lora_layer, "wk",
                     lora).reshape(batch, seq, kv, hd)
    v = _lora_matmul(normed, layer["wv"], lora_layer, "wv",
                     lora).reshape(batch, seq, kv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    new_pool = _paged_write_rows(pool_layer, k, v, tables, positions)
    q_g = q.reshape(batch, seq, kv, h // kv, hd)
    use_kernel, interpret = decode_kernel_mode()
    if use_kernel:
        # The kernel walks the block table directly in HBM — the
        # steady-state decode path never gathers the pool.
        out = paged_decode_attention(
            q_g[:, 0], new_pool["k"], new_pool["v"], tables, positions,
            ks=new_pool.get("ks"), vs=new_pool.get("vs"),
            window=config.sliding_window, interpret=interpret)[:, None]
    else:
        gathered = _paged_gather(new_pool, tables)
        out = _cached_gqa_attention(q_g, gathered, positions[:, None],
                                    hd, window=config.sliding_window)
    out = out.reshape(batch, seq, h * hd)
    return x + _lora_matmul(out, layer["wo"], lora_layer, "wo",
                            lora).astype(x.dtype), new_pool


def _decode_core_paged(params, token, pool, tables, positions,
                       config: LlamaConfig, lora=None):
    positions_2d = positions[:, None]
    cos, sin = _rope_freqs(config, positions_2d)
    x = _embed_lookup(params, token, config.dtype)
    new_pool = []
    lora_layers = lora["layers"] if lora else [None] * len(pool)
    for layer, pool_layer, lora_layer in zip(params["layers"], pool,
                                             lora_layers):
        x, updated = _attention_decode_paged(layer, config, x, cos, sin,
                                             pool_layer, tables,
                                             positions, lora,
                                             lora_layer)
        new_pool.append(updated)
        x = _mlp_block(layer, config, x)
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    logits = _matmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_pool


def _chunk_scan(step_core, tokens, positions, cache_state, active,
                num_steps, temperatures, top_ps, rng_key,
                collect_logits: bool = False):
    """Shared chunk-decode scaffolding for the contiguous and paged
    layouts: per-slot greedy/sampled pick, active-mask token/position
    advance, one ``lax.scan`` over steps.  ``step_core(token,
    cache_state, positions) -> (logits, cache_state)`` supplies the
    layout-specific write/read; everything else (the sampling semantics
    the exactness tests pin down) exists ONCE here.

    ``collect_logits``: also stack each step's next-token logits —
    speculative DRAFT runs need them so acceptance can reconstruct the
    exact proposal distribution (``sampling_probs``)."""
    sampled_mode = temperatures is not None
    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)
    if sampled_mode and top_ps is None:
        top_ps = jnp.ones_like(temperatures)

    def pick(logits, key):
        greedy = logits.argmax(-1).astype(jnp.int32)
        if not sampled_mode:
            return greedy
        sampled = _sample_logits_per_row(logits, key, temperatures,
                                         top_ps)
        return jnp.where(temperatures > 0, sampled, greedy)

    def body(carry, _):
        token, positions, cache_state, key = carry
        key, step_key = jax.random.split(key)
        logits, cache_state = step_core(token, cache_state, positions)
        next_token = pick(logits[:, -1], step_key)[:, None]
        next_token = jnp.where(active[:, None], next_token, token)
        positions = jnp.where(active, positions + 1, positions)
        ys = (next_token[:, 0], logits[:, -1]) if collect_logits \
            else next_token[:, 0]
        return (next_token, positions, cache_state, key), ys

    (token, positions, cache_state, _), ys = jax.lax.scan(
        body, (tokens, positions, cache_state, rng_key), None,
        length=num_steps)
    if collect_logits:
        tokens_out, step_logits = ys
        # (steps, slots, vocab) -> (slots, steps, vocab)
        return (tokens_out.T, step_logits.transpose(1, 0, 2), token,
                positions, cache_state)
    return ys.T, token, positions, cache_state


@functools.partial(jax.jit,
                   static_argnames=("config", "num_steps",
                                    "return_logits"),
                   donate_argnames=("pool",))
def decode_chunk_paged(params, tokens, pool, tables, positions, active,
                       num_steps, config: LlamaConfig,
                       temperatures=None, top_ps=None, rng_key=None,
                       lora=None, return_logits: bool = False):
    """Paged twin of :func:`decode_chunk_ragged`: one compiled scan of
    ``num_steps`` steps over the block pool.  Inactive slots write into
    scratch block 0 at their slot offset (blocked from live tables by
    the allocator) and do not advance.

    Returns (tokens_out (slots, num_steps), last_token, positions,
    pool) — ``return_logits=True`` inserts the per-step next-token
    logits after ``tokens_out``, same contract as
    :func:`decode_chunk_ragged` (paged DRAFT runs for speculative
    serving)."""
    block_size = pool[0]["k"].shape[1]
    slots = tokens.shape[0]
    scratch_tables = jnp.zeros_like(tables)
    scratch_positions = (jnp.arange(slots, dtype=jnp.int32)
                         % block_size)

    def step_core(token, pool, positions):
        write_tables = jnp.where(active[:, None], tables,
                                 scratch_tables)
        write_pos = jnp.where(active, positions, scratch_positions)
        return _decode_core_paged(params, token, pool, write_tables,
                                  write_pos, config, lora=lora)

    return _chunk_scan(step_core, tokens, positions, pool, active,
                       num_steps, temperatures, top_ps, rng_key,
                       collect_logits=return_logits)


@functools.partial(jax.jit, donate_argnames=("pool",))
def paged_insert_prefix(pool, tables, prefix_cache, slot):
    """Copy a contiguous prefilled cache (1, padded, kv, hd per layer;
    same quantize_kv layout as the pool) into ``slot``'s allocated
    blocks.  ``tables`` (slots, max_blocks); padded must be a multiple
    of the pool block size."""
    block_size = pool[0]["k"].shape[1]
    padded = prefix_cache[0]["k"].shape[1]
    n_blocks = padded // block_size
    block_ids = jax.lax.dynamic_slice_in_dim(
        tables[slot], 0, n_blocks, 0)
    return paged_scatter_blocks(pool, block_ids, prefix_cache,
                                jnp.int32(0))


@functools.partial(jax.jit, donate_argnames=("pool",))
def paged_scatter_blocks(pool, block_ids, prefix_cache, start_block):
    """Write contiguous prefilled rows into explicit pool blocks:
    prefix rows ``[start_block*bs, (start_block+len(ids))*bs)`` land in
    ``pool[block_ids]`` (prefix-cache tail insertion writes ONLY the
    private tail blocks; shared prefix blocks are never touched)."""
    block_size = pool[0]["k"].shape[1]
    n_blocks = block_ids.shape[0]
    new_pool = []
    for pool_layer, prefix_layer in zip(pool, prefix_cache):
        padded = prefix_layer["k"].shape[1]
        updated = {}
        for key, buf in pool_layer.items():
            src = prefix_layer[key][0]
            blocked = src.reshape((padded // block_size, block_size)
                                  + src.shape[1:]).astype(buf.dtype)
            sel = jax.lax.dynamic_slice_in_dim(blocked, start_block,
                                               n_blocks, 0)
            updated[key] = buf.at[block_ids].set(sel)
        new_pool.append(updated)
    return new_pool


@functools.partial(jax.jit, donate_argnames=("bucket",))
def paged_gather_blocks(pool, block_ids, bucket, start_block=None):
    """Read ``pool[block_ids]`` into ``len(ids)*bs`` contiguous rows of
    a bucket cache starting at block ``start_block`` (prefix-cache
    admission: materialize the shared prefix so the tail's chunked
    prefill can attend over it).  ``start_block`` is TRACED (default 0)
    so a long shared prefix can be gathered in a handful of
    power-of-two sub-gathers without compiling one program per prefix
    length."""
    block_size = pool[0]["k"].shape[1]
    rows = block_ids.shape[0] * block_size
    start_row = (jnp.int32(0) if start_block is None
                 else start_block.astype(jnp.int32) * block_size)
    new_bucket = []
    for pool_layer, bucket_layer in zip(pool, bucket):
        updated = {}
        for key, buf in bucket_layer.items():
            src = pool_layer[key][block_ids]
            flat = src.reshape((rows,) + src.shape[2:])
            starts = (jnp.int32(0), start_row) + (jnp.int32(0),) * (
                buf.ndim - 2)
            updated[key] = jax.lax.dynamic_update_slice(
                buf, flat[None].astype(buf.dtype), starts)
        new_bucket.append(updated)
    return new_bucket


def _decode_core(params, token, cache, cache_index, config: LlamaConfig):
    """One autoregressive step (traceable core): token (batch, 1) +
    shared cache position → (logits (batch, 1, vocab), new_cache).

    Delegates to the ragged (per-row-position) core with a constant
    position vector, so the plain and continuous-batching decode paths
    are ONE implementation (their exact equivalence is what the
    continuous-batching tests assert)."""
    batch = token.shape[0]
    positions = jnp.full((batch,), cache_index, jnp.int32)
    return _decode_core_ragged(params, token, cache, positions, config)


decode_step = functools.partial(jax.jit, static_argnames=("config",),
                                donate_argnames=("cache",))(_decode_core)


# Masked GQA attention over a KV cache — the ONE jnp implementation
# shared by ragged decode (CPU fallback), chunked prefill, and
# speculative verify.  Lives in ops/paged_attention.py next to the
# Pallas decode kernel it is the oracle for; the int8-KV path
# dequantizes one span at a time (the kv8 per-step full-cache-copy
# regression fix).
_cached_gqa_attention = cached_gqa_attention


def _decode_attention_contiguous(q_g, cache_layer, positions, hd,
                                 window):
    """Single-token ragged decode attention over a CONTIGUOUS cache:
    dispatch to the Pallas paged-decode kernel (the cache reshaped to a
    degenerate block pool — a free reshape — with iota block tables) on
    TPU, else the jnp oracle.  Rolling caches always take the oracle
    (ring rows need the stored-position mask)."""
    use_kernel, interpret = decode_kernel_mode()
    max_seq = cache_layer["k"].shape[1]
    block_size = contiguous_block_size(max_seq)
    if not use_kernel or not block_size or "pos" in cache_layer:
        return cached_gqa_attention(q_g, cache_layer,
                                    positions[:, None], hd,
                                    window=window)
    batch = q_g.shape[0]
    blocks_per_row = max_seq // block_size
    tables = (jnp.arange(batch, dtype=jnp.int32)[:, None]
              * blocks_per_row
              + jnp.arange(blocks_per_row, dtype=jnp.int32)[None, :])
    pool = {key: buf.reshape((batch * blocks_per_row, block_size)
                             + buf.shape[2:])
            for key, buf in cache_layer.items()}
    out = paged_decode_attention(
        q_g[:, 0], pool["k"], pool["v"], tables, positions,
        ks=pool.get("ks"), vs=pool.get("vs"), window=window,
        interpret=interpret)
    return out[:, None]


def _attention_decode_ragged(layer, config, x, cos, sin, cache_layer,
                             positions, lora=None, lora_layer=None):
    """Single-token decode where every batch row sits at its OWN cache
    position (continuous batching: slots admit/finish independently).
    ``x`` (batch, 1, d), ``positions`` (batch,) int32.  ``lora``:
    optional per-row batched adapters (see :func:`_lora_delta`)."""
    batch, seq, _ = x.shape
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    normed = rms_norm(x, layer["attn_norm"], config.norm_eps)
    q = _lora_matmul(normed, layer["wq"], lora_layer, "wq",
                     lora).reshape(batch, seq, h, hd)
    k = _lora_matmul(normed, layer["wk"], lora_layer, "wk",
                     lora).reshape(batch, seq, kv, hd)
    v = _lora_matmul(normed, layer["wv"], lora_layer, "wv",
                     lora).reshape(batch, seq, kv, hd)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    new_cache = _cache_write_rows(cache_layer, k, v, positions)

    group = h // kv
    q_g = q.reshape(batch, seq, kv, group, hd)
    out = _decode_attention_contiguous(q_g, new_cache, positions, hd,
                                       config.sliding_window)
    out = out.reshape(batch, seq, h * hd)
    return x + _lora_matmul(out, layer["wo"], lora_layer, "wo",
                            lora).astype(x.dtype), new_cache


def _decode_core_ragged(params, token, cache, positions,
                        config: LlamaConfig, lora=None):
    """One autoregressive step with PER-ROW cache positions: token
    (batch, 1) + positions (batch,) → (logits (batch, 1, vocab),
    new_cache).  ``lora``: optional batched per-row adapters —
    ``{"ids": (batch,), "scale": float, "layers": [per-layer
    {target: {"a": (n, d_in, r), "b": (n, r, d_out)}}]}``."""
    positions_2d = positions[:, None]
    cos, sin = _rope_freqs(config, positions_2d)
    x = _embed_lookup(params, token, config.dtype)
    new_cache = []
    lora_layers = lora["layers"] if lora else [None] * len(cache)
    for layer, cache_layer, lora_layer in zip(params["layers"], cache,
                                              lora_layers):
        x, updated = _attention_decode_ragged(layer, config, x, cos,
                                              sin, cache_layer,
                                              positions, lora,
                                              lora_layer)
        new_cache.append(updated)
        x = _mlp_block(layer, config, x)
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    logits = _matmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


def _cache_write_ragged_slab(cache_layer, k, v, starts):
    """Write a (batch, K, kv, hd) slab at PER-ROW start positions
    (speculative verify inside continuous batching: each slot scores
    its drafted tokens at its OWN absolute position).  Contiguous
    layouts only (rolling is rejected by the caller)."""
    def write(buf_rows, new, start):
        zeros = (0,) * (buf_rows.ndim - 1)
        return jax.lax.dynamic_update_slice(
            buf_rows, new.astype(buf_rows.dtype), (start,) + zeros)

    write = jax.vmap(write)
    return {key: write(cache_layer[key], src, starts)
            for key, src in _quantize_pairs(cache_layer, k, v).items()}


@functools.partial(jax.jit, static_argnames=("config",),
                   donate_argnames=("cache",))
def verify_chunk_ragged(params, tokens, cache, positions, active,
                        config: LlamaConfig, lora=None):
    """Teacher-forced scoring of K given tokens per slot, every row at
    its OWN absolute start position — the speculative-verification
    twin of :func:`prefill_chunk` for the continuous-batching slot
    layout.  ``tokens`` (batch, K) int32, ``positions`` (batch,)
    absolute position of tokens[:, 0].  Returns (logits (batch, K,
    vocab) — ``logits[:, j]`` predicts position ``positions + j + 1``
    — and the cache with the K rows written per slot).

    Inactive slots write their slab at row 0 of their OWN slot rows —
    slot isolation makes those rows garbage-tolerant, and admission's
    bucket prefill rewrites ``[0, padded)`` before the slot ever
    decodes (callers keep K ≤ the bucket floor).  Stale rows past a
    rejected proposal are unattendable by the absolute-position mask
    until rewritten (the module-wide invariant)."""
    if cache and "pos" in cache[0]:
        raise ValueError(
            "verify_chunk_ragged does not support rolling caches")
    starts = jnp.where(active, positions, 0)
    positions_b = starts[:, None] + jnp.arange(tokens.shape[1])[None]
    return _chunk_forward(
        params, tokens, cache, positions_b,
        lambda cache_layer, k, v: _cache_write_ragged_slab(
            cache_layer, k, v, starts),
        config, lora)


def _chunk_forward(params, tokens, cache, positions_b, cache_write,
                   config: LlamaConfig, lora):
    """The ONE transformer stack for chunked forwards over an existing
    cache — :func:`prefill_chunk` (scalar start) and
    :func:`verify_chunk_ragged` (per-row starts) differ only in how
    positions are built and how the K new rows are written
    (``cache_write(cache_layer, k, v) -> layer_cache``)."""
    batch, K = tokens.shape
    cos, sin = _rope_freqs(config, positions_b)
    x = _embed_lookup(params, tokens, config.dtype)
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    new_cache = []
    lora_layers = lora["layers"] if lora else [None] * len(cache)
    for layer, cache_layer, lora_layer in zip(params["layers"], cache,
                                              lora_layers):
        normed = rms_norm(x, layer["attn_norm"], config.norm_eps)
        q = _lora_matmul(normed, layer["wq"], lora_layer, "wq",
                         lora).reshape(batch, K, h, hd)
        k = _lora_matmul(normed, layer["wk"], lora_layer, "wk",
                         lora).reshape(batch, K, kv, hd)
        v = _lora_matmul(normed, layer["wv"], lora_layer, "wv",
                         lora).reshape(batch, K, kv, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        layer_cache = cache_write(cache_layer, k, v)
        new_cache.append(layer_cache)
        q_g = q.reshape(batch, K, kv, h // kv, hd)
        out = _cached_gqa_attention(q_g, layer_cache, positions_b, hd,
                                    window=config.sliding_window)
        x = x + _lora_matmul(out.reshape(batch, K, h * hd),
                             layer["wo"], lora_layer, "wo",
                             lora).astype(x.dtype)
        x = _mlp_block(layer, config, x)
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    logits = _matmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


@functools.partial(jax.jit,
                   static_argnames=("config", "num_steps",
                                    "return_logits"),
                   donate_argnames=("cache",))
def decode_chunk_ragged(params, tokens, cache, positions, active,
                        num_steps, config: LlamaConfig,
                        temperatures=None, top_ps=None, rng_key=None,
                        lora=None, return_logits: bool = False):
    """Decode ``num_steps`` tokens for a slot batch where each row has
    its own position and an ``active`` flag — ONE compiled scan (the
    continuous-batching inner loop; admission happens between chunks).
    Inactive rows still flow through the math but their cache writes
    land at position ``max_seq-1`` reserved as scratch and their
    position does not advance.

    Per-slot sampling: ``temperatures``/``top_ps`` are (batch,) vectors
    — a row with temperature 0 stays EXACTLY greedy while its
    neighbors sample (mixed batches; tested).  ``None`` (trace-time)
    compiles the pure-greedy program with no sampling math.

    Not for ROLLING caches: the inactive-slot scratch row (max_seq-1)
    is a live ring row there.  Rolling serves the plain decode path
    (prefill/generate_tokens/decode_step).

    Returns (tokens_out (batch, num_steps), last_token (batch, 1),
    positions (batch,), cache) — with ``return_logits=True``, the
    per-step next-token logits (batch, num_steps, vocab) are inserted
    after ``tokens_out`` (speculative draft runs: acceptance
    reconstructs the exact proposal distribution from them).
    """
    if "pos" in cache[0]:
        raise ValueError(
            "decode_chunk_ragged does not support rolling caches: the "
            "inactive-slot scratch row would land on a live ring row")
    max_seq = cache[0]["k"].shape[1]

    def step_core(token, cache, positions):
        # Inactive slots write into the scratch row so they cannot
        # corrupt a live slot's KV prefix.
        write_pos = jnp.where(active, positions, max_seq - 1)
        return _decode_core_ragged(params, token, cache, write_pos,
                                   config, lora=lora)

    return _chunk_scan(step_core, tokens, positions, cache, active,
                       num_steps, temperatures, top_ps, rng_key,
                       collect_logits=return_logits)


def _serve_scan(step_core, state, cache_state, num_steps, eos_id,
                sampled, rng_key):
    """Device-resident serving scan: like :func:`_chunk_scan` but the
    per-slot state (token/positions/active/remaining) lives in a device
    ``state`` dict and EOS/budget retirement happens IN-JIT, so the
    host never uploads decode state or downloads logits on the steady
    path.  Emit-then-deactivate: the EOS token itself is emitted (the
    host loop's semantics), then the lane goes inactive for the rest of
    the chunk — inactive lanes write scratch and freeze.

    ``step_core(token, cache_state, positions, active)`` supplies the
    layout-specific read/write.  Returns ``(tokens_out (slots, steps),
    counts (slots,), new_state, cache_state)`` where ``counts[s]`` is
    the number of leading entries of ``tokens_out[s]`` actually emitted
    (active only transitions True→False inside a chunk, so emissions
    are a prefix)."""
    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)
    temps, tops = state["temps"], state["tops"]

    def pick(logits, key):
        greedy = logits.argmax(-1).astype(jnp.int32)
        if not sampled:
            return greedy
        drawn = _sample_logits_per_row(logits, key, temps, tops)
        return jnp.where(temps > 0, drawn, greedy)

    def body(carry, _):
        token, positions, active, remaining, cache_state, key = carry
        key, step_key = jax.random.split(key)
        logits, cache_state = step_core(token, cache_state, positions,
                                        active)
        next_token = pick(logits[:, -1], step_key)[:, None]
        next_token = jnp.where(active[:, None], next_token, token)
        emitted = active
        positions = jnp.where(active, positions + 1, positions)
        remaining = jnp.where(active, remaining - 1, remaining)
        if eos_id >= 0:
            hit_eos = next_token[:, 0] == eos_id
        else:
            hit_eos = jnp.zeros_like(active)
        active = active & ~(hit_eos | (remaining <= 0))
        return ((next_token, positions, active, remaining, cache_state,
                 key), (next_token[:, 0], emitted))

    carry = (state["token"], state["positions"], state["active"],
             state["remaining"], cache_state, rng_key)
    (token, positions, active, remaining, cache_state, _), \
        (tokens_out, emits) = jax.lax.scan(body, carry, None,
                                           length=num_steps)
    counts = emits.astype(jnp.int32).sum(axis=0)
    new_state = dict(state, token=token, positions=positions,
                     active=active, remaining=remaining)
    return tokens_out.T, counts, new_state, cache_state


@jax.jit
def scatter_state_rows(state, rows, packet):
    """Compact host→device merge for the serving loop's dirty slots:
    write ``packet`` — the gathered rows of ONLY the slots an
    admission/retirement/sampling-edit actually touched — into
    ``state`` at ``rows``.  Upload cost is O(dirty rows), not
    O(slots): a fleet-sized server admitting one request no longer
    snapshots and re-merges every mirror.

    The caller pads ``rows``/``packet`` to a pow2 bucket by REPEATING
    the last dirty row, so compile shapes stay log-bounded under the
    steady-state-zero-compiles gate; duplicate indices are benign
    because every duplicate carries an identical payload — the scatter
    result is the same whichever write lands last.

    Nothing is donated: the state dict is a small immutable chain the
    host may hold references into (the in-flight ring)."""
    def scatter(dev, host):
        return dev.at[rows].set(host.astype(dev.dtype))
    return jax.tree.map(scatter, state, packet)


@functools.partial(jax.jit,
                   static_argnames=("config", "num_steps", "eos_id",
                                    "sampled"),
                   donate_argnames=("cache",))
def serve_chunk_ragged(params, state, cache, num_steps,
                       config: LlamaConfig, eos_id: int = -1,
                       sampled: bool = False, rng_key=None,
                       lora_shared=None):
    """Device-resident twin of :func:`decode_chunk_ragged` for the
    serving loop: all per-slot decode state (token tail, positions,
    active mask, remaining budget, sampling controls, adapter ids)
    arrives in the device ``state`` dict, EOS/budget retirement runs
    in-jit, and only the tiny ``(tokens_out, counts, state)`` result
    ever needs to cross back to the host.

    ``eos_id`` is STATIC (-1 disables EOS detection); ``sampled``
    statically selects the pure-greedy program when False so greedy
    traffic never pays sampling math.  ``lora_shared`` is the stacked
    adapter factors WITHOUT per-row ids — ids come from
    ``state["adapter_ids"]``, so adapter routing rides the resident
    state instead of a per-chunk upload.

    Only ``cache`` is donated: the state dict stays a small immutable
    chain the host may hold references into (the in-flight ring)."""
    if "pos" in cache[0]:
        raise ValueError(
            "serve_chunk_ragged does not support rolling caches: the "
            "inactive-slot scratch row would land on a live ring row")
    max_seq = cache[0]["k"].shape[1]
    lora = (dict(lora_shared, ids=state["adapter_ids"])
            if lora_shared is not None else None)

    def step_core(token, cache, positions, active):
        write_pos = jnp.where(active, positions, max_seq - 1)
        return _decode_core_ragged(params, token, cache, write_pos,
                                   config, lora=lora)

    return _serve_scan(step_core, state, cache, num_steps, eos_id,
                       sampled, rng_key)


@functools.partial(jax.jit,
                   static_argnames=("config", "num_steps", "eos_id",
                                    "sampled"),
                   donate_argnames=("pool",))
def serve_chunk_paged(params, state, pool, num_steps,
                      config: LlamaConfig, eos_id: int = -1,
                      sampled: bool = False, rng_key=None,
                      lora_shared=None):
    """Paged twin of :func:`serve_chunk_ragged`: block tables are part
    of the resident ``state`` (``state["tables"]``), so table updates
    on admission merge in with the rest of the dirty rows instead of a
    per-run upload.  Inactive lanes write scratch block 0 at their slot
    offset, exactly like :func:`decode_chunk_paged`."""
    block_size = pool[0]["k"].shape[1]
    tables = state["tables"]
    slots = tables.shape[0]
    scratch_tables = jnp.zeros_like(tables)
    scratch_positions = (jnp.arange(slots, dtype=jnp.int32)
                         % block_size)
    lora = (dict(lora_shared, ids=state["adapter_ids"])
            if lora_shared is not None else None)

    def step_core(token, pool, positions, active):
        write_tables = jnp.where(active[:, None], tables,
                                 scratch_tables)
        write_pos = jnp.where(active, positions, scratch_positions)
        return _decode_core_paged(params, token, pool, write_tables,
                                  write_pos, config, lora=lora)

    return _serve_scan(step_core, state, pool, num_steps, eos_id,
                       sampled, rng_key)


def _prefill_append_core(params, tokens, pool, tables, start_index,
                         config: LlamaConfig, lora=None, kv_limit=None,
                         compute_logits: bool = True):
    """Append-attention prefill straight against the block pool: the
    chunk's K/V land in their pool blocks and its queries attend over
    cached prefix blocks + the causally-visible chunk itself — no
    bucket gather, no scatter-back.  All rows share one scalar
    ``start_index`` (the admission loop prefills one request per call;
    ``tables`` is that request's (1, max_blocks) row, or a slot batch
    at a common boundary).

    Kernel dispatch mirrors the decode path
    (:func:`~..ops.paged_prefill.prefill_kernel_mode`); the reference
    dispatch writes the slab in place and attends over the gathered
    pool VIEW — still no bucket cache, so admission semantics are
    identical either way.  ``compute_logits=False`` skips the final
    norm + lm_head: the mixed serving step never reads prefill logits
    (activation seeds the LAST prompt token, so the first decode step
    produces the first output)."""
    batch, K = tokens.shape
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    start_index = jnp.asarray(start_index, jnp.int32)
    positions_b = jnp.broadcast_to(
        start_index + jnp.arange(K, dtype=jnp.int32), (batch, K))
    cached_lens = jnp.broadcast_to(start_index, (batch,))
    chunk_lens = jnp.full((batch,), K, jnp.int32)
    cos, sin = _rope_freqs(config, positions_b)
    x = _embed_lookup(params, tokens, config.dtype)
    use_kernel, interpret = prefill_kernel_mode()
    new_pool = []
    lora_layers = lora["layers"] if lora else [None] * len(pool)
    for layer, pool_layer, lora_layer in zip(params["layers"], pool,
                                             lora_layers):
        normed = rms_norm(x, layer["attn_norm"], config.norm_eps)
        q = _lora_matmul(normed, layer["wq"], lora_layer, "wq",
                         lora).reshape(batch, K, h, hd)
        k = _lora_matmul(normed, layer["wk"], lora_layer, "wk",
                         lora).reshape(batch, K, kv, hd)
        v = _lora_matmul(normed, layer["wv"], lora_layer, "wv",
                         lora).reshape(batch, K, kv, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        q_g = q.reshape(batch, K, kv, h // kv, hd)
        if use_kernel:
            out, pool_layer = paged_prefill_attention(
                q_g, k, v, pool_layer, tables, cached_lens, chunk_lens,
                window=config.sliding_window, interpret=interpret,
                kv_limit=kv_limit)
        else:
            pool_layer = _paged_write_slab(pool_layer, k, v, tables,
                                           positions_b)
            gathered = _paged_gather(pool_layer, tables)
            out = _cached_gqa_attention(q_g, gathered, positions_b, hd,
                                        window=config.sliding_window)
        new_pool.append(pool_layer)
        x = x + _lora_matmul(out.reshape(batch, K, h * hd),
                             layer["wo"], lora_layer, "wo",
                             lora).astype(x.dtype)
        x = _mlp_block(layer, config, x)
    if not compute_logits:
        return None, new_pool
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    logits = _matmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_pool


@functools.partial(jax.jit,
                   static_argnames=("config", "kv_limit",
                                    "compute_logits"),
                   donate_argnames=("pool",))
def prefill_append_paged(params, tokens, pool, tables, start_index,
                         config: LlamaConfig, lora=None,
                         kv_limit=None, compute_logits: bool = True):
    """Admit a (batch, K) prompt chunk into the block pool by append
    attention — the replacement for the gather → contiguous prefill →
    scatter admission chain.  Prefix-cache hits skip straight past the
    shared blocks: pass ``start_index = n_shared * block_size`` and the
    cached blocks are only READ, never materialized into a bucket.

    ``kv_limit`` (static) clips the kernel's block sweep to the
    request's own allocation so short prompts don't pay for the full
    table width; ``tokens`` width must be a multiple of the pool block
    size for the kernel path (the dispatcher falls back to the
    reference slab write otherwise)."""
    return _prefill_append_core(params, tokens, pool, tables,
                                start_index, config, lora=lora,
                                kv_limit=kv_limit,
                                compute_logits=compute_logits)


@functools.partial(jax.jit,
                   static_argnames=("config", "num_steps", "eos_id",
                                    "sampled", "prefill_kv_limit"),
                   donate_argnames=("pool",))
def serve_chunk_mixed(params, state, pool, prefill_tokens, prefill_row,
                      prefill_start, num_steps, config: LlamaConfig,
                      eos_id: int = -1, sampled: bool = False,
                      rng_key=None, lora_shared=None,
                      prefill_kv_limit=None):
    """Sarathi-style mixed step: ONE jitted dispatch that appends a
    chunked-prefill slice for one admitting request and then runs
    ``num_steps`` decode steps for the live slots — prefill no longer
    stalls decode between chunks.

    ``prefill_row`` is a TRACED slot index (the admitting slot's block
    table row and adapter id are dynamically sliced out of the resident
    state), so which slot is prefilling never triggers a recompile —
    only the slice width and ``prefill_kv_limit`` (both shape-bounded
    by the bucket ladder) are static.  The prefilling slot stays
    inactive in ``state`` until its last slice lands, so the decode
    scan treats it as a scratch lane; prefill logits are never
    computed (the activation seed is the last prompt token)."""
    block_size = pool[0]["k"].shape[1]
    tables = state["tables"]
    slots = tables.shape[0]
    prefill_row = jnp.asarray(prefill_row, jnp.int32)
    tables_row = jax.lax.dynamic_slice_in_dim(tables, prefill_row, 1,
                                              axis=0)
    if lora_shared is not None:
        row_ids = jax.lax.dynamic_slice_in_dim(state["adapter_ids"],
                                               prefill_row, 1, axis=0)
        prefill_lora = dict(lora_shared, ids=row_ids)
        lora = dict(lora_shared, ids=state["adapter_ids"])
    else:
        prefill_lora = lora = None
    _, pool = _prefill_append_core(params, prefill_tokens, pool,
                                   tables_row, prefill_start, config,
                                   lora=prefill_lora,
                                   kv_limit=prefill_kv_limit,
                                   compute_logits=False)
    scratch_tables = jnp.zeros_like(tables)
    scratch_positions = (jnp.arange(slots, dtype=jnp.int32)
                         % block_size)

    def step_core(token, pool, positions, active):
        write_tables = jnp.where(active[:, None], tables,
                                 scratch_tables)
        write_pos = jnp.where(active, positions, scratch_positions)
        return _decode_core_paged(params, token, pool, write_tables,
                                  write_pos, config, lora=lora)

    return _serve_scan(step_core, state, pool, num_steps, eos_id,
                       sampled, rng_key)


def _verify_append_core(params, tokens, pool, tables, positions,
                        active, config: LlamaConfig, lora=None,
                        kv_limit=None):
    """Teacher-forced scoring of a (batch, K) speculative window
    straight against the block pool — the paged twin of
    :func:`verify_chunk_ragged`: every row at its OWN absolute start
    position (mid-block starts included), the window's K/V appended
    into table-resolved pool blocks, no gather, no bucket.

    Kernel dispatch mirrors :func:`_prefill_append_core`; the reference
    dispatch writes the slab in place (:func:`_paged_write_slab`, the
    SAME quantizer the decode write path uses, so verify-written rows
    are byte-identical to what plain decode would have written) and
    attends over the gathered pool view.  Inactive rows write scratch
    block 0 (kernel: nothing at all — their programs identity-flush)
    and their logits are garbage the acceptance mask discards."""
    batch, K = tokens.shape
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    starts = jnp.where(active, positions, 0).astype(jnp.int32)
    positions_b = starts[:, None] + jnp.arange(K, dtype=jnp.int32)[None]
    cached_lens = starts
    chunk_lens = jnp.where(active, K, 0).astype(jnp.int32)
    scratch_tables = jnp.zeros_like(tables)
    write_tables = jnp.where(active[:, None], tables, scratch_tables)
    cos, sin = _rope_freqs(config, positions_b)
    x = _embed_lookup(params, tokens, config.dtype)
    use_kernel, interpret = prefill_kernel_mode()
    new_pool = []
    lora_layers = lora["layers"] if lora else [None] * len(pool)
    for layer, pool_layer, lora_layer in zip(params["layers"], pool,
                                             lora_layers):
        normed = rms_norm(x, layer["attn_norm"], config.norm_eps)
        q = _lora_matmul(normed, layer["wq"], lora_layer, "wq",
                         lora).reshape(batch, K, h, hd)
        k = _lora_matmul(normed, layer["wk"], lora_layer, "wk",
                         lora).reshape(batch, K, kv, hd)
        v = _lora_matmul(normed, layer["wv"], lora_layer, "wv",
                         lora).reshape(batch, K, kv, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        q_g = q.reshape(batch, K, kv, h // kv, hd)
        if use_kernel:
            out, pool_layer = paged_verify_attention(
                q_g, k, v, pool_layer, write_tables, cached_lens,
                chunk_lens, window=config.sliding_window,
                interpret=interpret, kv_limit=kv_limit)
        else:
            pool_layer = _paged_write_slab(pool_layer, k, v,
                                           write_tables, positions_b)
            gathered = _paged_gather(pool_layer, write_tables)
            out = _cached_gqa_attention(q_g, gathered, positions_b, hd,
                                        window=config.sliding_window)
        new_pool.append(pool_layer)
        x = x + _lora_matmul(out.reshape(batch, K, h * hd),
                             layer["wo"], lora_layer, "wo",
                             lora).astype(x.dtype)
        x = _mlp_block(layer, config, x)
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    logits = _matmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_pool


@functools.partial(jax.jit,
                   static_argnames=("config", "kv_limit"),
                   donate_argnames=("pool",))
def verify_chunk_paged(params, tokens, pool, tables, positions, active,
                       config: LlamaConfig, lora=None, kv_limit=None):
    """Speculative verify on the PAGED layout: score K tokens per slot
    against the block pool, each row at its own absolute position —
    the pool-backed twin of :func:`verify_chunk_ragged`.  ``tokens``
    (batch, K) int32 windows (seed token + proposals), ``tables`` the
    resident (slots, max_blocks) block tables, ``positions`` (batch,)
    the absolute position of ``tokens[:, 0]``.

    Returns ``(logits (batch, K, vocab), pool)`` — ``logits[:, j]``
    predicts position ``positions + j + 1``.  The window's K/V rows
    land in each slot's own blocks at ``[positions, positions + K)``;
    rejected-tail rows are left stale (unattendable by the absolute-
    position mask until a later round rewrites them — the module-wide
    invariant; the server counts them as ``spec_rollback_blocks``).
    Callers must reserve ``K`` rows of block headroom past the last
    committed position (the paged server's worst-case reservation
    includes ``spec_k + 1``)."""
    return _verify_append_core(params, tokens, pool, tables, positions,
                               active, config, lora=lora,
                               kv_limit=kv_limit)


def _sample_logits_per_row(logits, key, temperatures, top_ps):
    """Per-row temperature + nucleus: :func:`sample_logits` broadcasts
    (B, 1)-shaped controls, so the vector case is the SAME
    implementation (``top_p >= 1`` rows are a numeric no-op; the best
    token is always kept)."""
    return sample_logits(logits, key,
                         temperature=temperatures[:, None],
                         top_p=top_ps[:, None])


def _mask_logits(logits, temperature: float = 1.0, top_k: int = 0,
                 top_p=None):
    """Temperature-scale + top-k/top-p mask ``logits (batch, vocab)``
    — THE truncation implementation: sampling draws from it
    (:func:`sample_logits`) and speculative acceptance computes the
    matching distributions from it (:func:`sampling_probs`), so the
    two can never disagree."""
    logits = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    if isinstance(top_p, (int, float)) and top_p >= 1.0:
        top_p = None                 # trace-time no-op, not a tracer
    if (top_k and top_k > 0) or top_p is not None:
        sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
        if top_k and top_k > 0:
            kth = sorted_desc[:, top_k - 1][:, None]
            logits = jnp.where(logits < kth, -1e30, logits)
            sorted_desc = jnp.where(
                jnp.arange(sorted_desc.shape[-1])[None, :] < top_k,
                sorted_desc, -1e30)
        if top_p is not None:
            probs = jax.nn.softmax(sorted_desc, axis=-1)
            cumulative = jnp.cumsum(probs, axis=-1)
            # Keep the minimal prefix with cumulative mass >= top_p;
            # rank 0 is force-kept so top_p <= 0 degrades to argmax
            # instead of masking every token (uniform garbage).
            cutoff_mask = (cumulative - probs >= top_p) & (
                jnp.arange(sorted_desc.shape[-1])[None, :] > 0)
            # Cutoff = smallest KEPT logit (drop candidates -> +inf so
            # the min ranges over the nucleus only).
            cutoff = jnp.where(cutoff_mask, jnp.inf,
                               sorted_desc).min(axis=-1, keepdims=True)
            logits = jnp.where(logits < cutoff, -1e30, logits)
    return logits


def sample_logits(logits, key, temperature: float = 1.0,
                  top_k: int = 0, top_p=None):
    """Sample token ids from ``logits (batch, vocab)`` with the standard
    serving controls: temperature scaling, top-k truncation, and
    nucleus (top-p) truncation — jit-compatible (static vocab sort, no
    data-dependent shapes).  ``top_k`` must be static (it sizes a
    slice).  ``top_p=None`` (or a static value >= 1) compiles the
    nucleus out entirely; a float < 1 or a TRACED value applies it
    (per-request nucleus without recompiling).  One shared descending
    sort serves both truncations; the best token is always kept."""
    return jax.random.categorical(
        key, _mask_logits(logits, temperature, top_k,
                          top_p)).astype(jnp.int32)


def sampling_probs(logits, temperature: float = 1.0, top_p=None):
    """The EXACT distribution :func:`sample_logits` draws from at
    these controls (batch-shaped temperature/top_p broadcast like the
    per-row sampler): softmax of the same masked, scaled logits."""
    return jax.nn.softmax(_mask_logits(logits, temperature,
                                       top_k=0, top_p=top_p), axis=-1)


@functools.partial(jax.jit,
                   static_argnames=("config", "num_steps", "temperature",
                                    "top_k"),
                   donate_argnames=("cache",))
def generate_tokens(params, first_token, cache, start_index, num_steps,
                    config: LlamaConfig, temperature: float = 0.0,
                    rng_key=None, top_k: int = 0, top_p=None):
    """Greedy (or sampled) decode of ``num_steps`` tokens as ONE compiled
    program (``lax.scan`` over steps) — a single device dispatch instead
    of one per token, which matters both for dispatch overhead and for
    XLA's ability to keep the KV cache resident.

    Returns (tokens (batch, num_steps), cache)."""
    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)

    def body(carry, step):
        token, cache, key = carry
        logits, cache = _decode_core(params, token, cache,
                                     start_index + step, config)
        logits = logits[:, -1]
        if temperature and temperature > 0:
            key, sample_key = jax.random.split(key)
            next_token = sample_logits(logits, sample_key, temperature,
                                       top_k=top_k, top_p=top_p)
        else:
            next_token = logits.argmax(-1).astype(jnp.int32)
        next_token = next_token[:, None]
        return (next_token, cache, key), next_token[:, 0]

    (_, cache, _), tokens = jax.lax.scan(
        body, (first_token, cache, rng_key),
        jnp.arange(num_steps, dtype=jnp.int32))
    return tokens.T, cache   # (batch, num_steps)


@functools.partial(jax.jit,
                   static_argnames=("config", "num_steps"),
                   donate_argnames=("cache",))
def sample_tokens_with_logits(params, first_token, cache, start_index,
                              num_steps, config: LlamaConfig,
                              temperature, rng_key):
    """Sampled decode that ALSO returns each step's logits row — the
    speculative draft primitive: one compiled scan (no per-step host
    round-trips), one (batch, steps, vocab) transfer for the
    acceptance math.  Returns (tokens (batch, steps), logits (batch,
    steps, vocab) f32, cache)."""
    def body(carry, step):
        token, cache, key = carry
        logits, cache = _decode_core(params, token, cache,
                                     start_index + step, config)
        row = logits[:, -1].astype(jnp.float32)
        key, step_key = jax.random.split(key)
        scaled = row / jnp.maximum(temperature, 1e-6)
        next_token = jax.random.categorical(
            step_key, scaled).astype(jnp.int32)
        return (next_token[:, None], cache, key), (next_token, row)

    (_, cache, _), (tokens, rows) = jax.lax.scan(
        body, (first_token, cache, rng_key),
        jnp.arange(num_steps, dtype=jnp.int32))
    return tokens.T, rows.transpose(1, 0, 2), cache


@functools.partial(jax.jit, static_argnames=("config",),
                   donate_argnames=("cache",))
def prefill_chunk(params, tokens, cache, start_index,
                  config: LlamaConfig, lora=None):
    """Chunked prefill: run ``tokens (batch, K)`` through the model at
    absolute positions ``start_index + [0, K)``, extending an EXISTING
    cache prefix.  Returns (logits (batch, K, vocab) — every position,
    not just the last — and the cache).

    Uses: admitting long prompts chunk-by-chunk (continuous batching),
    and speculative-decode verification (score K draft tokens in one
    pass).  Attention masks by ABSOLUTE position (key_pos <= query_pos),
    so stale cache rows beyond the chunk are never attended.

    Rolling (ring-buffer) caches are rejected for chunk length > 1: the
    slab write lands all K rows BEFORE attention runs, so ring rows
    holding positions still inside earlier chunk queries' sliding
    windows would be overwritten (their stored position becomes future
    → masked out) and softmax would silently normalize over missing
    keys.  Feed rolling caches token-by-token (K=1) instead."""
    batch, K = tokens.shape
    if cache and "pos" in cache[0] and K > 1:
        raise ValueError(
            "prefill_chunk does not support rolling caches with chunk "
            "length > 1: the pre-attention slab write can evict ring "
            "rows still inside earlier chunk queries' sliding windows "
            "(silently wrong logits); feed K=1 chunks instead")
    positions = start_index + jnp.arange(K)
    positions_b = jnp.broadcast_to(positions, (batch, K))
    return _chunk_forward(
        params, tokens, cache, positions_b,
        lambda cache_layer, k, v: _cache_write_slab(cache_layer, k, v,
                                                    start_index),
        config, lora)


def stack_pipeline_params(params, config: LlamaConfig, pp: int):
    """Split ``params["layers"]`` into ``pp`` contiguous stage groups and
    stack them ``(pp, per_stage, …)`` — the layout
    :func:`~..parallel.pipeline_parallel.pipeline_apply_sharded` shards
    over the ``pp`` mesh axis.  Do this ONCE and pass the result as
    ``stages=`` for repeated :func:`pipeline_forward` calls; stacking is
    an O(model) copy."""
    from ..parallel.pipeline_parallel import stack_stages
    layers = params["layers"]
    assert len(layers) % pp == 0, (len(layers), pp)
    per_stage = len(layers) // pp
    groups = [stack_stages(layers[s * per_stage:(s + 1) * per_stage])
              for s in range(pp)]
    return stack_stages(groups)


@functools.partial(jax.jit,
                   static_argnames=("config", "mesh", "n_microbatches",
                                    "pp_axis"))
def pipeline_forward(params, tokens, config: LlamaConfig, mesh,
                     n_microbatches: int = 4, pp_axis: str = "pp",
                     stages=None):
    """Full-sequence forward with the transformer layers split into
    GPipe pipeline stages over the ``pp_axis`` mesh axis (embed, final
    norm and LM head stay replicated outside the pipeline; activations
    hop stage-to-stage with ``ppermute`` over ICI).  Numerics match
    :func:`forward` up to bf16 rounding at stage boundaries: the loop
    carry materializes activations in the model dtype each hop, where
    the fused single-program forward may keep excess precision.

    The host-level PP story (reference remote PipelineElements with MQTT
    frame hops) stays for cross-pod boundaries; this is the on-pod
    equivalent inside ONE jitted program.
    """
    from ..parallel.pipeline_parallel import pipeline_apply_sharded
    pp = mesh.shape[pp_axis]
    assert config.n_layers % pp == 0, (config.n_layers, pp)
    per_stage = config.n_layers // pp
    if stages is None:
        # Convenience path: stacks inside the compiled program (an
        # O(model) copy per call) — for repeated calls pre-stack with
        # :func:`stack_pipeline_params` and pass ``stages=``.
        stages = stack_pipeline_params(params, config, pp)

    batch, seq = tokens.shape

    def stage_fn(stage_params, x):
        positions = jnp.broadcast_to(jnp.arange(seq),
                                     (x.shape[0], seq))
        cos, sin = _rope_freqs(config, positions)
        for j in range(per_stage):
            layer = jax.tree.map(lambda leaf: leaf[j], stage_params)
            x, _ = _attention_block(layer, config, x, cos, sin,
                                    use_flash=False)
            x = _mlp_block(layer, config, x)
        return x

    x = _embed_lookup(params, tokens, config.dtype)
    x = pipeline_apply_sharded(stage_fn, stages, x, mesh, axis=pp_axis,
                               n_microbatches=n_microbatches)
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    return _matmul(x, params["lm_head"]).astype(jnp.float32)


def complete(params, prompt_tokens, config: LlamaConfig,
             max_new_tokens: int = 32, temperature: float = 0.0,
             rng_key=None, top_k: int = 0, top_p=None,
             eos_token: Optional[int] = None, quantize_kv: bool = False):
    """Convenience end-to-end completion: prefill + one-scan decode.

    ``prompt_tokens`` (batch, prompt_len) int32 → (batch, <=max_new)
    numpy array of generated token ids (prompt excluded), truncated at
    the first ``eos_token`` per row when given.  This is the API the
    chat elements and the golden-completion tests use against imported
    checkpoints; serving paths keep the explicit prefill/decode calls.
    """
    import numpy as np
    tokens = jnp.asarray(prompt_tokens, jnp.int32)
    batch, prompt_len = tokens.shape
    cache = init_cache(config, batch, prompt_len + max_new_tokens,
                       quantize_kv=quantize_kv)
    logits, cache = prefill(params, tokens, cache, config)
    last = logits[:, -1]
    if temperature and temperature > 0:
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        rng_key, first_key = jax.random.split(rng_key)
        first = sample_logits(last, first_key, temperature,
                              top_k=top_k, top_p=top_p)[:, None]
    else:
        first = last.argmax(-1).astype(jnp.int32)[:, None]
    generated, _ = generate_tokens(
        params, first, cache, jnp.int32(prompt_len),
        max_new_tokens - 1, config, temperature=temperature,
        rng_key=rng_key, top_k=top_k, top_p=top_p)
    out = np.concatenate([np.asarray(first), np.asarray(generated)],
                         axis=1)
    if eos_token is not None:
        rows = []
        for row in out:
            hits = np.nonzero(row == eos_token)[0]
            rows.append(row[:hits[0]] if hits.size else row)
        width = max((len(r) for r in rows), default=0)
        padded = np.full((len(rows), width), eos_token, out.dtype)
        for i, row in enumerate(rows):
            padded[i, :len(row)] = row
        return padded
    return out

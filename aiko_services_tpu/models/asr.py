"""Speech recognition: Whisper-architecture encoder-decoder.

The speech→chat workload (BASELINE.json config 3; the reference calls
WhisperX as an opaque library, ``examples/speech/speech_elements.py``).
Whisper architecture: log-mel spectrogram → 2×conv subsampling →
transformer encoder; transformer decoder with cross-attention generates
text tokens autoregressively.  Pure functional JAX, bf16, sinusoidal
encoder positions, learned decoder positions, scan-based greedy decode.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import attention_reference

__all__ = ["ASRConfig", "init_params", "encode", "decode_greedy",
           "decode_greedy_cached", "log_mel_spectrogram", "CONFIGS"]


@dataclasses.dataclass(frozen=True)
class ASRConfig:
    n_mels: int = 80
    n_audio_ctx: int = 1500       # encoder positions after subsampling
    d_model: int = 384
    n_heads: int = 6
    n_encoder_layers: int = 4
    n_decoder_layers: int = 4
    vocab_size: int = 51_865      # whisper tokenizer size
    n_text_ctx: int = 448
    dtype: Any = jnp.bfloat16


CONFIGS: Dict[str, ASRConfig] = {
    "tiny": ASRConfig(n_mels=20, n_audio_ctx=64, d_model=64, n_heads=2,
                      n_encoder_layers=2, n_decoder_layers=2,
                      vocab_size=512, n_text_ctx=64),
    "whisper_small": ASRConfig(n_mels=80, n_audio_ctx=1500, d_model=768,
                               n_heads=12, n_encoder_layers=12,
                               n_decoder_layers=12),
}


def _dense(key, shape, dtype):
    return (jax.random.normal(key, shape, jnp.float32)
            * shape[0] ** -0.5).astype(dtype)


def _block_params(key, d, dtype, cross: bool):
    keys = jax.random.split(key, 8)
    block = {
        "norm1": jnp.ones((d,), dtype),
        "wqkv": _dense(keys[0], (d, 3 * d), dtype),
        "wo": _dense(keys[1], (d, d), dtype),
        "norm_mlp": jnp.ones((d,), dtype),
        "w1": _dense(keys[2], (d, 4 * d), dtype),
        "w2": _dense(keys[3], (4 * d, d), dtype),
    }
    if cross:
        block.update({
            "norm_cross": jnp.ones((d,), dtype),
            "wq_cross": _dense(keys[4], (d, d), dtype),
            "wkv_cross": _dense(keys[5], (d, 2 * d), dtype),
            "wo_cross": _dense(keys[6], (d, d), dtype),
        })
    return block


def init_params(config: ASRConfig, key) -> Dict:
    keys = jax.random.split(key, 8)
    d, dt = config.d_model, config.dtype
    encoder_layers = [
        _block_params(jax.random.fold_in(keys[0], i), d, dt, cross=False)
        for i in range(config.n_encoder_layers)]
    decoder_layers = [
        _block_params(jax.random.fold_in(keys[1], i), d, dt, cross=True)
        for i in range(config.n_decoder_layers)]
    return {
        "conv1": _dense(keys[2], (3, config.n_mels, d), dt),
        "conv2": _dense(keys[3], (3, d, d), dt),
        "encoder_layers": encoder_layers,
        "encoder_norm": jnp.ones((d,), dt),
        "token_embed": _dense(keys[4], (config.vocab_size, d), dt),
        "pos_embed": _dense(keys[5], (config.n_text_ctx, d), dt),
        "decoder_layers": decoder_layers,
        "decoder_norm": jnp.ones((d,), dt),
    }


from .common import layer_norm as _norm, mha as _mha, gelu_mlp


def _mlp(block, x):
    return gelu_mlp(x, block["norm_mlp"], block["w1"], block["w2"])


def _sinusoid(length, channels):
    position = jnp.arange(length)[:, None].astype(jnp.float32)
    div = jnp.exp(-jnp.log(10000.0)
                  * jnp.arange(0, channels, 2) / channels)
    angles = position * div[None, :]
    embedding = jnp.zeros((length, channels), jnp.float32)
    embedding = embedding.at[:, 0::2].set(jnp.sin(angles))
    embedding = embedding.at[:, 1::2].set(jnp.cos(angles))
    return embedding


def _conv1d(x, w, stride):
    # x: (b, t, c_in), w: (k, c_in, c_out)
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding="SAME",
        dimension_numbers=("NWC", "WIO", "NWC"),
        preferred_element_type=jnp.float32).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("config",))
def encode(params, mel, config: ASRConfig):
    """mel (batch, frames, n_mels) → audio features
    (batch, frames//2, d_model)."""
    x = jax.nn.gelu(_conv1d(mel.astype(config.dtype), params["conv1"], 1)
                    .astype(jnp.float32)).astype(config.dtype)
    x = jax.nn.gelu(_conv1d(x, params["conv2"], 2)
                    .astype(jnp.float32)).astype(config.dtype)
    positions = _sinusoid(x.shape[1], config.d_model)
    x = x + positions[None].astype(x.dtype)
    for block in params["encoder_layers"]:
        normed = _norm(x, block["norm1"])
        x = x + _mha(normed, normed, block["wqkv"], block["wo"],
                     config.n_heads, causal=False)
        x = _mlp(block, x)
    return _norm(x, params["encoder_norm"])


def _decoder_step(params, tokens, audio_features, config: ASRConfig):
    """Full-sequence decoder (teacher-forced or re-run per step)."""
    b, t = tokens.shape
    x = params["token_embed"][tokens] + params["pos_embed"][:t][None]
    for block in params["decoder_layers"]:
        normed = _norm(x, block["norm1"])
        x = x + _mha(normed, normed, block["wqkv"], block["wo"],
                     config.n_heads, causal=True)
        normed = _norm(x, block["norm_cross"])
        x = x + _mha(normed, audio_features, block["wq_cross"],
                     block["wo_cross"], config.n_heads, causal=False,
                     cross=True, wkv=block["wkv_cross"])
        x = _mlp(block, x)
    x = _norm(x, params["decoder_norm"])
    return (x @ params["token_embed"].T).astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("config", "max_tokens"))
def decode_greedy(params, audio_features, config: ASRConfig,
                  max_tokens: int = 32, start_token: int = 1,
                  end_token: int = 2):
    """Greedy transcription as one compiled program: fixed-length scan
    with an is-done latch (XLA-friendly static shapes)."""
    batch = audio_features.shape[0]
    tokens = jnp.full((batch, max_tokens + 1), end_token, jnp.int32)
    tokens = tokens.at[:, 0].set(start_token)

    def body(carry, step):
        tokens, done = carry
        logits = _decoder_step(params, tokens[:, :max_tokens],
                               audio_features, config)
        next_token = logits[jnp.arange(batch), step].argmax(-1) \
            .astype(jnp.int32)
        next_token = jnp.where(done, end_token, next_token)
        done = done | (next_token == end_token)
        tokens = tokens.at[:, step + 1].set(next_token)
        return (tokens, done), ()

    (tokens, _), _ = jax.lax.scan(
        body, (tokens, jnp.zeros((batch,), bool)),
        jnp.arange(max_tokens, dtype=jnp.int32))
    return tokens


@functools.partial(jax.jit, static_argnames=("config", "max_tokens"))
def decode_greedy_cached(params, audio_features, config: ASRConfig,
                         max_tokens: int = 32, start_token: int = 1,
                         end_token: int = 2):
    """KV-cached greedy transcription: same outputs as
    :func:`decode_greedy` (tested), O(T) instead of O(T²) decoder work.

    Two cache ideas: (1) self-attention K/V accumulate per step instead
    of re-running the whole prefix through every layer; (2) the
    cross-attention K/V are projections of the FIXED audio features, so
    they are computed once per layer, not once per step — the dominant
    saving (audio context >> token count)."""
    batch = audio_features.shape[0]
    d, h = config.d_model, config.n_heads
    hd = d // h
    scale = hd ** -0.5
    dt = config.dtype

    # Per-layer fixed cross K/V.
    cross_kv = []
    for block in params["decoder_layers"]:
        kv = (audio_features @ block["wkv_cross"]).reshape(
            batch, -1, 2, h, hd)
        cross_kv.append({"k": kv[:, :, 0], "v": kv[:, :, 1]})
    self_cache = [{"k": jnp.zeros((batch, max_tokens, h, hd), dt),
                   "v": jnp.zeros((batch, max_tokens, h, hd), dt)}
                  for _ in params["decoder_layers"]]

    def attend(q, k_cache, v_cache, step=None):
        """q (b, 1, h, hd) over cached keys; mask rows > step when
        given (self-attn); full attention when step is None (cross —
        delegated to the shared attention_reference so numerics fixes
        in ops/attention.py apply here too)."""
        if step is None:
            out = attention_reference(
                q.transpose(0, 2, 1, 3), k_cache.transpose(0, 2, 1, 3),
                v_cache.transpose(0, 2, 1, 3), causal=False)
            return out.transpose(0, 2, 1, 3).reshape(batch, 1, d)
        s = jnp.einsum("bqhd,bshd->bhqs", q, k_cache,
                       preferred_element_type=jnp.float32) * scale
        valid = jnp.arange(k_cache.shape[1]) <= step
        s = jnp.where(valid[None, None, None, :], s, -1e30)
        weights = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqs,bshd->bqhd",
                         weights.astype(v_cache.dtype), v_cache)
        return out.reshape(batch, 1, d)

    def body(carry, step):
        token, done, caches = carry
        x = (params["token_embed"][token][:, None]
             + jax.lax.dynamic_slice_in_dim(params["pos_embed"], step,
                                            1)[None]).astype(dt)
        new_caches = []
        for block, cache, fixed in zip(params["decoder_layers"], caches,
                                       cross_kv):
            normed = _norm(x, block["norm1"])
            qkv = (normed @ block["wqkv"]).reshape(batch, 1, 3, h, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            k_cache = jax.lax.dynamic_update_slice(
                cache["k"], k.astype(dt), (0, step, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                cache["v"], v.astype(dt), (0, step, 0, 0))
            new_caches.append({"k": k_cache, "v": v_cache})
            x = x + (attend(q, k_cache, v_cache, step)
                     @ block["wo"]).astype(dt)
            normed = _norm(x, block["norm_cross"])
            qc = (normed @ block["wq_cross"]).reshape(batch, 1, h, hd)
            x = x + (attend(qc, fixed["k"], fixed["v"])
                     @ block["wo_cross"]).astype(dt)
            x = _mlp(block, x)
        x = _norm(x, params["decoder_norm"])
        logits = (x[:, 0] @ params["token_embed"].T).astype(jnp.float32)
        next_token = logits.argmax(-1).astype(jnp.int32)
        next_token = jnp.where(done, end_token, next_token)
        done = done | (next_token == end_token)
        return (next_token, done, new_caches), next_token

    start = jnp.full((batch,), start_token, jnp.int32)
    (_, _, _), generated = jax.lax.scan(
        body, (start, jnp.zeros((batch,), bool), self_cache),
        jnp.arange(max_tokens, dtype=jnp.int32))
    tokens = jnp.concatenate(
        [start[:, None], generated.T.astype(jnp.int32)], axis=1)
    return tokens


def log_mel_spectrogram(audio, n_mels: int, hop: int = 160,
                        n_fft: int = 400):
    """waveform (batch, samples) → log-mel (batch, frames, n_mels).
    jnp implementation (rfft on device); mel filter is a fixed matrix."""
    audio = jnp.asarray(audio, jnp.float32)
    n_frames = max(1, (audio.shape[-1] - n_fft) // hop + 1)
    idx = jnp.arange(n_fft)[None, :] + hop * jnp.arange(n_frames)[:, None]
    frames = audio[..., idx] * jnp.hanning(n_fft)
    spectrum = jnp.abs(jnp.fft.rfft(frames, axis=-1)) ** 2
    bins = spectrum.shape[-1]
    # Triangular mel filterbank (linear approximation adequate here).
    centers = jnp.linspace(0, bins - 1, n_mels + 2)
    filterbank = jnp.maximum(
        0.0,
        1.0 - jnp.abs(jnp.arange(bins)[None, :] - centers[1:-1, None])
        / jnp.maximum(1.0, (centers[2:] - centers[:-2])[:, None] / 2))
    mel = spectrum @ filterbank.T
    return jnp.log10(jnp.maximum(mel, 1e-10))

"""Speech recognition: Whisper-architecture encoder-decoder.

The speech→chat workload (BASELINE.json config 3; the reference calls
WhisperX as an opaque library, ``examples/speech/speech_elements.py``).
Whisper architecture: log-mel spectrogram → 2×conv subsampling →
transformer encoder; transformer decoder with cross-attention generates
text tokens autoregressively.  Pure functional JAX, bf16, sinusoidal
encoder positions, learned decoder positions, scan-based greedy decode.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import attention_reference

__all__ = ["ASRConfig", "init_params", "encode", "decode_greedy",
           "decode_greedy_cached", "log_mel_spectrogram", "CONFIGS"]


@dataclasses.dataclass(frozen=True)
class ASRConfig:
    n_mels: int = 80
    n_audio_ctx: int = 1500       # encoder positions after subsampling
    d_model: int = 384
    n_heads: int = 6
    n_encoder_layers: int = 4
    n_decoder_layers: int = 4
    vocab_size: int = 51_865      # whisper tokenizer size
    n_text_ctx: int = 448
    dtype: Any = jnp.bfloat16
    #: LayerNorm epsilon.  Randomly-initialised configs keep the
    #: historical 1e-6; imported Whisper checkpoints use torch's 1e-5.
    norm_eps: float = 1e-6


CONFIGS: Dict[str, ASRConfig] = {
    "tiny": ASRConfig(n_mels=20, n_audio_ctx=64, d_model=64, n_heads=2,
                      n_encoder_layers=2, n_decoder_layers=2,
                      vocab_size=512, n_text_ctx=64),
    "whisper_small": ASRConfig(n_mels=80, n_audio_ctx=1500, d_model=768,
                               n_heads=12, n_encoder_layers=12,
                               n_decoder_layers=12),
}


def _dense(key, shape, dtype):
    return (jax.random.normal(key, shape, jnp.float32)
            * shape[0] ** -0.5).astype(dtype)


def _block_params(key, d, dtype, cross: bool):
    keys = jax.random.split(key, 8)
    block = {
        "norm1": jnp.ones((d,), dtype),
        "wqkv": _dense(keys[0], (d, 3 * d), dtype),
        "wo": _dense(keys[1], (d, d), dtype),
        "norm_mlp": jnp.ones((d,), dtype),
        "w1": _dense(keys[2], (d, 4 * d), dtype),
        "w2": _dense(keys[3], (4 * d, d), dtype),
    }
    if cross:
        block.update({
            "norm_cross": jnp.ones((d,), dtype),
            "wq_cross": _dense(keys[4], (d, d), dtype),
            "wkv_cross": _dense(keys[5], (d, 2 * d), dtype),
            "wo_cross": _dense(keys[6], (d, d), dtype),
        })
    return block


def init_params(config: ASRConfig, key) -> Dict:
    keys = jax.random.split(key, 8)
    d, dt = config.d_model, config.dtype
    encoder_layers = [
        _block_params(jax.random.fold_in(keys[0], i), d, dt, cross=False)
        for i in range(config.n_encoder_layers)]
    decoder_layers = [
        _block_params(jax.random.fold_in(keys[1], i), d, dt, cross=True)
        for i in range(config.n_decoder_layers)]
    return {
        "conv1": _dense(keys[2], (3, config.n_mels, d), dt),
        "conv2": _dense(keys[3], (3, d, d), dt),
        "encoder_layers": encoder_layers,
        "encoder_norm": jnp.ones((d,), dt),
        "token_embed": _dense(keys[4], (config.vocab_size, d), dt),
        "pos_embed": _dense(keys[5], (config.n_text_ctx, d), dt),
        "decoder_layers": decoder_layers,
        "decoder_norm": jnp.ones((d,), dt),
    }


from .common import layer_norm as _layer_norm, mha as _mha, gelu_mlp


def _mlp(block, x, eps):
    return gelu_mlp(x, block["norm_mlp"], block["w1"], block["w2"],
                    norm_bias=block.get("norm_mlp_b"),
                    b1=block.get("b1"), b2=block.get("b2"), eps=eps)


def _sinusoid(length, channels):
    position = jnp.arange(length)[:, None].astype(jnp.float32)
    div = jnp.exp(-jnp.log(10000.0)
                  * jnp.arange(0, channels, 2) / channels)
    angles = position * div[None, :]
    embedding = jnp.zeros((length, channels), jnp.float32)
    embedding = embedding.at[:, 0::2].set(jnp.sin(angles))
    embedding = embedding.at[:, 1::2].set(jnp.cos(angles))
    return embedding


def _conv1d(x, w, stride, bias=None):
    # x: (b, t, c_in), w: (k, c_in, c_out).  Explicit symmetric padding
    # (torch Conv1d padding=1 semantics): under stride 2, "SAME" pads
    # 0-left/1-right, which shifts every window one sample against a
    # checkpoint trained with torch's 1/1 — same output length, wrong
    # alignment (caught by the Whisper differential test).
    pad = (w.shape[0] - 1) // 2
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=[(pad, pad)],
        dimension_numbers=("NWC", "WIO", "NWC"),
        preferred_element_type=jnp.float32).astype(x.dtype)
    return out if bias is None else out + bias


def _norm(x, weight, bias=None, eps=1e-6):
    return _layer_norm(x, weight, eps=eps, bias=bias)


def _self_attn(block, normed, n_heads, causal):
    return _mha(normed, normed, block["wqkv"], block["wo"], n_heads,
                causal=causal, b_in=block.get("b_qkv"),
                b_o=block.get("b_o"))


def _cross_attn(block, normed, audio_features, n_heads):
    return _mha(normed, audio_features, block["wq_cross"],
                block["wo_cross"], n_heads, causal=False, cross=True,
                wkv=block["wkv_cross"], b_in=block.get("b_q_cross"),
                b_o=block.get("b_o_cross"), b_kv=block.get("b_kv_cross"))


@functools.partial(jax.jit, static_argnames=("config",))
def encode(params, mel, config: ASRConfig):
    """mel (batch, frames, n_mels) → audio features
    (batch, frames//2, d_model)."""
    eps = config.norm_eps
    x = jax.nn.gelu(_conv1d(mel.astype(config.dtype), params["conv1"], 1,
                            params.get("conv1_b"))
                    .astype(jnp.float32),
                    approximate=False).astype(config.dtype)
    x = jax.nn.gelu(_conv1d(x, params["conv2"], 2, params.get("conv2_b"))
                    .astype(jnp.float32),
                    approximate=False).astype(config.dtype)
    if "enc_pos_embed" in params:
        # Imported checkpoints carry the encoder position table
        # (Whisper stores sin/cos as concatenated halves, not
        # interleaved like :func:`_sinusoid`).
        positions = params["enc_pos_embed"][:x.shape[1]]
    else:
        positions = _sinusoid(x.shape[1], config.d_model)
    x = x + positions[None].astype(x.dtype)
    for block in params["encoder_layers"]:
        normed = _norm(x, block["norm1"], block.get("norm1_b"), eps)
        x = x + _self_attn(block, normed, config.n_heads, causal=False)
        x = _mlp(block, x, eps)
    return _norm(x, params["encoder_norm"],
                 params.get("encoder_norm_b"), eps)


def _decoder_step(params, tokens, audio_features, config: ASRConfig):
    """Full-sequence decoder (teacher-forced or re-run per step)."""
    b, t = tokens.shape
    eps = config.norm_eps
    x = params["token_embed"][tokens] + params["pos_embed"][:t][None]
    for block in params["decoder_layers"]:
        normed = _norm(x, block["norm1"], block.get("norm1_b"), eps)
        x = x + _self_attn(block, normed, config.n_heads, causal=True)
        normed = _norm(x, block["norm_cross"],
                       block.get("norm_cross_b"), eps)
        x = x + _cross_attn(block, normed, audio_features,
                            config.n_heads)
        x = _mlp(block, x, eps)
    x = _norm(x, params["decoder_norm"], params.get("decoder_norm_b"),
              eps)
    return (x @ params["token_embed"].T).astype(jnp.float32)


#: Whisper vocab size → (SOT conditioning sequence, EOT id).
#: 51865 = multilingual v1/v2/small/medium; 51864 = English-only;
#: 51866 = large-v3 family (one extra language token shifts the task
#: ids up by one).  Sequences condition for en/transcribe/no-timestamps.
_WHISPER_SPECIALS = {
    51_865: ((50_258, 50_259, 50_359, 50_363), 50_257),
    51_864: ((50_257, 50_362), 50_256),
    51_866: ((50_258, 50_259, 50_360, 50_364), 50_257),
}


def sot_sequence(config: ASRConfig) -> Tuple[int, ...]:
    """Whisper's start-of-transcript conditioning for imported
    checkpoints, derived from the vocab size (see _WHISPER_SPECIALS).
    Random-init test configs (small vocabs) keep the plain
    (start_token,) seed; an UNRECOGNIZED Whisper-scale vocab raises —
    decoding a trained model with the stand-in tokens would produce
    silent garbage."""
    if config.vocab_size in _WHISPER_SPECIALS:
        return _WHISPER_SPECIALS[config.vocab_size][0]
    if config.vocab_size >= 40_000:
        raise ValueError(
            f"unknown Whisper vocab size {config.vocab_size}; add its "
            "special-token ids to _WHISPER_SPECIALS")
    return ()


def eot_token(config: ASRConfig, default: int = 2) -> int:
    if config.vocab_size in _WHISPER_SPECIALS:
        return _WHISPER_SPECIALS[config.vocab_size][1]
    if config.vocab_size >= 40_000:
        raise ValueError(
            f"unknown Whisper vocab size {config.vocab_size}; add its "
            "special-token ids to _WHISPER_SPECIALS")
    return default


@functools.partial(jax.jit, static_argnames=("config", "max_tokens",
                                             "seed"))
def decode_greedy(params, audio_features, config: ASRConfig,
                  max_tokens: int = 32, start_token: int = 1,
                  end_token: int = 2, seed: Tuple[int, ...] = ()):
    """Greedy transcription as one compiled program: fixed-length scan
    with an is-done latch (XLA-friendly static shapes).  ``seed``
    (static tuple) forces the first tokens — Whisper's SOT conditioning
    sequence (:func:`sot_sequence`); empty keeps the single
    ``start_token`` seed."""
    batch = audio_features.shape[0]
    if seed:
        start_token = seed[0]
    tokens = jnp.full((batch, max_tokens + 1), end_token, jnp.int32)
    tokens = tokens.at[:, 0].set(start_token)
    forced = jnp.asarray(list(seed[1:]) + [-1], jnp.int32)

    def body(carry, step):
        tokens, done = carry
        logits = _decoder_step(params, tokens[:, :max_tokens],
                               audio_features, config)
        next_token = logits[jnp.arange(batch), step].argmax(-1) \
            .astype(jnp.int32)
        if seed:
            force = forced[jnp.minimum(step, len(seed) - 1)]
            next_token = jnp.where(step < len(seed) - 1, force,
                                   next_token)
        next_token = jnp.where(done, end_token, next_token)
        done = done | (next_token == end_token)
        tokens = tokens.at[:, step + 1].set(next_token)
        return (tokens, done), ()

    (tokens, _), _ = jax.lax.scan(
        body, (tokens, jnp.zeros((batch,), bool)),
        jnp.arange(max_tokens, dtype=jnp.int32))
    return tokens


@functools.partial(jax.jit, static_argnames=("config", "max_tokens",
                                             "seed"))
def decode_greedy_cached(params, audio_features, config: ASRConfig,
                         max_tokens: int = 32, start_token: int = 1,
                         end_token: int = 2, seed: Tuple[int, ...] = ()):
    """KV-cached greedy transcription: same outputs as
    :func:`decode_greedy` (tested), O(T) instead of O(T²) decoder work.

    Two cache ideas: (1) self-attention K/V accumulate per step instead
    of re-running the whole prefix through every layer; (2) the
    cross-attention K/V are projections of the FIXED audio features, so
    they are computed once per layer, not once per step — the dominant
    saving (audio context >> token count)."""
    batch = audio_features.shape[0]
    d, h = config.d_model, config.n_heads
    hd = d // h
    scale = hd ** -0.5
    dt = config.dtype

    eps = config.norm_eps

    def _add(x, bias):
        return x if bias is None else x + bias

    # Per-layer fixed cross K/V.
    cross_kv = []
    for block in params["decoder_layers"]:
        kv = _add(audio_features @ block["wkv_cross"],
                  block.get("b_kv_cross")).reshape(batch, -1, 2, h, hd)
        cross_kv.append({"k": kv[:, :, 0], "v": kv[:, :, 1]})
    self_cache = [{"k": jnp.zeros((batch, max_tokens, h, hd), dt),
                   "v": jnp.zeros((batch, max_tokens, h, hd), dt)}
                  for _ in params["decoder_layers"]]

    def attend(q, k_cache, v_cache, step=None):
        """q (b, 1, h, hd) over cached keys; mask rows > step when
        given (self-attn); full attention when step is None (cross —
        delegated to the shared attention_reference so numerics fixes
        in ops/attention.py apply here too)."""
        if step is None:
            out = attention_reference(
                q.transpose(0, 2, 1, 3), k_cache.transpose(0, 2, 1, 3),
                v_cache.transpose(0, 2, 1, 3), causal=False)
            return out.transpose(0, 2, 1, 3).reshape(batch, 1, d)
        s = jnp.einsum("bqhd,bshd->bhqs", q, k_cache,
                       preferred_element_type=jnp.float32) * scale
        valid = jnp.arange(k_cache.shape[1]) <= step
        s = jnp.where(valid[None, None, None, :], s, -1e30)
        weights = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqs,bshd->bqhd",
                         weights.astype(v_cache.dtype), v_cache)
        return out.reshape(batch, 1, d)

    def body(carry, step):
        token, done, caches = carry
        x = (params["token_embed"][token][:, None]
             + jax.lax.dynamic_slice_in_dim(params["pos_embed"], step,
                                            1)[None]).astype(dt)
        new_caches = []
        for block, cache, fixed in zip(params["decoder_layers"], caches,
                                       cross_kv):
            normed = _norm(x, block["norm1"], block.get("norm1_b"), eps)
            qkv = _add(normed @ block["wqkv"], block.get("b_qkv")) \
                .reshape(batch, 1, 3, h, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            k_cache = jax.lax.dynamic_update_slice(
                cache["k"], k.astype(dt), (0, step, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                cache["v"], v.astype(dt), (0, step, 0, 0))
            new_caches.append({"k": k_cache, "v": v_cache})
            x = x + _add(attend(q, k_cache, v_cache, step)
                         @ block["wo"], block.get("b_o")).astype(dt)
            normed = _norm(x, block["norm_cross"],
                           block.get("norm_cross_b"), eps)
            qc = _add(normed @ block["wq_cross"],
                      block.get("b_q_cross")).reshape(batch, 1, h, hd)
            x = x + _add(attend(qc, fixed["k"], fixed["v"])
                         @ block["wo_cross"],
                         block.get("b_o_cross")).astype(dt)
            x = _mlp(block, x, eps)
        x = _norm(x, params["decoder_norm"],
                  params.get("decoder_norm_b"), eps)
        logits = (x[:, 0] @ params["token_embed"].T).astype(jnp.float32)
        next_token = logits.argmax(-1).astype(jnp.int32)
        if seed:
            force = forced[jnp.minimum(step, len(seed) - 1)]
            next_token = jnp.where(step < len(seed) - 1, force,
                                   next_token)
        next_token = jnp.where(done, end_token, next_token)
        done = done | (next_token == end_token)
        return (next_token, done, new_caches), next_token

    if seed:
        start_token = seed[0]
    forced = jnp.asarray(list(seed[1:]) + [-1], jnp.int32)
    start = jnp.full((batch,), start_token, jnp.int32)
    (_, _, _), generated = jax.lax.scan(
        body, (start, jnp.zeros((batch,), bool), self_cache),
        jnp.arange(max_tokens, dtype=jnp.int32))
    tokens = jnp.concatenate(
        [start[:, None], generated.T.astype(jnp.int32)], axis=1)
    return tokens


def _hz_to_mel_slaney(freq):
    """Slaney-scale mel (librosa htk=False): linear below 1 kHz, log
    spaced above — the scale Whisper's filterbank is built with."""
    import numpy as np
    freq = np.asarray(freq, np.float64)
    linear = freq / (200.0 / 3)
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / (200.0 / 3)
    logstep = np.log(6.4) / 27.0
    return np.where(freq >= min_log_hz,
                    min_log_mel + np.log(np.maximum(freq, 1e-10)
                                         / min_log_hz) / logstep,
                    linear)


def _mel_to_hz_slaney(mels):
    import numpy as np
    mels = np.asarray(mels, np.float64)
    freq = mels * (200.0 / 3)
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / (200.0 / 3)
    logstep = np.log(6.4) / 27.0
    return np.where(mels >= min_log_mel,
                    min_log_hz * np.exp(logstep * (mels - min_log_mel)),
                    freq)


@functools.lru_cache(maxsize=8)
def mel_filterbank(n_mels: int = 80, n_fft: int = 400,
                   sample_rate: int = 16_000):
    """Slaney-normalized triangular mel filterbank (n_mels, n_fft//2+1)
    — numerically the librosa/Whisper matrix."""
    import numpy as np
    fft_freqs = np.linspace(0, sample_rate / 2, 1 + n_fft // 2)
    mel_points = _mel_to_hz_slaney(
        np.linspace(_hz_to_mel_slaney(0.0),
                    _hz_to_mel_slaney(sample_rate / 2), n_mels + 2))
    fdiff = np.diff(mel_points)
    ramps = mel_points[:, None] - fft_freqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1][:, None]
    upper = ramps[2:] / fdiff[1:][:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))
    enorm = 2.0 / (mel_points[2:n_mels + 2] - mel_points[:n_mels])
    weights *= enorm[:, None]
    return weights.astype(np.float32)


def whisper_log_mel(audio, n_mels: int = 80, hop: int = 160,
                    n_fft: int = 400, pad_to_samples: int = 480_000):
    """Whisper's exact feature front end: reflect-centered STFT
    (periodic Hann), power spectrum, slaney mel, ``log10`` with an
    8-dB dynamic-range floor, ``(x+4)/4`` scaling.  waveform
    (batch, samples) @16 kHz → (batch, frames, n_mels); validated
    against ``transformers.WhisperFeatureExtractor`` differentially.

    Imported checkpoints must run through THIS front end —
    :func:`log_mel_spectrogram` below is a self-consistent
    approximation for the random-init test models only."""
    audio = jnp.asarray(audio, jnp.float32)
    if audio.ndim == 1:
        audio = audio[None]
    if pad_to_samples:
        take = min(audio.shape[-1], pad_to_samples)
        audio = jnp.pad(audio[:, :take],
                        ((0, 0), (0, pad_to_samples - take)))
    half = n_fft // 2
    audio = jnp.pad(audio, ((0, 0), (half, half)), mode="reflect")
    n_frames = 1 + (audio.shape[-1] - n_fft) // hop
    idx = jnp.arange(n_fft)[None, :] + hop * jnp.arange(n_frames)[:, None]
    window = 0.5 * (1.0 - jnp.cos(
        2.0 * jnp.pi * jnp.arange(n_fft) / n_fft))     # periodic Hann
    frames = audio[..., idx] * window
    spectrum = jnp.abs(jnp.fft.rfft(frames, axis=-1)) ** 2
    spectrum = spectrum[..., :-1, :]                   # drop last frame
    mel = spectrum @ mel_filterbank(n_mels, n_fft).T
    log_spec = jnp.log10(jnp.maximum(mel, 1e-10))
    log_spec = jnp.maximum(
        log_spec, jnp.max(log_spec, axis=(-2, -1), keepdims=True) - 8.0)
    return (log_spec + 4.0) / 4.0


def log_mel_spectrogram(audio, n_mels: int, hop: int = 160,
                        n_fft: int = 400):
    """waveform (batch, samples) → log-mel (batch, frames, n_mels).
    jnp implementation (rfft on device); mel filter is a fixed matrix."""
    audio = jnp.asarray(audio, jnp.float32)
    n_frames = max(1, (audio.shape[-1] - n_fft) // hop + 1)
    idx = jnp.arange(n_fft)[None, :] + hop * jnp.arange(n_frames)[:, None]
    frames = audio[..., idx] * jnp.hanning(n_fft)
    spectrum = jnp.abs(jnp.fft.rfft(frames, axis=-1)) ** 2
    bins = spectrum.shape[-1]
    # Triangular mel filterbank (linear approximation adequate here).
    centers = jnp.linspace(0, bins - 1, n_mels + 2)
    filterbank = jnp.maximum(
        0.0,
        1.0 - jnp.abs(jnp.arange(bins)[None, :] - centers[1:-1, None])
        / jnp.maximum(1.0, (centers[2:] - centers[:-2])[:, None] / 2))
    mel = spectrum @ filterbank.T
    return jnp.log10(jnp.maximum(mel, 1e-10))

"""Paged adapter storage: canonical LoRA factor bytes ⇄ pool pages.

S-LoRA's unified-paging move (PAPERS.md): adapter A/B factors live in
the SAME audited block pool as the KV cache, so hundreds of warm
adapters and the prefix cache compete for HBM under one eviction
clock instead of each adapter pinning dedicated slots forever.  This
module is the byte layer of that move — everything here is host-side
``numpy`` with zero device work:

* :func:`pack_adapter` serializes one adapter's ``{"layers": [...]}``
  factor tree into a single self-describing byte stream: an
  ``AIKOLOR1`` header (payload size + rank/alpha/targets, so a peer
  replica can reconstruct the :class:`~.lora.LoRAConfig` from the
  bytes alone) followed by every factor's raw bytes in the one
  canonical order both ends agree on — layer-major, targets sorted,
  ``a`` before ``b``, ``config.dtype`` wire dtype (bf16 rides as its
  uint16 bit pattern, the same convention as kvstore/transfer.py).
* :func:`unpack_adapter` is the bitwise inverse (shapes come from
  :func:`~.lora.factor_dims`, never from the wire).
* :func:`split_pages` / :func:`join_pages` chop the stream into
  fixed-size pages of :func:`page_payload_nbytes` (last page
  zero-padded), and :func:`payload_to_row_dict` /
  :func:`row_dict_to_payload` encode one page across the pool's
  per-field staging layout so ``scatter_block_row_dicts`` /
  ``gather_block_rows`` move adapter bytes with the exact machinery
  that moves KV rows.  Payload bytes are NOT bitcast raw into float
  pool fields: accelerator backends canonicalize NaN payloads (and
  TPUs flush denormals), so a raw bitcast silently rewrites ~0.4%%
  of random bytes.  Instead each float element carries ONE payload
  byte in the low mantissa bits of a fixed-exponent normal number
  (``2.0 + b/2048`` for bf16 — never NaN, never Inf, never
  denormal, exactly representable), while integer fields carry raw
  bytes at full width.  That makes a scatter → demote → spill →
  restore → gather round trip bit-exact ON EVERY BACKEND, at the
  cost of 1/itemsize packing density in float fields.

The decode path never reads pages: serving always runs from the
stacked ``_lora_shared`` factors (models/lora.py), so paging an
adapter in or out is invisible to traced programs — ARCHITECTURE.md
invariant 21.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np

from . import lora as _lora

#: Wire magic for a packed adapter stream (version 1).
MAGIC = b"AIKOLOR1"

_HEADER = struct.Struct("<8sIQIdI")   # magic, header bytes, payload
#                                     # bytes, rank, alpha, targets len


def _wire_dtype(dtype) -> np.dtype:
    """Numpy dtype whose bytes ARE the factor bytes (bf16 → uint16,
    the kvstore wire convention — ml_dtypes may be absent on a peer
    that only relays the stream)."""
    dtype = np.dtype(dtype)
    return np.dtype(np.uint16) if dtype.name == "bfloat16" else dtype


def _factor_bytes(array, dtype) -> np.ndarray:
    """One factor as its canonical flat byte view (cast to the model
    dtype first — the stacked serving copy is what must round-trip)."""
    host = np.asarray(array)
    if host.dtype != np.dtype(dtype):
        host = host.astype(dtype)
    return np.ascontiguousarray(host).view(np.uint8).reshape(-1)


def pack_adapter(config, lora_config, adapter) -> np.ndarray:
    """``{"layers": [...]}`` → one contiguous uint8 stream (header +
    every factor's bytes in canonical order)."""
    targets = ",".join(sorted(lora_config.targets)).encode("ascii")
    parts = []
    layers = adapter["layers"]
    if len(layers) != config.n_layers:
        raise ValueError(f"adapter has {len(layers)} layers, "
                         f"config.n_layers={config.n_layers}")
    for layer in layers:
        for target in sorted(lora_config.targets):
            parts.append(_factor_bytes(layer[target]["a"],
                                       config.dtype))
            parts.append(_factor_bytes(layer[target]["b"],
                                       config.dtype))
    payload = np.concatenate(parts) if parts else \
        np.empty(0, np.uint8)
    header_nbytes = _HEADER.size + len(targets)
    header = _HEADER.pack(MAGIC, header_nbytes, payload.nbytes,
                          int(lora_config.rank),
                          float(lora_config.alpha),
                          len(targets)) + targets
    return np.concatenate([np.frombuffer(header, np.uint8), payload])


def parse_header(data) -> Tuple[int, int, "_lora.LoRAConfig"]:
    """``(header_nbytes, payload_nbytes, LoRAConfig)`` from a packed
    stream (or any prefix of it spanning at least the header)."""
    raw = np.ascontiguousarray(np.asarray(data, np.uint8)).tobytes()
    if len(raw) < _HEADER.size:
        raise ValueError("adapter stream shorter than its header")
    magic, header_nbytes, payload_nbytes, rank, alpha, targets_len \
        = _HEADER.unpack_from(raw)
    if magic != MAGIC:
        raise ValueError(f"bad adapter stream magic {magic!r}")
    if len(raw) < header_nbytes:
        raise ValueError("adapter stream truncated inside header")
    targets = raw[_HEADER.size:_HEADER.size + targets_len] \
        .decode("ascii")
    lora_config = _lora.LoRAConfig(
        rank=int(rank), alpha=float(alpha),
        targets=tuple(targets.split(",")) if targets else ())
    return int(header_nbytes), int(payload_nbytes), lora_config


def unpack_adapter(config, data):
    """Packed stream → ``({"layers": [...]}, LoRAConfig)`` — the
    bitwise inverse of :func:`pack_adapter` (trailing page padding is
    ignored; the header says where the payload ends)."""
    stream = np.ascontiguousarray(np.asarray(data, np.uint8)) \
        .reshape(-1)
    header_nbytes, payload_nbytes, lora_config = parse_header(stream)
    if stream.nbytes < header_nbytes + payload_nbytes:
        raise ValueError("adapter stream truncated inside payload")
    # Copy: the variable-length header can leave the payload at an
    # odd byte offset, and numpy dtype views need alignment.
    payload = stream[header_nbytes:header_nbytes + payload_nbytes] \
        .copy()
    in_dims, out_dims = _lora.factor_dims(config)
    dtype = np.dtype(config.dtype)
    wire = _wire_dtype(dtype)
    layers, offset = [], 0
    for _ in range(config.n_layers):
        layer = {}
        for target in sorted(lora_config.targets):
            factors = {}
            for name, shape in (
                    ("a", (in_dims[target], lora_config.rank)),
                    ("b", (lora_config.rank, out_dims[target]))):
                nbytes = int(np.prod(shape)) * dtype.itemsize
                factors[name] = payload[offset:offset + nbytes] \
                    .view(wire).view(dtype).reshape(shape)
                offset += nbytes
            layer[target] = factors
        layers.append(layer)
    if offset != payload_nbytes:
        raise ValueError(f"adapter payload is {payload_nbytes} bytes"
                         f", factors claim {offset}")
    return {"layers": layers}, lora_config


def page_count(nbytes: int, page_bytes: int) -> int:
    return -(-int(nbytes) // int(page_bytes)) if nbytes else 0


def split_pages(data, page_bytes: int) -> List[np.ndarray]:
    """Packed stream → fixed-size uint8 pages (last page padded with
    zeros to exactly ``page_bytes``)."""
    stream = np.ascontiguousarray(np.asarray(data, np.uint8)) \
        .reshape(-1)
    pages = []
    for start in range(0, stream.nbytes, int(page_bytes)):
        page = stream[start:start + int(page_bytes)]
        if page.nbytes < page_bytes:
            page = np.concatenate(
                [page, np.zeros(int(page_bytes) - page.nbytes,
                                np.uint8)])
        pages.append(page)
    return pages


def join_pages(pages: List[np.ndarray]) -> np.ndarray:
    return np.concatenate(
        [np.ascontiguousarray(np.asarray(p, np.uint8)).reshape(-1)
         for p in pages]) if pages else np.empty(0, np.uint8)


#: Fixed safe bit patterns per float itemsize: exponent of 2.0, all
#: payload bits riding in low mantissa — every ``BASE | byte`` value
#: is a distinct, exactly-representable NORMAL number, so neither
#: NaN canonicalization nor denormal flushing can touch it.
_SAFE_BASE = {2: np.uint16(0x4000), 4: np.uint32(0x40000000),
              8: np.uint64(0x4000000000000000)}
_UINT = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _field_capacity(dtype) -> int:
    """Payload bytes one pool ELEMENT of ``dtype`` can carry safely:
    integers are value-transparent (full width); floats carry one
    byte in the mantissa of a fixed-exponent normal."""
    dtype = np.dtype(dtype)
    return dtype.itemsize if dtype.kind in "iu" else 1


def page_payload_nbytes(layout) -> int:
    """Payload bytes ONE pool block can carry under the safe
    encoding (``kvstore.transfer._field_layout`` tuples) — the page
    size every split/join below uses."""
    return sum((row_bytes // np.dtype(dtype).itemsize)
               * _field_capacity(dtype)
               for _field, _shape, dtype, row_bytes in layout)


def payload_to_row_dict(chunk, layout) -> Dict[str, np.ndarray]:
    """One page's payload bytes encoded across the pool's staging
    field layout: each field gets a flat array whose uint8 view is
    exactly its ``row_bytes`` — raw bytes for integer fields, safe
    mantissa-encoded elements for float fields — ready for the fused
    scatter's bitcast."""
    flat = np.ascontiguousarray(np.asarray(chunk, np.uint8)) \
        .reshape(-1)
    total = page_payload_nbytes(layout)
    if flat.nbytes != total:
        raise ValueError(f"page payload is {flat.nbytes} bytes, "
                         f"pool block carries {total}")
    rows, offset = {}, 0
    for field, _shape, dtype, row_bytes in layout:
        dtype = np.dtype(dtype)
        elems = row_bytes // dtype.itemsize
        take = elems * _field_capacity(dtype)
        span = flat[offset:offset + take]
        if dtype.kind in "iu":
            rows[field] = span
        else:
            unit = _UINT[dtype.itemsize]
            rows[field] = _SAFE_BASE[dtype.itemsize] | \
                span.astype(unit)
        offset += take
    return rows


def row_dict_to_payload(rows, layout) -> np.ndarray:
    """Inverse of :func:`payload_to_row_dict` for rows read back
    from ANY tier — gathered native-dtype pool rows, a host-tier
    entry's row dict, and the spill store's wire rows all decode to
    the same payload bytes."""
    parts = []
    for field, _shape, dtype, row_bytes in layout:
        dtype = np.dtype(dtype)
        flat = np.ascontiguousarray(np.asarray(rows[field])) \
            .view(np.uint8).reshape(-1)
        if flat.nbytes != row_bytes:
            raise ValueError(f"{field}: {flat.nbytes} bytes != "
                             f"{row_bytes}")
        if dtype.kind in "iu":
            parts.append(flat)
        else:
            unit = _UINT[dtype.itemsize]
            parts.append((flat.view(unit)
                          & unit(0xFF)).astype(np.uint8))
    return np.concatenate(parts) if parts else np.empty(0, np.uint8)

"""Text classifier: a compact transformer encoder (DistilBERT-class) for
the sentiment-pipeline workload (BASELINE.json config 1).

Pure functional JAX like :mod:`.llama`: params pytree + jittable
``forward``.  Mean-pooled encoder → 2-layer head.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..ops.attention import attention_reference

__all__ = ["ClassifierConfig", "init_params", "forward", "CONFIGS"]


@dataclasses.dataclass(frozen=True)
class ClassifierConfig:
    vocab_size: int = 30_522          # bert-style
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 4
    d_ff: int = 1024
    n_classes: int = 2
    max_seq_len: int = 512
    dtype: Any = jnp.bfloat16


CONFIGS: Dict[str, ClassifierConfig] = {
    "tiny": ClassifierConfig(vocab_size=1024, d_model=64, n_layers=2,
                             n_heads=2, d_ff=128, max_seq_len=128),
    "distilbert": ClassifierConfig(vocab_size=30_522, d_model=768,
                                   n_layers=6, n_heads=12, d_ff=3072),
}


def init_params(config: ClassifierConfig, key) -> Dict:
    keys = jax.random.split(key, config.n_layers + 3)
    dt = config.dtype
    d, f = config.d_model, config.d_ff

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                * shape[0] ** -0.5).astype(dt)

    layers = []
    for i in range(config.n_layers):
        lk = jax.random.split(keys[i], 6)
        layers.append({
            "norm1": jnp.ones((d,), dt),
            "wqkv": dense(lk[0], (d, 3 * d)),
            "wo": dense(lk[1], (d, d)),
            "norm2": jnp.ones((d,), dt),
            "w1": dense(lk[2], (d, f)),
            "w2": dense(lk[3], (f, d)),
        })
    return {
        "embed": dense(keys[-3], (config.vocab_size, d)),
        "pos_embed": dense(keys[-2], (config.max_seq_len, d)),
        "layers": layers,
        "head_w1": dense(keys[-1], (d, d)),
        "head_w2": (jax.random.normal(
            jax.random.fold_in(keys[-1], 1),
            (d, config.n_classes), jnp.float32) * d ** -0.5).astype(dt),
    }


from .common import layer_norm as _norm, mha as _mha, gelu_mlp


@functools.partial(jax.jit, static_argnames=("config",))
def forward(params, tokens, config: ClassifierConfig):
    """tokens (batch, seq) int32 → logits (batch, n_classes) f32."""
    batch, seq = tokens.shape
    x = params["embed"][tokens] + params["pos_embed"][:seq][None]
    for layer in params["layers"]:
        normed = _norm(x, layer["norm1"])
        x = x + _mha(normed, normed, layer["wqkv"], layer["wo"],
                     config.n_heads, causal=False)
        x = gelu_mlp(x, layer["norm2"], layer["w1"], layer["w2"])
    pooled = jnp.mean(x.astype(jnp.float32), axis=1)
    hidden = jnp.tanh(pooled @ params["head_w1"].astype(jnp.float32))
    return hidden @ params["head_w2"].astype(jnp.float32)

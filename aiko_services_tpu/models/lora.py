"""LoRA: low-rank adaptation for parameter-efficient fine-tuning.

The reference has no training at all; this framework trains (dp/tp/pp
steps in ``parallel/train.py``), and fine-tuning a large base model is
where LoRA earns its keep: train two rank-r factors per target matrix
(`r × (d_in + d_out)` params instead of `d_in × d_out`), keep the base
frozen — optimizer state shrinks by orders of magnitude and checkpoints
are megabytes.

Design: *merge-in-graph*.  The loss closes over (frozen base, lora) and
computes ``W_eff = W + (alpha/r) · A @ B`` per adapted leaf inside the
traced step; XLA CSEs the merge across uses and autodiff reaches only
A/B (the base enters as a constant operand).  ``merge_lora`` bakes the
same update into a plain parameter tree for serving (zero inference
overhead) — exact equality between the two paths is tested, as is
zero-init equivalence (fresh LoRA == base model exactly).

TP composability: A inherits the base leaf's row sharding, B its column
sharding, so the adapted matmul shards exactly like the base one.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import llama

__all__ = ["LoRAConfig", "factor_dims", "init_lora_params",
           "merge_lora", "lora_forward", "make_lora_train_step",
           "lora_param_specs", "stack_adapters", "SERVING_TARGETS"]

#: Targets the batched multi-adapter SERVING path supports (the
#: attention projections — llama._lora_matmul hooks).  MLP targets
#: train and merge fine but cannot yet run per-row batched.
SERVING_TARGETS = frozenset({"wq", "wk", "wv", "wo"})

#: Default adaptation targets (attention projections — the standard
#: LoRA recipe; extend with mlp names for higher capacity).
DEFAULT_TARGETS = ("wq", "wv")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Tuple[str, ...] = DEFAULT_TARGETS

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def factor_dims(config: llama.LlamaConfig):
    """``(in_dims, out_dims)`` per LoRA target: factor ``a`` is
    ``(in_dims[t], rank)``, ``b`` is ``(rank, out_dims[t])`` — the
    single source of truth for adapter factor shapes (init, stacking
    validation, checkpoint import)."""
    d = config.d_model
    h, kv, hd = config.n_heads, config.n_kv_heads, config.head_dim
    out_dims = {"wq": h * hd, "wk": kv * hd, "wv": kv * hd,
                "wo": d, "w_gate": config.d_ff, "w_up": config.d_ff,
                "w_down": d}
    in_dims = {"wq": d, "wk": d, "wv": d, "wo": h * hd,
               "w_gate": d, "w_up": d, "w_down": config.d_ff}
    return in_dims, out_dims


def init_lora_params(config: llama.LlamaConfig, lora: LoRAConfig,
                     key) -> Dict:
    """A ~ N(0, 1/d) (gaussian), B = 0 — so a fresh adapter is an exact
    no-op (tested)."""
    layers = []
    in_dims, out_dims = factor_dims(config)
    if config.n_experts:
        # MoE layers replace the dense MLP with an expert subtree.
        for target in lora.targets:
            if target in ("w_gate", "w_up", "w_down"):
                raise ValueError(
                    f"LoRA target {target!r} does not exist in MoE "
                    "configs (experts replace the dense MLP); adapt "
                    "attention projections instead")
    for i in range(config.n_layers):
        layer = {}
        for j, target in enumerate(lora.targets):
            if target not in out_dims:
                raise ValueError(f"unknown LoRA target {target!r}")
            sub = jax.random.fold_in(jax.random.fold_in(key, i), j)
            layer[target] = {
                "a": (jax.random.normal(
                    sub, (in_dims[target], lora.rank), jnp.float32)
                    * in_dims[target] ** -0.5).astype(config.dtype),
                "b": jnp.zeros((lora.rank, out_dims[target]),
                               config.dtype),
            }
        layers.append(layer)
    return {"layers": layers}


def lora_param_specs(config: llama.LlamaConfig, lora: LoRAConfig):
    """TP partition specs mirroring the base layout: A follows the
    base leaf's input (row) sharding, B its output (column) sharding."""
    from jax.sharding import PartitionSpec as P
    base = llama.param_specs(config)["layers"][0]
    layers = []
    for _ in range(config.n_layers):
        layer = {}
        for target in lora.targets:
            row, col = base[target]
            layer[target] = {"a": P(row, None), "b": P(None, col)}
        layers.append(layer)
    return {"layers": layers}


def _adapted_params(base, lora_params, lora: LoRAConfig):
    merged_layers = []
    for base_layer, lora_layer in zip(base["layers"],
                                      lora_params["layers"]):
        layer = dict(base_layer)
        for target, factors in lora_layer.items():
            delta = (factors["a"].astype(jnp.float32)
                     @ factors["b"].astype(jnp.float32)) * lora.scale
            layer[target] = (base_layer[target].astype(jnp.float32)
                             + delta).astype(base_layer[target].dtype)
        merged_layers.append(layer)
    return {**base, "layers": merged_layers}


def lora_forward(base, lora_params, tokens, config: llama.LlamaConfig,
                 lora: LoRAConfig, use_flash: bool = True):
    """Forward with the adapter applied functionally (differentiable in
    ``lora_params``; the base is a frozen constant)."""
    return llama.forward(_adapted_params(base, lora_params, lora),
                         tokens, config, use_flash=use_flash)


def merge_lora(base, lora_params, lora: LoRAConfig) -> Dict:
    """Bake the adapter into a plain parameter tree (serving path:
    zero inference overhead; == lora_forward exactly, tested)."""
    return _adapted_params(base, lora_params, lora)


def stack_adapters(config: llama.LlamaConfig, lora: LoRAConfig,
                   adapters: Sequence[Dict]) -> Dict:
    """Stack N trained adapters for batched multi-adapter serving
    (SLoRA-style): per layer and target, factors become
    ``a: (N+1, d_in, r)``, ``b: (N+1, r, d_out)`` with index 0 the
    ALL-ZERO identity adapter (a base-model row gathers an exact
    no-op).  The result is the ``lora`` argument of
    :func:`..llama.prefill` / :func:`..llama.decode_chunk_ragged`
    minus the per-row ``ids`` — serving supplies those per batch.

    All adapters must share ``lora`` (rank/scale/targets), and targets
    must be within :data:`SERVING_TARGETS`.  Every adapter's factor
    shapes are verified against ``config``/``lora`` BEFORE stacking —
    a wrong-rank, wrong-base, or differently-targeted adapter fails
    here by name, never as an opaque shape error inside the jitted
    decode (alpha is not recoverable from weights: an adapter trained
    at a different alpha but matching shapes is the caller's contract
    to reject)."""
    unsupported = set(lora.targets) - SERVING_TARGETS
    if unsupported:
        raise ValueError(
            f"multi-adapter serving supports attention targets only; "
            f"got {sorted(unsupported)}")
    in_dims, out_dims = factor_dims(config)
    for index, adapter in enumerate(adapters):
        try:
            adapter_layers = list(adapter["layers"])
        except (KeyError, TypeError):
            raise ValueError(
                f"adapter {index} params lack the per-layer target "
                f"layout")
        if len(adapter_layers) != config.n_layers:
            # A wrong-depth adapter (different base variant) would
            # otherwise truncate silently or die with a raw IndexError
            # in the stacking loop.
            raise ValueError(
                f"adapter {index} has {len(adapter_layers)} layers != "
                f"config.n_layers {config.n_layers}")
        for i, layer in enumerate(adapter_layers):
            if set(layer) != set(lora.targets):
                # Extra trained targets would otherwise be SILENTLY
                # DROPPED (the stack iterates lora.targets only) —
                # checked per layer, not just layer 0.
                raise ValueError(
                    f"adapter {index} layer {i} targets "
                    f"{sorted(layer)} != expected targets "
                    f"{sorted(lora.targets)}")
            for target in lora.targets:
                want_a = (in_dims[target], lora.rank)
                want_b = (lora.rank, out_dims[target])
                try:
                    got_a = tuple(layer[target]["a"].shape)
                    got_b = tuple(layer[target]["b"].shape)
                except (KeyError, TypeError, AttributeError):
                    raise ValueError(
                        f"adapter {index} layer {i} target {target!r} "
                        f"lacks array 'a'/'b' factors")
                if got_a != want_a or got_b != want_b:
                    raise ValueError(
                        f"adapter {index} layer {i} target {target!r} "
                        f"factor shapes a{got_a}/b{got_b} != expected "
                        f"a{want_a}/b{want_b} (rank {lora.rank})")
    layers = []
    for i in range(config.n_layers):
        layer = {}
        for target in lora.targets:
            a_stack = [a["layers"][i][target]["a"] for a in adapters]
            b_stack = [a["layers"][i][target]["b"] for a in adapters]
            layer[target] = {
                "a": jnp.stack([jnp.zeros_like(a_stack[0])] + a_stack),
                "b": jnp.stack([jnp.zeros_like(b_stack[0])] + b_stack),
            }
        layers.append(layer)
    return {"scale": lora.scale, "layers": layers}


def make_lora_train_step(config: llama.LlamaConfig, lora: LoRAConfig,
                         optimizer):
    """Training step over ADAPTER params only: optimizer state is
    O(rank·d·layers), the base never changes."""
    import optax

    from ..parallel.train import cross_entropy

    def loss_fn(lora_params, base, tokens, mask):
        logits = lora_forward(base, lora_params, tokens[:, :-1],
                              config, lora, use_flash=False)
        return cross_entropy(logits, tokens[:, 1:],
                             None if mask is None else mask[:, 1:])

    def train_step(lora_params, opt_state, base, tokens, mask=None):
        """``mask``: optional (batch, seq) 0/1 completion mask — loss
        on the answer bytes only, same contract as
        ``parallel.train.make_train_step``."""
        loss, grads = jax.value_and_grad(loss_fn)(lora_params, base,
                                                  tokens, mask)
        updates, opt_state = optimizer.update(grads, opt_state,
                                              lora_params)
        lora_params = optax.apply_updates(lora_params, updates)
        return lora_params, opt_state, loss

    return train_step

"""Byte-level BPE tokenizer — self-contained, no network, no deps.

The reference's LLM / ASR examples lean on external runtimes (Ollama,
WhisperX) whose tokenizers arrive with the model
(reference examples/llm/elements_llm.py:191-220,
examples/speech/speech_elements.py:109).  Here the tokenizer is part of
the framework: a pure-Python byte-level BPE engine that loads the two
formats real checkpoints ship with —

- **HF ``tokenizer.json``** (GPT-2, Whisper, Llama-3 style): BPE vocab
  + merges, byte-level pre-tokenization, added/special tokens.
- **tiktoken ``tokenizer.model``** (Meta's Llama-3 distribution):
  ``base64(token) rank`` lines; merge ranks are implicit in the ids.

Internals are bytes-first: every vocab entry is a ``bytes`` key, so
both formats share one BPE engine; HF's printable byte-alias alphabet
(the GPT-2 ``bytes_to_unicode`` table) is translated at load time.

Correctness is enforced differentially in
``tests/test_tokenizer.py``: encodings must match the HF ``tokenizers``
runtime token-for-token on every fixture where that library is
available.
"""

from __future__ import annotations

import base64
import functools
import json
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

try:                                    # transformers dependency, in image
    import regex as _regex
except ImportError:                     # pragma: no cover - regex is baked in
    _regex = None

__all__ = ["Tokenizer", "GPT2_PATTERN", "LLAMA3_PATTERN"]

#: GPT-2's pre-tokenization split (also Whisper's).  Requires the
#: ``regex`` module for \p classes and lookahead.
GPT2_PATTERN = (r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+"
                r"| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")

#: Llama-3's split (tiktoken cl100k-family).
LLAMA3_PATTERN = (r"(?i:'s|'t|'re|'ve|'m|'ll|'d)"
                  r"|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}"
                  r"| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+")


@functools.lru_cache(maxsize=None)
def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's printable alias for every byte value: bytes that are
    printable-and-not-space map to themselves, the rest to U+0100+n.
    This is the alphabet HF byte-level BPE vocab files are written in."""
    printable = (list(range(ord("!"), ord("~") + 1))
                 + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    mapping = {}
    n = 0
    for b in range(256):
        if b in printable:
            mapping[b] = chr(b)
        else:
            mapping[b] = chr(0x100 + n)
            n += 1
    return mapping


@functools.lru_cache(maxsize=None)
def _unicode_to_bytes() -> Dict[str, int]:
    return {c: b for b, c in _bytes_to_unicode().items()}


def _alias_to_bytes(token: str) -> bytes:
    """HF vocab entry (byte-alias alphabet) → raw bytes."""
    table = _unicode_to_bytes()
    return bytes(table[ch] for ch in token)


class Tokenizer:
    """Byte-level BPE encode/decode over a bytes-keyed vocab.

    Parameters
    ----------
    vocab: ``bytes -> id`` for ordinary tokens.
    merge_ranks: ``(left, right) -> rank`` pair priorities.  When
        absent (tiktoken checkpoints), ranks fall back to the vocab id
        of the concatenation — exactly tiktoken's rule.
    special_tokens: ``str -> id``; matched verbatim before the split
        regex, never byte-merged.
    pattern: pre-tokenization regex (``regex`` syntax).
    """

    def __init__(self, vocab: Dict[bytes, int],
                 merge_ranks: Optional[Dict[Tuple[bytes, bytes], int]]
                 = None,
                 special_tokens: Optional[Dict[str, int]] = None,
                 pattern: str = GPT2_PATTERN):
        if _regex is None:               # pragma: no cover
            raise RuntimeError("the 'regex' module is required")
        self.vocab = dict(vocab)
        self.merge_ranks = dict(merge_ranks or {})
        self.special_tokens = dict(special_tokens or {})
        self.pattern = pattern
        self._compiled = _regex.compile(pattern)
        self._id_to_bytes: Dict[int, bytes] = {
            i: b for b, i in self.vocab.items()}
        self._id_to_special: Dict[int, str] = {
            i: s for s, i in self.special_tokens.items()}
        self._special_split = None
        if self.special_tokens:
            alternation = "|".join(
                _regex.escape(s) for s in
                sorted(self.special_tokens, key=len, reverse=True))
            self._special_split = _regex.compile(f"({alternation})")

    # ---------------------------------------------------------------- load

    @classmethod
    def from_file(cls, path: str) -> "Tokenizer":
        """Sniff the format: HF ``tokenizer.json`` or tiktoken ranks."""
        with open(path, "rb") as fh:
            head = fh.read(64)
        if head.lstrip().startswith(b"{"):
            return cls.from_hf_json(path)
        return cls.from_tiktoken(path)

    @classmethod
    def from_hf_json(cls, path: str) -> "Tokenizer":
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        model = doc.get("model", {})
        if model.get("type") != "BPE":
            raise ValueError(f"unsupported tokenizer model: "
                             f"{model.get('type')!r}")
        vocab = {_alias_to_bytes(tok): i
                 for tok, i in model["vocab"].items()}
        merge_ranks = {}
        for rank, merge in enumerate(model.get("merges", [])):
            if isinstance(merge, str):       # "left right"
                left, right = merge.split(" ", 1)
            else:                            # ["left", "right"]
                left, right = merge
            merge_ranks[(_alias_to_bytes(left),
                         _alias_to_bytes(right))] = rank
        special = {}
        for added in doc.get("added_tokens", []):
            special[added["content"]] = added["id"]
        pattern = _extract_pattern(doc) or GPT2_PATTERN
        return cls(vocab, merge_ranks, special, pattern)

    @classmethod
    def from_tiktoken(cls, path: str,
                      special_tokens: Optional[Dict[str, int]] = None,
                      pattern: str = LLAMA3_PATTERN) -> "Tokenizer":
        """Meta Llama-3 ``tokenizer.model``: ``base64(token) rank``
        lines; merge priority is the concatenation's vocab rank.  The
        Llama-3 reserved specials (<|begin_of_text|> …) are appended
        after the base vocab when none are given — their standard ids."""
        vocab: Dict[bytes, int] = {}
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                token_b64, rank = line.split()
                vocab[base64.b64decode(token_b64)] = int(rank)
        if special_tokens is None:
            base = len(vocab)
            names = ["<|begin_of_text|>", "<|end_of_text|>",
                     "<|reserved_special_token_0|>",
                     "<|reserved_special_token_1|>",
                     "<|finetune_right_pad_id|>",
                     "<|step_id|>", "<|start_header_id|>",
                     "<|end_header_id|>", "<|eom_id|>", "<|eot_id|>",
                     "<|python_tag|>"]
            names += [f"<|reserved_special_token_{i}|>"
                      for i in range(2, 256 - len(names) + 2)]
            special_tokens = {name: base + i
                              for i, name in enumerate(names[:256])}
        return cls(vocab, None, special_tokens, pattern)

    # -------------------------------------------------------------- encode

    def _pair_rank(self, left: bytes, right: bytes) -> Optional[int]:
        if self.merge_ranks:
            return self.merge_ranks.get((left, right))
        return self.vocab.get(left + right)     # tiktoken rule

    def _bpe(self, word: bytes) -> List[int]:
        parts: List[bytes] = [word[i:i + 1] for i in range(len(word))]
        while len(parts) > 1:
            best_rank = None
            best_i = -1
            for i in range(len(parts) - 1):
                rank = self._pair_rank(parts[i], parts[i + 1])
                if rank is not None and (best_rank is None
                                         or rank < best_rank):
                    best_rank, best_i = rank, i
            if best_rank is None:
                break
            parts[best_i:best_i + 2] = [parts[best_i]
                                        + parts[best_i + 1]]
        out = []
        for part in parts:
            token_id = self.vocab.get(part)
            if token_id is None:
                # Unmergeable byte with no vocab entry: byte fallback
                # ids if present, else skip (matches HF's byte-level
                # guarantee that single bytes are always in vocab).
                for byte in part:
                    byte_id = self.vocab.get(bytes([byte]))
                    if byte_id is not None:
                        out.append(byte_id)
                continue
            out.append(token_id)
        return out

    def encode_ordinary(self, text: str) -> List[int]:
        """Encode with NO special-token recognition."""
        ids: List[int] = []
        for piece in self._compiled.findall(text):
            ids.extend(self._bpe(piece.encode("utf-8")))
        return ids

    def encode(self, text: str, allow_special: bool = True) -> List[int]:
        if not allow_special or self._special_split is None:
            return self.encode_ordinary(text)
        ids: List[int] = []
        for chunk in self._special_split.split(text):
            if not chunk:
                continue
            if chunk in self.special_tokens:
                ids.append(self.special_tokens[chunk])
            else:
                ids.extend(self.encode_ordinary(chunk))
        return ids

    # -------------------------------------------------------------- decode

    def decode(self, ids: Iterable[int],
               skip_special: bool = False) -> str:
        out: List[bytes] = []
        for i in ids:
            i = int(i)
            if i in self._id_to_special:
                if not skip_special:
                    out.append(self._id_to_special[i].encode("utf-8"))
            elif i in self._id_to_bytes:
                out.append(self._id_to_bytes[i])
        return b"".join(out).decode("utf-8", errors="replace")

    # --------------------------------------------------------------- misc

    @property
    def vocab_size(self) -> int:
        top = max(
            [max(self.vocab.values(), default=-1)]
            + [max(self.special_tokens.values(), default=-1)])
        return top + 1

    def token_id(self, special: str) -> int:
        return self.special_tokens[special]


def _extract_pattern(doc) -> Optional[str]:
    """Pull the split regex out of a tokenizer.json pre_tokenizer
    (possibly nested in a Sequence).  ByteLevel with use_regex=True
    means the GPT-2 pattern."""
    pre = doc.get("pre_tokenizer") or {}

    def walk(node):
        if not isinstance(node, dict):
            return None
        kind = node.get("type")
        if kind == "Sequence":
            for sub in node.get("pretokenizers", []):
                found = walk(sub)
                if found:
                    return found
        if kind == "Split":
            pattern = node.get("pattern", {})
            if isinstance(pattern, dict):
                return pattern.get("Regex") or pattern.get("String")
            return pattern
        if kind == "ByteLevel" and node.get("use_regex", True):
            return GPT2_PATTERN
        return None

    return walk(pre)

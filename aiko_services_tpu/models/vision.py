"""Vision encoder: CLIP-architecture ViT producing image embeddings.

The vision half of the CLIP→LLM fan-out workload (BASELINE.json
config 5).  Patchify → transformer encoder → pooled, L2-normalized
embedding; ``project_to_llm`` maps embeddings into an LLM's embedding
space (the LLaVA-style bridge for vision-chat pipelines).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..ops.attention import attention_reference

__all__ = ["VisionConfig", "init_params", "encode", "CONFIGS"]


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 16
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    embed_dim: int = 512          # output embedding dimensionality
    dtype: Any = jnp.bfloat16

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


CONFIGS: Dict[str, VisionConfig] = {
    "tiny": VisionConfig(image_size=32, patch_size=8, d_model=64,
                         n_layers=2, n_heads=2, embed_dim=64),
    "clip_base": VisionConfig(image_size=224, patch_size=16, d_model=768,
                              n_layers=12, n_heads=12, embed_dim=512),
}


def init_params(config: VisionConfig, key) -> Dict:
    keys = jax.random.split(key, config.n_layers + 4)
    d, dt = config.d_model, config.dtype
    patch_dim = 3 * config.patch_size ** 2

    def dense(k, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                * shape[0] ** -0.5).astype(dt)

    layers = []
    for i in range(config.n_layers):
        lk = jax.random.split(keys[i], 4)
        layers.append({
            "norm1": jnp.ones((d,), dt),
            "wqkv": dense(lk[0], (d, 3 * d)),
            "wo": dense(lk[1], (d, d)),
            "norm2": jnp.ones((d,), dt),
            "w1": dense(lk[2], (d, 4 * d)),
            "w2": dense(lk[3], (4 * d, d)),
        })
    return {
        "patch_proj": dense(keys[-4], (patch_dim, d)),
        "cls_token": jnp.zeros((1, 1, d), dt),
        "pos_embed": dense(keys[-3], (config.n_patches + 1, d)),
        "layers": layers,
        "final_norm": jnp.ones((d,), dt),
        "head": dense(keys[-2], (d, config.embed_dim)),
    }


from .common import layer_norm as _norm, mha as _mha, gelu_mlp


@functools.partial(jax.jit, static_argnames=("config",))
def encode(params, images, config: VisionConfig):
    """images (batch, H, W, 3) float [0,1] → dict with ``embedding``
    (batch, embed_dim) L2-normalized and ``patch_features``
    (batch, n_patches+1, d_model) for LLaVA-style token bridges."""
    b = images.shape[0]
    p = config.patch_size
    grid = config.image_size // p
    x = images.astype(config.dtype)
    x = x.reshape(b, grid, p, grid, p, 3).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(b, grid * grid, p * p * 3)
    x = x @ params["patch_proj"]
    cls = jnp.broadcast_to(params["cls_token"],
                           (b, 1, config.d_model)).astype(x.dtype)
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"][None]
    for layer in params["layers"]:
        normed = _norm(x, layer["norm1"])
        x = x + _mha(normed, normed, layer["wqkv"], layer["wo"],
                     config.n_heads, causal=False)
        x = gelu_mlp(x, layer["norm2"], layer["w1"], layer["w2"])
    x = _norm(x, params["final_norm"])
    embedding = (x[:, 0] @ params["head"]).astype(jnp.float32)
    embedding = embedding / jnp.maximum(
        jnp.linalg.norm(embedding, axis=-1, keepdims=True), 1e-6)
    return {"embedding": embedding, "patch_features": x}

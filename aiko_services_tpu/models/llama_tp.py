"""Tensor-parallel serving engine: one replica = one mesh.

shard_map mirrors of the paged serving entry points in
:mod:`.llama` (``serve_chunk_paged`` / ``serve_chunk_mixed`` /
``prefill_append_paged``) that run TP-sharded over a
:class:`~..parallel.mesh.ReplicaMesh`:

* Every 2-D weight leaf is sharded on its LAST (output-feature) axis —
  one uniform rule that covers dense bf16 weights, int8 ``{"q","s"}``
  and int4 ``{"q4","s"}`` trees (scales are 2-D with the output axis
  last), the embedding (feature-sharded rows), and the LM head
  (vocab-sharded logits).  Each local matmul therefore keeps the FULL
  contraction dimension and computes a contiguous slice of output
  columns; the only collective is an ``all_gather`` of those columns.
  An all-gather is pure data movement — no partial-sum reduction whose
  float ordering could differ from the single-chip program — which is
  what makes TP greedy decode token-identical to single-chip greedy
  (the exact-equality gate in tests/test_tp_serving.py).  The
  row-parallel/``reduce-scatter`` layout (see
  :mod:`..parallel.collective_matmul`, usable on TPU to overlap the
  collective with the matmul) trades that exactness for bandwidth and
  is deliberately NOT used here.

* The paged KV pool shards along its kv-head axis (dim 2) as GLOBAL
  ``jax.Array``s — host-side block bookkeeping (prefix-cache
  scatter/gather, kvstore export/import) keeps operating on full-width
  arrays and jax resolves blocks to per-shard slices.  Because wq/wk/wv
  shard by whole heads (contiguous output ranges), shard ``i`` computes
  exactly q-heads ``[i*h/tp, (i+1)*h/tp)`` and kv-heads
  ``[i*kv/tp, (i+1)*kv/tp)`` — and since ``tp | n_kv_heads``, every
  shard's q-head range covers whole GQA groups of its local kv heads.
  Attention is a per-kv-head computation, so it stays entirely local
  between the QKV projections and the output-projection gather: the
  pool is NEVER gathered across shards (jaxpr-guarded).

* Per-slot decode state (tokens/positions/active/remaining/tables) is
  replicated, so the host admission/commit/dirty-sync protocol is
  byte-identical to the single-chip server, and the tiny per-step
  (tokens, counts) sync stays tiny.

LoRA adapters and MoE configs are rejected under TP (adapter factors
and expert weights don't fit the 2-D output-axis rule yet).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                      # jax >= 0.8
    from jax import shard_map
except ImportError:                       # jax 0.4.x
    from jax.experimental.shard_map import shard_map

from ..ops.paged_attention import paged_decode_attention
from ..ops.paged_prefill import (paged_prefill_attention,
                                 paged_verify_attention)
from . import llama
from .llama import LlamaConfig

__all__ = ["TPEngine", "tp_param_specs", "tp_pool_specs",
           "shard_params", "shard_pool", "replicate",
           "scatter_state_rows"]


# --------------------------------------------------------------------------- #
# Sharding layout


def tp_param_specs(params, axis: str = "tp"):
    """Output-axis PartitionSpecs for an ACTUAL parameter tree (dense
    or quantized): every 2-D leaf shards its last axis, everything
    else (1-D norm vectors) replicates.  Operating on the real tree —
    not the config — means one rule serves bf16, int8 and int4
    layouts identically."""
    return jax.tree.map(
        lambda leaf: P(None, axis) if getattr(leaf, "ndim", 0) == 2
        else P(), params)


def tp_pool_specs(pool, axis: str = "tp"):
    """Kv-head-axis PartitionSpecs for a paged pool (list of per-layer
    ``{"k","v"[,"ks","vs"]}`` dicts): the 4-D k/v buffers
    ``(n_blocks, block_size, kv_heads, head_dim)`` shard dim 2, the
    3-D int8 scales shard their trailing kv-head dim."""
    return jax.tree.map(
        lambda buf: P(None, None, axis, None) if buf.ndim == 4
        else P(None, None, axis), pool)


def shard_params(params, mesh: Mesh, axis: str = "tp"):
    """Lay a parameter tree out over the replica mesh (global arrays,
    output axis sharded)."""
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf,
                                          NamedSharding(mesh, spec)),
        params, tp_param_specs(params, axis))


def shard_pool(pool, mesh: Mesh, axis: str = "tp"):
    """Lay a paged pool out over the replica mesh (global arrays,
    kv-head axis sharded)."""
    return jax.tree.map(
        lambda buf, spec: jax.device_put(buf, NamedSharding(mesh, spec)),
        pool, tp_pool_specs(pool, axis))


def replicate(tree, mesh: Mesh):
    """Replicate a pytree onto every device of the replica mesh (the
    per-slot decode state layout)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda leaf: jax.device_put(leaf, sharding),
                        tree)


def scatter_state_rows(state, rows, packet, mesh: Mesh):
    """TP twin of :func:`.llama.scatter_state_rows`: the compact
    dirty-row packet (tiny numpy rows) is explicitly replicated onto
    the replica mesh before the jitted scatter, so the merged decode
    state stays a replicated ``jax.Array`` that shard_map's ``P()``
    in_specs accept — same contract as :func:`replicate`."""
    sharding = NamedSharding(mesh, P())
    rows = jax.device_put(rows, sharding)
    packet = jax.tree.map(
        lambda leaf: jax.device_put(leaf, sharding), packet)
    return llama.scatter_state_rows(state, rows, packet)


# --------------------------------------------------------------------------- #
# Shard-local model mirrors
#
# These mirror llama's paged decode/prefill cores LINE FOR LINE, with
# three mechanical changes: head counts become shard-local
# (h/tp, kv/tp), LoRA plumbing is dropped (rejected under TP), and an
# output-column all_gather follows each matmul whose result the next
# (replicated-input) op needs in full.  f32 cast discipline is kept
# exactly where the originals cast — every gathered value is bitwise
# the concatenation of per-shard values, so the math matches the
# single-chip program bit for bit.


def _gather_cols(x, axis_name: str):
    """All-gather the local output columns back to the full feature
    axis (pure data movement — the exactness-preserving collective)."""
    return jax.lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)


def _tp_embed(params, tokens, config: LlamaConfig, axis: str):
    return _gather_cols(
        llama._embed_lookup(params, tokens, config.dtype), axis)


def _tp_lm_head(params, config: LlamaConfig, axis: str, x):
    x = llama.rms_norm(x, params["final_norm"], config.norm_eps)
    logits = llama._matmul(x, params["lm_head"]).astype(jnp.float32)
    return _gather_cols(logits, axis)


def _tp_mlp_block(layer, config: LlamaConfig, axis: str, x):
    normed = llama.rms_norm(x, layer["mlp_norm"], config.norm_eps)
    gate = jax.nn.silu(
        llama._matmul(normed, layer["w_gate"]).astype(jnp.float32))
    up = llama._matmul(normed, layer["w_up"]).astype(jnp.float32)
    act = _gather_cols((gate * up).astype(x.dtype), axis)
    return x + _gather_cols(llama._matmul(act, layer["w_down"]), axis)


def _tp_attention_decode_paged(layer, config: LlamaConfig, tp: int,
                               axis: str, x, cos, sin, pool_layer,
                               tables, positions):
    """Shard-local mirror of ``llama._attention_decode_paged``:
    projections produce this shard's contiguous head range, the pool
    write and the attention kernel/reference run on the LOCAL kv-head
    slice, and only the attention output's feature columns gather
    before the output projection."""
    batch, seq = x.shape[:2]
    h, kv = config.n_heads // tp, config.n_kv_heads // tp
    hd = config.head_dim
    normed = llama.rms_norm(x, layer["attn_norm"], config.norm_eps)
    q = llama._matmul(normed, layer["wq"]).reshape(batch, seq, h, hd)
    k = llama._matmul(normed, layer["wk"]).reshape(batch, seq, kv, hd)
    v = llama._matmul(normed, layer["wv"]).reshape(batch, seq, kv, hd)
    q = llama.apply_rope(q, cos, sin)
    k = llama.apply_rope(k, cos, sin)
    new_pool = llama._paged_write_rows(pool_layer, k, v, tables,
                                       positions)
    use_kernel, interpret = llama.decode_kernel_mode()
    q_g = q.reshape(batch, seq, kv, h // kv, hd)
    if use_kernel:
        out = paged_decode_attention(
            q_g[:, 0], new_pool["k"], new_pool["v"], tables, positions,
            ks=new_pool.get("ks"), vs=new_pool.get("vs"),
            window=config.sliding_window, interpret=interpret)[:, None]
    else:
        gathered = llama._paged_gather(new_pool, tables)
        out = llama._cached_gqa_attention(q_g, gathered,
                                          positions[:, None], hd,
                                          window=config.sliding_window)
    out = _gather_cols(out.reshape(batch, seq, h * hd), axis)
    attn = _gather_cols(llama._matmul(out, layer["wo"]), axis)
    return x + attn.astype(x.dtype), new_pool


def _tp_decode_core_paged(params, token, pool, tables, positions,
                          config: LlamaConfig, tp: int, axis: str):
    positions_2d = positions[:, None]
    cos, sin = llama._rope_freqs(config, positions_2d)
    x = _tp_embed(params, token, config, axis)
    new_pool = []
    for layer, pool_layer in zip(params["layers"], pool):
        x, layer_pool = _tp_attention_decode_paged(
            layer, config, tp, axis, x, cos, sin, pool_layer, tables,
            positions)
        new_pool.append(layer_pool)
        x = _tp_mlp_block(layer, config, axis, x)
    logits = _tp_lm_head(params, config, axis, x)
    return logits, new_pool


def _tp_prefill_append_core(params, tokens, pool, tables, start_index,
                            config: LlamaConfig, tp: int, axis: str,
                            kv_limit=None,
                            compute_logits: bool = False):
    """Shard-local mirror of ``llama._prefill_append_core``: the
    chunk's K/V land in the LOCAL pool slice, append attention runs
    per local kv head, activations gather after each projection."""
    batch, K = tokens.shape
    h, kv = config.n_heads // tp, config.n_kv_heads // tp
    hd = config.head_dim
    start_index = jnp.asarray(start_index, jnp.int32)
    positions_b = jnp.broadcast_to(
        start_index + jnp.arange(K, dtype=jnp.int32), (batch, K))
    cached_lens = jnp.broadcast_to(start_index, (batch,))
    chunk_lens = jnp.full((batch,), K, jnp.int32)
    cos, sin = llama._rope_freqs(config, positions_b)
    x = _tp_embed(params, tokens, config, axis)
    use_kernel, interpret = llama.prefill_kernel_mode()
    new_pool = []
    for layer, pool_layer in zip(params["layers"], pool):
        normed = llama.rms_norm(x, layer["attn_norm"], config.norm_eps)
        q = llama._matmul(normed, layer["wq"]).reshape(batch, K, h, hd)
        k = llama._matmul(normed, layer["wk"]).reshape(batch, K, kv, hd)
        v = llama._matmul(normed, layer["wv"]).reshape(batch, K, kv, hd)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        q_g = q.reshape(batch, K, kv, h // kv, hd)
        if use_kernel:
            out, pool_layer = paged_prefill_attention(
                q_g, k, v, pool_layer, tables, cached_lens, chunk_lens,
                window=config.sliding_window, interpret=interpret,
                kv_limit=kv_limit)
        else:
            pool_layer = llama._paged_write_slab(pool_layer, k, v,
                                                 tables, positions_b)
            gathered = llama._paged_gather(pool_layer, tables)
            out = llama._cached_gqa_attention(
                q_g, gathered, positions_b, hd,
                window=config.sliding_window)
        new_pool.append(pool_layer)
        out = _gather_cols(out.reshape(batch, K, h * hd), axis)
        x = x + _gather_cols(llama._matmul(out, layer["wo"]),
                             axis).astype(x.dtype)
        x = _tp_mlp_block(layer, config, axis, x)
    if not compute_logits:
        return None, new_pool
    return _tp_lm_head(params, config, axis, x), new_pool


def _tp_verify_core(params, tokens, pool, tables, positions, active,
                    config: LlamaConfig, tp: int, axis: str,
                    kv_limit=None):
    """Shard-local mirror of ``llama._verify_append_core`` (the
    speculative verify): every row at its OWN absolute start position,
    the window's K/V appended into the LOCAL kv-head slice of the
    pool, inactive rows routed to scratch block 0.  The all-gathers
    are the same column gathers as the decode/prefill mirrors —
    bitwise concatenations — so TP verify logits equal single-chip
    verify logits bit for bit (invariants 9 + 11)."""
    batch, K = tokens.shape
    h, kv = config.n_heads // tp, config.n_kv_heads // tp
    hd = config.head_dim
    starts = jnp.where(active, positions, 0).astype(jnp.int32)
    positions_b = starts[:, None] + jnp.arange(K, dtype=jnp.int32)[None]
    cached_lens = starts
    chunk_lens = jnp.where(active, K, 0).astype(jnp.int32)
    write_tables = jnp.where(active[:, None], tables,
                             jnp.zeros_like(tables))
    cos, sin = llama._rope_freqs(config, positions_b)
    x = _tp_embed(params, tokens, config, axis)
    use_kernel, interpret = llama.prefill_kernel_mode()
    new_pool = []
    for layer, pool_layer in zip(params["layers"], pool):
        normed = llama.rms_norm(x, layer["attn_norm"], config.norm_eps)
        q = llama._matmul(normed, layer["wq"]).reshape(batch, K, h, hd)
        k = llama._matmul(normed, layer["wk"]).reshape(batch, K, kv, hd)
        v = llama._matmul(normed, layer["wv"]).reshape(batch, K, kv, hd)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        q_g = q.reshape(batch, K, kv, h // kv, hd)
        if use_kernel:
            out, pool_layer = paged_verify_attention(
                q_g, k, v, pool_layer, write_tables, cached_lens,
                chunk_lens, window=config.sliding_window,
                interpret=interpret, kv_limit=kv_limit)
        else:
            pool_layer = llama._paged_write_slab(pool_layer, k, v,
                                                 write_tables,
                                                 positions_b)
            gathered = llama._paged_gather(pool_layer, write_tables)
            out = llama._cached_gqa_attention(
                q_g, gathered, positions_b, hd,
                window=config.sliding_window)
        new_pool.append(pool_layer)
        out = _gather_cols(out.reshape(batch, K, h * hd), axis)
        x = x + _gather_cols(llama._matmul(out, layer["wo"]),
                             axis).astype(x.dtype)
        x = _tp_mlp_block(layer, config, axis, x)
    return _tp_lm_head(params, config, axis, x), new_pool


# --------------------------------------------------------------------------- #
# The engine


class TPEngine:
    """Per-server dispatcher for the TP serving entry points.

    Built once per :class:`PagedContinuousServer` (the shard_map
    in/out spec trees depend on the server's actual parameter and pool
    pytree structure — quantization layout, layer count — so the
    jitted closures are constructed per engine and cached per static
    signature).  Mirrors the llama entry points' signatures so the
    server's dispatch sites stay one-line switches:

    * :meth:`serve_chunk_paged` — decode chunk (pool donated)
    * :meth:`serve_chunk_mixed` — chunked-prefill slice + decode chunk
    * :meth:`prefill_append_paged` — standalone prefill append
    * :meth:`verify_chunk_paged` — speculative verify window
    """

    def __init__(self, config: LlamaConfig, mesh: Mesh, params, pool,
                 axis: str = "tp"):
        if axis not in mesh.axis_names:
            raise ValueError(
                f"mesh has no '{axis}' axis: {mesh.axis_names}")
        self.config = config
        self.mesh = mesh
        self.axis = axis
        self.tp = mesh.shape[axis]
        if config.n_kv_heads % self.tp or config.n_heads % self.tp:
            raise ValueError(
                f"tp={self.tp} must divide n_kv_heads="
                f"{config.n_kv_heads} and n_heads={config.n_heads}")
        self._param_specs = tp_param_specs(params, axis)
        self._pool_specs = tp_pool_specs(pool, axis)
        self._cache: Dict[Any, Any] = {}

    # -- spec helpers -------------------------------------------------- #

    def _shard_map(self, body, in_specs, out_specs):
        return shard_map(body, mesh=self.mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    # -- decode chunk -------------------------------------------------- #

    def serve_chunk_paged(self, params, state, pool, num_steps,
                          eos_id: int = -1, sampled: bool = False,
                          rng_key=None):
        """TP twin of :func:`llama.serve_chunk_paged` (no LoRA)."""
        num_steps = int(num_steps)
        key = ("serve", num_steps, int(eos_id), bool(sampled),
               rng_key is not None)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build_serve(num_steps, int(eos_id),
                                   bool(sampled), rng_key is not None)
            self._cache[key] = fn
        args = (params, state, pool) + (
            (rng_key,) if rng_key is not None else ())
        return fn(*args)

    def _build_serve(self, num_steps, eos_id, sampled, has_rng):
        config, tp, axis = self.config, self.tp, self.axis

        def body(params, state, pool, rng_key=None):
            block_size = pool[0]["k"].shape[1]
            tables = state["tables"]
            slots = tables.shape[0]
            scratch_tables = jnp.zeros_like(tables)
            scratch_positions = (jnp.arange(slots, dtype=jnp.int32)
                                 % block_size)

            def step_core(token, pool, positions, active):
                write_tables = jnp.where(active[:, None], tables,
                                         scratch_tables)
                write_pos = jnp.where(active, positions,
                                      scratch_positions)
                return _tp_decode_core_paged(params, token, pool,
                                             write_tables, write_pos,
                                             config, tp, axis)

            return llama._serve_scan(step_core, state, pool, num_steps,
                                     eos_id, sampled, rng_key)

        in_specs = (self._param_specs, P(), self._pool_specs)
        if has_rng:
            in_specs += (P(),)
        out_specs = (P(), P(), P(), self._pool_specs)
        return jax.jit(self._shard_map(body, in_specs, out_specs),
                       donate_argnums=(2,))

    # -- mixed prefill/decode chunk ------------------------------------ #

    def serve_chunk_mixed(self, params, state, pool, prefill_tokens,
                          prefill_row, prefill_start, num_steps,
                          eos_id: int = -1, sampled: bool = False,
                          rng_key=None, prefill_kv_limit=None):
        """TP twin of :func:`llama.serve_chunk_mixed` (no LoRA)."""
        num_steps = int(num_steps)
        key = ("mixed", num_steps, int(eos_id), bool(sampled),
               rng_key is not None, prefill_kv_limit)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build_mixed(num_steps, int(eos_id),
                                   bool(sampled), rng_key is not None,
                                   prefill_kv_limit)
            self._cache[key] = fn
        args = (params, state, pool, prefill_tokens,
                jnp.asarray(prefill_row, jnp.int32),
                jnp.asarray(prefill_start, jnp.int32)) + (
            (rng_key,) if rng_key is not None else ())
        return fn(*args)

    def _build_mixed(self, num_steps, eos_id, sampled, has_rng,
                     prefill_kv_limit):
        config, tp, axis = self.config, self.tp, self.axis

        def body(params, state, pool, prefill_tokens, prefill_row,
                 prefill_start, rng_key=None):
            block_size = pool[0]["k"].shape[1]
            tables = state["tables"]
            slots = tables.shape[0]
            tables_row = jax.lax.dynamic_slice_in_dim(
                tables, prefill_row, 1, axis=0)
            _, pool = _tp_prefill_append_core(
                params, prefill_tokens, pool, tables_row,
                prefill_start, config, tp, axis,
                kv_limit=prefill_kv_limit, compute_logits=False)
            scratch_tables = jnp.zeros_like(tables)
            scratch_positions = (jnp.arange(slots, dtype=jnp.int32)
                                 % block_size)

            def step_core(token, pool, positions, active):
                write_tables = jnp.where(active[:, None], tables,
                                         scratch_tables)
                write_pos = jnp.where(active, positions,
                                      scratch_positions)
                return _tp_decode_core_paged(params, token, pool,
                                             write_tables, write_pos,
                                             config, tp, axis)

            return llama._serve_scan(step_core, state, pool, num_steps,
                                     eos_id, sampled, rng_key)

        in_specs = (self._param_specs, P(), self._pool_specs,
                    P(), P(), P())
        if has_rng:
            in_specs += (P(),)
        out_specs = (P(), P(), P(), self._pool_specs)
        return jax.jit(self._shard_map(body, in_specs, out_specs),
                       donate_argnums=(2,))

    # -- speculative verify window ------------------------------------- #

    def verify_chunk_paged(self, params, tokens, pool, tables,
                           positions, active, kv_limit=None):
        """TP twin of :func:`llama.verify_chunk_paged` (no LoRA):
        score a (slots, k+1) speculative window against the sharded
        pool, each row at its own absolute position.  Returns
        ``(logits (slots, k+1, vocab), pool)`` with the pool donated —
        bitwise equal to the single-chip verify (all-gather is the
        only collective)."""
        K = int(tokens.shape[1])
        key = ("verify", K, kv_limit)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build_verify(kv_limit)
            self._cache[key] = fn
        return fn(params, tokens, pool, tables, positions, active)

    def _build_verify(self, kv_limit):
        config, tp, axis = self.config, self.tp, self.axis

        def body(params, tokens, pool, tables, positions, active):
            return _tp_verify_core(params, tokens, pool, tables,
                                   positions, active, config, tp,
                                   axis, kv_limit=kv_limit)

        in_specs = (self._param_specs, P(), self._pool_specs,
                    P(), P(), P())
        out_specs = (P(), self._pool_specs)
        return jax.jit(self._shard_map(body, in_specs, out_specs),
                       donate_argnums=(2,))

    # -- standalone prefill append ------------------------------------- #

    def prefill_append_paged(self, params, tokens, pool, tables,
                             start_index, kv_limit=None,
                             compute_logits: bool = False):
        """TP twin of :func:`llama.prefill_append_paged` (no LoRA).
        Always dispatched with ``compute_logits=False`` by the paged
        server (the mixed step owns logits); returns ``(None,
        new_pool)`` to match the llama call-site unpacking."""
        if compute_logits:
            raise NotImplementedError(
                "TP prefill_append_paged serves the paged admission "
                "path, which never reads prefill logits")
        key = ("prefill", kv_limit)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build_prefill(kv_limit)
            self._cache[key] = fn
        return None, fn(params, tokens, pool, tables,
                        jnp.asarray(start_index, jnp.int32))

    def _build_prefill(self, kv_limit):
        config, tp, axis = self.config, self.tp, self.axis

        def body(params, tokens, pool, tables, start_index):
            _, new_pool = _tp_prefill_append_core(
                params, tokens, pool, tables, start_index, config, tp,
                axis, kv_limit=kv_limit, compute_logits=False)
            return new_pool

        in_specs = (self._param_specs, P(), self._pool_specs, P(), P())
        out_specs = self._pool_specs
        return jax.jit(self._shard_map(body, in_specs, out_specs),
                       donate_argnums=(2,))

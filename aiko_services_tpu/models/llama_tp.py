"""Tensor-parallel serving engine: one replica = one mesh.

shard_map mirrors of the paged serving entry points in
:mod:`.llama` (``serve_chunk_paged`` / ``serve_chunk_mixed`` /
``prefill_append_paged``) that run TP-sharded over a
:class:`~..parallel.mesh.ReplicaMesh`:

* Every 2-D weight leaf is sharded on its LAST (output-feature) axis —
  one uniform rule that covers dense bf16 weights, int8 ``{"q","s"}``
  and int4 ``{"q4","s"}`` trees (scales are 2-D with the output axis
  last), the embedding (feature-sharded rows), and the LM head
  (vocab-sharded logits).  Each local matmul therefore keeps the FULL
  contraction dimension and computes a contiguous slice of output
  columns; the only collective is an ``all_gather`` of those columns.
  An all-gather is pure data movement — no partial-sum reduction whose
  float ordering could differ from the single-chip program — which is
  what makes TP greedy decode token-identical to single-chip greedy
  (the exact-equality gate in tests/test_tp_serving.py).  The
  row-parallel/``reduce-scatter`` layout (see
  :mod:`..parallel.collective_matmul`, usable on TPU to overlap the
  collective with the matmul) trades that exactness for bandwidth and
  is deliberately NOT used here.

* The paged KV pool shards along its kv-head axis (dim 2) as GLOBAL
  ``jax.Array``s — host-side block bookkeeping (prefix-cache
  scatter/gather, kvstore export/import) keeps operating on full-width
  arrays and jax resolves blocks to per-shard slices.  Because wq/wk/wv
  shard by whole heads (contiguous output ranges), shard ``i`` computes
  exactly q-heads ``[i*h/tp, (i+1)*h/tp)`` and kv-heads
  ``[i*kv/tp, (i+1)*kv/tp)`` — and since ``tp | n_kv_heads``, every
  shard's q-head range covers whole GQA groups of its local kv heads.
  Attention is a per-kv-head computation, so it stays entirely local
  between the QKV projections and the output-projection gather: the
  pool is NEVER gathered across shards (jaxpr-guarded).

* Per-slot decode state (tokens/positions/active/remaining/tables) is
  replicated, so the host admission/commit/dirty-sync protocol is
  byte-identical to the single-chip server, and the tiny per-step
  (tokens, counts) sync stays tiny.

* SECOND mesh axis (2-D ReplicaMesh).  ``sp`` (sequence parallel):
  one chunked-admission dispatch carries ``sp`` consecutive prompt
  chunks, sharded over the axis — each shard prefills its own chunk
  at its own absolute offset, all-gathers the window's K/V over
  ``sp`` (pure data movement) and writes the FULL window into its
  pool copy, so the pool stays sharded on ``tp`` and bitwise
  REPLICATED on ``sp``.  Attention for chunk ``j`` runs with
  ``cached_lens = start + j*W`` — exactly the sequential chunk-``j``
  program — so sp-sharded prefill is bitwise the single-chip chunked
  admission (invariant 19).  ``ep`` (expert parallel): MoE expert
  weights shard ``P(ep, None, tp)``; the dispatch/combine einsums run
  on exact expert/feature slices and only all-gathers recombine them,
  so MoE TP/EP greedy decode equals single-chip bit for bit — the old
  blanket MoE rejection is gone.  Decode runs replicated over the
  second axis (every sp/ep row computes identical tokens).

* LoRA adapters compose with TP (multi-tenant serving): the stacked
  factor tree shards by the SAME output-column rule as the base
  weights — A factors and the scale replicate, B factors column-shard
  on d_out (:func:`tp_lora_specs` / :func:`shard_lora`).  Because
  :func:`.llama._lora_delta` is two PINNED einsums, the rank-r hidden
  ``x@A`` is computed identically on every shard and each output
  column of ``hidden@B`` is an independent r-dot — a shard's local
  delta is bitwise the column slice of the single-chip delta, added
  before the same all-gather the base matmul takes.  TP LoRA greedy
  decode is therefore token-identical to single-chip LoRA serving
  (tests/test_multi_lora.py TP gates).

``overlap=True`` (opt-in, bench-only) routes the dense-MLP
down-projection through :func:`..parallel.collective_matmul.
matmul_reducescatter` — the row-parallel lossy-LAYOUT path whose
ring partial sums reorder float addition vs single-chip, trading
exactness for ICI/compute overlap on real hardware.  Off by default;
every exactness test pins the exact all-gather path.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                      # jax >= 0.8
    from jax import shard_map
except ImportError:                       # jax 0.4.x
    from jax.experimental.shard_map import shard_map

from ..ops.paged_attention import paged_decode_attention
from ..ops.paged_prefill import (paged_prefill_attention,
                                 paged_verify_attention)
from . import llama
from .llama import LlamaConfig

__all__ = ["TPEngine", "tp_param_specs", "tp_pool_specs",
           "tp_lora_specs", "shard_params", "shard_pool", "shard_lora",
           "replicate", "scatter_state_rows"]


# --------------------------------------------------------------------------- #
# Sharding layout


def tp_param_specs(params, axis: str = "tp", ep_axis=None,
                   overlap: bool = False):
    """Output-axis PartitionSpecs for an ACTUAL parameter tree (dense
    or quantized): every 2-D leaf shards its last axis, everything
    else (1-D norm vectors) replicates.  Operating on the real tree —
    not the config — means one rule serves bf16, int8 and int4
    layouts identically.

    Two structured exceptions to the generic rule:

    * MoE expert weights (3-D ``(E, d, f)`` / ``(E, f, d)`` leaves
      under ``layers[i]["moe"]``) shard ``P(ep_axis, None, axis)`` AT
      REST — experts over the second mesh axis (replicated when
      ``ep_axis`` is None), per-expert features over ``tp`` — and are
      all-gathered per layer by :func:`_tp_moe_block` (weight-gathered
      EP).  The router REPLICATES (``moe_param_specs``): the gathered
      forward runs the exact single-chip ``moe_ffn`` program, which
      needs the full router resident.
    * ``overlap=True`` re-lays the dense MLP ``w_down`` row-parallel
      (``P(axis, None)`` on its contraction dim) for the
      reduce-scatter overlap path — lossy layout, bench-only.
    """
    specs = jax.tree.map(
        lambda leaf: P(None, axis) if getattr(leaf, "ndim", 0) == 2
        else P(), params)
    for layer, layer_specs in zip(params.get("layers", ()),
                                  specs.get("layers", ())):
        if "moe" in layer:
            from .moe import moe_param_specs
            moe_specs = moe_param_specs(ep_axis=ep_axis,
                                        feature_axis=axis)
            for name, leaf in layer["moe"].items():
                spec = moe_specs.get(name, P())
                # A quantized router is a {"q","s"} subtree — every
                # leaf under the name takes the same spec.
                layer_specs["moe"][name] = jax.tree.map(
                    lambda _leaf: spec, leaf)
        if overlap and getattr(layer.get("w_down"), "ndim", 0) == 2:
            layer_specs["w_down"] = P(axis, None)
    return specs


def tp_pool_specs(pool, axis: str = "tp"):
    """Kv-head-axis PartitionSpecs for a paged pool (list of per-layer
    ``{"k","v"[,"ks","vs"]}`` dicts): the 4-D k/v buffers
    ``(n_blocks, block_size, kv_heads, head_dim)`` shard dim 2, the
    3-D int8 scales shard their trailing kv-head dim."""
    return jax.tree.map(
        lambda buf: P(None, None, axis, None) if buf.ndim == 4
        else P(None, None, axis), pool)


def shard_params(params, mesh: Mesh, axis: str = "tp", ep_axis=None,
                 overlap: bool = False):
    """Lay a parameter tree out over the replica mesh (global arrays,
    output axis sharded; MoE experts over ``ep_axis`` when given)."""
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf,
                                          NamedSharding(mesh, spec)),
        params, tp_param_specs(params, axis, ep_axis=ep_axis,
                               overlap=overlap))


def tp_lora_specs(lora, axis: str = "tp"):
    """PartitionSpecs for a stacked-adapter tree
    (:func:`.lora.stack_adapters` layout): A factors
    ``(n_adapters, d_in, r)`` and the scalar scale REPLICATE — the
    rank-r hidden ``x@A`` must be computed identically on every shard
    — while B factors ``(n_adapters, r, d_out)`` column-shard their
    output axis exactly like the base weight they adapt, so the local
    delta columns line up with the local base-matmul columns.  An
    ``ids`` leaf (per-row adapter indices, present on the verify /
    standalone-prefill call shapes) replicates like the rest of the
    decode state."""
    specs = {
        "scale": P(),
        "layers": [{target: {"a": P(), "b": P(None, None, axis)}
                    for target in layer}
                   for layer in lora["layers"]],
    }
    if "ids" in lora:
        specs["ids"] = P()
    return specs


def shard_lora(lora_shared, mesh: Mesh, axis: str = "tp"):
    """Lay a stacked-adapter tree out over the replica mesh (A + scale
    replicated, B output-column-sharded).  The python-float scale stays
    host-side — jit traces it as the same weak-typed scalar the
    single-chip program folds in."""
    specs = tp_lora_specs(lora_shared, axis)

    def put(leaf, spec):
        if isinstance(leaf, (int, float)):
            return leaf
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(put, lora_shared, specs)


def shard_pool(pool, mesh: Mesh, axis: str = "tp"):
    """Lay a paged pool out over the replica mesh (global arrays,
    kv-head axis sharded)."""
    return jax.tree.map(
        lambda buf, spec: jax.device_put(buf, NamedSharding(mesh, spec)),
        pool, tp_pool_specs(pool, axis))


def replicate(tree, mesh: Mesh):
    """Replicate a pytree onto every device of the replica mesh (the
    per-slot decode state layout)."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda leaf: jax.device_put(leaf, sharding),
                        tree)


def scatter_state_rows(state, rows, packet, mesh: Mesh):
    """TP twin of :func:`.llama.scatter_state_rows`: the compact
    dirty-row packet (tiny numpy rows) is explicitly replicated onto
    the replica mesh before the jitted scatter, so the merged decode
    state stays a replicated ``jax.Array`` that shard_map's ``P()``
    in_specs accept — same contract as :func:`replicate`."""
    sharding = NamedSharding(mesh, P())
    rows = jax.device_put(rows, sharding)
    packet = jax.tree.map(
        lambda leaf: jax.device_put(leaf, sharding), packet)
    return llama.scatter_state_rows(state, rows, packet)


# --------------------------------------------------------------------------- #
# Shard-local model mirrors
#
# These mirror llama's paged decode/prefill cores LINE FOR LINE, with
# three mechanical changes: head counts become shard-local
# (h/tp, kv/tp), LoRA factors ride the SAME column sharding as the
# base weight they adapt (A + scale replicated, B column-sharded —
# see :func:`tp_lora_specs`), and an output-column all_gather follows
# each matmul whose result the next (replicated-input) op needs in
# full.  f32 cast discipline is kept exactly where the originals cast
# — every gathered value is bitwise the concatenation of per-shard
# values, so the math matches the single-chip program bit for bit.
# LoRA exactness leans on llama._lora_delta's two pinned einsums: the
# rank-r hidden x@A depends only on replicated inputs (identical on
# every shard), and each output column of hidden@B is an independent
# r-length dot — a shard holding B's column slice computes exactly its
# column slice of the single-chip delta, added BEFORE the gather.


def _gather_cols(x, axis_name: str):
    """All-gather the local output columns back to the full feature
    axis (pure data movement — the exactness-preserving collective)."""
    return jax.lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)


def _tp_embed(params, tokens, config: LlamaConfig, axis: str):
    return _gather_cols(
        llama._embed_lookup(params, tokens, config.dtype), axis)


def _tp_lm_head(params, config: LlamaConfig, axis: str, x):
    x = llama.rms_norm(x, params["final_norm"], config.norm_eps)
    logits = llama._matmul(x, params["lm_head"]).astype(jnp.float32)
    return _gather_cols(logits, axis)


def _tp_mlp_block(layer, config: LlamaConfig, axis: str, x,
                  ep_axis=None, ep: int = 1, overlap: bool = False):
    if "moe" in layer:
        return _tp_moe_block(layer, config, axis, x, ep_axis, ep)
    normed = llama.rms_norm(x, layer["mlp_norm"], config.norm_eps)
    gate = jax.nn.silu(
        llama._matmul(normed, layer["w_gate"]).astype(jnp.float32))
    up = llama._matmul(normed, layer["w_up"]).astype(jnp.float32)
    if overlap:
        # Opt-in lossy-layout path: w_down is laid out row-parallel
        # (P(axis, None) — its CONTRACTION rows match the act columns
        # this shard already holds), so the down-projection skips the
        # act gather entirely and reduce-scatters ring partial sums
        # behind the matmuls.  Partial-sum float order differs from
        # the single-chip program — bench-only, never the default.
        from ..parallel.collective_matmul import matmul_reducescatter
        act = (gate * up).astype(x.dtype)
        b, s, fl = act.shape
        down = matmul_reducescatter(act.reshape(b * s, fl),
                                    layer["w_down"], axis)
        return x + _gather_cols(down, axis).reshape(x.shape)
    act = _gather_cols((gate * up).astype(x.dtype), axis)
    return x + _gather_cols(llama._matmul(act, layer["w_down"]), axis)


def _tp_moe_block(layer, config: LlamaConfig, axis: str, x,
                  ep_axis=None, ep: int = 1):
    """Shard-local mirror of ``llama._mlp_block``'s MoE branch:
    WEIGHT-GATHERED expert parallelism.

    The 3-D expert weights live SHARDED at rest — experts over the
    ``ep`` mesh axis, per-expert feature columns over ``tp`` (that is
    the HBM-capacity win: each chip holds ``E/ep`` experts' columns).
    The forward pass all-gathers the expert tree (tiled all-gathers =
    pure data movement) and then runs the EXACT single-chip
    :func:`..models.moe.moe_ffn` program on the full tree, replicated.

    Why not compute-sharded dispatch (all-to-all)?  Exactness.  The
    XLA backend does not guarantee the same bits for a re-decomposed
    MoE graph — measured on CPU, even an op-by-op re-statement of
    ``moe_ffn``'s own einsums (barriered, same shapes) diverges from
    the fused single-chip program in the last bf16 ulp.  Running the
    same traced ``moe_ffn`` on identical full inputs is the only
    layout for which 2-D greedy ≡ single-chip holds BITWISE
    (invariants 9 + 19); compute-sharded token dispatch is a
    documented lossy-layout future step, same bucket as the
    ``overlap`` matmul path."""
    from .moe import moe_ffn
    moe, mcfg = layer["moe"], config.moe_config
    normed = llama.rms_norm(x, layer["mlp_norm"], config.norm_eps)
    full = dict(moe)
    for name in ("w_gate", "w_up", "w_down"):
        w = moe[name]
        if ep > 1:
            w = jax.lax.all_gather(w, ep_axis, axis=0, tiled=True)
        # Feature columns gather over tp (axis size 1 is a no-op).
        full[name] = jax.lax.all_gather(w, axis, axis=2, tiled=True)
    out = moe_ffn(full, normed, mcfg)
    return x + out.astype(x.dtype)


def _tp_attention_decode_paged(layer, config: LlamaConfig, tp: int,
                               axis: str, x, cos, sin, pool_layer,
                               tables, positions, lora=None,
                               lora_layer=None):
    """Shard-local mirror of ``llama._attention_decode_paged``:
    projections produce this shard's contiguous head range, the pool
    write and the attention kernel/reference run on the LOCAL kv-head
    slice, and only the attention output's feature columns gather
    before the output projection.  ``lora_layer`` holds this shard's
    column slice of the stacked B factors (A replicated), so each
    ``_lora_matmul`` delta lands on exactly the local output columns
    — added BEFORE the gather, like the base matmul's columns."""
    batch, seq = x.shape[:2]
    h, kv = config.n_heads // tp, config.n_kv_heads // tp
    hd = config.head_dim
    normed = llama.rms_norm(x, layer["attn_norm"], config.norm_eps)
    q = llama._lora_matmul(normed, layer["wq"], lora_layer, "wq",
                           lora).reshape(batch, seq, h, hd)
    k = llama._lora_matmul(normed, layer["wk"], lora_layer, "wk",
                           lora).reshape(batch, seq, kv, hd)
    v = llama._lora_matmul(normed, layer["wv"], lora_layer, "wv",
                           lora).reshape(batch, seq, kv, hd)
    q = llama.apply_rope(q, cos, sin)
    k = llama.apply_rope(k, cos, sin)
    new_pool = llama._paged_write_rows(pool_layer, k, v, tables,
                                       positions)
    use_kernel, interpret = llama.decode_kernel_mode()
    q_g = q.reshape(batch, seq, kv, h // kv, hd)
    if use_kernel:
        out = paged_decode_attention(
            q_g[:, 0], new_pool["k"], new_pool["v"], tables, positions,
            ks=new_pool.get("ks"), vs=new_pool.get("vs"),
            window=config.sliding_window, interpret=interpret)[:, None]
    else:
        gathered = llama._paged_gather(new_pool, tables)
        out = llama._cached_gqa_attention(q_g, gathered,
                                          positions[:, None], hd,
                                          window=config.sliding_window)
    out = _gather_cols(out.reshape(batch, seq, h * hd), axis)
    attn = _gather_cols(
        llama._lora_matmul(out, layer["wo"], lora_layer, "wo", lora),
        axis)
    return x + attn.astype(x.dtype), new_pool


def _lora_layers(lora, n_layers: int):
    """Per-layer factor dicts (or Nones) matching llama's iteration."""
    return lora["layers"] if lora else [None] * n_layers


def _tp_decode_core_paged(params, token, pool, tables, positions,
                          config: LlamaConfig, tp: int, axis: str,
                          lora=None, ep_axis=None, ep: int = 1,
                          overlap: bool = False):
    positions_2d = positions[:, None]
    cos, sin = llama._rope_freqs(config, positions_2d)
    x = _tp_embed(params, token, config, axis)
    new_pool = []
    lora_layers = _lora_layers(lora, len(pool))
    for layer, pool_layer, lora_layer in zip(params["layers"], pool,
                                             lora_layers):
        x, layer_pool = _tp_attention_decode_paged(
            layer, config, tp, axis, x, cos, sin, pool_layer, tables,
            positions, lora=lora, lora_layer=lora_layer)
        new_pool.append(layer_pool)
        x = _tp_mlp_block(layer, config, axis, x, ep_axis=ep_axis,
                          ep=ep, overlap=overlap)
    logits = _tp_lm_head(params, config, axis, x)
    return logits, new_pool


def _tp_prefill_append_core(params, tokens, pool, tables, start_index,
                            config: LlamaConfig, tp: int, axis: str,
                            lora=None, kv_limit=None,
                            compute_logits: bool = False,
                            ep_axis=None, ep: int = 1,
                            overlap: bool = False):
    """Shard-local mirror of ``llama._prefill_append_core``: the
    chunk's K/V land in the LOCAL pool slice, append attention runs
    per local kv head, activations gather after each projection."""
    batch, K = tokens.shape
    h, kv = config.n_heads // tp, config.n_kv_heads // tp
    hd = config.head_dim
    start_index = jnp.asarray(start_index, jnp.int32)
    positions_b = jnp.broadcast_to(
        start_index + jnp.arange(K, dtype=jnp.int32), (batch, K))
    cached_lens = jnp.broadcast_to(start_index, (batch,))
    chunk_lens = jnp.full((batch,), K, jnp.int32)
    cos, sin = llama._rope_freqs(config, positions_b)
    x = _tp_embed(params, tokens, config, axis)
    use_kernel, interpret = llama.prefill_kernel_mode()
    new_pool = []
    lora_layers = _lora_layers(lora, len(pool))
    for layer, pool_layer, lora_layer in zip(params["layers"], pool,
                                             lora_layers):
        normed = llama.rms_norm(x, layer["attn_norm"], config.norm_eps)
        q = llama._lora_matmul(normed, layer["wq"], lora_layer, "wq",
                               lora).reshape(batch, K, h, hd)
        k = llama._lora_matmul(normed, layer["wk"], lora_layer, "wk",
                               lora).reshape(batch, K, kv, hd)
        v = llama._lora_matmul(normed, layer["wv"], lora_layer, "wv",
                               lora).reshape(batch, K, kv, hd)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        q_g = q.reshape(batch, K, kv, h // kv, hd)
        if use_kernel:
            out, pool_layer = paged_prefill_attention(
                q_g, k, v, pool_layer, tables, cached_lens, chunk_lens,
                window=config.sliding_window, interpret=interpret,
                kv_limit=kv_limit)
        else:
            pool_layer = llama._paged_write_slab(pool_layer, k, v,
                                                 tables, positions_b)
            gathered = llama._paged_gather(pool_layer, tables)
            out = llama._cached_gqa_attention(
                q_g, gathered, positions_b, hd,
                window=config.sliding_window)
        new_pool.append(pool_layer)
        out = _gather_cols(out.reshape(batch, K, h * hd), axis)
        x = x + _gather_cols(
            llama._lora_matmul(out, layer["wo"], lora_layer, "wo",
                               lora), axis).astype(x.dtype)
        x = _tp_mlp_block(layer, config, axis, x, ep_axis=ep_axis,
                          ep=ep, overlap=overlap)
    if not compute_logits:
        return None, new_pool
    return _tp_lm_head(params, config, axis, x), new_pool


def _tp_sp_prefill_core(params, tokens, pool, tables, start_index,
                        config: LlamaConfig, tp: int, axis: str,
                        sp_axis: str, sp: int, lora=None, kv_limit=None,
                        ep_axis=None, ep: int = 1,
                        overlap: bool = False):
    """Sequence-parallel chunked-prefill core: the dispatch window
    ``(batch, sp*W)`` arrives sharded over ``sp_axis`` — this shard
    holds chunk ``j = axis_index(sp_axis)`` of width ``W`` at absolute
    start ``start_index + j*W``.  Per layer:

    * project this chunk's q/k/v (tp-local heads), rope at the chunk's
      own absolute positions;
    * all-gather the WINDOW's K/V over ``sp`` (pure data movement) and
      slab-write all ``sp`` chunks into the local pool copy — the pool
      is sharded on ``tp`` and replicated on ``sp``, and every copy
      receives bitwise the same rows, so the replicas never diverge;
    * run the SAME append attention as the sequential core with
      ``cached_lens = start_index + j*W``: rows of later chunks sit
      beyond the absolute-position mask / cached-length bound, so
      chunk ``j``'s math is bitwise the sequential chunk-``j``
      dispatch of the single-chip server (invariant 19) — the
      sp window just runs all ``sp`` chunk programs at once.

    The in-kernel int8 writer is bit-identical to the aligned slab
    writer's per-row absmax (see ops/paged_prefill), so the kernel
    path re-writing this shard's own chunk leaves every sp copy
    byte-identical too."""
    batch, W = tokens.shape
    h, kv = config.n_heads // tp, config.n_kv_heads // tp
    hd = config.head_dim
    start_index = jnp.asarray(start_index, jnp.int32)
    j = jax.lax.axis_index(sp_axis).astype(jnp.int32)
    my_start = start_index + j * W
    positions_b = jnp.broadcast_to(
        my_start + jnp.arange(W, dtype=jnp.int32), (batch, W))
    win_positions = jnp.broadcast_to(
        start_index + jnp.arange(sp * W, dtype=jnp.int32),
        (batch, sp * W))
    cached_lens = jnp.broadcast_to(my_start, (batch,))
    chunk_lens = jnp.full((batch,), W, jnp.int32)
    cos, sin = llama._rope_freqs(config, positions_b)
    x = _tp_embed(params, tokens, config, axis)
    use_kernel, interpret = llama.prefill_kernel_mode()
    new_pool = []
    lora_layers = _lora_layers(lora, len(pool))
    for layer, pool_layer, lora_layer in zip(params["layers"], pool,
                                             lora_layers):
        normed = llama.rms_norm(x, layer["attn_norm"], config.norm_eps)
        q = llama._lora_matmul(normed, layer["wq"], lora_layer, "wq",
                               lora).reshape(batch, W, h, hd)
        k = llama._lora_matmul(normed, layer["wk"], lora_layer, "wk",
                               lora).reshape(batch, W, kv, hd)
        v = llama._lora_matmul(normed, layer["wv"], lora_layer, "wv",
                               lora).reshape(batch, W, kv, hd)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        k_win = jax.lax.all_gather(k, sp_axis, axis=1, tiled=True)
        v_win = jax.lax.all_gather(v, sp_axis, axis=1, tiled=True)
        pool_layer = llama._paged_write_slab(pool_layer, k_win, v_win,
                                             tables, win_positions)
        q_g = q.reshape(batch, W, kv, h // kv, hd)
        if use_kernel:
            out, pool_layer = paged_prefill_attention(
                q_g, k, v, pool_layer, tables, cached_lens, chunk_lens,
                window=config.sliding_window, interpret=interpret,
                kv_limit=kv_limit)
        else:
            gathered = llama._paged_gather(pool_layer, tables)
            out = llama._cached_gqa_attention(
                q_g, gathered, positions_b, hd,
                window=config.sliding_window)
        new_pool.append(pool_layer)
        out = _gather_cols(out.reshape(batch, W, h * hd), axis)
        x = x + _gather_cols(
            llama._lora_matmul(out, layer["wo"], lora_layer, "wo",
                               lora), axis).astype(x.dtype)
        x = _tp_mlp_block(layer, config, axis, x, ep_axis=ep_axis,
                          ep=ep, overlap=overlap)
    return new_pool


def _tp_verify_core(params, tokens, pool, tables, positions, active,
                    config: LlamaConfig, tp: int, axis: str,
                    lora=None, kv_limit=None, ep_axis=None, ep: int = 1,
                    overlap: bool = False):
    """Shard-local mirror of ``llama._verify_append_core`` (the
    speculative verify): every row at its OWN absolute start position,
    the window's K/V appended into the LOCAL kv-head slice of the
    pool, inactive rows routed to scratch block 0.  The all-gathers
    are the same column gathers as the decode/prefill mirrors —
    bitwise concatenations — so TP verify logits equal single-chip
    verify logits bit for bit (invariants 9 + 11)."""
    batch, K = tokens.shape
    h, kv = config.n_heads // tp, config.n_kv_heads // tp
    hd = config.head_dim
    starts = jnp.where(active, positions, 0).astype(jnp.int32)
    positions_b = starts[:, None] + jnp.arange(K, dtype=jnp.int32)[None]
    cached_lens = starts
    chunk_lens = jnp.where(active, K, 0).astype(jnp.int32)
    write_tables = jnp.where(active[:, None], tables,
                             jnp.zeros_like(tables))
    cos, sin = llama._rope_freqs(config, positions_b)
    x = _tp_embed(params, tokens, config, axis)
    use_kernel, interpret = llama.prefill_kernel_mode()
    new_pool = []
    lora_layers = _lora_layers(lora, len(pool))
    for layer, pool_layer, lora_layer in zip(params["layers"], pool,
                                             lora_layers):
        normed = llama.rms_norm(x, layer["attn_norm"], config.norm_eps)
        q = llama._lora_matmul(normed, layer["wq"], lora_layer, "wq",
                               lora).reshape(batch, K, h, hd)
        k = llama._lora_matmul(normed, layer["wk"], lora_layer, "wk",
                               lora).reshape(batch, K, kv, hd)
        v = llama._lora_matmul(normed, layer["wv"], lora_layer, "wv",
                               lora).reshape(batch, K, kv, hd)
        q = llama.apply_rope(q, cos, sin)
        k = llama.apply_rope(k, cos, sin)
        q_g = q.reshape(batch, K, kv, h // kv, hd)
        if use_kernel:
            out, pool_layer = paged_verify_attention(
                q_g, k, v, pool_layer, write_tables, cached_lens,
                chunk_lens, window=config.sliding_window,
                interpret=interpret, kv_limit=kv_limit)
        else:
            pool_layer = llama._paged_write_slab(pool_layer, k, v,
                                                 write_tables,
                                                 positions_b)
            gathered = llama._paged_gather(pool_layer, write_tables)
            out = llama._cached_gqa_attention(
                q_g, gathered, positions_b, hd,
                window=config.sliding_window)
        new_pool.append(pool_layer)
        out = _gather_cols(out.reshape(batch, K, h * hd), axis)
        x = x + _gather_cols(
            llama._lora_matmul(out, layer["wo"], lora_layer, "wo",
                               lora), axis).astype(x.dtype)
        x = _tp_mlp_block(layer, config, axis, x, ep_axis=ep_axis,
                          ep=ep, overlap=overlap)
    return _tp_lm_head(params, config, axis, x), new_pool


# --------------------------------------------------------------------------- #
# The engine


class TPEngine:
    """Per-server dispatcher for the TP serving entry points.

    Built once per :class:`PagedContinuousServer` (the shard_map
    in/out spec trees depend on the server's actual parameter and pool
    pytree structure — quantization layout, layer count — so the
    jitted closures are constructed per engine and cached per static
    signature).  Mirrors the llama entry points' signatures so the
    server's dispatch sites stay one-line switches:

    * :meth:`serve_chunk_paged` — decode chunk (pool donated)
    * :meth:`serve_chunk_mixed` — chunked-prefill slice + decode chunk
      (``sp_shard=True`` runs the slice as an sp-sharded window)
    * :meth:`prefill_append_paged` — standalone prefill append
    * :meth:`prefill_append_sp` — standalone sp-window prefill
    * :meth:`verify_chunk_paged` — speculative verify window
    """

    def __init__(self, config: LlamaConfig, mesh: Mesh, params, pool,
                 axis: str = "tp", sp_axis: Optional[str] = None,
                 ep_axis: Optional[str] = None,
                 overlap: bool = False):
        if axis not in mesh.axis_names:
            raise ValueError(
                f"mesh has no '{axis}' axis: {mesh.axis_names}")
        self.config = config
        self.mesh = mesh
        self.axis = axis
        self.tp = mesh.shape[axis]
        # Second mesh axis (at most one): sp shards prefill windows,
        # ep shards MoE experts.  Size 1 ⇔ absent.
        self.sp_axis = sp_axis if (sp_axis in mesh.axis_names) else None
        self.ep_axis = ep_axis if (ep_axis in mesh.axis_names) else None
        self.sp = mesh.shape[self.sp_axis] if self.sp_axis else 1
        self.ep = mesh.shape[self.ep_axis] if self.ep_axis else 1
        self.overlap = bool(overlap)
        if config.n_kv_heads % self.tp or config.n_heads % self.tp:
            raise ValueError(
                f"tp={self.tp} must divide n_kv_heads="
                f"{config.n_kv_heads} and n_heads={config.n_heads}")
        if config.n_experts and config.n_experts % self.ep:
            raise ValueError(
                f"ep={self.ep} must divide n_experts="
                f"{config.n_experts}")
        if self.overlap:
            for layer in params.get("layers", ()):
                if getattr(layer.get("w_down"), "ndim", 0) != 2:
                    raise ValueError(
                        "overlap mode needs dense (unquantized) MLP "
                        "weights: w_down re-lays row-parallel for the "
                        "reduce-scatter path")
        self._param_specs = tp_param_specs(params, axis,
                                           ep_axis=self.ep_axis,
                                           overlap=self.overlap)
        self._pool_specs = tp_pool_specs(pool, axis)
        self._cache: Dict[Any, Any] = {}

    # -- spec helpers -------------------------------------------------- #

    def _shard_map(self, body, in_specs, out_specs):
        return shard_map(body, mesh=self.mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)

    def _core_kwargs(self):
        """Second-axis / overlap context threaded into every mirror
        core (inert on a 1-D exact-path mesh)."""
        return dict(ep_axis=self.ep_axis, ep=self.ep,
                    overlap=self.overlap)

    # -- decode chunk -------------------------------------------------- #

    def _lora_specs(self, lora):
        """Spec tree for a stacked-adapter operand (or None)."""
        return (tp_lora_specs(lora, self.axis)
                if lora is not None else None)

    def serve_chunk_paged(self, params, state, pool, num_steps,
                          eos_id: int = -1, sampled: bool = False,
                          rng_key=None, lora_shared=None):
        """TP twin of :func:`llama.serve_chunk_paged`.  ``lora_shared``
        is the stacked adapter tree laid out by :func:`shard_lora`
        (A + scale replicated, B column-sharded); per-row ids come from
        ``state["adapter_ids"]`` exactly like the single-chip twin."""
        num_steps = int(num_steps)
        key = ("serve", num_steps, int(eos_id), bool(sampled),
               rng_key is not None, lora_shared is not None)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build_serve(num_steps, int(eos_id),
                                   bool(sampled), rng_key is not None,
                                   self._lora_specs(lora_shared))
            self._cache[key] = fn
        args = (params, state, pool) + (
            (rng_key,) if rng_key is not None else ()) + (
            (lora_shared,) if lora_shared is not None else ())
        return fn(*args)

    def _build_serve(self, num_steps, eos_id, sampled, has_rng,
                     lora_specs=None):
        config, tp, axis = self.config, self.tp, self.axis
        core_kwargs = self._core_kwargs()

        def body(params, state, pool, rng_key=None, lora_shared=None):
            block_size = pool[0]["k"].shape[1]
            tables = state["tables"]
            slots = tables.shape[0]
            scratch_tables = jnp.zeros_like(tables)
            scratch_positions = (jnp.arange(slots, dtype=jnp.int32)
                                 % block_size)
            lora = (dict(lora_shared, ids=state["adapter_ids"])
                    if lora_shared is not None else None)

            def step_core(token, pool, positions, active):
                write_tables = jnp.where(active[:, None], tables,
                                         scratch_tables)
                write_pos = jnp.where(active, positions,
                                      scratch_positions)
                return _tp_decode_core_paged(params, token, pool,
                                             write_tables, write_pos,
                                             config, tp, axis,
                                             lora=lora, **core_kwargs)

            return llama._serve_scan(step_core, state, pool, num_steps,
                                     eos_id, sampled, rng_key)

        if lora_specs is not None:
            if has_rng:
                def wrapped(params, state, pool, rng_key, lora_shared):
                    return body(params, state, pool, rng_key,
                                lora_shared)
            else:
                def wrapped(params, state, pool, lora_shared):
                    return body(params, state, pool, None, lora_shared)
        else:
            wrapped = body
        in_specs = (self._param_specs, P(), self._pool_specs)
        if has_rng:
            in_specs += (P(),)
        if lora_specs is not None:
            in_specs += (lora_specs,)
        out_specs = (P(), P(), P(), self._pool_specs)
        return jax.jit(self._shard_map(wrapped, in_specs, out_specs),
                       donate_argnums=(2,))

    # -- mixed prefill/decode chunk ------------------------------------ #

    def serve_chunk_mixed(self, params, state, pool, prefill_tokens,
                          prefill_row, prefill_start, num_steps,
                          eos_id: int = -1, sampled: bool = False,
                          rng_key=None, lora_shared=None,
                          prefill_kv_limit=None,
                          sp_shard: bool = False):
        """TP twin of :func:`llama.serve_chunk_mixed` — the admitting
        slot's adapter id is dynamically sliced out of the resident
        state for the prefill leg, exactly like the single-chip twin.

        ``sp_shard=True`` (needs an sp mesh axis): the prefill slice is
        an sp-WINDOW — ``sp`` consecutive chunks in one dispatch,
        sharded over the sp axis through
        :func:`_tp_sp_prefill_core` — while the decode part runs
        replicated over sp exactly as before."""
        num_steps = int(num_steps)
        if sp_shard and self.sp <= 1:
            raise ValueError("sp_shard needs an sp mesh axis > 1")
        key = ("mixed", num_steps, int(eos_id), bool(sampled),
               rng_key is not None, prefill_kv_limit, bool(sp_shard),
               lora_shared is not None)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build_mixed(num_steps, int(eos_id),
                                   bool(sampled), rng_key is not None,
                                   prefill_kv_limit, bool(sp_shard),
                                   self._lora_specs(lora_shared))
            self._cache[key] = fn
        args = (params, state, pool, prefill_tokens,
                jnp.asarray(prefill_row, jnp.int32),
                jnp.asarray(prefill_start, jnp.int32)) + (
            (rng_key,) if rng_key is not None else ()) + (
            (lora_shared,) if lora_shared is not None else ())
        return fn(*args)

    def _build_mixed(self, num_steps, eos_id, sampled, has_rng,
                     prefill_kv_limit, sp_shard=False,
                     lora_specs=None):
        config, tp, axis = self.config, self.tp, self.axis
        sp_axis, sp = self.sp_axis, self.sp
        core_kwargs = self._core_kwargs()

        def body(params, state, pool, prefill_tokens, prefill_row,
                 prefill_start, rng_key=None, lora_shared=None):
            block_size = pool[0]["k"].shape[1]
            tables = state["tables"]
            slots = tables.shape[0]
            tables_row = jax.lax.dynamic_slice_in_dim(
                tables, prefill_row, 1, axis=0)
            if lora_shared is not None:
                row_ids = jax.lax.dynamic_slice_in_dim(
                    state["adapter_ids"], prefill_row, 1, axis=0)
                prefill_lora = dict(lora_shared, ids=row_ids)
                lora = dict(lora_shared, ids=state["adapter_ids"])
            else:
                prefill_lora = lora = None
            if sp_shard:
                pool = _tp_sp_prefill_core(
                    params, prefill_tokens, pool, tables_row,
                    prefill_start, config, tp, axis, sp_axis, sp,
                    lora=prefill_lora, kv_limit=prefill_kv_limit,
                    **core_kwargs)
            else:
                _, pool = _tp_prefill_append_core(
                    params, prefill_tokens, pool, tables_row,
                    prefill_start, config, tp, axis, lora=prefill_lora,
                    kv_limit=prefill_kv_limit, compute_logits=False,
                    **core_kwargs)
            scratch_tables = jnp.zeros_like(tables)
            scratch_positions = (jnp.arange(slots, dtype=jnp.int32)
                                 % block_size)

            def step_core(token, pool, positions, active):
                write_tables = jnp.where(active[:, None], tables,
                                         scratch_tables)
                write_pos = jnp.where(active, positions,
                                      scratch_positions)
                return _tp_decode_core_paged(params, token, pool,
                                             write_tables, write_pos,
                                             config, tp, axis,
                                             lora=lora, **core_kwargs)

            return llama._serve_scan(step_core, state, pool, num_steps,
                                     eos_id, sampled, rng_key)

        if lora_specs is not None:
            if has_rng:
                def wrapped(params, state, pool, prefill_tokens,
                            prefill_row, prefill_start, rng_key,
                            lora_shared):
                    return body(params, state, pool, prefill_tokens,
                                prefill_row, prefill_start, rng_key,
                                lora_shared)
            else:
                def wrapped(params, state, pool, prefill_tokens,
                            prefill_row, prefill_start, lora_shared):
                    return body(params, state, pool, prefill_tokens,
                                prefill_row, prefill_start, None,
                                lora_shared)
        else:
            wrapped = body
        prefill_spec = P(None, sp_axis) if sp_shard else P()
        in_specs = (self._param_specs, P(), self._pool_specs,
                    prefill_spec, P(), P())
        if has_rng:
            in_specs += (P(),)
        if lora_specs is not None:
            in_specs += (lora_specs,)
        out_specs = (P(), P(), P(), self._pool_specs)
        return jax.jit(self._shard_map(wrapped, in_specs, out_specs),
                       donate_argnums=(2,))

    # -- speculative verify window ------------------------------------- #

    def verify_chunk_paged(self, params, tokens, pool, tables,
                           positions, active, lora=None,
                           kv_limit=None):
        """TP twin of :func:`llama.verify_chunk_paged`: score a
        (slots, k+1) speculative window against the sharded pool, each
        row at its own absolute position.  ``lora`` is the full dict
        WITH per-row ids (the llama signature).  Returns ``(logits
        (slots, k+1, vocab), pool)`` with the pool donated — bitwise
        equal to the single-chip verify (all-gather is the only
        collective)."""
        K = int(tokens.shape[1])
        key = ("verify", K, kv_limit, lora is not None)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build_verify(kv_limit, self._lora_specs(lora))
            self._cache[key] = fn
        args = (params, tokens, pool, tables, positions, active) + (
            (lora,) if lora is not None else ())
        return fn(*args)

    def _build_verify(self, kv_limit, lora_specs=None):
        config, tp, axis = self.config, self.tp, self.axis
        core_kwargs = self._core_kwargs()

        def body(params, tokens, pool, tables, positions, active,
                 lora=None):
            return _tp_verify_core(params, tokens, pool, tables,
                                   positions, active, config, tp,
                                   axis, lora=lora, kv_limit=kv_limit,
                                   **core_kwargs)

        in_specs = (self._param_specs, P(), self._pool_specs,
                    P(), P(), P())
        if lora_specs is not None:
            in_specs += (lora_specs,)
        out_specs = (P(), self._pool_specs)
        return jax.jit(self._shard_map(body, in_specs, out_specs),
                       donate_argnums=(2,))

    # -- standalone prefill append ------------------------------------- #

    def prefill_append_paged(self, params, tokens, pool, tables,
                             start_index, lora=None, kv_limit=None,
                             compute_logits: bool = False):
        """TP twin of :func:`llama.prefill_append_paged` — ``lora`` is
        the full dict WITH per-row ids (the llama signature).  Always
        dispatched with ``compute_logits=False`` by the paged server
        (the mixed step owns logits); returns ``(None, new_pool)`` to
        match the llama call-site unpacking."""
        if compute_logits:
            raise NotImplementedError(
                "TP prefill_append_paged serves the paged admission "
                "path, which never reads prefill logits")
        key = ("prefill", kv_limit, lora is not None)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build_prefill(kv_limit, self._lora_specs(lora))
            self._cache[key] = fn
        args = (params, tokens, pool, tables,
                jnp.asarray(start_index, jnp.int32)) + (
            (lora,) if lora is not None else ())
        return None, fn(*args)

    def _build_prefill(self, kv_limit, lora_specs=None):
        config, tp, axis = self.config, self.tp, self.axis
        core_kwargs = self._core_kwargs()

        def body(params, tokens, pool, tables, start_index, lora=None):
            _, new_pool = _tp_prefill_append_core(
                params, tokens, pool, tables, start_index, config, tp,
                axis, lora=lora, kv_limit=kv_limit,
                compute_logits=False, **core_kwargs)
            return new_pool

        in_specs = (self._param_specs, P(), self._pool_specs, P(), P())
        if lora_specs is not None:
            in_specs += (lora_specs,)
        out_specs = self._pool_specs
        return jax.jit(self._shard_map(body, in_specs, out_specs),
                       donate_argnums=(2,))

    # -- sequence-parallel prefill window ------------------------------ #

    def prefill_append_sp(self, params, tokens, pool, tables,
                          start_index, lora=None, kv_limit=None):
        """Standalone sp-window prefill: ``tokens (1, sp*W)`` is
        ``sp`` consecutive chunks of one prompt, sharded over the sp
        axis — each shard appends its own chunk at its own absolute
        offset and every pool copy receives the full window (see
        :func:`_tp_sp_prefill_core`).  Returns ``(None, new_pool)``
        to match the ``prefill_append_paged`` call-site unpacking."""
        if self.sp <= 1:
            raise ValueError("prefill_append_sp needs an sp mesh "
                             "axis > 1")
        if tokens.shape[1] % self.sp:
            raise ValueError(
                f"sp window width {tokens.shape[1]} must divide by "
                f"sp={self.sp}")
        key = ("prefill_sp", kv_limit, lora is not None)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build_prefill_sp(kv_limit,
                                        self._lora_specs(lora))
            self._cache[key] = fn
        args = (params, tokens, pool, tables,
                jnp.asarray(start_index, jnp.int32)) + (
            (lora,) if lora is not None else ())
        return None, fn(*args)

    def _build_prefill_sp(self, kv_limit, lora_specs=None):
        config, tp, axis = self.config, self.tp, self.axis
        sp_axis, sp = self.sp_axis, self.sp
        core_kwargs = self._core_kwargs()

        def body(params, tokens, pool, tables, start_index, lora=None):
            return _tp_sp_prefill_core(
                params, tokens, pool, tables, start_index, config, tp,
                axis, sp_axis, sp, lora=lora, kv_limit=kv_limit,
                **core_kwargs)

        in_specs = (self._param_specs, P(None, sp_axis),
                    self._pool_specs, P(), P())
        if lora_specs is not None:
            in_specs += (lora_specs,)
        out_specs = self._pool_specs
        return jax.jit(self._shard_map(body, in_specs, out_specs),
                       donate_argnums=(2,))

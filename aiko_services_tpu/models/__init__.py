from . import llama
from . import classifier
from . import detector

from . import llama
from . import moe
from . import classifier
from . import detector
from . import asr
from . import vision
from . import speculative
from . import lora

from . import llama
from . import classifier
from . import detector
from . import asr
from . import vision

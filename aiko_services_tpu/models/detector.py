"""Single-shot object detector: YOLO-class conv backbone + grid head
(the object-detection video-pipeline workload, BASELINE.json config 2).

Anchor-free YOLO-style output: for each grid cell, ``(x, y, w, h,
objectness, class…)``.  NHWC layout (TPU-native), bf16 weights, all
convs lower to MXU matmuls via ``lax.conv_general_dilated``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

__all__ = ["DetectorConfig", "init_params", "forward", "decode_boxes",
           "CONFIGS", "save_checkpoint", "load_checkpoint"]


@dataclasses.dataclass(frozen=True)
class DetectorConfig:
    image_size: int = 320
    n_classes: int = 80
    widths: Tuple[int, ...] = (16, 32, 64, 128, 256)
    dtype: Any = jnp.bfloat16

    @property
    def grid_size(self) -> int:
        return self.image_size // (2 ** len(self.widths))

    @property
    def out_channels(self) -> int:
        return 5 + self.n_classes


CONFIGS: Dict[str, DetectorConfig] = {
    "tiny": DetectorConfig(image_size=64, n_classes=4,
                           widths=(8, 16, 32)),
    "yolo_n": DetectorConfig(image_size=320, n_classes=80,
                             widths=(16, 32, 64, 128, 256)),
}


def init_params(config: DetectorConfig, key) -> Dict:
    keys = jax.random.split(key, len(config.widths) + 1)
    dt = config.dtype
    layers = []
    c_in = 3
    for i, width in enumerate(config.widths):
        fan = 3 * 3 * c_in
        layers.append({
            "w": (jax.random.normal(keys[i], (3, 3, c_in, width),
                                    jnp.float32)
                  * (2.0 / fan) ** 0.5).astype(dt),
            "b": jnp.zeros((width,), dt),
        })
        c_in = width
    head = {
        "w": (jax.random.normal(keys[-1],
                                (1, 1, c_in, config.out_channels),
                                jnp.float32) * c_in ** -0.5).astype(dt),
        "b": jnp.zeros((config.out_channels,), dt),
    }
    return {"layers": layers, "head": head}


def _conv(x, w, b, stride):
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    return (out + b.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("config",))
def forward(params, images, config: DetectorConfig):
    """images (batch, H, W, 3) → raw grid (batch, gh, gw, 5+classes)."""
    x = images.astype(config.dtype)
    for layer in params["layers"]:
        x = jax.nn.silu(_conv(x, layer["w"], layer["b"], stride=2))
    head = params["head"]
    return _conv(x, head["w"], head["b"], stride=1).astype(jnp.float32)


def decode_boxes(raw, config: DetectorConfig,
                 score_threshold: float = 0.5):
    """Raw grid → (boxes xyxy [0,1], scores, classes) with a static-shape
    mask (XLA-friendly: no dynamic shapes; filter host-side if needed)."""
    batch, gh, gw, _ = raw.shape
    xy_cell = jax.nn.sigmoid(raw[..., 0:2])
    wh = jax.nn.sigmoid(raw[..., 2:4])
    obj = jax.nn.sigmoid(raw[..., 4])
    cls_logits = raw[..., 5:]
    col = jax.lax.broadcasted_iota(jnp.float32, (gh, gw), 1)
    row = jax.lax.broadcasted_iota(jnp.float32, (gh, gw), 0)
    cx = (xy_cell[..., 0] + col) / gw
    cy = (xy_cell[..., 1] + row) / gh
    half_w, half_h = wh[..., 0] / 2, wh[..., 1] / 2
    boxes = jnp.stack([cx - half_w, cy - half_h,
                       cx + half_w, cy + half_h], axis=-1)
    scores = obj * jax.nn.softmax(cls_logits, axis=-1).max(-1)
    classes = cls_logits.argmax(-1)
    keep = scores >= score_threshold
    return (boxes.reshape(batch, -1, 4), scores.reshape(batch, -1),
            classes.reshape(batch, -1), keep.reshape(batch, -1))


def save_checkpoint(params, config: DetectorConfig, path: str) -> None:
    """Single-file ``.npz`` checkpoint: flattened param tree + the
    config fields needed to rebuild it (a trained detector travels to
    pipeline elements as one artifact — ``FaceDetector(checkpoint=)``,
    matching the reference's file-path model deployment idiom,
    reference examples/face/face.py / examples/yolo/yolo.py:46)."""
    import json

    import numpy as np

    arrays = {"head.w": np.asarray(params["head"]["w"], np.float32),
              "head.b": np.asarray(params["head"]["b"], np.float32)}
    for i, layer in enumerate(params["layers"]):
        arrays[f"layers.{i}.w"] = np.asarray(layer["w"], np.float32)
        arrays[f"layers.{i}.b"] = np.asarray(layer["b"], np.float32)
    arrays["config_json"] = np.frombuffer(json.dumps({
        "image_size": config.image_size,
        "n_classes": config.n_classes,
        "widths": list(config.widths),
        "dtype": jnp.dtype(config.dtype).name,
    }).encode(), dtype=np.uint8)
    if not str(path).endswith(".npz"):
        path = f"{path}.npz"        # np.savez appends it silently;
    np.savez(path, **arrays)        # keep save/load paths agreeing


def load_checkpoint(path: str):
    """→ ``(params, DetectorConfig)`` from :func:`save_checkpoint`
    (weights cast back to the config dtype)."""
    import json

    import numpy as np

    if not str(path).endswith(".npz"):
        path = f"{path}.npz"
    with np.load(path) as arrays:
        meta = json.loads(arrays["config_json"].tobytes().decode())
        config = DetectorConfig(
            image_size=int(meta["image_size"]),
            n_classes=int(meta["n_classes"]),
            widths=tuple(int(w) for w in meta["widths"]),
            dtype=jnp.dtype(meta["dtype"]))
        dt = config.dtype
        params = {
            "layers": [
                {"w": jnp.asarray(arrays[f"layers.{i}.w"], dt),
                 "b": jnp.asarray(arrays[f"layers.{i}.b"], dt)}
                for i in range(len(config.widths))],
            "head": {"w": jnp.asarray(arrays["head.w"], dt),
                     "b": jnp.asarray(arrays["head.b"], dt)},
        }
    return params, config

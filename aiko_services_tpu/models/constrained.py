"""Grammar-constrained decoding: hard output guarantees, on device.

The reference coaxes its LLM into emitting S-expression robot commands
by PROMPTING and then filters failures by hand (the `PE_LLM` element's
prompt forbids prose and a regexp fishes out the command).  Here the
constraint is structural: a finite-state token automaton masks the
logits at every step inside the compiled decode scan, so ONLY strings
the grammar accepts can ever be produced — greedy or sampled, zero
post-hoc filtering.

TPU-native design: the automaton is two dense arrays —

* ``allowed``  (n_states, vocab) bool — which tokens may follow
* ``next_state`` (n_states, vocab) int32 — where each token leads

so a decode step is a gather + a mask, fully inside ``lax.scan`` (no
data-dependent control flow, no host round-trips).  States with no
allowed tokens are terminal: decoding emits ``pad_token`` forever once
accepted (callers trim).

Build automata directly, or from a token-level regular grammar via
:func:`automaton_from_rules`.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import llama

__all__ = ["TokenAutomaton", "automaton_from_rules",
           "constrained_generate"]


@dataclasses.dataclass(frozen=True)
class TokenAutomaton:
    """Dense token-level DFA.  ``allowed[s, t]`` — token ``t`` legal in
    state ``s``; ``next_state[s, t]`` — resulting state.  State 0 is
    the start; ``accepting`` marks states where the output may end."""
    allowed: np.ndarray        # (n_states, vocab) bool
    next_state: np.ndarray     # (n_states, vocab) int32
    accepting: np.ndarray      # (n_states,) bool

    @property
    def n_states(self) -> int:
        return self.allowed.shape[0]

    @property
    def vocab(self) -> int:
        return self.allowed.shape[1]

    def accepts(self, tokens: Sequence[int]) -> bool:
        """Host-side check (tests / validation)."""
        state = 0
        for token in tokens:
            if not self.allowed[state, token]:
                return False
            state = int(self.next_state[state, token])
        return bool(self.accepting[state])


def automaton_from_rules(vocab: int,
                         rules: Dict[int, Iterable[Tuple[object, int]]],
                         accepting: Iterable[int]) -> TokenAutomaton:
    """Build a dense automaton from sparse rules: ``rules[state]`` is
    a list of ``(tokens, next_state)`` where ``tokens`` is an iterable
    of token ids or the string ``"*"`` (any token not otherwise
    listed).  Later entries override earlier ones; ``"*"`` applies
    first so specific tokens win."""
    n_states = max(max(rules, default=0),
                   max((dst for moves in rules.values()
                        for _, dst in moves), default=0)) + 1
    allowed = np.zeros((n_states, vocab), bool)
    next_state = np.zeros((n_states, vocab), np.int32)
    for state, moves in rules.items():
        wildcard = [(tok, dst) for tok, dst in moves if tok == "*"]
        for _, dst in wildcard:
            allowed[state, :] = True
            next_state[state, :] = dst
        for tokens, dst in moves:
            if tokens == "*":
                continue
            ids = np.asarray(list(tokens), np.int32)
            allowed[state, ids] = True
            next_state[state, ids] = dst
    accept = np.zeros((n_states,), bool)
    accept[np.asarray(list(accepting), np.int32)] = True
    return TokenAutomaton(allowed, next_state, accept)


@functools.partial(jax.jit,
                   static_argnames=("config", "num_steps",
                                    "temperature"),
                   donate_argnames=("cache",))
def constrained_generate(params, first_logits, cache, start_index,
                         num_steps, config: llama.LlamaConfig,
                         allowed, next_state, pad_token: int = 0,
                         temperature: float = 0.0, rng_key=None):
    """Decode ``num_steps`` tokens with the automaton masking every
    step — one compiled scan.  ``first_logits`` (batch, vocab) are the
    prefill logits for the first constrained position; ``allowed`` /
    ``next_state`` are the automaton arrays (device-convertible).

    A row whose state has NO legal token (terminal) emits
    ``pad_token`` and stays terminal.  Returns (tokens (batch,
    num_steps), final_states (batch,), cache)."""
    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)
    allowed = jnp.asarray(allowed, bool)
    next_state = jnp.asarray(next_state, jnp.int32)
    batch = first_logits.shape[0]

    def pick(logits, states, key):
        mask = allowed[states]                        # (batch, vocab)
        terminal = ~mask.any(axis=-1)
        masked = jnp.where(mask, logits.astype(jnp.float32),
                           -jnp.inf)
        if temperature and temperature > 0:
            choice = jax.random.categorical(
                key, masked / jnp.float32(temperature)).astype(
                    jnp.int32)
        else:
            choice = masked.argmax(-1).astype(jnp.int32)
        token = jnp.where(terminal, pad_token, choice)
        new_states = jnp.where(
            terminal, states,
            next_state[states, token])
        return token, new_states

    key0, loop_key = jax.random.split(rng_key)
    states0 = jnp.zeros((batch,), jnp.int32)
    first_token, states = pick(first_logits, states0, key0)

    def body(carry, step):
        token, states, cache, key = carry
        logits, cache = llama._decode_core(
            params, token[:, None], cache, start_index + step, config)
        key, step_key = jax.random.split(key)
        next_token, states = pick(logits[:, -1], states, step_key)
        return (next_token, states, cache, key), next_token

    (_, states, cache, _), rest = jax.lax.scan(
        body, (first_token, states, cache, loop_key),
        jnp.arange(num_steps - 1, dtype=jnp.int32))
    tokens = jnp.concatenate([first_token[None], rest], axis=0).T
    return tokens, states, cache

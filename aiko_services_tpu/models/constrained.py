"""Grammar-constrained decoding: hard output guarantees, on device.

The reference coaxes its LLM into emitting S-expression robot commands
by PROMPTING and then filters failures by hand (the `PE_LLM` element's
prompt forbids prose and a regexp fishes out the command).  Here the
constraint is structural: a finite-state token automaton masks the
logits at every step inside the compiled decode scan, so ONLY strings
the grammar accepts can ever be produced — greedy or sampled, zero
post-hoc filtering.

TPU-native design: the automaton is two dense arrays —

* ``allowed``  (n_states, vocab) bool — which tokens may follow
* ``next_state`` (n_states, vocab) int32 — where each token leads

so a decode step is a gather + a mask, fully inside ``lax.scan`` (no
data-dependent control flow, no host round-trips).  States with no
allowed tokens are terminal: decoding emits ``pad_token`` forever once
accepted (callers trim).

Build automata directly, or from a token-level regular grammar via
:func:`automaton_from_rules`.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Iterable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import llama

__all__ = ["TokenAutomaton", "automaton_from_rules",
           "constrained_generate", "AutomatonTable", "stack_automata",
           "constrained_accept_batch"]


@dataclasses.dataclass(frozen=True)
class TokenAutomaton:
    """Dense token-level DFA.  ``allowed[s, t]`` — token ``t`` legal in
    state ``s``; ``next_state[s, t]`` — resulting state.  State 0 is
    the start; ``accepting`` marks states where the output may end."""
    allowed: np.ndarray        # (n_states, vocab) bool
    next_state: np.ndarray     # (n_states, vocab) int32
    accepting: np.ndarray      # (n_states,) bool

    @property
    def n_states(self) -> int:
        return self.allowed.shape[0]

    @property
    def vocab(self) -> int:
        return self.allowed.shape[1]

    def accepts(self, tokens: Sequence[int]) -> bool:
        """Host-side check (tests / validation)."""
        state = 0
        for token in tokens:
            if not self.allowed[state, token]:
                return False
            state = int(self.next_state[state, token])
        return bool(self.accepting[state])


def automaton_from_rules(vocab: int,
                         rules: Dict[int, Iterable[Tuple[object, int]]],
                         accepting: Iterable[int]) -> TokenAutomaton:
    """Build a dense automaton from sparse rules: ``rules[state]`` is
    a list of ``(tokens, next_state)`` where ``tokens`` is an iterable
    of token ids or the string ``"*"`` (any token not otherwise
    listed).  Later entries override earlier ones; ``"*"`` applies
    first so specific tokens win."""
    n_states = max(max(rules, default=0),
                   max((dst for moves in rules.values()
                        for _, dst in moves), default=0)) + 1
    allowed = np.zeros((n_states, vocab), bool)
    next_state = np.zeros((n_states, vocab), np.int32)
    for state, moves in rules.items():
        wildcard = [(tok, dst) for tok, dst in moves if tok == "*"]
        for _, dst in wildcard:
            allowed[state, :] = True
            next_state[state, :] = dst
        for tokens, dst in moves:
            if tokens == "*":
                continue
            ids = np.asarray(list(tokens), np.int32)
            allowed[state, ids] = True
            next_state[state, ids] = dst
    accept = np.zeros((n_states,), bool)
    accept[np.asarray(list(accepting), np.int32)] = True
    return TokenAutomaton(allowed, next_state, accept)


@functools.partial(jax.jit,
                   static_argnames=("config", "num_steps",
                                    "temperature"),
                   donate_argnames=("cache",))
def constrained_generate(params, first_logits, cache, start_index,
                         num_steps, config: llama.LlamaConfig,
                         allowed, next_state, pad_token: int = 0,
                         temperature: float = 0.0, rng_key=None):
    """Decode ``num_steps`` tokens with the automaton masking every
    step — one compiled scan.  ``first_logits`` (batch, vocab) are the
    prefill logits for the first constrained position; ``allowed`` /
    ``next_state`` are the automaton arrays (device-convertible).

    A row whose state has NO legal token (terminal) emits
    ``pad_token`` and stays terminal.  Returns (tokens (batch,
    num_steps), final_states (batch,), cache)."""
    if rng_key is None:
        rng_key = jax.random.PRNGKey(0)
    allowed = jnp.asarray(allowed, bool)
    next_state = jnp.asarray(next_state, jnp.int32)
    batch = first_logits.shape[0]

    def pick(logits, states, key):
        mask = allowed[states]                        # (batch, vocab)
        terminal = ~mask.any(axis=-1)
        masked = jnp.where(mask, logits.astype(jnp.float32),
                           -jnp.inf)
        if temperature and temperature > 0:
            choice = jax.random.categorical(
                key, masked / jnp.float32(temperature)).astype(
                    jnp.int32)
        else:
            choice = masked.argmax(-1).astype(jnp.int32)
        token = jnp.where(terminal, pad_token, choice)
        new_states = jnp.where(
            terminal, states,
            next_state[states, token])
        return token, new_states

    key0, loop_key = jax.random.split(rng_key)
    states0 = jnp.zeros((batch,), jnp.int32)
    first_token, states = pick(first_logits, states0, key0)

    def body(carry, step):
        token, states, cache, key = carry
        logits, cache = llama._decode_core(
            params, token[:, None], cache, start_index + step, config)
        key, step_key = jax.random.split(key)
        next_token, states = pick(logits[:, -1], states, step_key)
        return (next_token, states, cache, key), next_token

    (_, states, cache, _), rest = jax.lax.scan(
        body, (first_token, states, cache, loop_key),
        jnp.arange(num_steps - 1, dtype=jnp.int32))
    tokens = jnp.concatenate([first_token[None], rest], axis=0).T
    return tokens, states, cache


# ------------------------------------------------------------------- #
# Serving-side: stacked automaton registry + jump-forward walker.


class AutomatonTable:
    """A registry of named automata STACKED into one dense table so the
    serving tier ships a single ``(total_states, vocab)`` allowed-mask
    array to the device regardless of how many grammars are registered.
    Per-slot automaton state is then one GLOBAL int (start offset of
    the request's grammar + its local state).

    Host-side navigation (jump-forward segment walking, per-token
    advance, terminal detection) lives here; only ``allowed`` crosses
    to the device (once, at server construction) for logit masking in
    :func:`constrained_accept_batch`."""

    def __init__(self, automata: Dict[str, TokenAutomaton]):
        if not automata:
            raise ValueError("AutomatonTable needs >= 1 automaton")
        vocabs = {a.vocab for a in automata.values()}
        if len(vocabs) != 1:
            raise ValueError(
                f"automata disagree on vocab size: {sorted(vocabs)}")
        self.vocab = vocabs.pop()
        self.names: Tuple[str, ...] = tuple(automata)
        self.offsets: Dict[str, int] = {}
        allowed_parts, next_parts, accept_parts = [], [], []
        offset = 0
        for name in self.names:
            auto = automata[name]
            self.offsets[name] = offset
            allowed_parts.append(np.asarray(auto.allowed, bool))
            # Remap local next-state ids to global ids.  Disallowed
            # entries remap too — harmless, ``advance`` checks
            # ``allowed`` first and never follows them.
            next_parts.append(
                np.asarray(auto.next_state, np.int64) + offset)
            accept_parts.append(np.asarray(auto.accepting, bool))
            offset += auto.n_states
        self.n_states = offset
        self.allowed = np.concatenate(allowed_parts, axis=0)
        self.next_state = np.concatenate(next_parts, axis=0).astype(
            np.int32)
        self.accepting = np.concatenate(accept_parts, axis=0)
        # Jump-forward precompute: states admitting EXACTLY one token
        # are deterministic — record that token (else -1).
        n_allowed = self.allowed.sum(axis=-1)
        self._forced_token = np.where(
            n_allowed == 1,
            self.allowed.argmax(axis=-1), -1).astype(np.int32)

    def start(self, name: str) -> int:
        """Global start state for the named grammar."""
        return self.offsets[name]

    def is_terminal(self, state: int) -> bool:
        """No legal continuation — the request must stop here."""
        return not bool(self.allowed[state].any())

    def advance(self, state: int, token: int) -> int:
        """Consume one generated token; -1 if the token is illegal in
        ``state`` (a masked server can only produce this through a
        bug — callers treat it as a hard error)."""
        if not self.allowed[state, token]:
            return -1
        return int(self.next_state[state, token])

    def deterministic_segment(self, state: int, max_len: int
                              ) -> Tuple[list, int]:
        """Walk the forced chain from ``state``: while the current
        state admits exactly one token, that token is the ONLY output
        a masked decode could produce, so it needs no model pass at
        all — it becomes a jump-forward speculation window verified
        (and cache-written) through the target's verify pass.  Returns
        ``(tokens, end_state)`` with ``len(tokens) <= max_len``."""
        tokens = []
        while len(tokens) < max_len:
            forced = int(self._forced_token[state])
            if forced < 0:
                break
            tokens.append(forced)
            state = int(self.next_state[state, forced])
        return tokens, state


def stack_automata(automata: Dict[str, TokenAutomaton]
                   ) -> AutomatonTable:
    """Stack a named-automata registry into one :class:`AutomatonTable`
    (the serving tier's construction entry point)."""
    return AutomatonTable(automata)


@jax.jit
def constrained_accept_batch(target_logits, base_window, base_counts,
                             forced, forced_counts, states, cons_mask,
                             allowed, temperatures, top_ps, key):
    """Merge grammar-constrained rows into one speculative round's
    accepted window.  For a constrained row the window is: the forced
    jump-forward prefix committed UNCONDITIONALLY (each forced token is
    the only string the grammar admits — the verify pass only ran to
    write its KV rows), then ONE free token chosen from the target's
    logits at the first non-deterministic position, masked to the
    automaton's allowed set (argmax for greedy rows, the shared
    temperature/top-p sampler otherwise).  Rows whose free-position
    state is TERMINAL (no legal continuation) commit the forced prefix
    only — the host retires them.

    Inputs: ``target_logits`` (slots, k+1, vocab) from the verify
    pass; ``base_window``/``base_counts`` the unconstrained acceptance
    result (constrained rows overwrite it); ``forced`` (slots, k)
    zero-padded forced proposals with ``forced_counts`` (slots,) valid
    lengths; ``states`` (slots,) GLOBAL automaton state at the free
    position (host-known at dispatch — the forced chain is
    deterministic); ``cons_mask`` (slots,) selects constrained rows;
    ``allowed`` the stacked (total_states, vocab) mask.

    Returns ``(window (slots, k+1), counts (slots,))`` under the same
    committed-token-count contract as ``greedy_accept_batch``."""
    slots, k1 = target_logits.shape[:2]
    fc = forced_counts.astype(jnp.int32)
    free_logits = jnp.take_along_axis(
        target_logits, fc[:, None, None], axis=1)[:, 0]
    mask = allowed[states]                              # (slots, vocab)
    has_free = mask.any(axis=-1)
    masked = jnp.where(mask, free_logits.astype(jnp.float32),
                       -jnp.inf)
    # Terminal rows would feed all--inf rows to argmax/softmax (NaNs);
    # their choice is discarded below, so give them the raw logits.
    safe = jnp.where(has_free[:, None], masked,
                     free_logits.astype(jnp.float32))
    greedy_tok = safe.argmax(-1).astype(jnp.int32)
    probs = llama.sampling_probs(safe, temperatures[:, None],
                                 top_ps[:, None])
    sampled_tok = jax.random.categorical(
        key, jnp.log(jnp.maximum(probs, 1e-30))).astype(jnp.int32)
    free_tok = jnp.where(temperatures > 0, sampled_tok, greedy_tok)

    pos = jnp.arange(k1)[None, :]
    forced_pad = jnp.concatenate(
        [forced.astype(jnp.int32),
         jnp.zeros((slots, 1), jnp.int32)], axis=1)
    cons_window = jnp.where(pos < fc[:, None], forced_pad, 0)
    cons_window = jnp.where(
        (pos == fc[:, None]) & has_free[:, None],
        free_tok[:, None], cons_window)
    cons_counts = fc + has_free.astype(jnp.int32)

    window = jnp.where(cons_mask[:, None], cons_window, base_window)
    counts = jnp.where(cons_mask, cons_counts, base_counts)
    return window, counts

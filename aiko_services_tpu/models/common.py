"""Shared model building blocks (LayerNorm-family encoders).

Used by :mod:`.classifier`, :mod:`.asr`, :mod:`.vision` — one LayerNorm
and one multi-head-attention plumbing implementation so numerics fixes
apply everywhere.  (:mod:`.llama` uses RMSNorm/GQA and keeps its own
blocks.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.attention import attention_reference

__all__ = ["layer_norm", "mha", "gelu_mlp"]

LN_EPS = 1e-6


def layer_norm(x, weight, eps: float = LN_EPS, bias=None):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = ((x32 - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype) \
        * weight
    return out if bias is None else out + bias


def _add(x, bias):
    return x if bias is None else x + bias


def mha(x_q, x_kv, w_in, wo, n_heads: int, causal: bool,
        cross: bool = False, wkv=None, b_in=None, b_o=None, b_kv=None):
    """Fused-projection multi-head attention.

    Self-attention: ``w_in`` is the (d, 3d) qkv projection and ``x_kv``
    is ignored.  Cross-attention (``cross=True``): ``w_in`` is the
    (d, d) q projection and ``wkv`` the (d_kv, 2d) kv projection over
    ``x_kv``.  Biases are optional (randomly-initialised models omit
    them; imported checkpoints — Whisper layout — carry them).
    """
    b, q_len, d = x_q.shape
    hd = d // n_heads
    if cross:
        q = _add(x_q @ w_in, b_in).reshape(b, q_len, n_heads, hd)
        kv = _add(x_kv @ wkv, b_kv).reshape(
            b, x_kv.shape[1], 2, n_heads, hd)
        k, v = kv[:, :, 0], kv[:, :, 1]
    else:
        qkv = _add(x_q @ w_in, b_in).reshape(b, q_len, 3, n_heads, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    out = attention_reference(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=causal)
    out = out.transpose(0, 2, 1, 3).reshape(b, q_len, d)
    return _add(out @ wo, b_o).astype(x_q.dtype)


def gelu_mlp(x, norm_weight, w1, w2, norm_bias=None, b1=None, b2=None,
             eps: float = LN_EPS):
    normed = layer_norm(x, norm_weight, eps=eps, bias=norm_bias)
    # Exact (erf) GELU: what torch nn.GELU() computes — BERT-family,
    # ViT and Whisper checkpoints are all trained with it, and the
    # tanh approximation drifts ~3e-3 per activation, enough to break
    # differential tests against imported weights.
    return x + _add(
        jax.nn.gelu(_add(normed @ w1, b1).astype(jnp.float32),
                    approximate=False)
        .astype(x.dtype) @ w2, b2)

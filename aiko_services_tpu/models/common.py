"""Shared model building blocks (LayerNorm-family encoders).

Used by :mod:`.classifier`, :mod:`.asr`, :mod:`.vision` — one LayerNorm
and one multi-head-attention plumbing implementation so numerics fixes
apply everywhere.  (:mod:`.llama` uses RMSNorm/GQA and keeps its own
blocks.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.attention import attention_reference

__all__ = ["layer_norm", "mha", "gelu_mlp"]

LN_EPS = 1e-6


def layer_norm(x, weight, eps: float = LN_EPS):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype) \
        * weight


def mha(x_q, x_kv, w_in, wo, n_heads: int, causal: bool,
        cross: bool = False, wkv=None):
    """Fused-projection multi-head attention.

    Self-attention: ``w_in`` is the (d, 3d) qkv projection and ``x_kv``
    is ignored.  Cross-attention (``cross=True``): ``w_in`` is the
    (d, d) q projection and ``wkv`` the (d_kv, 2d) kv projection over
    ``x_kv``.
    """
    b, q_len, d = x_q.shape
    hd = d // n_heads
    if cross:
        q = (x_q @ w_in).reshape(b, q_len, n_heads, hd)
        kv = (x_kv @ wkv).reshape(b, x_kv.shape[1], 2, n_heads, hd)
        k, v = kv[:, :, 0], kv[:, :, 1]
    else:
        qkv = (x_q @ w_in).reshape(b, q_len, 3, n_heads, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    out = attention_reference(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=causal)
    out = out.transpose(0, 2, 1, 3).reshape(b, q_len, d)
    return (out @ wo).astype(x_q.dtype)


def gelu_mlp(x, norm_weight, w1, w2):
    normed = layer_norm(x, norm_weight)
    return x + (jax.nn.gelu((normed @ w1).astype(jnp.float32))
                .astype(x.dtype) @ w2)
